"""The program inventory: one record per compiled-program candidate.

This is the artifact half of progcheck (ISSUE 9): the shape signature,
FLOPs (XLA `cost_analysis` where the build exposes it), and collective
payload of every program the repo compiles — the seed data for the
planned CompiledRegistry (ROADMAP item 5), and what
`tools/telemetry_report.py --programs` folds into bench records so the
MFUEstimator's analytic FLOPs can be cross-checked against the
compiler's own count.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

from tools.progcheck.jaxpr_utils import collect_collectives, walk_eqns

INVENTORY_VERSION = 1


@dataclasses.dataclass
class ProgramRecord:
    """One audited program. `jaxpr` is the live ClosedJaxpr the checks
    walk; everything else serializes into the inventory JSON."""

    name: str                 # "family/mode" — the finding anchor
    family: str               # train | v3 | probe | gradsync | serve | aug_step | eval
    mode: str | None          # grad_sync mode / bucket / trim shape
    jaxpr: Any
    in_avals: list[str]
    out_avals: list[str]
    n_eqns: int
    collectives: list
    donated: tuple | None = None   # per-flat-input donation flags
    flops: float | None = None
    bytes_accessed: float | None = None
    analytic_flops: float | None = None  # MFUEstimator's count, same config
    meta: dict = dataclasses.field(default_factory=dict)

    @property
    def shape_signature(self) -> tuple:
        return tuple(self.in_avals)

    def collective_bytes(self) -> int:
        return sum(c.operand_bytes for c in self.collectives)

    def json_obj(self) -> dict:
        obj = {
            "name": self.name,
            "family": self.family,
            "mode": self.mode,
            "in_avals": self.in_avals,
            "out_avals_n": len(self.out_avals),
            "n_eqns": self.n_eqns,
            "collectives": [c.json_obj() for c in self.collectives],
            "collective_bytes": self.collective_bytes(),
        }
        if self.donated is not None:
            obj["donated_inputs"] = int(sum(bool(d) for d in self.donated))
        if self.flops is not None:
            obj["flops"] = self.flops
        if self.bytes_accessed is not None:
            obj["bytes_accessed"] = self.bytes_accessed
        if self.analytic_flops is not None:
            obj["analytic_flops"] = self.analytic_flops
            if self.flops:
                obj["flops_vs_analytic"] = round(self.flops / self.analytic_flops, 4)
        for key in ("sync_bytes_per_step", "buckets", "max_programs"):
            if key in self.meta:
                obj[key] = self.meta[key]
        return obj


def make_record(name: str, family: str, mode: str | None, closed_jaxpr,
                donated=None, meta: dict | None = None) -> ProgramRecord:
    jaxpr = closed_jaxpr.jaxpr if hasattr(closed_jaxpr, "jaxpr") else closed_jaxpr
    n_eqns = sum(1 for _ in walk_eqns(closed_jaxpr))
    return ProgramRecord(
        name=name,
        family=family,
        mode=mode,
        jaxpr=closed_jaxpr,
        in_avals=[str(v.aval) for v in jaxpr.invars],
        out_avals=[str(v.aval) for v in jaxpr.outvars],
        n_eqns=n_eqns,
        collectives=collect_collectives(closed_jaxpr),
        donated=donated,
        meta=dict(meta or {}),
    )


def inventory_json(records: list[ProgramRecord], mesh_size: int) -> dict:
    by_family: dict[str, int] = {}
    for r in records:
        by_family[r.family] = by_family.get(r.family, 0) + 1
    return {
        "version": INVENTORY_VERSION,
        "tool": "progcheck",
        "mesh_size": mesh_size,
        "program_count": len(records),
        "by_family": dict(sorted(by_family.items())),
        "programs": [r.json_obj() for r in records],
    }


def write_inventory(path: str, records: list[ProgramRecord],
                    mesh_size: int) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(inventory_json(records, mesh_size), f, indent=2)
        f.write("\n")


# ---------------------------------------------------------------------------
# golden invariant summaries (satellite: refactors diff loudly)
# ---------------------------------------------------------------------------


def invariant_summary(record: ProgramRecord) -> dict:
    """The parts of a step program a refactor must not silently change:
    collective count/shape/payload and the donation/output contract.
    FLOPs and eqn counts are deliberately absent — they churn with every
    fusion-level change and would make the golden noisy."""
    colls = sorted(
        (dataclasses.asdict(c) for c in record.collectives),
        key=lambda c: (c["prim"], c["axes"], c["operand_dtypes"],
                       c["operand_elems"]),
    )
    return {
        "collectives": colls,
        "collective_bytes": record.collective_bytes(),
        "n_outputs": len(record.out_avals),
        "donated_inputs": (int(sum(bool(d) for d in record.donated))
                           if record.donated is not None else 0),
    }


def golden_json(records: list[ProgramRecord], mesh_size: int) -> dict:
    return {
        "version": INVENTORY_VERSION,
        "mesh_size": mesh_size,
        "programs": {
            r.name: invariant_summary(r)
            for r in sorted(records, key=lambda r: r.name)
            if r.family in ("train", "v3")
        },
    }
