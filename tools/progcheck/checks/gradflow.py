"""P1: no differentiable path from the loss into the key encoder / queue.

THE MoCo contract (He et al.): the key encoder moves only by EMA, the
queue only by enqueue — gradients must never reach either. The probe
programs (train_step.build_grad_probe / v3_step.build_v3_grad_probe)
differentiate the production key-path + loss code w.r.t. the query
params AND the key params AND the queue; with the key branch's
stop_gradient in place, the key/queue gradients are SYMBOLIC zeros, so
in the jaxpr those outputs depend on no program input. Deleting the
stop_gradient gives them real data paths — which this check sees
immediately, without running a single flop.

The flow side is the vacuity guard: if the QUERY grads also depended on
nothing, the probe would be auditing a constant function and a pass
would be meaningless.
"""

from __future__ import annotations

from tools.progcheck.jaxpr_utils import input_dependence
from tools.progcheck.registry import Check, register


@register
class GradFlow(Check):
    id = "P1"
    title = "no gradient reaches the key encoder or the queue"
    rationale = ("MoCo's key encoder moves only by EMA and the queue only "
                 "by enqueue; a differentiable path into either silently "
                 "turns the method into end-to-end contrastive training")
    families = ("probe",)

    def check_program(self, record):
        deps = input_dependence(record.jaxpr)
        for group, start, end in record.meta.get("zero_groups", ()):
            leaky = [i for i in range(start, min(end, len(deps))) if deps[i]]
            if leaky:
                yield self.finding(
                    record,
                    f"gradient flows into {group}: {len(leaky)} of "
                    f"{end - start} grad outputs depend on program inputs "
                    "— the key-branch stop_gradient is gone or bypassed",
                )
        for group, start, end in record.meta.get("flow_groups", ()):
            if not any(deps[i] for i in range(start, min(end, len(deps)))):
                yield self.finding(
                    record,
                    f"no {group} gradient depends on any input — the probe "
                    "is differentiating a constant; the audit is vacuous",
                )
