"""P6: step programs host no Python callbacks.

A `debug_print`/`pure_callback`/`io_callback` inside a step program
drags a host round-trip onto the device critical path EVERY step — the
async dispatch pipeline the whole input-overlap design depends on stalls
behind it (mocolint R8 guards the source-level cousins; this sees the
traced truth, including callbacks smuggled in through a library call).
"""

from __future__ import annotations

from tools.progcheck.jaxpr_utils import CALLBACK_PRIMS, walk_eqns
from tools.progcheck.registry import Check, register


@register
class NoHostCallbacks(Check):
    id = "P6"
    title = "no host callbacks or debug prints in step programs"
    rationale = ("a callback in a compiled step synchronizes device and "
                 "host every step, defeating async dispatch and the "
                 "overlapped input pipeline")

    def check_program(self, record):
        seen = set()
        for eqn, _bound in walk_eqns(record.jaxpr):
            name = eqn.primitive.name
            if name in CALLBACK_PRIMS and name not in seen:
                seen.add(name)
                detail = ""
                cb = eqn.params.get("callback")
                if cb is not None:
                    detail = f" ({getattr(cb, '__name__', cb)!r})"
                yield self.finding(
                    record,
                    f"host callback primitive {name!r}{detail} inside a "
                    "compiled step program — remove it or move it to the "
                    "host side of the step boundary",
                )
