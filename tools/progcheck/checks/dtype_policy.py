"""P4/P5: the per-leaf reduce dtype policy, checked in the program.

gradsync's contract (parallel/gradsync.leaf_wire_dtype): integer leaves
are SUMMED exactly — never averaged, never cast — and bf16 float leaves
reduce in their OWN dtype under the float32 policy instead of being
silently widened (which doubles their wire bytes and hides the fact the
leaf was ever bf16). Source-level lint can't see either: both hazards
are one `.astype`/`/ n` away and live in traced code.

P4 — an integer sum-reduce result must not feed a division: psum(int)/n
is an average of a counter, which silently corrupts exact-sum semantics
(ratios land in some float, remainders vanish in int).

P5 — a sum-reduce operand must not be the direct product (through
layout ops) of a bf16→wider-float cast: that is the old `_pmean_grads`
widening regression, re-materialized.
"""

from __future__ import annotations

import warnings

with warnings.catch_warnings():
    warnings.simplefilter("ignore", DeprecationWarning)
    from jax import core as jax_core

from tools.progcheck.jaxpr_utils import (
    SUM_REDUCE_PRIMS,
    build_producers,
    iter_jaxprs,
    trace_back,
)
from tools.progcheck.registry import Check, register

_LAYOUT = ("reshape", "concatenate", "transpose", "squeeze", "copy",
           "convert_element_type", "broadcast_in_dim", "slice")


def _is_int(aval) -> bool:
    kind = getattr(getattr(aval, "dtype", None), "kind", "")
    return kind in ("i", "u", "b")


@register
class IntLeavesNeverAveraged(Check):
    id = "P4"
    title = "integer reduce results are never averaged"
    rationale = ("an int leaf in a grads-shaped tree is a counter; "
                 "psum(int)/n silently corrupts its exact-sum semantics")

    def check_program(self, record):
        reported = False
        for jaxpr in iter_jaxprs(record.jaxpr):
            # vars that are (layout-transparently) integer sum-reduce
            # results
            int_reduced = set()
            for eqn in jaxpr.eqns:
                name = eqn.primitive.name
                if name in SUM_REDUCE_PRIMS:
                    for vin, vout in zip(eqn.invars, eqn.outvars):
                        if _is_int(vin.aval):
                            int_reduced.add(vout)
                elif name in _LAYOUT:
                    if any(v in int_reduced for v in eqn.invars
                           if not isinstance(v, jax_core.Literal)):
                        int_reduced.update(eqn.outvars)
                elif name == "div" and not reported:
                    num = eqn.invars[0]
                    if not isinstance(num, jax_core.Literal) and num in int_reduced:
                        reported = True
                        yield self.finding(
                            record,
                            "an integer sum-reduce result feeds a division "
                            "— integer leaves must be summed exactly, "
                            "never averaged (gradsync dtype policy)",
                        )


@register
class NoSilentBf16Widen(Check):
    id = "P5"
    title = "bf16 leaves are not widened before the reduce"
    rationale = ("casting a bf16 leaf to f32 on the wire doubles its "
                 "reduce bytes and silently reverts the per-leaf dtype "
                 "policy — the old _pmean_grads regression")

    def check_program(self, record):
        reported = set()
        for jaxpr in iter_jaxprs(record.jaxpr):
            producers = build_producers(jaxpr)
            for eqn in jaxpr.eqns:
                if eqn.primitive.name not in SUM_REDUCE_PRIMS:
                    continue
                for v in eqn.invars:
                    if isinstance(v, jax_core.Literal):
                        continue
                    src = trace_back(v, producers,
                                     through=("reshape", "concatenate",
                                              "transpose", "squeeze",
                                              "copy"))
                    if src is None or src.primitive.name != "convert_element_type":
                        continue
                    opnd = [x for x in src.invars
                            if not isinstance(x, jax_core.Literal)]
                    if not opnd:
                        continue
                    from_dt = str(opnd[0].aval.dtype)
                    to_dt = str(src.outvars[0].aval.dtype)
                    if from_dt == "bfloat16" and to_dt in ("float32",
                                                           "float64"):
                        key = (from_dt, to_dt)
                        if key in reported:
                            continue
                        reported.add(key)
                        yield self.finding(
                            record,
                            f"sum-reduce operand was widened {from_dt} -> "
                            f"{to_dt} immediately before the collective — "
                            "bf16 leaves must reduce in their own dtype "
                            "(gradsync dtype policy)",
                        )
