"""P8: the gradsync wire-bytes claim is machine-checked.

GradSync.sync_bytes_per_step() is what telemetry/bench report as the
per-device sync payload — every "quantized cuts sync bytes 4×" claim in
a BENCH record rests on it. This check recomputes the payload FROM THE
JAXPR of the isolated reduce program (GradSync.audit_region_program) and
requires exact equality, so the analytic accounting can never drift from
what the program actually moves.

Wire conventions (mirroring the analytic side):
  - the grads-ready probe (one scalar f32 psum) is excluded — scalars
    are reserved for it by the audit program's contract;
  - quantized int8 rides an int32 CARRIER (XLA exposes no in-collective
    requantization) but the modeled wire payload is the int8 it carries:
    a carrier psum whose operand was converted FROM int8 counts 1 B/elem;
  - the per-leaf scale pmax counts at its native f32 width;
  - demo's sparse (vals, idx) pairs leave the region as P(data)-sharded
    outputs and merge at the outer jit level, so their wire share is the
    per-device slice of the payload avals.
"""

from __future__ import annotations

import warnings

with warnings.catch_warnings():
    warnings.simplefilter("ignore", DeprecationWarning)
    from jax import core as jax_core

from tools.progcheck.jaxpr_utils import (
    SUM_REDUCE_PRIMS,
    build_producers,
    iter_jaxprs,
    trace_back,
)
from tools.progcheck.registry import Check, register


def _size(aval) -> int:
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n


def measured_wire_bytes(record) -> int:
    """Per-device wire bytes the audited reduce program moves per call."""
    total = 0
    for jaxpr in iter_jaxprs(record.jaxpr):
        producers = build_producers(jaxpr)
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in SUM_REDUCE_PRIMS:
                for v in eqn.invars:
                    if isinstance(v, jax_core.Literal):
                        continue
                    aval = v.aval
                    if aval.shape == ():
                        continue  # the grads-ready probe scalar
                    src = trace_back(v, producers, through=("reshape",))
                    if (str(aval.dtype) == "int32" and src is not None
                            and src.primitive.name == "convert_element_type"):
                        opnd = [x for x in src.invars
                                if not isinstance(x, jax_core.Literal)]
                        if opnd and str(opnd[0].aval.dtype) == "int8":
                            total += _size(aval)  # int8 payload on carrier
                            continue
                    total += _size(aval) * int(aval.dtype.itemsize)
            elif name == "pmax":
                for v in eqn.invars:
                    if isinstance(v, jax_core.Literal) or v.aval.shape == ():
                        continue
                    total += _size(v.aval) * int(v.aval.dtype.itemsize)
    # demo: the sparse payload leaves the region as sharded outputs
    payload = record.meta.get("payload_shape")
    n = record.meta.get("mesh_size", 1)
    if isinstance(payload, dict):
        import jax

        for key in ("vals", "idx"):
            for leaf in jax.tree.leaves(payload.get(key, ())):
                total += (_size(leaf) * int(leaf.dtype.itemsize)) // n
    return total


@register
class WireBytesMatchTelemetry(Check):
    id = "P8"
    title = "gradsync wire bytes match the analytic telemetry claim"
    rationale = ("sync_bytes_per_step feeds telemetry and BENCH records; "
                 "if the program moves different bytes than the analytic "
                 "count, every compression claim built on it is fiction")
    families = ("gradsync",)

    def check_program(self, record):
        gs = record.meta.get("gradsync")
        if gs is None:
            return
        if int(getattr(gs, "cadence", 1)) != 1:
            # the analytic count amortizes demo's payload over the cadence;
            # a static audit sees the sync-step program, so the surface
            # builds its audit strategies at cadence 1 where the two agree
            yield self.finding(
                record,
                f"audit program built at cadence {gs.cadence} — wire-bytes "
                "parity is only defined at cadence 1 (fix the surface)",
            )
            return
        claimed = int(gs.sync_bytes_per_step())
        measured = measured_wire_bytes(record)
        if measured != claimed:
            yield self.finding(
                record,
                f"jaxpr wire payload is {measured} B/device/sync but the "
                f"analytic sync-bytes claim is {claimed} B — the telemetry "
                "accounting and the compiled program have drifted",
            )
