"""Built-in progcheck checks. Importing this package registers them."""

from tools.progcheck.checks import (  # noqa: F401
    callbacks,
    collective_axes,
    compile_set,
    donation,
    dtype_policy,
    gradflow,
    health,
    wire_bytes,
)
