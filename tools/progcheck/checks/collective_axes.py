"""P2/P3: collective axis hygiene.

P2 — every collective's named axes must exist in the mesh the program
was built over AND be bound by an enclosing shard_map at the reduce
site. A collective over a foreign axis name means the program and its
mesh have forked (a copy-pasted region built against a different mesh
layout) — it traces fine in its own world and deadlocks or mis-reduces
in this one.

P3 — no value is sum-reduced twice over the same axis: the
double-reduced gradient (an inline pmean left in front of the gradsync
reduce) scales grads by an extra 1/n and is invisible to tests that only
check for finiteness. Taint-based: see jaxpr_utils.double_sum_reduces
for why a forward-pass psum does NOT taint gradients computed from it.
"""

from __future__ import annotations

from tools.progcheck.jaxpr_utils import (
    COLLECTIVE_PRIMS,
    double_sum_reduces,
    named_axes,
    walk_eqns,
)
from tools.progcheck.registry import Check, register


@register
class CollectiveAxes(Check):
    id = "P2"
    title = "collective axes exist in the program's mesh"
    rationale = ("a collective over an axis the mesh doesn't define means "
                 "program and mesh have forked — it mis-reduces or "
                 "deadlocks on the hardware the mesh actually describes")

    def check_program(self, record):
        mesh_axes = set(record.meta.get("mesh_axes", ()))
        seen = set()
        for eqn, bound in walk_eqns(record.jaxpr):
            if eqn.primitive.name not in COLLECTIVE_PRIMS:
                continue
            for ax in named_axes(eqn):
                if mesh_axes and ax not in mesh_axes and (eqn.primitive.name, ax) not in seen:
                    seen.add((eqn.primitive.name, ax))
                    yield self.finding(
                        record,
                        f"{eqn.primitive.name} over axis {ax!r} which the "
                        f"mesh does not define (mesh axes: "
                        f"{sorted(mesh_axes)})",
                    )
                elif ax not in bound and (eqn.primitive.name, ax, "unbound") not in seen:
                    seen.add((eqn.primitive.name, ax, "unbound"))
                    yield self.finding(
                        record,
                        f"{eqn.primitive.name} over axis {ax!r} outside any "
                        "shard_map binding it — the reduce has no device "
                        "group to run over",
                    )


@register
class DoubleReduce(Check):
    id = "P3"
    title = "gradients are sum-reduced exactly once"
    rationale = ("a second psum/pmean over an already-reduced value "
                 "rescales it by the mesh size — the classic inline-pmean-"
                 "before-gradsync regression, invisible to finiteness tests")

    def check_program(self, record):
        seen = set()
        for prim, axis in double_sum_reduces(record.jaxpr):
            if (prim, axis) in seen:
                continue
            seen.add((prim, axis))
            yield self.finding(
                record,
                f"{prim} over axis {axis!r} consumes a value already "
                "sum-reduced over that axis — the operand is reduced "
                "twice (grads through gradsync must meet exactly one "
                "collective)",
            )
