"""P10: health diagnostics add no collectives to the step program.

The in-graph learning-health diagnostics (ISSUE 13; telemetry/health.py)
promise to ride the step's EXISTING metrics reduction: every scalar they
produce joins the one metrics pmean, and the stride gate is a lax.cond —
a control-flow primitive, never a collective. A diagnostics branch that
grew its own psum (or smuggled an all_gather of embeddings) would put a
new synchronization point on the every-step critical path — including
the off-stride steps, because a collective inside EITHER cond branch
must execute on both (SPMD cond semantics). This check compares each
`<base>+health` program against its base: the collective primitive
multiset must be identical (the metrics reduce may carry more bytes —
that is the design — but gather/permute collectives must not change at
all). P6 separately proves the diagnostics host no callbacks.
"""

from __future__ import annotations

from tools.progcheck.registry import Check, register

_SUFFIX = "+health"
# prims whose payload the health variant may legitimately grow: the
# metrics reduction the diagnostics ride
_REDUCE_PRIMS = ("psum", "psum2", "pmean")


@register
class HealthNoNewCollectives(Check):
    id = "P10"
    title = "health-instrumented steps add no collectives over their base"
    families = ("train", "v3")
    rationale = ("the diagnostics contract is observational: scalars join "
                 "the existing metrics reduce — a new collective would "
                 "add an every-step synchronization point even at "
                 "off-stride steps (SPMD cond runs collectives in both "
                 "branches)")

    def finalize(self, inventory):
        by_name = {r.name: r for r in inventory}
        for rec in inventory:
            if not rec.name.endswith(_SUFFIX):
                continue
            base = by_name.get(rec.name[: -len(_SUFFIX)])
            if base is None:
                continue  # base family not traced this run
            base_prims = sorted(c.prim for c in base.collectives)
            health_prims = sorted(c.prim for c in rec.collectives)
            if base_prims != health_prims:
                yield self.finding(
                    rec,
                    f"collective set changed vs {base.name}: "
                    f"{base_prims} -> {health_prims} — diagnostics must "
                    "ride the existing metrics reduction, never add "
                    "their own collective",
                )
                continue
            base_gathers = sorted(
                (c.prim, tuple(c.axes), c.operand_bytes)
                for c in base.collectives if c.prim not in _REDUCE_PRIMS
            )
            health_gathers = sorted(
                (c.prim, tuple(c.axes), c.operand_bytes)
                for c in rec.collectives if c.prim not in _REDUCE_PRIMS
            )
            if base_gathers != health_gathers:
                yield self.finding(
                    rec,
                    f"non-reduce collective payloads changed vs "
                    f"{base.name}: {base_gathers} -> {health_gathers} — "
                    "the diagnostics may widen the metrics reduce only, "
                    "never a gather/permute",
                )
