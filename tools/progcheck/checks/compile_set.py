"""P9: the compile set stays bounded.

serve's zero-recompile contract (ISSUE 5) generalized to training: a
family that promises a FIXED program ladder (the serve buckets, the
h2d_trim 64-rounded canvas shapes) must enumerate no more distinct
shape signatures than its declared bound. A new uncontrolled shape here
is tomorrow's multi-second compile stall under load — caught at audit
time instead of at p99.
"""

from __future__ import annotations

from tools.progcheck.registry import Check, register


@register
class BoundedCompileSet(Check):
    id = "P9"
    title = "program families stay within their compile-set bound"
    rationale = ("every distinct input shape is a compile; a family that "
                 "outgrows its declared ladder recompiles under load — "
                 "the stall serve's bucket design exists to prevent")

    def finalize(self, inventory):
        by_family: dict[str, list] = {}
        for rec in inventory:
            if "max_programs" in rec.meta:
                by_family.setdefault(rec.family, []).append(rec)
        for family, recs in sorted(by_family.items()):
            bound = max(r.meta["max_programs"] for r in recs)
            signatures = {r.shape_signature for r in recs}
            if len(signatures) > bound:
                yield self.finding(
                    family,
                    f"{len(signatures)} distinct compiled shapes but the "
                    f"declared bound is {bound} — the compile set is no "
                    "longer closed (a shape outside the ladder slipped in)",
                )
