"""P7: donated buffers really alias an output.

The train step donates its state so XLA updates params/queue in place in
HBM. Donation is only an ALIAS REQUEST: a donated input with no
shape/dtype-matching output silently degrades to a copy (jax warns once,
at lower time, on a machine nobody watches) — doubling the state's HBM
footprint exactly where it hurts. This check makes the aliasing budget a
gate: every donated input aval must be coverable by a distinct output
aval.

("Read after donation" from the CALLER's side is enforced by the runtime
itself — jax poisons donated buffers; what the runtime does NOT enforce
is that the donation bought anything.)
"""

from __future__ import annotations

from collections import Counter

from tools.progcheck.registry import Check, register


@register
class DonationAliases(Check):
    id = "P7"
    title = "donated inputs alias a matching output"
    rationale = ("a donated buffer with no matching output silently "
                 "becomes a copy — the state's HBM footprint doubles and "
                 "the only witness is a lower-time warning nobody reads")
    families = ("train", "v3", "aug_step", "resize")

    def check_program(self, record):
        if not record.donated:
            return
        jaxpr = record.jaxpr.jaxpr
        donated_avals = [
            v.aval for v, d in zip(jaxpr.invars, record.donated) if d
        ]
        outs = Counter(
            (tuple(v.aval.shape), str(v.aval.dtype)) for v in jaxpr.outvars
        )
        unmatched = []
        for aval in donated_avals:
            key = (tuple(aval.shape), str(aval.dtype))
            if outs.get(key, 0) > 0:
                outs[key] -= 1
            else:
                unmatched.append(aval)
        if unmatched:
            sample = ", ".join(str(a) for a in unmatched[:3])
            yield self.finding(
                record,
                f"{len(unmatched)} donated input(s) cannot alias any "
                f"output (no shape/dtype match): {sample} — the donation "
                "silently degrades to a copy",
            )
