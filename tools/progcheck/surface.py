"""Enumerate the repo's compiled-program surface (ISSUE 9 part a).

Every program the framework compiles, rebuilt ABSTRACTLY on a tiny proxy
config and traced with `jax.make_jaxpr` over `jax.eval_shape`-built
states — no weights are initialized for the train/v3/probe families, no
program executes, so the full surface traces in seconds on the CPU
backend:

  train/<mode>     — the v1/v2 fused-queue step under each grad_sync mode
  v3/<mode>        — the queue-free symmetric step under each mode
  probe/train,v3   — the grad-flow audit programs (train_step.
                     build_grad_probe / v3_step.build_v3_grad_probe)
  gradsync/<mode>  — the isolated region reduce (GradSync.
                     audit_region_program), the wire-bytes check's input
  resize/<mode>@2dev — the train step REBUILT on a 2-device sub-mesh
                     (ISSUE 11): the program an elastic 1→2 resize
                     relaunch compiles. P2 pins its collectives to the
                     resized mesh, P7 its donation contract — a step
                     builder that bakes in the boot mesh size would trace
                     fine at 8 devices and desync after every resize
  serve/bucket<N>  — the EmbeddingEngine program at each ladder bucket
  aug_step/<HxW>   — the fused aug+step program at each h2d_trim canvas
                     shape (trim rounds to 64, so the variant set is the
                     bounded compile set the P9 check pins)
  eval/feature,knn — the frozen-feature eval forward + kNN vote programs

The proxy uses `resnet_tiny` at 16 px — program STRUCTURE (collectives,
grad topology, dtype policy, donation) is what the checks audit, and it
is arch-size-independent; `cost_analysis` FLOPs are proxy-sized and
labeled as such in the inventory.
"""

from __future__ import annotations

import warnings

FAMILIES = ("train", "v3", "probe", "gradsync", "serve", "aug_step", "eval",
            "resize")
RESIZE_MESH_SIZE = 2  # the resized-mesh proxy (the 1→2→1 drill's middle leg)
HEALTH_STRIDE = 10    # telemetry/health.DEFAULT_STRIDE (literal: the
                      # surface must enumerate without importing jax-side
                      # modules at module load)

# the tiny proxy (mirrors tests/test_gradsync.py)
B, IMG, DIM, K = 16, 16, 16, 64
CANVAS = 128          # aug_step staging canvas; h2d_trim grid = {64,128}²
SERVE_BUCKETS = (1, 8, 32, 128)
EVAL_BATCH = 32
GRAD_SYNC_KNOBS = dict(grad_sync_bucket_mb=0.05, grad_sync_topk=0.25,
                       grad_sync_cadence=1)


def _proxy_config(**kw):
    from moco_tpu.config import PretrainConfig

    base = dict(variant="v1", arch="resnet_tiny", cifar_stem=True,
                num_negatives=K, embed_dim=DIM, batch_size=B, epochs=2,
                lr=0.1, image_size=IMG, dataset="synthetic")
    base.update(kw)
    return PretrainConfig(**base)


def _cost(lowerable, args, with_cost: bool):
    """(flops, bytes_accessed) from XLA's own cost model, or (None, None)
    when the build doesn't expose it — never fabricated."""
    if not with_cost:
        return None, None
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            ca = lowerable.lower(*args).cost_analysis()
        if isinstance(ca, dict):
            return (float(ca["flops"]) if "flops" in ca else None,
                    float(ca.get("bytes accessed"))
                    if "bytes accessed" in ca else None)
    except Exception:  # jax version surface: NotImplementedError,
        return None, None  # XlaRuntimeError, KeyError... — cost is optional
    return None, None


def _donated(closed_jaxpr):
    """Flat donation flags when the program is one pjit (a jitted fn with
    donate_argnums traces to exactly that)."""
    jaxpr = closed_jaxpr.jaxpr
    if len(jaxpr.eqns) == 1 and jaxpr.eqns[0].primitive.name == "pjit":
        don = jaxpr.eqns[0].params.get("donated_invars")
        if don is not None and any(don):
            return tuple(don)
    return None


def _state_shapes(config, mesh):
    """eval_shape the full TrainState (+ gradsync accumulators) — abstract
    init: no weights materialize."""
    import jax

    from moco_tpu.parallel.gradsync import GradSync
    from moco_tpu.train_step import build_encoder, build_optimizer

    model = build_encoder(config)
    tx, sched = build_optimizer(config, 8)
    gs = GradSync(config, mesh.size)

    def build():
        if config.variant == "v3":
            from moco_tpu.v3_step import create_v3_train_state

            state = create_v3_train_state(
                jax.random.key(0), model, tx,
                (B // mesh.size, IMG, IMG, 3),
            )
        else:
            from moco_tpu.train_state import create_train_state

            state = create_train_state(
                jax.random.key(0), model, tx, (B // mesh.size, IMG, IMG, 3),
                K, DIM,
            )
        return gs.attach(state, mesh)

    return jax.eval_shape(build), model, tx, sched


def _step_records(mesh, with_cost, family):
    import jax
    import jax.numpy as jnp

    from moco_tpu.train_step import build_train_step
    from tools.progcheck.inventory import make_record

    variant = "v1" if family == "train" else "v3"
    records = []
    im = jax.ShapeDtypeStruct((B, IMG, IMG, 3), jnp.float32)
    # the health-instrumented variant (ISSUE 13): the fused step with the
    # stride-gated in-graph diagnostics traced in. Audited as its own
    # program — P6 proves the diagnostics host no callbacks, P10 that
    # they added no collective beyond the existing metrics reduction —
    # and pinned in golden_invariants.json next to its base
    modes = [("fused", {}), ("bucketed", {}), ("quantized", {}),
             ("demo", {}),
             ("fused+health", {"grad_sync": "fused",
                               "health_stride": HEALTH_STRIDE})]
    if variant == "v3":
        # the FSDP-sharded programs (ISSUE 15): params sharded over the
        # 2-D mesh's fsdp axis, gathered on use, grads reduce-scattered.
        # fused pins the exact-DP collective topology, quantized the
        # compressed one (and, on a data>1 mesh, the multi-hop reduce).
        # P2 verifies every collective axis is bound by the 2-D mesh.
        modes += [("fsdp+fused", {"grad_sync": "fused",
                                  "sharding": "fsdp"}),
                  ("fsdp+quantized", {"grad_sync": "quantized",
                                      "sharding": "fsdp"})]
    for mode, extra in modes:
        config = _proxy_config(variant=variant,
                               grad_sync=extra.get("grad_sync", mode),
                               **GRAD_SYNC_KNOBS, **{
                                   k: v for k, v in extra.items()
                                   if k != "grad_sync"})
        step_mesh = mesh
        if extra.get("sharding", "dp") != "dp":
            from moco_tpu.parallel.mesh import mesh_for_config

            step_mesh = mesh_for_config(config, mesh)
        state, model, tx, sched = _state_shapes(config, step_mesh)
        step = build_train_step(config, model, tx, step_mesh, 8, sched,
                                state=state)
        closed = jax.make_jaxpr(step)(state, im, im)
        flops, nbytes = _cost(step, (state, im, im), with_cost)
        rec = make_record(
            f"{family}/{mode}", family, mode, closed,
            donated=_donated(closed),
            meta={"mesh_axes": tuple(str(a) for a in step_mesh.axis_names)},
        )
        # cost_analysis sees the PER-PARTITION program of an SPMD step;
        # scale to the whole global batch so the number is comparable to
        # MFUEstimator's analytic per-step count (ratio ≈ 1 expected:
        # the compiler counts every op, the analytic model only encoder
        # passes — agreement within tens of % is healthy, an order of
        # magnitude means one side broke)
        rec.flops = flops * mesh.size if flops is not None else None
        rec.bytes_accessed = nbytes
        if flops is not None:
            from moco_tpu.telemetry.mfu import train_step_flops

            try:
                rec.analytic_flops = float(train_step_flops(config))
            except (KeyError, ValueError):
                rec.analytic_flops = None
        records.append(rec)
    return records


def _probe_records(mesh):
    import jax
    import jax.numpy as jnp

    from tools.progcheck.inventory import make_record

    records = []
    im = jax.ShapeDtypeStruct((B, IMG, IMG, 3), jnp.float32)

    # v1/v2 probe: grads w.r.t. (params_q, params_k, queue)
    config = _proxy_config()
    state, model, tx, _ = _state_shapes(config, mesh)
    from moco_tpu.train_step import build_grad_probe

    probe = build_grad_probe(config, model, mesh)
    key = jax.eval_shape(lambda: jax.random.key(0))
    args = (state.params_q, state.params_k, state.batch_stats_q,
            state.batch_stats_k, state.queue, im, im, key)
    closed = jax.make_jaxpr(probe)(*args)
    n_q = len(jax.tree.leaves(state.params_q))
    n_k = len(jax.tree.leaves(state.params_k))
    records.append(make_record(
        "probe/train", "probe", None, closed,
        meta={
            "mesh_axes": tuple(str(a) for a in mesh.axis_names),
            # flat OUTPUT leaf ranges: (g_q, g_k, g_queue)
            "flow_groups": [("params_q", 0, n_q)],
            "zero_groups": [("params_k", n_q, n_q + n_k),
                            ("queue", n_q + n_k, n_q + n_k + 1)],
        },
    ))

    # v3 probe: grads w.r.t. (params_q, params_k)
    config = _proxy_config(variant="v3")
    state, model, tx, _ = _state_shapes(config, mesh)
    from moco_tpu.v3_step import build_v3_grad_probe

    probe = build_v3_grad_probe(config, model, mesh)
    args = (state.params_q, state.params_k, state.batch_stats_q,
            state.batch_stats_k, im, im)
    closed = jax.make_jaxpr(probe)(*args)
    n_q = len(jax.tree.leaves(state.params_q))
    n_k = len(jax.tree.leaves(state.params_k))
    records.append(make_record(
        "probe/v3", "probe", None, closed,
        meta={
            "mesh_axes": tuple(str(a) for a in mesh.axis_names),
            "flow_groups": [("params_q", 0, n_q)],
            "zero_groups": [("params_k", n_q, n_q + n_k)],
        },
    ))
    return records


def _gradsync_records(mesh):
    import jax
    import jax.numpy as jnp

    from moco_tpu.parallel.gradsync import GradSync
    from tools.progcheck.inventory import make_record

    # a grads-shaped tree exercising the whole dtype policy: f32, bf16,
    # and an exact-sum integer leaf (scalar leaves are reserved for the
    # probe — see the wire-bytes check's probe exclusion)
    params = {
        "w": jnp.zeros((300,), jnp.float32),
        "b": jnp.zeros((12, 12), jnp.float32),
        "h": jnp.zeros((64,), jnp.bfloat16),
        "count": jnp.zeros((4,), jnp.int32),
    }
    records = []
    for mode in ("fused", "bucketed", "quantized", "demo"):
        config = _proxy_config(grad_sync=mode, **GRAD_SYNC_KNOBS)
        gs = GradSync(config, mesh.size)
        fn, args, payload_shape = gs.audit_region_program(params, mesh)
        closed = jax.make_jaxpr(fn)(*args)
        records.append(make_record(
            f"gradsync/{mode}", "gradsync", mode, closed,
            meta={
                "mesh_axes": tuple(str(a) for a in mesh.axis_names),
                "gradsync": gs,
                "payload_shape": payload_shape,
                "mesh_size": mesh.size,
                "sync_bytes_per_step": gs.sync_bytes_per_step(),
            },
        ))
    # the topology-aware multi-hop reduce (ISSUE 15): quantized over a
    # 2-D mesh with BOTH axes > 1 — exact intra-hop psum + compressed
    # inter-hop. P8 verifies the per-hop wire bytes (intra f32 + inter
    # int8 payload + scales) sum to sync_bytes_per_step's claim.
    if mesh.size >= 4:
        from moco_tpu.parallel.mesh import create_mesh_2d

        mesh2d = create_mesh_2d(mesh.size // 2, devices=list(mesh.devices.flat))
        config = _proxy_config(grad_sync="quantized", **GRAD_SYNC_KNOBS)
        gs = GradSync(
            config, mesh2d.size,
            axes=tuple(str(a) for a in mesh2d.axis_names),
            axis_sizes=tuple(int(s) for s in mesh2d.devices.shape),
        )
        fn, args, payload_shape = gs.audit_region_program(params, mesh2d)
        closed = jax.make_jaxpr(fn)(*args)
        records.append(make_record(
            "gradsync/quantized@2d", "gradsync", "quantized@2d", closed,
            meta={
                "mesh_axes": tuple(str(a) for a in mesh2d.axis_names),
                "gradsync": gs,
                "payload_shape": payload_shape,
                "mesh_size": mesh2d.size,
                "sync_bytes_per_step": gs.sync_bytes_per_step(),
            },
        ))
    return records


def _serve_records(mesh, with_cost):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from moco_tpu.serve.engine import EmbeddingEngine
    from moco_tpu.train_step import build_encoder
    from tools.progcheck.inventory import make_record

    config = _proxy_config()
    model = build_encoder(config)
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, IMG, IMG, 3), jnp.float32),
                           train=False)
    engine = EmbeddingEngine(
        model, variables["params"], variables.get("batch_stats", {}),
        image_size=IMG, buckets=SERVE_BUCKETS,
    )
    records = []
    for bucket in engine.buckets:
        images = jax.ShapeDtypeStruct((bucket, IMG, IMG, 3), np.uint8)
        args = (engine.params, engine.batch_stats, images)
        closed = jax.make_jaxpr(engine._jitted)(*args)
        flops, nbytes = _cost(engine._jitted, args, with_cost)
        rec = make_record(
            f"serve/bucket{bucket}", "serve", str(bucket), closed,
            meta={
                "mesh_axes": tuple(str(a) for a in mesh.axis_names),
                "max_programs": len(engine.buckets),
                "buckets": list(engine.buckets),
            },
        )
        rec.flops, rec.bytes_accessed = flops, nbytes
        records.append(rec)
    return records


def _aug_step_records(mesh, with_cost):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from moco_tpu.data.augment import (
        aug_config_for,
        build_two_crops_sharded,
        with_dtype,
    )
    from moco_tpu.train_step import build_fused_step, build_train_step
    from tools.progcheck.inventory import make_record

    config = _proxy_config()
    state, model, tx, sched = _state_shapes(config, mesh)
    step = build_train_step(config, model, tx, mesh, 8, sched)
    aug_cfg = with_dtype(aug_config_for(config), config.compute_dtype)
    two_crops = build_two_crops_sharded(aug_cfg, mesh)
    fused = build_fused_step(step, two_crops, jax.random.key(0))
    # the h2d_trim bounded compile set: trim rounds each canvas dim up to
    # 64, so a CANVAS staging canvas admits exactly (CANVAS//64)² shapes
    sizes = list(range(64, CANVAS + 1, 64))
    max_programs = len(sizes) ** 2
    records = []
    step_sds = jax.ShapeDtypeStruct((), jnp.int32)
    extents = jax.ShapeDtypeStruct((B, 2), np.int32)
    for th in sizes:
        for tw in sizes:
            imgs = jax.ShapeDtypeStruct((B, th, tw, 3), np.uint8)
            args = (state, imgs, extents, step_sds)
            closed = jax.make_jaxpr(fused)(*args)
            flops, nbytes = _cost(fused, args, with_cost)
            rec = make_record(
                f"aug_step/{th}x{tw}", "aug_step", f"{th}x{tw}", closed,
                donated=_donated(closed),
                meta={
                    "mesh_axes": tuple(str(a) for a in mesh.axis_names),
                    "max_programs": max_programs,
                },
            )
            rec.flops, rec.bytes_accessed = flops, nbytes
            records.append(rec)
    return records


def _resize_records(mesh, with_cost):
    """The elastic-relaunch programs (ISSUE 11 satellite): the train step
    rebuilt over a RESIZED sub-mesh. `fused` is the exact-DP baseline;
    `quantized` additionally carries the [n_dev, ...] gradsync
    accumulators the dialect shim rebuilds fresh-zero on a mesh-size
    change — its collectives and donation over the 2-device mesh are what
    the resized relaunch actually compiles."""
    import jax
    import jax.numpy as jnp

    from moco_tpu.parallel.mesh import create_mesh
    from moco_tpu.train_step import build_train_step
    from tools.progcheck.inventory import make_record

    if len(jax.devices()) < RESIZE_MESH_SIZE:
        return []  # single-device backend: nothing to resize onto
    small = create_mesh(RESIZE_MESH_SIZE)
    im = jax.ShapeDtypeStruct((B, IMG, IMG, 3), jnp.float32)
    records = []
    for mode in ("fused", "quantized"):
        config = _proxy_config(grad_sync=mode, **GRAD_SYNC_KNOBS)
        state, model, tx, sched = _state_shapes(config, small)
        step = build_train_step(config, model, tx, small, 8, sched)
        closed = jax.make_jaxpr(step)(state, im, im)
        flops, nbytes = _cost(step, (state, im, im), with_cost)
        rec = make_record(
            f"resize/{mode}@{RESIZE_MESH_SIZE}dev", "resize", mode, closed,
            donated=_donated(closed),
            meta={
                "mesh_axes": tuple(str(a) for a in small.axis_names),
                "mesh_size": small.size,
            },
        )
        rec.flops = flops * small.size if flops is not None else None
        rec.bytes_accessed = nbytes
        records.append(rec)
    return records


def _eval_records(mesh, with_cost):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from moco_tpu.evals.knn import build_feature_fn
    from moco_tpu.ops.knn import _knn_predict_prenormalized
    from moco_tpu.train_step import build_encoder
    from tools.progcheck.inventory import make_record

    config = _proxy_config()
    model = build_encoder(config)
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0),
                           jnp.zeros((1, IMG, IMG, 3), jnp.float32),
                           train=False)
    )
    feature_fn = build_feature_fn(model)
    images = jax.ShapeDtypeStruct((EVAL_BATCH, IMG, IMG, 3), jnp.float32)
    args = (variables["params"], variables.get("batch_stats", {}), images)
    closed = jax.make_jaxpr(feature_fn)(*args)
    flops, nbytes = _cost(feature_fn, args, with_cost)
    rec = make_record(
        "eval/feature", "eval", None, closed,
        meta={"mesh_axes": tuple(str(a) for a in mesh.axis_names)},
    )
    rec.flops, rec.bytes_accessed = flops, nbytes
    records = [rec]

    feats = jax.ShapeDtypeStruct((EVAL_BATCH, DIM), jnp.float32)
    bank = jax.ShapeDtypeStruct((256, DIM), jnp.float32)
    labels = jax.ShapeDtypeStruct((256,), np.int32)
    for name, chunk in (("knn", None), ("knn_chunked", 64)):
        def knn(f, b, l, _chunk=chunk):
            return _knn_predict_prenormalized(
                f, b, l, num_classes=10, k=8, bank_chunk=_chunk
            )

        closed = jax.make_jaxpr(knn)(feats, bank, labels)
        records.append(make_record(
            f"eval/{name}", "eval", None, closed,
            meta={"mesh_axes": tuple(str(a) for a in mesh.axis_names)},
        ))
    return records


def build_surface(mesh=None, families=None, with_cost: bool = True):
    """Trace the full program surface; returns `list[ProgramRecord]`.

    `families` limits the work (tests audit one family at a time); order
    is deterministic. Requires an initialized CPU/TPU backend — the CLI
    forces 8 fake CPU devices before the first jax import."""
    from moco_tpu.parallel.mesh import create_mesh

    if mesh is None:
        mesh = create_mesh()
    wanted = tuple(families) if families else FAMILIES
    unknown = set(wanted) - set(FAMILIES)
    if unknown:
        raise ValueError(f"unknown families: {sorted(unknown)}")
    records = []
    if "train" in wanted:
        records.extend(_step_records(mesh, with_cost, "train"))
    if "v3" in wanted:
        records.extend(_step_records(mesh, with_cost, "v3"))
    if "probe" in wanted:
        records.extend(_probe_records(mesh))
    if "gradsync" in wanted:
        records.extend(_gradsync_records(mesh))
    if "serve" in wanted:
        records.extend(_serve_records(mesh, with_cost))
    if "aug_step" in wanted:
        records.extend(_aug_step_records(mesh, with_cost))
    if "eval" in wanted:
        records.extend(_eval_records(mesh, with_cost))
    if "resize" in wanted:
        records.extend(_resize_records(mesh, with_cost))
    return records
