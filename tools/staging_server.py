#!/usr/bin/env python
"""staging_server — one disaggregated input-service server (ISSUE 14).

    python tools/staging_server.py --health-port 8080 \
        --dataset imagefolder --data-dir /data/imagenet/train

Runs the stdlib supervisor half of one staging server
(`moco_tpu/data/service/server.py`): it binds the health endpoint
(`/healthz`, `/stats`), spawns the numpy decode worker as a SUBPROCESS
(`python -m moco_tpu.data.service.worker`) on the data port, probes it
over the REAL serving path (a `ping` frame — an answer is the
heartbeat), kills probe-stale workers (SIGTERM → grace → SIGKILL) and
relaunches within a restart budget refunded on healthy lives.

Flags this CLI does not recognize are forwarded VERBATIM to the decode
worker (its `--dataset/--data-dir/--prestage/--cache-mb/...` surface —
`worker.add_dataset_flags` is the single source), so the two halves
cannot drift: the supervisor stays pure stdlib (mocolint R11
`staging-server-stdlib-only` — it must outlive a wedged numpy/jax
runtime) without re-declaring the worker's numpy-side flags.

Exit codes (resilience/exitcodes.py): EXIT_STAGING_BIND=50 when the
health port (or, classified from the worker, the data port) cannot be
bound — reschedule-don't-retry, the serve-bind semantics; 45 on a
config-class worker death; 0 on SIGTERM drain.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moco_tpu.data.service.server import StagingServer
from moco_tpu.resilience.exitcodes import EXIT_OK, EXIT_STAGING_BIND
from moco_tpu.serve.fleet import FleetPolicy
from moco_tpu.utils.logging import log_event


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="one staging server: stdlib supervisor + decode-"
                    "worker subprocess (unrecognized flags forward to "
                    "the worker)",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--data-port", type=int, default=0,
                        help="frame-protocol port (0 = auto)")
    parser.add_argument("--health-port", type=int, default=0,
                        help="/healthz + /stats port (0 = auto)")
    parser.add_argument("--server-id", type=int, default=0)
    parser.add_argument("--telemetry-dir", default="",
                        help="events.jsonl + worker.log + spans land "
                             "here (default ./staging_server<id>)")
    parser.add_argument("--probe-secs", type=float, default=1.0)
    parser.add_argument("--health-stale-secs", type=float, default=10.0)
    parser.add_argument("--startup-grace-secs", type=float, default=60.0)
    parser.add_argument("--max-restarts", type=int, default=5)
    args, worker_args = parser.parse_known_args(argv)

    policy = FleetPolicy(
        probe_secs=args.probe_secs,
        health_stale_secs=args.health_stale_secs,
        startup_grace_secs=args.startup_grace_secs,
        max_restarts=args.max_restarts,
    )
    try:
        server = StagingServer(
            worker_args, host=args.host, data_port=args.data_port,
            health_port=args.health_port,
            telemetry_dir=args.telemetry_dir, server_id=args.server_id,
            policy=policy,
        )
    except OSError as e:
        log_event("input_server",
                  f"cannot bind health port {args.host}:"
                  f"{args.health_port}: {e}")
        return EXIT_STAGING_BIND

    stop = threading.Event()

    def _drain(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)
    try:
        server.start()
        log_event(
            "input_server",
            f"staging server {args.server_id}: data "
            f"{server.host}:{server.data_port}, health "
            f"http://{server.host}:{server.health_port}/healthz",
        )
        while not stop.is_set():
            if server.abandoned_class() is not None:
                # the worker died a fatal class or exhausted its budget:
                # the supervisor speaks for the server it fronts
                return server.exit_code()
            time.sleep(0.2)
        return EXIT_OK
    finally:
        server.close_quietly()


if __name__ == "__main__":
    sys.exit(main())
