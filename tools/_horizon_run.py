"""Learning-dynamics-at-horizon run (VERDICT r1 #4 / r2 #3): config-1-shaped
MoCo-v1 pretrain on the real chip for 3200 steps with the per-epoch kNN
monitor. Redirect stdout to runs/horizon_tpu_r3.log; the committed log (a
converging, monotone-trending curve with the backend recorded) is the
evidence behind test_smoke_train's thresholds.

The r2 CPU log's 49-86% oscillation showed lr 0.06-0.12 churns at micro
scale; the default here is the cooler 0.03 (override: argv[1]). The dataset
is sized so 3200 steps are REAL (the r2 run configured 3200 but the loader
exhausted its 2048-sample set after 768 — fixed by train()'s clamp + the
explicit 16384-sample set here: 64 steps/epoch x 50 epochs).

Usage: python tools/_horizon_run.py [lr] > runs/horizon_tpu_r3.log
"""
import json, os, sys, time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
from moco_tpu.config import get_preset
from moco_tpu.data.datasets import SyntheticDataset
from moco_tpu.train import train

lr = float(sys.argv[1]) if len(sys.argv) > 1 else 0.03
cfg = get_preset("cifar10-moco-v1").replace(
    arch="resnet18", cifar_stem=True, dataset="synthetic", image_size=32,
    batch_size=256, num_negatives=4096, embed_dim=128, lr=lr, cos=True,
    epochs=50, steps_per_epoch=None,         # 16384/256 = 64 steps x 50 epochs
    knn_monitor=True, knn_bank_size=2048, num_classes=10,
    ckpt_dir="", tb_dir="", print_freq=64, num_workers=1,
    compute_dtype="bfloat16" if jax.default_backend() == "tpu" else "float32",
)
data = SyntheticDataset(num_samples=16384, image_size=32, num_classes=10)
print(json.dumps({"lr": lr, "backend": jax.default_backend(),
                  "config": "cifar10-moco-v1 horizon (resnet18 32px K=4096, "
                            "16384-sample synthetic, 3200 steps)"}),
      flush=True)
t0 = time.time()
state, metrics = train(cfg, dataset=data)
print(json.dumps({"final_knn_train_top1": metrics.get("knn_train_top1"),
                  "final_loss": metrics.get("loss"), "lr": lr,
                  "steps": int(state.step), "wall_s": round(time.time()-t0,1),
                  "backend": jax.default_backend()}))
