"""Learning-dynamics-at-horizon run (VERDICT r1 #4 / r2 #3 / r3 #3):
config-1-shaped MoCo-v1 pretrain for 3200 REAL steps with the per-epoch kNN
monitor — on a dataset an UNTRAINED network cannot solve.

r3's run used `SyntheticDataset`, whose classes random-init features
separate at ~86% — a curve an untrained network matches is not a
convergence demonstration. `SyntheticTextureDataset` splits the class
signal (augmentation-invariant texture) from the dominant pixel variance
(augmentation-destroyed color cast): random features score ~chance (1/16 =
6.25%), so any kNN gain IS learning. The driver prints the untrained
baseline as an `Epoch [-1]` row (train.py knn_monitor), and this tool FAILS
(exit 1) unless the final kNN beats that baseline by a wide margin and the
loss visibly departs from the K+1-way chance level log(K+1) = 8.32.

Usage: python tools/_horizon_run.py [lr] [batch] > runs/horizon_<backend>_r4.log

Batch picks the wall-clock budget, not the science: the honest properties
(resnet18@32, K=4096, 3200 REAL optimizer steps, chance-level untrained
baseline, val-split monitor, the two gates) hold at any batch. On the TPU
the config-1 batch 256 run is minutes; on the 1-core CPU sandbox a B=256
step costs 10-26 s (measured 2026-07-30), so 3200 steps would be >10 h —
B=64 (default off-TPU) fits the round while keeping 3200 real steps.
"""
import json, math, os, sys, time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if os.environ.get("MOCO_TPU_FORCE_CPU"):
    # the sandbox sitecustomize force-registers the axon TPU platform, whose
    # init can HANG for tens of minutes when the tunnel is down — switch
    # platforms in-process BEFORE the first backend touch (bench.py child
    # convention)
    from moco_tpu.parallel.mesh import force_cpu_devices

    force_cpu_devices(1)
import jax
from moco_tpu.config import get_preset
from moco_tpu.data.datasets import SyntheticTextureDataset
from moco_tpu.train import train

on_tpu = jax.default_backend() == "tpu"
lr = float(sys.argv[1]) if len(sys.argv) > 1 else 0.06
batch = int(sys.argv[2]) if len(sys.argv) > 2 else (256 if on_tpu else 32)
# 3200 real steps at any batch: dataset sized for 25 epochs x 128 steps
# (or 50 x 64 at B=256)
samples = batch * 128 if batch * 128 <= 16384 else 16384
epochs = 3200 // (samples // batch)
cfg = get_preset("cifar10-moco-v1").replace(
    arch="resnet18", cifar_stem=True, dataset="synthetic_texture",
    image_size=32, batch_size=batch, num_negatives=4096, embed_dim=128,
    lr=lr, cos=True, epochs=epochs, steps_per_epoch=None,
    knn_monitor=True, knn_bank_size=2048, num_classes=16,
    ckpt_dir="", tb_dir="", print_freq=128, num_workers=1,
    compute_dtype="bfloat16" if on_tpu else "float32",
)
data = SyntheticTextureDataset(num_samples=samples, image_size=32,
                               num_classes=16)
chance = 1.0 / data.num_classes
print(json.dumps({"lr": lr, "batch": batch, "backend": jax.default_backend(),
                  "config": f"horizon r4 (resnet18 32px K=4096, B={batch}, "
                            f"{samples}-sample synthetic_texture/16-class, "
                            f"{epochs * (samples // batch)} steps)",
                  "chance_knn": chance,
                  "chance_loss": round(math.log(cfg.num_negatives + 1), 3)}),
      flush=True)
t0 = time.time()
state, metrics = train(cfg, dataset=data)
# the monitor reports a REAL val split for synthetic_texture (held-out
# seed, same fixed class tiles) — fall back to train-hold-out tags only if
# that ever changes
baseline = metrics.get("knn_val_top1_untrained",
                       metrics.get("knn_train_top1_untrained", chance))
final_knn = metrics.get("knn_val_top1", metrics.get("knn_train_top1"))
final_loss = metrics.get("loss")
record = {"untrained_knn": baseline, "final_knn_top1": final_knn,
          "split": "val" if "knn_val_top1" in metrics else "train-holdout",
          "final_loss": final_loss, "lr": lr, "steps": int(state.step),
          "wall_s": round(time.time() - t0, 1),
          "backend": jax.default_backend()}
print(json.dumps(record, default=float), flush=True)
# the honesty gates (VERDICT r3 weak #3): an untrained network must FAIL
# this run, and the loss must have left the (K+1)-way chance plateau
assert final_knn is not None and final_knn > baseline + 0.15, (
    f"kNN gain over the untrained baseline is not convincing: "
    f"{final_knn} vs baseline {baseline}")
assert final_loss is not None and final_loss < math.log(cfg.num_negatives + 1) - 1.0, (
    f"loss {final_loss} has not departed the chance level "
    f"log(K+1)={math.log(cfg.num_negatives + 1):.2f}")
print("HORIZON GATES PASSED", flush=True)
