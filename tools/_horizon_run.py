"""Learning-dynamics-at-horizon run (VERDICT r1 #4 / r2 #3 / r3 #3 / r4 #1):
config-1-shaped MoCo-v1 pretrain with the per-epoch kNN monitor — on a
dataset an UNTRAINED network cannot solve — gated on the trained features
beating the random-init baseline by a wide margin.

r3's run used `SyntheticDataset`, whose classes random-init features
separate at ~86% — a curve an untrained network matches is not a
convergence demonstration. `SyntheticTextureDataset` splits the class
signal (augmentation-invariant texture) from the dominant pixel variance
(augmentation-destroyed color cast): random features score ~chance (1/16 =
6.25%), so any kNN gain IS learning. The driver prints the untrained
baseline as an `Epoch [-1]` row (train.py knn_monitor), and this tool FAILS
(exit 1) unless the final kNN beats that baseline by a wide margin and the
loss visibly departs from the K+1-way chance level log(K+1) = 8.32.

Usage:
    python tools/_horizon_run.py [--lr L] [--batch B] [--momentum M]
        [--steps N] [--knn-every E] > runs/horizon_<backend>_r5.log

Batch/steps pick the wall-clock budget, not the science: the honest
properties (resnet18@32, K=4096, REAL optimizer steps, chance-level
untrained baseline, val-split monitor, the two gates) hold at any scale.
On the TPU the config-1 batch-256 3200-step run is minutes; on the 1-core
CPU sandbox a step costs ~3-4 s (B=32/64, measured 2026-07-30), so the
step budget is chosen to fit the round window.

Operating point (r5): the r4 run (lr 0.06, m=0.999, B=32, 3200 steps)
failed its gate with loss RISING 6.2->7.4 over the run — the queue/key
encoder hardened faster than the query encoder learned. At 128-step
epochs, m=0.999 gives the EMA a ~1000-step time constant (8 epochs of
lag); m=0.99 (~100 steps) matches this scale, and lr follows the linear
rule ~0.03*B/256 x a small-batch-safe factor. Defaults below come from the
r5 micro-sweep (runs/horizon_sweep_r5.log).
"""
import argparse, json, math, os, sys, time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if os.environ.get("MOCO_TPU_FORCE_CPU"):
    # the sandbox sitecustomize force-registers the axon TPU platform, whose
    # init can HANG for tens of minutes when the tunnel is down — switch
    # platforms in-process BEFORE the first backend touch (bench.py child
    # convention)
    from moco_tpu.parallel.mesh import force_cpu_devices

    force_cpu_devices(1)
import jax
from moco_tpu.config import get_preset
from moco_tpu.data.datasets import SyntheticTextureDataset
from moco_tpu.train import train

on_tpu = jax.default_backend() == "tpu"
p = argparse.ArgumentParser()
p.add_argument("--lr", type=float, default=0.03)
p.add_argument("--batch", type=int, default=256 if on_tpu else 64)
p.add_argument("--momentum", type=float, default=0.99)
p.add_argument("--steps", type=int, default=3200)
p.add_argument("--knn-every", type=int, default=1 if on_tpu else 2)
p.add_argument("--samples", type=int, default=0,
               help="dataset size (0 = batch*128 capped at 16384)")
p.add_argument("--arch", default="resnet18",
               help="backbone (default = the certified resnet18 config; "
                    "--arch resnet50 runs the FLAGSHIP width under the "
                    "same gates — r5 supplementary evidence)")
p.add_argument("--image-size", type=int, default=32)
p.add_argument("--ckpt-dir", default="",
               help="Orbax checkpoint dir ('' = off): makes the long CPU "
                    "run preemption-proof — a killed run resumes with "
                    "--resume auto semantics via the train driver")
args = p.parse_args()
lr, batch = args.lr, args.batch
# at least one full batch per epoch: --samples below --batch would make
# steps_per_epoch 0 and die on integer division
samples = max(args.samples or min(batch * 128, 16384), batch)
steps_per_epoch = samples // batch
epochs = max(args.steps // steps_per_epoch, 1)
total_steps = epochs * steps_per_epoch

if args.ckpt_dir:
    # resume hygiene (review, r5): a resume MUST continue the same run —
    # same step budget (the cosine schedule decays over `epochs`; different
    # --steps would splice two schedules and gate a hybrid nobody ran),
    # same batch/samples/lr/m. Persist the knobs on the fresh start and
    # refuse a mismatched resume. Also fail FAST on a resume whose
    # untrained-baseline sidecar is gone/corrupt: without it the gate
    # cannot run, and discovering that AFTER the remaining epochs wastes
    # the whole run (exit 4 semantics, just hours earlier).
    run_args = {"steps": total_steps, "batch": batch, "samples": samples,
                "arch": args.arch, "image_size": args.image_size,
                "lr": lr, "momentum_ema": args.momentum,
                # numerics regime: a CPU-started f32 run must not silently
                # resume on TPU in bf16 (or vice versa) — that would gate a
                # spliced two-dtype run
                "backend": jax.default_backend(),
                "compute_dtype": "bfloat16" if on_tpu else "float32"}
    args_path = os.path.join(args.ckpt_dir, "horizon_args.json")
    baseline_path = os.path.join(args.ckpt_dir, "untrained_baseline.json")
    has_ckpt = os.path.isdir(args.ckpt_dir) and any(
        p_.isdigit() for p_ in os.listdir(args.ckpt_dir))
    if has_ckpt:
        try:
            with open(args_path) as f:
                prev = json.load(f)
        except (OSError, json.JSONDecodeError):
            print(f"resume refused: {args_path} missing/corrupt — cannot "
                  "prove the resumed flags match the original run", flush=True)
            sys.exit(4)
        # fingerprints written before the r5 --arch/--image-size flags
        # lack the two keys; their runs WERE resnet18@32, so defaulting
        # preserves resumability of in-flight checkpoints while keeping
        # the strict refusal for real mismatches (review, r5)
        prev.setdefault("arch", "resnet18")
        prev.setdefault("image_size", 32)
        if prev != run_args:
            print(f"resume refused: flags changed {prev} -> {run_args}",
                  flush=True)
            sys.exit(4)
        try:
            with open(baseline_path) as f:
                side = json.load(f)
            ok = (isinstance(side, dict) and len(side) >= 1 and all(
                k.startswith("knn_") and k.endswith("_untrained")
                and isinstance(v, float) for k, v in side.items()))
        except (OSError, json.JSONDecodeError):
            ok = False
        if not ok:
            print(f"resume refused: {baseline_path} missing/corrupt — the "
                  "gate would have nothing honest to compare against",
                  flush=True)
            sys.exit(4)
    else:
        os.makedirs(args.ckpt_dir, exist_ok=True)
        tmp = args_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(run_args, f)
        os.replace(tmp, args_path)
cfg = get_preset("cifar10-moco-v1").replace(
    arch=args.arch, cifar_stem=True, dataset="synthetic_texture",
    image_size=args.image_size, batch_size=batch, num_negatives=4096,
    embed_dim=128,
    lr=lr, momentum_ema=args.momentum, cos=True, epochs=epochs,
    steps_per_epoch=None,
    knn_monitor=True, knn_every_epochs=args.knn_every,
    knn_bank_size=2048, num_classes=16,
    ckpt_dir=args.ckpt_dir, ckpt_every_epochs=4,
    resume="auto" if args.ckpt_dir else "",
    tb_dir="", print_freq=steps_per_epoch, num_workers=1,
    compute_dtype="bfloat16" if on_tpu else "float32",
)
data = SyntheticTextureDataset(num_samples=samples,
                               image_size=args.image_size, num_classes=16)
chance = 1.0 / data.num_classes
print(json.dumps({"lr": lr, "batch": batch, "momentum_ema": args.momentum,
                  "backend": jax.default_backend(),
                  "config": f"horizon r5 ({args.arch} {args.image_size}px "
                            f"K=4096, B={batch}, "
                            f"m={args.momentum}, {samples}-sample "
                            f"synthetic_texture/16-class, {total_steps} steps)",
                  "chance_knn": chance,
                  "chance_loss": round(math.log(cfg.num_negatives + 1), 3)}),
      flush=True)
t0 = time.time()
state, metrics = train(cfg, dataset=data)
# the monitor reports a REAL val split for synthetic_texture (held-out
# seed, same fixed class tiles) — fall back to train-hold-out tags only if
# that ever changes
baseline = metrics.get("knn_val_top1_untrained",
                       metrics.get("knn_train_top1_untrained"))
final_knn = metrics.get("knn_val_top1", metrics.get("knn_train_top1"))
final_loss = metrics.get("loss")
if int(state.step) >= total_steps and final_loss is None:
    # resumed AFTER the final checkpoint: no step ran this invocation, so
    # there is nothing fresh to gate — the original run's log carries the
    # verdict. A distinct exit code, not a fake "gate failed"
    print(json.dumps({"already_complete": True, "steps": int(state.step),
                      "ckpt_dir": args.ckpt_dir}), flush=True)
    sys.exit(3)
if baseline is None:
    # a resumed run could not restore the measured untrained baseline
    # (missing sidecar): refusing is the honest outcome — falling back to
    # chance would silently LOWER the gate
    print("no untrained baseline available (resume without sidecar?) — "
          "cannot gate honestly", flush=True)
    sys.exit(4)
record = {"untrained_knn": baseline, "final_knn_top1": final_knn,
          "split": "val" if "knn_val_top1" in metrics else "train-holdout",
          "final_loss": final_loss, "lr": lr, "momentum_ema": args.momentum,
          "batch": batch, "steps": int(state.step),
          "wall_s": round(time.time() - t0, 1),
          "backend": jax.default_backend()}
print(json.dumps(record, default=float), flush=True)
# the honesty gates (VERDICT r3 weak #3): an untrained network must FAIL
# this run, and the loss must have left the (K+1)-way chance plateau
assert final_knn is not None and final_knn > baseline + 0.15, (
    f"kNN gain over the untrained baseline is not convincing: "
    f"{final_knn} vs baseline {baseline}")
assert final_loss is not None and final_loss < math.log(cfg.num_negatives + 1) - 1.0, (
    f"loss {final_loss} has not departed the chance level "
    f"log(K+1)={math.log(cfg.num_negatives + 1):.2f}")
print("HORIZON GATES PASSED", flush=True)
