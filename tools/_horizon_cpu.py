"""CPU-scale learning-dynamics run (config-1 shape at micro scale): evidence
for hardening test_smoke_train thresholds and for choosing the horizon-run
lr. The r2 log (runs/horizon_cpu_r2.log, lr 0.12 cos) oscillated 49-86%
after peaking — lr churn, not convergence (VERDICT r2 weak #3); this r3
variant runs the cooler lr the TPU horizon run uses. Writes stdout; redirect
to runs/horizon_cpu_r3.log.

Usage: python tools/_horizon_cpu.py [lr]
"""
import json, os, sys, time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from moco_tpu.parallel.mesh import force_cpu_devices
force_cpu_devices(8)
import jax
from moco_tpu.config import get_preset
from moco_tpu.train import train

lr = float(sys.argv[1]) if len(sys.argv) > 1 else 0.03
cfg = get_preset("cifar10-moco-v1").replace(
    arch="resnet_tiny", cifar_stem=True, dataset="synthetic", image_size=16,
    batch_size=64, num_negatives=512, embed_dim=32, lr=lr, cos=True,
    epochs=24, steps_per_epoch=None,  # 2048/64 = 32 steps x 24 epochs = 768
    knn_monitor=True, knn_bank_size=1024, num_classes=10,
    ckpt_dir="", tb_dir="", print_freq=9999, num_workers=1,
)
print(json.dumps({"lr": lr, "config": "cifar10-moco-v1 micro (resnet_tiny 16px K=512)"}))
t0 = time.time()
state, metrics = train(cfg)
print(json.dumps({"final_knn_train_top1": metrics.get("knn_train_top1"),
                  "final_loss": metrics.get("loss"), "lr": lr,
                  "steps": int(state.step), "wall_s": round(time.time()-t0,1)}))
