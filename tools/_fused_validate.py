"""On-chip validation + A/B timing for the r3 perf levers (run when the TPU
tunnel is up; the backend hung/UNAVAILABLE for the whole r3 build window).

1) bn_relu_matmul numerics on TPU vs the plain jnp math (bf16 tolerance)
2) Bottleneck fused-tail fwd+bwd vs unfused on TPU
3) fused MoCo-v2 R50 step timing A/B: {fused_bn_conv on/off} x {remat on/off}

Usage: python tools/_fused_validate.py [batch]
"""
import os as _os, sys as _sys, time

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np

print("backend:", jax.default_backend(), flush=True)

# --- 1) kernel numerics ---
from moco_tpu.ops.pallas_fused_conv import bn_relu_matmul

m, k, n = 2048, 64, 256
x = jax.random.normal(jax.random.key(0), (m, k)).astype(jnp.bfloat16)
a = 1.0 + 0.1 * jax.random.normal(jax.random.key(1), (k,))
b = 0.1 * jax.random.normal(jax.random.key(2), (k,))
w = (0.05 * jax.random.normal(jax.random.key(3), (k, n))).astype(jnp.bfloat16)
got = np.asarray(bn_relu_matmul(x, a, b, w, out_dtype=jnp.bfloat16), np.float32)
z = np.maximum(np.asarray(x, np.float32) * np.asarray(a) + np.asarray(b), 0)
want = z.astype(np.float32) @ np.asarray(w, np.float32)
err = np.abs(got - want) / (np.abs(want) + 1.0)
print(f"kernel rel err: mean {err.mean():.2e} max {err.max():.2e}")
assert err.max() < 0.05, "fused kernel numerics off on TPU"

# --- 1b) dW backward kernel numerics ---
from moco_tpu.ops.pallas_fused_conv import bn_relu_matmul_dw

dy = jax.random.normal(jax.random.key(4), (m, n)).astype(jnp.bfloat16)
dw_got = np.asarray(bn_relu_matmul_dw(x, a, b, dy), np.float32)
# apples-to-apples reference (first-chip finding, r5): the kernel — like
# the UNFUSED bf16 path — quantizes ẑ to bf16 before the MXU contraction
# (f32 accumulate). Comparing against an f32-ẑ product instead conflates
# that inherent input quantization with kernel error, and over an M=2048
# contraction the accumulated bf16 rounding alone reaches ~0.14 on
# near-zero entries (measured on the v5e, runs/fused_validate_tpu.log).
# So: gate hard against the bf16-ẑ f32-accumulate product; report the
# f32-ẑ delta for context only.
zb = np.asarray(jnp.asarray(z).astype(jnp.bfloat16), np.float32)
dw_want = zb.T @ np.asarray(dy, np.float32)
dw_f32 = z.astype(np.float32).T @ np.asarray(dy, np.float32)
dw_err = np.abs(dw_got - dw_want) / (np.abs(dw_want) + 1.0)
dw_info = np.abs(dw_got - dw_f32) / (np.abs(dw_f32) + 1.0)
print(f"dW kernel rel err vs bf16-z ref: mean {dw_err.mean():.2e} "
      f"max {dw_err.max():.2e} (vs f32-z ref, info only: "
      f"mean {dw_info.mean():.2e} max {dw_info.max():.2e})")
assert dw_err.max() < 0.05, "dW kernel numerics off on TPU"

# --- 1c) 3x3 kernels: forward + dW backward numerics ---
from moco_tpu.ops.pallas_fused_conv3x3 import bn_relu_conv3x3, conv3x3_dw

bsz3, h3, w3, k3, n3 = 8, 28, 28, 128, 128
x3 = jax.random.normal(jax.random.key(20), (bsz3, h3, w3, k3)).astype(jnp.bfloat16)
a3 = 1.0 + 0.1 * jax.random.normal(jax.random.key(21), (k3,))
b3 = 0.1 * jax.random.normal(jax.random.key(22), (k3,))
w3x3 = (0.05 * jax.random.normal(jax.random.key(23), (3, 3, k3, n3))).astype(jnp.bfloat16)
dy3 = jax.random.normal(jax.random.key(24), (bsz3, h3, w3, n3)).astype(jnp.bfloat16)


def _ref3(x_, w_):
    z_ = jnp.maximum(x_.astype(jnp.float32) * a3 + b3, 0.0)
    return jax.lax.conv_general_dilated(
        z_, w_.astype(jnp.float32), (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


got3 = np.asarray(bn_relu_conv3x3(x3, a3, b3, w3x3, out_dtype=jnp.bfloat16), np.float32)
want3 = np.asarray(_ref3(x3, w3x3), np.float32)
err3 = np.abs(got3 - want3) / (np.abs(want3) + 1.0)
print(f"conv3x3 kernel rel err: mean {err3.mean():.2e} max {err3.max():.2e}")
assert err3.max() < 0.05, "fused 3x3 kernel numerics off on TPU"

# bf16-ẑ reference, same reasoning as 1b: the kernel quantizes the
# recomputed ẑ to dy's dtype before each tap contraction
def _ref3q(x_, w_):
    z_ = jnp.maximum(x_.astype(jnp.float32) * a3 + b3, 0.0)
    z_ = z_.astype(jnp.bfloat16).astype(jnp.float32)
    return jax.lax.conv_general_dilated(
        z_, w_.astype(jnp.float32), (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


_, _vjp3 = jax.vjp(lambda w_: _ref3q(x3, w_), w3x3.astype(jnp.float32))
(dw3_want,) = _vjp3(jnp.asarray(dy3, jnp.float32))
dw3_got = np.asarray(conv3x3_dw(x3, a3, b3, dy3), np.float32)
dw3_err = np.abs(dw3_got - np.asarray(dw3_want)) / (np.abs(np.asarray(dw3_want)) + 1.0)
print(f"conv3x3 dW kernel rel err: mean {dw3_err.mean():.2e} max {dw3_err.max():.2e}")
assert dw3_err.max() < 0.05, "3x3 dW kernel numerics off on TPU"

# --- 1d) stride-2 forward kernel numerics ---
from moco_tpu.ops.pallas_fused_conv3x3 import bn_relu_conv3x3_s2

gots2 = np.asarray(
    bn_relu_conv3x3_s2(x3, a3, b3, w3x3, out_dtype=jnp.bfloat16), np.float32)
wants2 = np.asarray(jax.lax.conv_general_dilated(
    jnp.maximum(x3.astype(jnp.float32) * a3 + b3, 0.0),
    w3x3.astype(jnp.float32), (2, 2), ((1, 1), (1, 1)),
    dimension_numbers=("NHWC", "HWIO", "NHWC")), np.float32)
errs2 = np.abs(gots2 - wants2) / (np.abs(wants2) + 1.0)
print(f"conv3x3 s2 kernel rel err: mean {errs2.mean():.2e} max {errs2.max():.2e}")
assert errs2.max() < 0.05, "stride-2 fused kernel numerics off on TPU"

# --- 2) block equivalence on TPU ---
from functools import partial
import flax.linen as nn
from moco_tpu.models.resnet import Bottleneck

conv = partial(nn.Conv, use_bias=False, dtype=jnp.bfloat16, param_dtype=jnp.float32)
norm = partial(nn.BatchNorm, use_running_average=False, momentum=0.9,
               epsilon=1e-5, dtype=jnp.bfloat16, param_dtype=jnp.float32)
kw = dict(filters=64, strides=1, conv=conv, norm=norm)
plain = Bottleneck(**kw)
fused = Bottleneck(fused_tail=True, bn_momentum=0.9, dtype=jnp.bfloat16, **kw)
xb = jax.random.normal(jax.random.key(4), (8, 28, 28, 256), jnp.float32)
v = plain.init(jax.random.key(5), xb)


def loss(params, model):
    out, _ = model.apply({"params": params, "batch_stats": v["batch_stats"]},
                         xb, mutable=["batch_stats"])
    return jnp.sum((out.astype(jnp.float32)) ** 2)


la, ga = jax.jit(jax.value_and_grad(lambda p: loss(p, plain)))(v["params"])
lb, gb = jax.jit(jax.value_and_grad(lambda p: loss(p, fused)))(v["params"])
print(f"block loss plain {float(la):.4f} fused {float(lb):.4f}")
for pa, pb in zip(jax.tree.leaves(ga), jax.tree.leaves(gb), strict=True):
    d = np.abs(np.asarray(pa, np.float32) - np.asarray(pb, np.float32))
    s = np.abs(np.asarray(pa, np.float32)).max() + 1e-6
    assert d.max() / s < 0.05, f"grad mismatch {d.max() / s}"
print("block fwd/bwd equivalence OK (bf16 tolerance)")

# --- 3) step timing A/B ---
from moco_tpu.config import get_preset
from moco_tpu.data.augment import build_two_crops_sharded, v2_aug_config, with_dtype
from moco_tpu.data.datasets import full_extents
from moco_tpu.parallel.mesh import create_mesh
from moco_tpu.train_state import create_train_state
from moco_tpu.train_step import (
    build_encoder, build_fused_step, build_optimizer, build_train_step,
)

B = int(_sys.argv[1]) if len(_sys.argv) > 1 else 128
mesh = create_mesh(1)
rng = np.random.RandomState(0)
stage = 252
imgs = jnp.asarray(rng.randint(0, 256, (B, stage, stage, 3), dtype=np.uint8))
ext = full_extents(B, stage, stage)


def time_step(fused_flag, remat_flag):
    cfg = get_preset("imagenet-moco-v2").replace(
        batch_size=B, fused_bn_conv=fused_flag, remat=remat_flag
    )
    model = build_encoder(cfg)
    tx, sched = build_optimizer(cfg, 1000)
    state = create_train_state(jax.random.key(0), model, tx, (B, 224, 224, 3),
                               cfg.num_negatives, cfg.embed_dim)
    step = build_train_step(cfg, model, tx, mesh, 1000, sched)
    two = build_two_crops_sharded(with_dtype(v2_aug_config(224), "bfloat16"), mesh)
    fstep = build_fused_step(step, two, jax.random.key(1))
    for i in range(8):
        state, mtr = fstep(state, imgs, ext, i)
    float(mtr["loss"])  # sync (block_until_ready unreliable on the relay)
    best = 1e9
    for r in range(2):
        t0 = time.perf_counter()
        for i in range(20):
            state, mtr = fstep(state, imgs, ext, 100 * r + i)
        float(mtr["loss"])
        best = min(best, (time.perf_counter() - t0) / 20)
    return best


for fused_flag, remat_flag in [(False, False), (True, False), (True, True), (False, True)]:
    dt = time_step(fused_flag, remat_flag)
    print(
        f"fused={fused_flag} remat={remat_flag}: {dt * 1e3:.2f} ms/step "
        f"-> {B / dt:.1f} imgs/s/chip",
        flush=True,
    )
