"""TPU validation + timing after s2d stem and FastBatchNorm.
1) fast_bn/pallas-stats numerics on TPU vs jnp
2) s2d stem on TPU matches plain conv
3) fused-step timing at B=128 and B=256
4) train a few steps: record the first losses (finite, reference-magnitude)
   alongside the timing sweep
"""
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import time, sys
import jax, jax.numpy as jnp, numpy as np

print("backend:", jax.default_backend())

# --- 1) pallas stats vs jnp on TPU ---
from moco_tpu.ops.pallas_stats import channel_sums, channel_grad_sums
x = jax.random.normal(jax.random.key(0), (128*56*56, 64)).astype(jnp.bfloat16)
s, sq = channel_sums(x)
xf = np.asarray(x, np.float32)
np.testing.assert_allclose(np.asarray(s), xf.sum(0), rtol=2e-3, atol=2.0)
np.testing.assert_allclose(np.asarray(sq), (xf*xf).sum(0), rtol=2e-3, atol=2.0)
print("channel_sums OK")

def timeit(fn, args, n=30, warm=8):
    for _ in range(warm): out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    t0=time.perf_counter()
    for _ in range(n): out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    return (time.perf_counter()-t0)/n*1e3

nbytes = x.size*2
t = timeit(jax.jit(channel_sums), (x,))
print(f"pallas channel_sums [{x.shape}]: {t:.2f} ms = {nbytes/t/1e6:.0f} GB/s")
@jax.jit
def xla_sums(x):
    xf = x.astype(jnp.float32)
    return jnp.sum(xf, axis=0), jnp.sum(xf*xf, axis=0)
t2 = timeit(xla_sums, (x,))
print(f"xla    sums        [{x.shape}]: {t2:.2f} ms = {nbytes/t2/1e6:.0f} GB/s")

# --- 3) fused step timing ---
from moco_tpu.config import get_preset
from moco_tpu.data.augment import build_two_crops_sharded, v2_aug_config, with_dtype
from moco_tpu.data.datasets import full_extents
from moco_tpu.parallel.mesh import create_mesh
from moco_tpu.train_state import create_train_state
from moco_tpu.train_step import build_encoder, build_optimizer, build_train_step, build_fused_step

for B in (128, 256):
    mesh = create_mesh(1)
    config = get_preset("imagenet-moco-v2").replace(batch_size=B, dataset="synthetic")
    model = build_encoder(config)
    tx, sched = build_optimizer(config, 1000)
    state = create_train_state(jax.random.key(0), model, tx, (B,224,224,3), 65536, 128)
    step_fn = build_train_step(config, model, tx, mesh, 1000, sched)
    aug = with_dtype(v2_aug_config(224), "bfloat16")
    fused = build_fused_step(step_fn, build_two_crops_sharded(aug, mesh), jax.random.key(1))
    rng = np.random.RandomState(0)
    imgs = jnp.asarray(rng.randint(0,256,(B,252,252,3),dtype=np.uint8))
    ext = full_extents(B,252,252)
    st = state
    losses = []
    for i in range(10):
        st, m = fused(st, imgs, ext, i)
        if i < 3: losses.append(float(m["loss"]))
    float(m["loss"])
    best=1e9
    for r in range(2):
        t0=time.perf_counter()
        for i in range(20):
            st, m = fused(st, imgs, ext, 100*r+i)
        float(m["loss"])
        best=min(best,(time.perf_counter()-t0)/20)
    print(f"B={B}: {best*1e3:.2f} ms/step -> {B/best:.1f} imgs/s  first losses {losses}")
