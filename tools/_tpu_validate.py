"""TPU validation + timing after s2d stem and FastBatchNorm.
1) fast_bn/pallas-stats numerics on TPU vs jnp
2) s2d stem on TPU matches plain conv
3) fused-step timing at B=128 and B=256
4) train a few steps: record the first losses (finite, reference-magnitude)
   alongside the timing sweep
"""
import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import time, sys
import jax, jax.numpy as jnp, numpy as np

print("backend:", jax.default_backend())

# --- 1) pallas stats vs jnp on TPU ---
from moco_tpu.ops.pallas_stats import channel_sums, channel_grad_sums
x = jax.random.normal(jax.random.key(0), (128*56*56, 64)).astype(jnp.bfloat16)
s, sq = channel_sums(x)
xf = np.asarray(x, np.float32)
np.testing.assert_allclose(np.asarray(s), xf.sum(0), rtol=2e-3, atol=2.0)
np.testing.assert_allclose(np.asarray(sq), (xf*xf).sum(0), rtol=2e-3, atol=2.0)
print("channel_sums OK")

def timeit(fn, args, n=30, warm=8):
    for _ in range(warm): out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    t0=time.perf_counter()
    for _ in range(n): out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    return (time.perf_counter()-t0)/n*1e3

nbytes = x.size*2
t = timeit(jax.jit(channel_sums), (x,))
print(f"pallas channel_sums [{x.shape}]: {t:.2f} ms = {nbytes/t/1e6:.0f} GB/s")
@jax.jit
def xla_sums(x):
    xf = x.astype(jnp.float32)
    return jnp.sum(xf, axis=0), jnp.sum(xf*xf, axis=0)
t2 = timeit(xla_sums, (x,))
print(f"xla    sums        [{x.shape}]: {t2:.2f} ms = {nbytes/t2/1e6:.0f} GB/s")

# --- 3) fused step timing (assembly + timing shared via benchkit with
#        bench.py's step child and tools/_perf_ab.py — review, r5) ---
from moco_tpu.config import get_preset
from moco_tpu.parallel.mesh import create_mesh
from moco_tpu.utils.benchkit import build_v2_fused_bench, time_fused_step

for B in (128, 256):
    mesh = create_mesh(1)
    config = get_preset("imagenet-moco-v2").replace(batch_size=B, dataset="synthetic")
    fused, st, imgs, ext = build_v2_fused_bench(config, mesh)
    losses = []
    for i in range(3):
        st, m = fused(st, imgs, ext, i)
        losses.append(float(m["loss"]))
    best, _warm, _loss, st = time_fused_step(
        fused, st, imgs, ext, warmup=7, steps=20, rounds=2)
    print(f"B={B}: {best*1e3:.2f} ms/step -> {B/best:.1f} imgs/s  first losses {losses}")
