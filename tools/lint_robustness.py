#!/usr/bin/env python
"""Forbid silent exception swallowing in moco_tpu/ (ISSUE 1 tooling).

The fault-tolerance subsystem only works if faults are VISIBLE: a bare
`except:` (which eats KeyboardInterrupt/SystemExit and hides the
preemption path) or an `except Exception: pass` (which discards the very
errors the retry/rollback machinery routes on) would quietly defeat it.

Rules, AST-enforced over every .py file under the package:

  R1  no bare `except:` handlers;
  R2  no handler over `Exception`/`BaseException` whose body is only
      `pass`/`...` — swallowing EVERYTHING silently is never a policy.
      Narrow named exceptions (`except (AttributeError, ValueError): pass`)
      stay legal: deliberately ignoring a specific, expected failure is a
      policy the type spells out.
  R3  (ISSUE 2) no bare `print(...)` outside utils/logging.py and
      utils/meters.py — an event printed anywhere else bypasses the
      structured channel (`log_event` → telemetry events.jsonl) and the
      one sanctioned plain-line path (`logging.info`), so an external
      monitor can never consume it.

Exit 0 when clean; exit 1 with one `path:line: message` per violation.
Runs in tier-1 via tests/test_lint_robustness.py.
"""

from __future__ import annotations

import ast
import os
import sys

BROAD = {"Exception", "BaseException"}

# the only files allowed to call print(): the structured/sanctioned
# channels themselves (log_event/info) and the console meters
PRINT_ALLOWED = ("utils/logging.py", "utils/meters.py")


def _names(node: ast.expr | None):
    """Exception class names a handler catches (dotted tails included)."""
    if node is None:
        return []
    if isinstance(node, ast.Tuple):
        return [n for elt in node.elts for n in _names(elt)]
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, ast.Attribute):
        return [node.attr]
    return []


def _silent(body: list[ast.stmt]) -> bool:
    return all(
        isinstance(stmt, ast.Pass)
        or (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)
        for stmt in body
    )


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: unparseable ({e.msg})"]
    out = []
    print_allowed = os.path.normpath(path).replace(os.sep, "/").endswith(
        PRINT_ALLOWED
    )
    for node in ast.walk(tree):
        if (
            not print_allowed
            and isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            out.append(
                f"{path}:{node.lineno}: bare `print(...)` — route through "
                "utils.logging (log_event for events, info for plain lines) "
                "so the structured telemetry sinks see it"
            )
            continue
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append(
                f"{path}:{node.lineno}: bare `except:` — name the exception "
                "types (a bare handler hides SIGINT and the preemption path)"
            )
        elif _silent(node.body) and BROAD & set(_names(node.type)):
            out.append(
                f"{path}:{node.lineno}: `except "
                f"{'/'.join(sorted(BROAD & set(_names(node.type))))}` with a "
                "pass-only body silently swallows every error — narrow the "
                "type or handle/log it"
            )
    return out


def check_tree(root: str) -> list[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                out.extend(check_file(os.path.join(dirpath, fname)))
    return out


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "moco_tpu"
    )
    violations = check_tree(root)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} robustness violation(s) in {root}")
        return 1
    print(f"robustness lint clean: {root}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
