#!/usr/bin/env python
"""Legacy CLI/API shim over the mocolint engine (ISSUE 7).

The seven robustness rules (R1–R7) that used to live here as one
monolithic walker are now plugin rules in `tools/mocolint/rules/` —
see that package for the engine (single parse per file, shared visitor
dispatch, inline suppression, baselines, `--json`) and the four newer
rules R8–R11. This file keeps the original surface alive unchanged:

  - `check_file(path)` / `check_tree(root)` return the historical
    `"path:line: message"` strings (no rule ids), sorted by
    path/line/rule, running exactly rules R1–R7 with their historical
    scoping (`LEGACY_CONFIG`);
  - the CLI exits 0 when clean, 1 with one line per violation plus a
    count — the contract tests/test_lint_robustness.py pins.

Rule summary (full rationale lives on each rule class):

  R1  no bare `except:`;
  R2  no pass-only handler over Exception/BaseException;
  R3  no bare print() outside utils/logging.py, utils/meters.py;
  R4  Prefetcher/epoch_loader constructions close in a finally
      (direct `return Prefetcher(...)` is the factory pattern, exempt);
  R5  no numeric-literal process exits (named exitcodes.py constants);
  R6  nothing under moco_tpu/serve/ imports the train stack;
  R7  gradient pmean/psum only under moco_tpu/parallel/.

New work should call the engine directly: `python -m tools.mocolint`.
"""

from __future__ import annotations

import os
import sys

# The shim is invoked by file path (subprocess tests, importlib loads),
# so the repo root may not be importable yet.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.mocolint.config import LEGACY_CONFIG  # noqa: E402
from tools.mocolint.engine import Engine  # noqa: E402


def check_file(path: str) -> list[str]:
    result = Engine(LEGACY_CONFIG).run([path])
    return [f.legacy() for f in result.findings]


def check_tree(root: str) -> list[str]:
    result = Engine(LEGACY_CONFIG).run([root])
    return [f.legacy() for f in result.findings]


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(_REPO, "moco_tpu")
    violations = check_tree(root)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} robustness violation(s) in {root}")
        return 1
    print(f"robustness lint clean: {root}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
