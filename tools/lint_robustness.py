#!/usr/bin/env python
"""Forbid silent exception swallowing in moco_tpu/ (ISSUE 1 tooling).

The fault-tolerance subsystem only works if faults are VISIBLE: a bare
`except:` (which eats KeyboardInterrupt/SystemExit and hides the
preemption path) or an `except Exception: pass` (which discards the very
errors the retry/rollback machinery routes on) would quietly defeat it.

Rules, AST-enforced over every .py file under the package:

  R1  no bare `except:` handlers;
  R2  no handler over `Exception`/`BaseException` whose body is only
      `pass`/`...` — swallowing EVERYTHING silently is never a policy.
      Narrow named exceptions (`except (AttributeError, ValueError): pass`)
      stay legal: deliberately ignoring a specific, expected failure is a
      policy the type spells out.
  R3  (ISSUE 2) no bare `print(...)` outside utils/logging.py and
      utils/meters.py — an event printed anywhere else bypasses the
      structured channel (`log_event` → telemetry events.jsonl) and the
      one sanctioned plain-line path (`logging.info`), so an external
      monitor can never consume it.
  R4  (ISSUE 3) every `Prefetcher(...)` / `epoch_loader(...)` construction
      bound to a name must have a `finally` in the same function calling
      `<name>.close()` or `<name>.close_quietly()` — the staging threads
      and `depth` device batches leak otherwise (the class of leak ISSUE 1
      fixed by hand at every call site, now enforced). A construction
      returned directly (`return Prefetcher(...)`) is the factory pattern
      and exempt: the caller owns the close.
  R5  (ISSUE 4) no numeric-literal process exits — `sys.exit(43)`,
      `exit(1)`, `os._exit(2)`, `raise SystemExit(3)` — anywhere in the
      package. Driver exits are the supervisor's classification protocol:
      they must go through the NAMED constants in
      resilience/exitcodes.py, so the exit-code table has exactly one
      source of truth and a renumbering can never silently fork the
      supervisor from the drivers. (`sys.exit()` bare and
      `sys.exit(EXIT_PREEMPTED)` are fine.)
  R7  (ISSUE 6) gradient collectives — `pmean`/`psum` whose operand names
      mention gradients — may only appear under `moco_tpu/parallel/`. The
      step builders (train_step/v3_step) must route gradients through the
      gradsync API: an inline `lax.pmean(grads, ...)` silently reverts the
      step to the fused end-of-step reduce, bypassing the configured
      bucketing/quantization/sparsification AND the comm telemetry that
      measures it. Collectives on non-gradient values (BN stats, metrics)
      stay legal anywhere.
  R6  (ISSUE 5) nothing under `moco_tpu/serve/` may import train,
      train_step, v3_step, train_state, optimizer modules (optax,
      ops/schedules) — the serving runtime must stay import-light and
      train-free: an accidental train dependency drags the optimizer
      stack (and its compile/memory footprint) into every serving
      process, and a server that CAN touch training state eventually
      will. Applies to every import in the file, module-level or lazy.

Exit 0 when clean; exit 1 with one `path:line: message` per violation.
Runs in tier-1 via tests/test_lint_robustness.py (which also holds
bench.py to R4 even though it lives outside the package tree).
"""

from __future__ import annotations

import ast
import os
import sys

BROAD = {"Exception", "BaseException"}

# the only files allowed to call print(): the structured/sanctioned
# channels themselves (log_event/info) and the console meters
PRINT_ALLOWED = ("utils/logging.py", "utils/meters.py")

# R4: constructors whose result owns background staging threads
LOADER_FACTORIES = {"Prefetcher", "epoch_loader"}

# R6: modules the serving runtime must never import (directly or lazily).
# Exact module or any submodule; `from moco_tpu import train` counts too.
R6_FORBIDDEN = (
    "moco_tpu.train",
    "moco_tpu.train_step",
    "moco_tpu.train_state",
    "moco_tpu.v3_step",
    "optax",
    "moco_tpu.ops.schedules",
)
R6_FORBIDDEN_TAILS = {m.rsplit(".", 1)[-1] for m in R6_FORBIDDEN}


def _r6_module_forbidden(module: str | None) -> bool:
    if not module:
        return False
    return any(module == f or module.startswith(f + ".") for f in R6_FORBIDDEN)


def _r6_violations(tree: ast.AST, path: str) -> list[str]:
    out = []

    def flag(node, module):
        out.append(
            f"{path}:{node.lineno}: serve/ imports {module!r} — the serving "
            "runtime must stay train-free (lint R6): no train, train_step, "
            "v3_step, train_state, or optimizer modules"
        )

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if _r6_module_forbidden(alias.name):
                    flag(node, alias.name)
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative import inside serve/: always fine
                continue
            if _r6_module_forbidden(node.module):
                flag(node, node.module)
            elif node.module in ("moco_tpu", "moco_tpu.ops"):
                for alias in node.names:
                    full = f"{node.module}.{alias.name}"
                    if (alias.name in R6_FORBIDDEN_TAILS
                            and _r6_module_forbidden(full)):
                        flag(node, full)
    return out

def _r7_violation(node: ast.Call) -> bool:
    """True for `pmean(...)`/`psum(...)` (bare or attribute call, e.g.
    `lax.pmean`) whose FIRST argument is a name or attribute mentioning
    gradients (`grads`, `grad_tree`, `g_grads`, ...). Deliberately
    name-based: the lint guards the obvious regression (pasting the old
    `_pmean_grads` body back into a step builder), not adversarial
    renaming."""
    name = _call_name(node.func)
    if name not in ("pmean", "psum") or not node.args:
        return False
    first = node.args[0]
    if isinstance(first, ast.Name):
        return "grad" in first.id.lower()
    if isinstance(first, ast.Attribute):
        return "grad" in first.attr.lower()
    return False


def _is_exit_call(func: ast.expr) -> bool:
    """Exactly the process-exit spellings: `sys.exit`, `os._exit`, the
    bare builtins `exit`/`SystemExit`. NOT any method that happens to be
    named exit (`parser.exit(2)` is argparse's API, not the protocol)."""
    if isinstance(func, ast.Name):
        return func.id in ("exit", "SystemExit")
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return (func.value.id == "sys" and func.attr == "exit") or \
            (func.value.id == "os" and func.attr == "_exit")
    return False


def _r5_violation(node: ast.Call) -> bool:
    """True for a process-exit call whose first argument is a bare int
    literal (bool is an int subclass but `sys.exit(True)` is a different
    bug — still flagged, deliberately)."""
    if not _is_exit_call(node.func) or not node.args:
        return False
    first = node.args[0]
    return isinstance(first, ast.Constant) and isinstance(first.value, int)


def _call_name(node: ast.expr) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _r4_scope_violations(scope: ast.AST, path: str) -> list[str]:
    """R4 within one function (or module) body, NOT descending into nested
    function definitions (each is its own scope with its own finallys)."""

    def walk_shallow(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            yield child
            yield from walk_shallow(child)

    constructions: list[tuple[str | None, int]] = []
    closed_in_finally: set[str] = set()
    for node in walk_shallow(scope):
        if isinstance(node, ast.Call) and _call_name(node.func) in LOADER_FACTORIES:
            parent = getattr(node, "_r4_parent", None)
            if isinstance(parent, ast.Return):
                continue  # factory pattern: the caller owns the close
            if (isinstance(parent, ast.Assign)
                    and len(parent.targets) == 1
                    and isinstance(parent.targets[0], ast.Name)):
                constructions.append((parent.targets[0].id, node.lineno))
            else:
                constructions.append((None, node.lineno))
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                for call in ast.walk(stmt):
                    if (isinstance(call, ast.Call)
                            and isinstance(call.func, ast.Attribute)
                            and call.func.attr in ("close", "close_quietly")
                            and isinstance(call.func.value, ast.Name)):
                        closed_in_finally.add(call.func.value.id)
    out = []
    for var, lineno in constructions:
        if var is None:
            out.append(
                f"{path}:{lineno}: Prefetcher/epoch_loader constructed "
                "without binding a name — the staging threads can never be "
                "close()d; bind it and close in a finally"
            )
        elif var not in closed_in_finally:
            out.append(
                f"{path}:{lineno}: `{var} = ...` builds a Prefetcher but no "
                f"`finally` in this function calls `{var}.close()`/"
                f"`{var}.close_quietly()` — an early break leaks the "
                "staging threads and the staged batches"
            )
    return out


def _r4_check(tree: ast.AST, path: str) -> list[str]:
    # annotate each Call with its immediate parent so the Return/Assign
    # context is known at the Call
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Call):
                child._r4_parent = node
    out = []
    scopes = [tree] + [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for scope in scopes:
        out.extend(_r4_scope_violations(scope, path))
    return out


def _names(node: ast.expr | None):
    """Exception class names a handler catches (dotted tails included)."""
    if node is None:
        return []
    if isinstance(node, ast.Tuple):
        return [n for elt in node.elts for n in _names(elt)]
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, ast.Attribute):
        return [node.attr]
    return []


def _silent(body: list[ast.stmt]) -> bool:
    return all(
        isinstance(stmt, ast.Pass)
        or (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)
        for stmt in body
    )


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: unparseable ({e.msg})"]
    out = []
    print_allowed = os.path.normpath(path).replace(os.sep, "/").endswith(
        PRINT_ALLOWED
    )
    # R4 everywhere except the defining module itself (its factory returns
    # and self-methods are the ownership boundary the rule protects)
    if not os.path.normpath(path).replace(os.sep, "/").endswith(
        "data/loader.py"
    ):
        out.extend(_r4_check(tree, path))
    if "moco_tpu/serve/" in os.path.normpath(path).replace(os.sep, "/"):
        out.extend(_r6_violations(tree, path))
    # R7: gradient collectives live in parallel/ only (the gradsync API)
    grad_collectives_allowed = (
        "moco_tpu/parallel/" in os.path.normpath(path).replace(os.sep, "/")
    )
    for node in ast.walk(tree):
        if (not grad_collectives_allowed
                and isinstance(node, ast.Call) and _r7_violation(node)):
            out.append(
                f"{path}:{node.lineno}: gradient collective outside "
                "moco_tpu/parallel/ — route grads through the gradsync API "
                "(parallel/gradsync.GradSync); an inline pmean/psum on grads "
                "bypasses the configured sync mode and its telemetry"
            )
            continue
        if isinstance(node, ast.Call) and _r5_violation(node):
            out.append(
                f"{path}:{node.lineno}: numeric-literal process exit — use "
                "the named constants in resilience/exitcodes.py (the "
                "supervisor classifies deaths by these codes; a magic "
                "number here silently forks the protocol)"
            )
            continue
        if (
            not print_allowed
            and isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            out.append(
                f"{path}:{node.lineno}: bare `print(...)` — route through "
                "utils.logging (log_event for events, info for plain lines) "
                "so the structured telemetry sinks see it"
            )
            continue
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append(
                f"{path}:{node.lineno}: bare `except:` — name the exception "
                "types (a bare handler hides SIGINT and the preemption path)"
            )
        elif _silent(node.body) and BROAD & set(_names(node.type)):
            out.append(
                f"{path}:{node.lineno}: `except "
                f"{'/'.join(sorted(BROAD & set(_names(node.type))))}` with a "
                "pass-only body silently swallows every error — narrow the "
                "type or handle/log it"
            )
    return out


def check_tree(root: str) -> list[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                out.extend(check_file(os.path.join(dirpath, fname)))
    return out


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "moco_tpu"
    )
    violations = check_tree(root)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} robustness violation(s) in {root}")
        return 1
    print(f"robustness lint clean: {root}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
