#!/usr/bin/env python
"""Closed-loop load generator for the embedding service (ISSUE 5).

    python tools/serve_bench.py --url http://127.0.0.1:8080 \
        --concurrency 32 --requests 512 --image-size 224

`--concurrency` workers each send their share of `--requests` back to
back (closed loop — a new request only after the previous one resolved),
so the offered load is exactly the in-flight concurrency the
micro-batcher coalesces. EVERY request must end in a result or a
STRUCTURED rejection (overloaded / deadline_exceeded / draining JSON
body); anything else — connection error, unstructured 5xx — counts as
LOST and fails the run. Prints one BENCH-style JSON record: latency
p50/p95/p99, throughput at the fixed concurrency, shed counts, and the
server's own /stats fold (mean batch occupancy, compile-bucket ladder).

Pure stdlib + numpy: runs anywhere the server is reachable, no jax.
"""

from __future__ import annotations

import argparse
import base64
import http.client
import json
import sys
import threading
import time
import urllib.parse
import urllib.request

import numpy as np

STRUCTURED_REJECTIONS = ("overloaded", "deadline_exceeded", "draining")


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = max(0, min(len(ordered) - 1, round(q / 100.0 * (len(ordered) - 1))))
    return ordered[int(rank)]


class _Client:
    """One persistent keep-alive connection (http.client): a closed-loop
    worker that reconnects per request measures TCP setup, not serving —
    and its turnaround jitter smears the very bursts the micro-batcher
    exists to coalesce. Reconnects transparently when the server (or an
    idle timeout) dropped the socket."""

    def __init__(self, base_url: str, timeout_s: float):
        parsed = urllib.parse.urlsplit(base_url)
        self._host = parsed.hostname
        self._port = parsed.port or 80
        self._timeout = timeout_s
        self._conn: http.client.HTTPConnection | None = None

    def _connect(self) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self._host, self._port, timeout=self._timeout
            )
        return self._conn

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def post_json(self, path: str, payload: bytes):
        """POST → (status, parsed JSON | None). Non-200 statuses with a
        JSON body are STRUCTURED answers, not transport failures; one
        silent retry on a dropped keep-alive socket."""
        for attempt in (0, 1):
            conn = self._connect()
            try:
                conn.request("POST", path, body=payload,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                body = resp.read()
            except (http.client.HTTPException, ConnectionError, OSError):
                self.close()
                if attempt:
                    raise
                continue
            try:
                return resp.status, json.loads(body)
            except (ValueError, json.JSONDecodeError):
                return resp.status, None
        raise OSError("unreachable")  # both attempts raised above


def fetch_stats(base_url: str, timeout_s: float = 5.0) -> dict | None:
    try:
        with urllib.request.urlopen(base_url.rstrip("/") + "/stats",
                                    timeout=timeout_s) as resp:
            return json.loads(resp.read())
    except (OSError, ValueError, json.JSONDecodeError):
        return None


def run_load(
    base_url: str,
    *,
    concurrency: int = 32,
    total_requests: int = 512,
    image_size: int = 224,
    pool: int = 16,
    deadline_ms: float = 0.0,
    timeout_s: float = 30.0,
    endpoint: str = "/v1/embed",
    seed: int = 0,
    capture: dict | None = None,
) -> dict:
    """Drive the server; returns the summary dict (see module docstring).
    `capture`, when given, collects `pool_index -> embedding list` from
    successful responses so a caller can verify served embeddings against
    a direct `model.apply` (the CPU-smoke fidelity check)."""
    rng = np.random.RandomState(seed)
    images = rng.randint(
        0, 256, (pool, image_size, image_size, 3)
    ).astype(np.uint8)
    payloads = []
    for im in images:
        body = {"image_b64": base64.b64encode(im.tobytes()).decode("ascii"),
                "shape": list(im.shape)}
        if deadline_ms:
            body["deadline_ms"] = deadline_ms
        payloads.append(json.dumps(body).encode("utf-8"))

    lock = threading.Lock()
    latencies: list[float] = []
    outcomes: dict[str, int] = {}
    lost: list[str] = []
    per = [total_requests // concurrency] * concurrency
    for i in range(total_requests - sum(per)):
        per[i] += 1
    start_gate = threading.Event()

    def worker(wid: int, n: int) -> None:
        client = _Client(base_url, timeout_s)
        start_gate.wait()
        try:
            for j in range(n):
                k = (wid * 31 + j * 7) % pool  # deterministic mixed replay
                t0 = time.monotonic()
                try:
                    status, resp = client.post_json(endpoint, payloads[k])
                except (OSError, TimeoutError, http.client.HTTPException) as e:
                    with lock:
                        lost.append(f"worker{wid}: {type(e).__name__}: {e}")
                    continue
                dt = time.monotonic() - t0
                if status == 200 and isinstance(resp, dict):
                    with lock:
                        latencies.append(dt)
                        outcomes["ok"] = outcomes.get("ok", 0) + 1
                    if capture is not None and "embedding" in resp:
                        with lock:
                            capture.setdefault(k, resp["embedding"])
                elif (isinstance(resp, dict)
                        and resp.get("error") in STRUCTURED_REJECTIONS):
                    with lock:
                        key = str(resp["error"])
                        outcomes[key] = outcomes.get(key, 0) + 1
                else:
                    with lock:
                        lost.append(
                            f"worker{wid}: unstructured status {status}: "
                            f"{str(resp)[:120]}"
                        )
        finally:
            client.close()

    threads = [
        threading.Thread(target=worker, args=(i, per[i]), daemon=True)
        for i in range(concurrency)
    ]
    for t in threads:
        t.start()
    t0 = time.monotonic()
    start_gate.set()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0

    resolved = sum(outcomes.values())
    return {
        "sent": total_requests,
        "resolved": resolved,
        "ok": outcomes.get("ok", 0),
        "shed": {k: v for k, v in outcomes.items() if k != "ok"},
        "lost": len(lost),
        "lost_detail": lost[:8],
        "concurrency": concurrency,
        "wall_s": round(wall, 3),
        "throughput_rps": round(resolved / wall, 1) if wall else 0.0,
        "latency_ms": {
            f"p{q}": round(_percentile(latencies, q) * 1e3, 3)
            for q in (50, 95, 99)
        },
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    parser.add_argument("--url", required=True,
                        help="server base url, e.g. http://127.0.0.1:8080")
    parser.add_argument("--concurrency", type=int, default=32)
    parser.add_argument("--requests", type=int, default=512)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--pool", type=int, default=16,
                        help="distinct images replayed (cache-hit mix)")
    parser.add_argument("--deadline-ms", type=float, default=0.0,
                        help="per-request deadline forwarded to the server "
                             "(0 = server default)")
    parser.add_argument("--timeout-s", type=float, default=30.0)
    parser.add_argument("--endpoint", default="/v1/embed",
                        choices=["/v1/embed", "/v1/knn"])
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    summary = run_load(
        args.url,
        concurrency=args.concurrency,
        total_requests=args.requests,
        image_size=args.image_size,
        pool=args.pool,
        deadline_ms=args.deadline_ms,
        timeout_s=args.timeout_s,
        endpoint=args.endpoint,
        seed=args.seed,
    )
    record = {
        "metric": "serve_embed_p95_latency_ms",
        "value": summary["latency_ms"]["p95"],
        "unit": "ms",
        "vs_baseline": 0.0,
        "detail": summary,
    }
    stats = fetch_stats(args.url, args.timeout_s)
    if stats is not None:
        record["server"] = {
            k: stats[k]
            for k in ("batches", "occupancy_mean", "buckets",
                      "shed_overload", "shed_deadline", "cache")
            if k in stats
        }
    print(json.dumps(record))
    # zero-requests-lost is the contract; a lost request is a real failure
    return 1 if summary["lost"] else 0


if __name__ == "__main__":
    sys.exit(main())
