#!/usr/bin/env python
"""Closed-loop load generator for the embedding service (ISSUE 5).

    python tools/serve_bench.py --url http://127.0.0.1:8080 \
        --concurrency 32 --requests 512 --image-size 224

`--concurrency` workers each send their share of `--requests` back to
back (closed loop — a new request only after the previous one resolved),
so the offered load is exactly the in-flight concurrency the
micro-batcher coalesces. EVERY request must end in a result or a
STRUCTURED rejection (overloaded / deadline_exceeded / draining JSON
body — and the fleet router's no_healthy_backend / upstream_* codes);
anything else — connection error, unstructured 5xx — counts as LOST and
fails the run. Prints one BENCH-style JSON record: latency p50/p95/p99,
throughput at the fixed concurrency, shed counts, and the server's own
/stats fold (mean batch occupancy, compile-bucket ladder).

Fleet mode (ISSUE 10):

    python tools/serve_bench.py --fleet 1,2,4 [--kill-drill] -- \
        python tools/serve.py --pretrained encoder.npz --arch resnet50

spins up `tools/serve_fleet.py` at each replica count (everything after
`--` is one replica's base command), drives the SAME closed loop through
the router, and reports rps/p99/lost per count. `--kill-drill` SIGKILLs
one replica mid-load (pid from the router's /stats) — the zero-lost
contract must hold THROUGH the kill: the router's single-retry absorbs
in-flight failures. `--fleet-args` forwards extra flags to the
supervisor (e.g. "--ann-shards 4" to bench the sharded kNN fan-out);
`--tier batch` tags every request for the batch admission lane.

Autoscale drill (ISSUE 20):

    python tools/serve_bench.py --autoscale-drill --requests 2048 \
        --fleet-args "--autoscale-max 3 --autoscale-cooldown-s 3" -- \
        python tools/serve.py --pretrained encoder.npz --arch resnet_tiny

one fleet, three acts: a batch-lane surge drives the router's shed rate
over the breach threshold (capacity must FOLLOW — /healthz grows within
the cooldown), low-rate interactive probes ride through the whole surge
(they must see ZERO sheds: the lanes exist so bulk work cannot starve
people), then the load stops and the fleet must drain-and-reap back to
its floor. Zero lost accepted requests across every phase, or exit 1.

Pure stdlib + numpy: runs anywhere the server is reachable, no jax.
"""

from __future__ import annotations

import argparse
import base64
import http.client
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.parse
import urllib.request

import numpy as np

# the replica's own shed codes + the fleet router's (ISSUE 10): all are
# ANSWERS — a client told to back off was served a decision, not dropped
STRUCTURED_REJECTIONS = (
    "overloaded", "deadline_exceeded", "draining",
    "no_healthy_backend", "upstream_timeout", "upstream_error",
)


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = max(0, min(len(ordered) - 1, round(q / 100.0 * (len(ordered) - 1))))
    return ordered[int(rank)]


class _Client:
    """One persistent keep-alive connection (http.client): a closed-loop
    worker that reconnects per request measures TCP setup, not serving —
    and its turnaround jitter smears the very bursts the micro-batcher
    exists to coalesce. Reconnects transparently when the server (or an
    idle timeout) dropped the socket."""

    def __init__(self, base_url: str, timeout_s: float):
        parsed = urllib.parse.urlsplit(base_url)
        self._host = parsed.hostname
        self._port = parsed.port or 80
        self._timeout = timeout_s
        self._conn: http.client.HTTPConnection | None = None

    def _connect(self) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self._host, self._port, timeout=self._timeout
            )
        return self._conn

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def post_json(self, path: str, payload: bytes):
        """POST → (status, parsed JSON | None). Non-200 statuses with a
        JSON body are STRUCTURED answers, not transport failures; one
        silent retry on a dropped keep-alive socket."""
        for attempt in (0, 1):
            conn = self._connect()
            try:
                conn.request("POST", path, body=payload,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                body = resp.read()
            except (http.client.HTTPException, ConnectionError, OSError):
                self.close()
                if attempt:
                    raise
                continue
            try:
                return resp.status, json.loads(body)
            except (ValueError, json.JSONDecodeError):
                return resp.status, None
        raise OSError("unreachable")  # both attempts raised above


def fetch_stats(base_url: str, timeout_s: float = 5.0) -> dict | None:
    try:
        with urllib.request.urlopen(base_url.rstrip("/") + "/stats",
                                    timeout=timeout_s) as resp:
            return json.loads(resp.read())
    except (OSError, ValueError, json.JSONDecodeError):
        return None


def run_load(
    base_url: str,
    *,
    concurrency: int = 32,
    total_requests: int = 512,
    image_size: int = 224,
    pool: int = 16,
    deadline_ms: float = 0.0,
    timeout_s: float = 30.0,
    endpoint: str = "/v1/embed",
    seed: int = 0,
    tier: str = "",
    capture: dict | None = None,
    stop: threading.Event | None = None,
) -> dict:
    """Drive the server; returns the summary dict (see module docstring).
    `capture`, when given, collects `pool_index -> embedding list` from
    successful responses so a caller can verify served embeddings against
    a direct `model.apply` (the CPU-smoke fidelity check)."""
    rng = np.random.RandomState(seed)
    images = rng.randint(
        0, 256, (pool, image_size, image_size, 3)
    ).astype(np.uint8)
    payloads = []
    for im in images:
        body = {"image_b64": base64.b64encode(im.tobytes()).decode("ascii"),
                "shape": list(im.shape)}
        if deadline_ms:
            body["deadline_ms"] = deadline_ms
        if tier:
            body["tier"] = tier
        payloads.append(json.dumps(body).encode("utf-8"))

    lock = threading.Lock()
    latencies: list[float] = []
    outcomes: dict[str, int] = {}
    lost: list[str] = []
    per = [total_requests // concurrency] * concurrency
    for i in range(total_requests - sum(per)):
        per[i] += 1
    start_gate = threading.Event()

    def worker(wid: int, n: int) -> None:
        client = _Client(base_url, timeout_s)
        start_gate.wait()
        try:
            for j in range(n):
                if stop is not None and stop.is_set():
                    break
                k = (wid * 31 + j * 7) % pool  # deterministic mixed replay
                t0 = time.monotonic()
                try:
                    status, resp = client.post_json(endpoint, payloads[k])
                except (OSError, TimeoutError, http.client.HTTPException) as e:
                    with lock:
                        lost.append(f"worker{wid}: {type(e).__name__}: {e}")
                    continue
                dt = time.monotonic() - t0
                if status == 200 and isinstance(resp, dict):
                    with lock:
                        latencies.append(dt)
                        outcomes["ok"] = outcomes.get("ok", 0) + 1
                    if capture is not None and "embedding" in resp:
                        with lock:
                            capture.setdefault(k, resp["embedding"])
                elif (isinstance(resp, dict)
                        and resp.get("error") in STRUCTURED_REJECTIONS):
                    with lock:
                        key = str(resp["error"])
                        outcomes[key] = outcomes.get(key, 0) + 1
                else:
                    with lock:
                        lost.append(
                            f"worker{wid}: unstructured status {status}: "
                            f"{str(resp)[:120]}"
                        )
        finally:
            client.close()

    threads = [
        threading.Thread(target=worker, args=(i, per[i]), daemon=True)
        for i in range(concurrency)
    ]
    for t in threads:
        t.start()
    t0 = time.monotonic()
    start_gate.set()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0

    resolved = sum(outcomes.values())
    return {
        "sent": total_requests,
        "resolved": resolved,
        "ok": outcomes.get("ok", 0),
        "shed": {k: v for k, v in outcomes.items() if k != "ok"},
        "lost": len(lost),
        "lost_detail": lost[:8],
        "concurrency": concurrency,
        "wall_s": round(wall, 3),
        "throughput_rps": round(resolved / wall, 1) if wall else 0.0,
        "latency_ms": {
            f"p{q}": round(_percentile(latencies, q) * 1e3, 3)
            for q in (50, 95, 99)
        },
    }


# ---------------------------------------------------------------------------
# fleet mode (ISSUE 10): closed-loop load vs replica count
# ---------------------------------------------------------------------------


def _wait_fleet_ready(proc, want_replicas: int, boot_timeout_s: float):
    """Parse the fleet's announcement line, then poll /healthz until all
    replicas are in rotation. Returns the router url."""
    url = None
    deadline = time.monotonic() + boot_timeout_s
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError("fleet exited before announcing its url")
        if "fleet serving on http://" in line:
            url = "http://" + line.split("http://")[1].split()[0].rstrip("/")
            break
    if url is None:
        raise RuntimeError("fleet never announced its url")
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url + "/healthz", timeout=2.0) as r:
                body = json.loads(r.read())
        except (OSError, ValueError):
            body = {}
        if body.get("healthy", 0) >= want_replicas:
            return url
        time.sleep(0.2)
    raise RuntimeError(
        f"fleet never reached {want_replicas} healthy replicas"
    )


def _kill_one_replica(url: str) -> int | None:
    """SIGKILL one healthy replica, pid from the router's /stats (the
    drill a production orchestrator performs by accident)."""
    stats = fetch_stats(url)
    if not stats:
        return None
    for rep in stats.get("replicas", []):
        if rep.get("healthy") and rep.get("pid"):
            os.kill(int(rep["pid"]), signal.SIGKILL)
            return int(rep["pid"])
    return None


def run_fleet_bench(
    replica_cmd: list,
    counts=(1, 2, 4),
    *,
    concurrency: int = 32,
    total_requests: int = 512,
    image_size: int = 224,
    pool: int = 16,
    timeout_s: float = 30.0,
    deadline_ms: float = 0.0,
    endpoint: str = "/v1/embed",
    seed: int = 0,
    tier: str = "",
    kill_drill: bool = False,
    kill_after_s: float = 1.0,
    boot_timeout_s: float = 240.0,
    fleet_args: list | None = None,
    env: dict | None = None,
) -> list[dict]:
    """One closed-loop run per replica count against a freshly spawned
    `tools/serve_fleet.py`; returns one row per count. With `kill_drill`
    (counts > 1 only) one replica is SIGKILLed `kill_after_s` into the
    load — `lost` must stay 0 through it (the acceptance contract)."""
    import shutil

    fleet_py = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "serve_fleet.py")
    rows = []
    for n in counts:
        tdir = tempfile.mkdtemp(prefix=f"fleet_bench_{n}r_")
        argv = [
            sys.executable, "-u", fleet_py,
            "--replicas", str(n), "--port", "0", "--base-port", "0",
            "--telemetry-dir", tdir,
            "--probe-secs", "0.2", "--probe-timeout-s", "2.0",
            "--health-stale-secs", "10",
            "--startup-grace-secs", str(boot_timeout_s),
            "--backoff-base-secs", "0.1",
        ] + list(fleet_args or []) + ["--"] + list(replica_cmd)
        proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        row: dict = {"replicas": n}
        killer = None
        try:
            url = _wait_fleet_ready(proc, n, boot_timeout_s)
            drill = kill_drill and n > 1
            killed = {}
            if drill:
                def _later():
                    time.sleep(kill_after_s)
                    killed["pid"] = _kill_one_replica(url)

                killer = threading.Thread(target=_later, daemon=True)
                killer.start()
            summary = run_load(
                url, concurrency=concurrency,
                total_requests=total_requests, image_size=image_size,
                pool=pool, timeout_s=timeout_s, deadline_ms=deadline_ms,
                endpoint=endpoint, seed=seed, tier=tier,
            )
            if killer is not None:
                killer.join(timeout=10.0)
            row.update({
                "throughput_rps": summary["throughput_rps"],
                "latency_ms": summary["latency_ms"],
                "ok": summary["ok"],
                "shed": summary["shed"],
                "lost": summary["lost"],
                "lost_detail": summary["lost_detail"],
            })
            if drill:
                row["killed_pid"] = killed.get("pid")
            stats = fetch_stats(url)
            if stats:
                row["router"] = stats.get("router")
        except (RuntimeError, OSError) as e:
            row["error"] = f"{type(e).__name__}: {e}"
        finally:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                try:
                    proc.wait(timeout=30.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
            if "error" in row:
                # keep the telemetry for a post-mortem, and say where
                row["telemetry_dir"] = tdir
            else:
                shutil.rmtree(tdir, ignore_errors=True)
        rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# autoscale step drill (ISSUE 20): surge -> scale up -> idle -> drain-reap
# ---------------------------------------------------------------------------


def _fetch_healthy(url: str) -> int:
    try:
        with urllib.request.urlopen(url + "/healthz", timeout=2.0) as r:
            return int(json.loads(r.read()).get("healthy", 0))
    except (OSError, ValueError):
        return -1


def run_autoscale_drill(
    replica_cmd: list,
    *,
    base_replicas: int = 1,
    concurrency: int = 32,
    total_requests: int = 2048,
    image_size: int = 224,
    pool: int = 16,
    timeout_s: float = 30.0,
    deadline_ms: float = 0.0,
    seed: int = 0,
    boot_timeout_s: float = 240.0,
    drill_timeout_s: float = 180.0,
    probe_interval_s: float = 0.25,
    fleet_args: list | None = None,
    env: dict | None = None,
) -> dict:
    """The ISSUE 20 step drill. Boots ONE fleet at `base_replicas` with
    autoscaling armed (caller supplies --autoscale-* via fleet_args),
    then: (1) surge — a batch-lane closed loop saturates the router
    while low-rate INTERACTIVE probes run beside it; capacity must grow
    past the starting healthy count before the surge ends. (2) idle —
    the load stops; the fleet must drain-and-reap back down to its
    floor within `drill_timeout_s`. Verdict fails on any lost request,
    any interactive shed during the surge, or either transition not
    observed."""
    import shutil

    fleet_py = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "serve_fleet.py")
    tdir = tempfile.mkdtemp(prefix="fleet_autoscale_")
    argv = [
        sys.executable, "-u", fleet_py,
        "--replicas", str(base_replicas), "--port", "0", "--base-port", "0",
        "--telemetry-dir", tdir,
        "--probe-secs", "0.2", "--probe-timeout-s", "2.0",
        "--health-stale-secs", "10",
        "--startup-grace-secs", str(boot_timeout_s),
        "--backoff-base-secs", "0.1",
        # the autoscaler observes on the stats cadence: a drill-speed
        # window so breach/idle streaks accumulate in seconds, not
        # the production default half-minutes
        "--stats-every-secs", "0.5",
    ] + list(fleet_args or []) + ["--"] + list(replica_cmd)
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env,
    )
    out: dict = {"base_replicas": base_replicas}
    probes = {"sent": 0, "ok": 0, "shed": 0, "lost": 0}
    try:
        url = _wait_fleet_ready(proc, base_replicas, boot_timeout_s)
        healthy0 = _fetch_healthy(url)
        out["healthy_start"] = healthy0

        surge_summary: dict = {}
        surge_done = threading.Event()

        def _surge():
            surge_summary.update(run_load(
                url, concurrency=concurrency,
                total_requests=total_requests, image_size=image_size,
                pool=pool, timeout_s=timeout_s, deadline_ms=deadline_ms,
                endpoint="/v1/embed", seed=seed, tier="batch",
            ))
            surge_done.set()

        surge = threading.Thread(target=_surge, daemon=True)
        t0 = time.monotonic()
        surge.start()

        # interactive probes beside the surge: ONE request in flight at
        # a steady trickle — the lane the batch flood must never starve
        rng = np.random.RandomState(seed + 1)
        im = rng.randint(0, 256, (image_size, image_size, 3)).astype(np.uint8)
        probe_payload = json.dumps({
            "image_b64": base64.b64encode(im.tobytes()).decode("ascii"),
            "shape": list(im.shape), "tier": "interactive",
        }).encode("utf-8")
        probe_client = _Client(url, timeout_s)

        peak = healthy0
        scale_up_s = None
        while not surge_done.is_set():
            probes["sent"] += 1
            try:
                status, resp = probe_client.post_json("/v1/embed",
                                                      probe_payload)
                if status == 200 and isinstance(resp, dict):
                    probes["ok"] += 1
                elif (isinstance(resp, dict)
                        and resp.get("error") in STRUCTURED_REJECTIONS):
                    probes["shed"] += 1
                else:
                    probes["lost"] += 1
            except (OSError, TimeoutError, http.client.HTTPException):
                probes["lost"] += 1
            h = _fetch_healthy(url)
            if h > peak:
                peak = h
                if scale_up_s is None:
                    scale_up_s = round(time.monotonic() - t0, 2)
            surge_done.wait(probe_interval_s)
        surge.join(timeout=timeout_s)
        probe_client.close()
        out["surge"] = surge_summary
        out["interactive_probes"] = probes
        out["healthy_peak"] = peak
        out["scale_up_s"] = scale_up_s

        # idle: no load — the supervisor must drain and reap back down
        t1 = time.monotonic()
        scale_down_s = None
        floor = healthy0
        while time.monotonic() - t1 < drill_timeout_s:
            h = _fetch_healthy(url)
            if 0 <= h <= floor:
                scale_down_s = round(time.monotonic() - t1, 2)
                break
            time.sleep(0.5)
        out["healthy_end"] = _fetch_healthy(url)
        out["scale_down_s"] = scale_down_s

        out["pass"] = bool(
            surge_summary
            and surge_summary.get("lost", 1) == 0
            and probes["lost"] == 0
            and probes["shed"] == 0
            and peak > healthy0
            and scale_down_s is not None
        )
    except (RuntimeError, OSError) as e:
        out["error"] = f"{type(e).__name__}: {e}"
        out["pass"] = False
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=30.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        if out.get("pass"):
            shutil.rmtree(tdir, ignore_errors=True)
        else:
            out["telemetry_dir"] = tdir  # keep for the post-mortem
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    parser.add_argument("--url",
                        help="server base url, e.g. http://127.0.0.1:8080 "
                             "(required unless --fleet)")
    parser.add_argument("--concurrency", type=int, default=32)
    parser.add_argument("--requests", type=int, default=512)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--pool", type=int, default=16,
                        help="distinct images replayed (cache-hit mix)")
    parser.add_argument("--deadline-ms", type=float, default=0.0,
                        help="per-request deadline forwarded to the server "
                             "(0 = server default)")
    parser.add_argument("--timeout-s", type=float, default=30.0)
    parser.add_argument("--endpoint", default="/v1/embed",
                        choices=["/v1/embed", "/v1/knn"])
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--tier", default="", choices=["", "interactive",
                                                       "batch"],
                        help="admission lane tag on every request "
                             "(ISSUE 20); empty = untagged (interactive)")
    parser.add_argument("--fleet", default="",
                        help="fleet mode: comma-separated replica counts "
                             "(e.g. 1,2,4); everything after -- is one "
                             "replica's base command")
    parser.add_argument("--fleet-args", default="",
                        help="extra serve_fleet.py flags, one string "
                             "(e.g. \"--ann-shards 4\")")
    parser.add_argument("--kill-drill", action="store_true",
                        help="fleet mode: SIGKILL one replica mid-load "
                             "at counts > 1 (lost must stay 0)")
    parser.add_argument("--kill-after-s", type=float, default=1.0)
    parser.add_argument("--autoscale-drill", action="store_true",
                        help="step drill: batch surge -> scale up -> "
                             "idle -> drain-reap (see module docstring); "
                             "arm the autoscaler via --fleet-args")
    parser.add_argument("--base-replicas", type=int, default=1,
                        help="autoscale drill: replicas at boot (the "
                             "floor the fleet must reap back down to)")
    parser.add_argument("--drill-timeout-s", type=float, default=180.0,
                        help="autoscale drill: max wait for the "
                             "drain-reap back to the floor")
    parser.add_argument("replica_cmd", nargs=argparse.REMAINDER,
                        help="fleet mode: -- then one replica's command")
    args = parser.parse_args(argv)

    cmd = args.replica_cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    fleet_extra = args.fleet_args.split() if args.fleet_args else []

    if args.autoscale_drill:
        if not cmd:
            parser.error("--autoscale-drill needs `-- <replica command>`")
        out = run_autoscale_drill(
            cmd,
            base_replicas=args.base_replicas,
            concurrency=args.concurrency,
            total_requests=args.requests,
            image_size=args.image_size,
            pool=args.pool,
            timeout_s=args.timeout_s,
            deadline_ms=args.deadline_ms,
            seed=args.seed,
            drill_timeout_s=args.drill_timeout_s,
            fleet_args=fleet_extra,
        )
        record = {
            "metric": "serve_autoscale_drill",
            "value": 1.0 if out.get("pass") else 0.0,
            "unit": "pass",
            "vs_baseline": 0.0,
            "detail": out,
        }
        print(json.dumps(record))
        return 0 if out.get("pass") else 1

    if args.fleet:
        counts = tuple(int(c) for c in args.fleet.split(",") if c.strip())
        if not counts or not cmd:
            parser.error("--fleet needs counts AND `-- <replica command>`")
        rows = run_fleet_bench(
            cmd, counts,
            concurrency=args.concurrency,
            total_requests=args.requests,
            image_size=args.image_size,
            pool=args.pool,
            timeout_s=args.timeout_s,
            deadline_ms=args.deadline_ms,
            endpoint=args.endpoint,
            seed=args.seed,
            tier=args.tier,
            kill_drill=args.kill_drill,
            kill_after_s=args.kill_after_s,
            fleet_args=fleet_extra,
        )
        complete = [r for r in rows if "error" not in r]
        best = max((r["throughput_rps"] for r in complete), default=0.0)
        record = {
            "metric": "serve_fleet_rps",
            "value": best,
            "unit": "rps",
            "vs_baseline": 0.0,
            "detail": {"rows": rows, "kill_drill": args.kill_drill,
                       "concurrency": args.concurrency,
                       "requests": args.requests},
        }
        print(json.dumps(record))
        lost = sum(r.get("lost", 0) for r in rows)
        return 1 if (lost or len(complete) < len(rows)) else 0

    if not args.url:
        parser.error("--url is required (or use --fleet)")
    summary = run_load(
        args.url,
        concurrency=args.concurrency,
        total_requests=args.requests,
        image_size=args.image_size,
        pool=args.pool,
        deadline_ms=args.deadline_ms,
        timeout_s=args.timeout_s,
        endpoint=args.endpoint,
        seed=args.seed,
        tier=args.tier,
    )
    record = {
        "metric": "serve_embed_p95_latency_ms",
        "value": summary["latency_ms"]["p95"],
        "unit": "ms",
        "vs_baseline": 0.0,
        "detail": summary,
    }
    stats = fetch_stats(args.url, args.timeout_s)
    if stats is not None:
        record["server"] = {
            k: stats[k]
            for k in ("batches", "occupancy_mean", "buckets",
                      "shed_overload", "shed_deadline", "cache")
            if k in stats
        }
    print(json.dumps(record))
    # zero-requests-lost is the contract; a lost request is a real failure
    return 1 if summary["lost"] else 0


if __name__ == "__main__":
    sys.exit(main())
