#!/usr/bin/env python
"""Offline summarizer for telemetry events.jsonl (ISSUE 2 tentpole part 6).

    python tools/telemetry_report.py <run_dir>/telemetry/events.jsonl
    python tools/telemetry_report.py events.jsonl --json
    python tools/telemetry_report.py events.jsonl --follow
    python tools/telemetry_report.py <fleet_telemetry_dir>   # ISSUE 10

Renders, from the schema-versioned record stream the driver writes
(moco_tpu/telemetry/registry.py):

  - step-time p50/p95/p99 (ms) + the data/host/device phase split
  - gradient sync (ISSUE 6): mode + analytic sync-bytes/step/device from
    the `grad_sync` records, comm-phase share from the fenced `comm_s`
    samples (grads-ready → reduced)
  - MFU (mean/max) and the peak-FLOPs assumption it was judged against
  - throughput (rolling at end-of-run, cumulative mean)
  - HBM high-water mark + host-RSS high-water
  - input pipeline (ISSUE 3): prefetch queue depth, staging-worker busy
    fraction, decode-once cache hit rate, staged-batch latency p50/p95
  - incident counts by event kind (preempt/rollback/chaos/watchdog/...)
  - supervisor lifecycle (ISSUE 4): launches/restarts/kills, death
    classifications, final budget state and outcome — the `kind:
    "supervisor"` records tools/supervise.py appends to the same stream
  - elastic resize (ISSUE 11): requests, relaunches (old→new device
    count, cadence overrides), and preflight mesh_change incidents from
    the same supervisor stream, folded as a `resize:` section (and
    rendered live by --follow, like fleet lines)
  - serving (ISSUE 5): request/shed counts, latency p50/p95/p99, batch
    count and mean bucket occupancy, embedding-cache hit rate — from the
    cumulative `kind: "serve"` snapshots the embedding service emits
    (the LAST snapshot summarizes the run)
  - serve fleet (ISSUE 10): pass the FLEET telemetry DIRECTORY (the
    `--telemetry-dir` of tools/serve_fleet.py) and the report merges the
    fleet's own events.jsonl with every `replica*/events.jsonl` under
    it: per-replica launch/restart/kill/ejection counts and death
    classifications from the `kind: "fleet"` records, router totals +
    shed rate from the last `router_stats` record, reload history
    (detected / rolled / quarantined), and a per-replica fold of each
    replica's own last serve snapshot (the single-file `serve:` section
    assumes exactly one server)
  - bank lifecycle (ISSUE 16): the `kind: "bank"` records the bank
    builder (build_start/shard_done/build_done), the embedding service
    (the atomic dual `swap`), and the fleet (bank_waiting / quarantine /
    bank_quarantine / rollback) emit, folded as a `bank:` section
    (builds, swaps, quarantines, rollbacks, last build/swap, bank age) —
    and rendered live by --follow, like fleet lines
  - SLO transitions (ISSUE 12): the `kind: "slo"` alert/recovery records
    tools/obsd.py appends into the same stream, folded per rule
    (alert/recovery counts, still-active rules) as a `slo:` section —
    and rendered live by --follow, like fleet/resize lines
  - learning health (ISSUE 13): the `health` blocks the driver stamps on
    health-stride step records (embedding std / participation ratio,
    logit margin, queue norm/age, q↔k drift — telemetry/health.py) plus
    CollapseSentinel incident/recovery events, folded as a `health:`
    section (last sample + window-worst floors) — and rendered live by
    --follow as their own `health:` tail lines
  - pod-record count and worst cross-host step-time spread

`--follow` (ISSUE 8 satellite) is the live-tail mode: poll the file and
render step/incident/supervisor/serve lines AS THEY LAND — the operator's
view of a run in progress, reading the same stream every offline consumer
reads. Reads are partial-line-safe (the writer flushes whole buffers, but
a poll can still catch a line mid-write: bytes after the last newline
stay buffered until the newline arrives), survive the file not existing
yet (supervisor started before the child), and reset on truncation.

Robustness: unparseable lines (a torn tail from a SIGKILL mid-flush) are
counted and skipped, never fatal; unknown record kinds and unknown future
schema versions are tallied but not interpreted. `--json` emits one
machine-readable summary object instead of the human text. Pure stdlib —
runs anywhere the events file can be copied to.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# stdlib-safe: aggregate is pinned pure-stdlib (R11 obsd-stdlib-only)
from moco_tpu.telemetry.aggregate import TELEMETRY_SUBDIR_PREFIXES  # noqa: E402


def load_events(path: str) -> tuple[list[dict], int]:
    """Parse a JSONL events file; returns (records, skipped_line_count)."""
    records, skipped = [], 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                skipped += 1
    return records, skipped


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = max(0, min(len(ordered) - 1, round(q / 100.0 * (len(ordered) - 1))))
    return ordered[int(rank)]


def expand_events_arg(path: str) -> list[tuple[str, str]]:
    """`(label, events_path)` pairs for one CLI argument. A FILE is
    itself (label ""); a DIRECTORY is a fleet telemetry dir (ISSUE 10:
    its own events.jsonl plus every `replica*/events.jsonl`) or an
    input-service telemetry root (ISSUE 14: the run's events.jsonl plus
    every `staging_server*/events.jsonl` beside it)."""
    if not os.path.isdir(path):
        return [("", path)]
    pairs = []
    own = os.path.join(path, "events.jsonl")
    if os.path.exists(own):
        pairs.append(("fleet", own))
    for name in sorted(os.listdir(path)):
        sub = os.path.join(path, name, "events.jsonl")
        if (name.startswith(TELEMETRY_SUBDIR_PREFIXES)
                and os.path.exists(sub)):
            pairs.append((name, sub))
    if not pairs:
        raise OSError(f"no events.jsonl under directory {path}")
    return pairs


def load_events_multi(pairs: list[tuple[str, str]]) -> tuple[list[dict], int]:
    """Merge several events files; each record is tagged with its source
    label under `_src` (empty for the single-file case) so per-replica
    folds can group without re-reading."""
    records, skipped = [], 0
    for label, path in pairs:
        recs, skip = load_events(path)
        if label:
            for r in recs:
                r["_src"] = label
        records.extend(recs)
        skipped += skip
    return records, skipped


def summarize(records: list[dict], skipped: int = 0) -> dict:
    """Fold parsed records into one summary dict (the --json payload)."""
    steps = [r for r in records if r.get("kind") == "step"]
    events = [r for r in records if r.get("kind") == "event"]
    pods = [r for r in records if r.get("kind") == "pod"]
    run_starts = [r for r in records if r.get("kind") == "run_start"]
    run_ends = [r for r in records if r.get("kind") == "run_end"]
    supervisor = [r for r in records if r.get("kind") == "supervisor"]
    serves = [r for r in records if r.get("kind") == "serve"]
    fleet = [r for r in records if r.get("kind") == "fleet"]
    slos = [r for r in records if r.get("kind") == "slo"]
    input_servers = [r for r in records if r.get("kind") == "input_server"]
    banks = [r for r in records if r.get("kind") == "bank"]

    step_s = [r["step_s"] for r in steps if "step_s" in r]
    data_s = [r["data_s"] for r in steps if "data_s" in r]
    host_s = [r["host_s"] for r in steps if "host_s" in r]
    device_s = [r["device_s"] for r in steps if "device_s" in r]
    mfu = [r["mfu"] for r in steps if "mfu" in r]
    hbm = [r["hbm_peak_bytes"] for r in steps if "hbm_peak_bytes" in r]
    rss = [r["host_rss_bytes"] for r in steps if "host_rss_bytes" in r]

    events_by_kind: dict[str, int] = {}
    for e in events:
        key = str(e.get("event", "unknown"))
        events_by_kind[key] = events_by_kind.get(key, 0) + 1
    # incidents = events that signal trouble; routine markers the driver
    # emits on purpose (epoch/eval bookkeeping) are reported separately,
    # matching the driver's own `incidents` counter (log_event-routed only)
    routine = {"epoch_summary", "knn_eval", "grad_sync", "sharding"}
    incidents = {k: v for k, v in events_by_kind.items() if k not in routine}

    summary: dict = {
        "records": len(records),
        "skipped_lines": skipped,
        "runs": len(run_starts),
        "steps": len(steps),
        "events_by_kind": events_by_kind,
        "incidents": incidents,
        "incidents_total": sum(incidents.values()),
        "pod_records": len(pods),
    }
    if run_starts:
        first = run_starts[0]
        summary["run"] = {
            k: first[k]
            for k in ("name", "variant", "arch", "batch_size", "n_chips",
                      "n_procs", "device_kind", "peak_flops_per_chip",
                      "flops_per_step", "run_id", "trace_id")
            if k in first
        }
    if step_s:
        summary["step_time_ms"] = {
            f"p{q}": round(_percentile(step_s, q) * 1e3, 3) for q in (50, 95, 99)
        }
        total = sum(step_s)
        summary["phase_share"] = {
            "data": round(sum(data_s) / total, 4) if total else 0.0,
            "host": round(sum(host_s) / total, 4) if total else 0.0,
        }
        summary["steps_span"] = [steps[0].get("step"), steps[-1].get("step")]
    if device_s:
        summary["device_time_ms"] = {
            "samples": len(device_s),
            "p50": round(_percentile(device_s, 50) * 1e3, 3),
            "max": round(max(device_s) * 1e3, 3),
        }
    # gradient sync (ISSUE 6): comm-phase share over the fenced samples
    # (grads-ready → reduced, from the same strided fence as device_s) plus
    # the static plan (mode + analytic sync-bytes/step/device) — from the
    # one routine `grad_sync` event or the stamped step records
    comm = [(r["comm_s"], r["step_s"]) for r in steps
            if "comm_s" in r and r.get("step_s")]
    if comm:
        shares = [c / s for c, s in comm]
        summary["comm"] = {
            "samples": len(comm),
            "p50_ms": round(_percentile([c for c, _ in comm], 50) * 1e3, 3),
            "max_ms": round(max(c for c, _ in comm) * 1e3, 3),
            "share_mean": round(sum(shares) / len(shares), 4),
        }
    gs_events = [e for e in events if e.get("event") == "grad_sync"]
    gs_steps = [r["grad_sync"] for r in steps
                if isinstance(r.get("grad_sync"), dict)]
    if gs_events or gs_steps:
        last = gs_steps[-1] if gs_steps else {
            k: v for k, v in gs_events[-1].items()
            if k not in ("kind", "event", "t", "schema")
        }
        summary["grad_sync"] = last
    # sharding plan (ISSUE 15): mode + mesh shape + measured per-device
    # param/opt bytes, from the one routine `sharding` event
    sh_events = [e for e in events if e.get("event") == "sharding"]
    if sh_events:
        summary["sharding"] = {
            k: v for k, v in sh_events[-1].items()
            if k not in ("kind", "event", "t", "schema")
        }
    if mfu:
        summary["mfu"] = {
            "mean": round(sum(mfu) / len(mfu), 5),
            "max": round(max(mfu), 5),
        }
    throughputs = [r["imgs_per_sec"] for r in steps if "imgs_per_sec" in r]
    if throughputs:
        summary["imgs_per_sec"] = {
            "last": round(throughputs[-1], 2),
            "mean": round(sum(throughputs) / len(throughputs), 2),
        }
    if hbm:
        summary["hbm_high_water_bytes"] = int(max(hbm))
    if rss:
        summary["host_rss_high_water_bytes"] = int(max(rss))
    # input-pipeline snapshots are cumulative — the LAST one (run_end wins
    # over the last sampled step) summarizes the whole run
    input_snaps = [r["input"] for r in steps if isinstance(r.get("input"), dict)]
    if run_ends and isinstance(run_ends[-1].get("input"), dict):
        input_snaps.append(run_ends[-1]["input"])
    if input_snaps:
        summary["input"] = input_snaps[-1]
    if pods:
        spreads = [
            p["step_s_max"] - p["step_s_min"]
            for p in pods
            if "step_s_max" in p and "step_s_min" in p
        ]
        if spreads:
            summary["pod_step_spread_ms_max"] = round(max(spreads) * 1e3, 3)
    if supervisor:
        by_event: dict[str, int] = {}
        for r in supervisor:
            key = str(r.get("event", "unknown"))
            by_event[key] = by_event.get(key, 0) + 1
        exits = [r for r in supervisor if r.get("event") == "exit"]
        sup: dict = {
            "events": by_event,
            "launches": by_event.get("launch", 0),
            "restarts": by_event.get("restart", 0),
            # one kill may emit two records (sigterm escalation, then
            # sigkill); count children killed, not signals sent
            "kills": sum(1 for r in supervisor if r.get("event") == "kill"
                         and r.get("phase") != "sigkill"),
            "classifications": [str(r.get("classification", "?"))
                                for r in exits],
        }
        finals = [r for r in supervisor if r.get("event") in ("done", "give_up")]
        if finals:
            last = finals[-1]
            sup["outcome"] = str(last["event"])
            if "reason" in last:
                sup["reason"] = last["reason"]
        budgets = [r["budget_left"] for r in supervisor if "budget_left" in r]
        if budgets:
            sup["budget_left"] = budgets[-1]
        summary["supervisor"] = sup
        # elastic resize (ISSUE 11): the resize_* / mesh_change records ride
        # the same supervisor stream; fold them into their own section so a
        # resize reads as ONE incident (request → exit 49 → relaunch)
        resize_sec = _summarize_resize(supervisor)
        if resize_sec:
            summary["resize"] = resize_sec
    if serves and not fleet:
        # snapshots are cumulative; the last one summarizes the run.
        # With FLEET records present this section is suppressed: N
        # replicas each write their own cumulative stream, and "the last
        # merged snapshot" would present one arbitrary replica's
        # counters as the run's — the fleet section carries the honest
        # per-replica fold + served_total instead.
        last = serves[-1]
        summary["serve"] = {
            k: last[k]
            for k in ("requests", "served", "shed_overload", "shed_deadline",
                      "batch_errors", "batches", "occupancy_mean", "buckets",
                      "latency_ms", "queue_wait_ms", "cache", "draining",
                      "uptime_s")
            if k in last
        }
        summary["serve"]["snapshots"] = len(serves)
    if fleet:
        summary["fleet"] = _summarize_fleet(fleet, serves)
    if input_servers:
        summary["input_servers"] = _summarize_input_servers(input_servers)
    if banks:
        summary["bank"] = _summarize_bank(banks)
    health_sec = _summarize_health(steps, events)
    if health_sec:
        summary["health"] = health_sec
    if slos:
        summary["slo"] = _summarize_slo(slos)
    if run_ends:
        summary["run_end"] = run_ends[-1]
    return summary


_ADDITIVE_SERVER_STATS = ("shards", "streamed_mb", "decode_s",
                          "credit_stall_s", "wall_s", "errors",
                          "decode_failures", "decode_total")


def _summarize_input_servers(records: list[dict]) -> dict:
    """Fold the `kind:"input_server"` records of the staging-server
    telemetry dirs (ISSUE 14): per server, the cumulative `stats`
    counters SUMMED across decode-worker lives (a relaunch restarts
    them from zero — detected as a counter decrease, the obsd
    counter-reset discipline — so the kill-drill report still counts
    every shard the pre-kill life served), latency p50/p95 and
    cache-hit rate from the last life, plus the supervisor half's
    lifecycle counts (launches/ejections/kills/death classes) — one
    story per server, totals across the pool."""
    by_server: dict[int, dict] = {}
    for r in records:
        sid = int(r.get("server_id", -1))
        entry = by_server.setdefault(sid, {"events": {}})
        event = str(r.get("event", "?"))
        if event == "stats":
            snap = {
                k: r[k]
                for k in ("shards", "streamed_mb", "shard_s_p50",
                          "shard_s_p95", "decode_s", "credit_stall_s",
                          "wall_s", "errors", "connections",
                          "connections_peak", "cache_hit_rate",
                          "decode_failures", "decode_total")
                if k in r
            }
            prev = entry.get("stats")
            pid, prev_pid = r.get("pid"), entry.get("_stats_pid")
            if prev is not None:
                if pid is not None and prev_pid is not None:
                    # exact: a relaunch changes the worker pid — catches
                    # a new life whose first snapshot already exceeds
                    # the old life's last (counters never decreased)
                    relaunched = pid != prev_pid
                else:  # legacy records without pid: counter decrease
                    relaunched = (
                        snap.get("wall_s", 0) < prev.get("wall_s", 0)
                        or snap.get("shards", 0) < prev.get("shards", 0))
                if relaunched:
                    base = entry.setdefault("_lives_base", {})
                    for k in _ADDITIVE_SERVER_STATS:
                        base[k] = base.get(k, 0) + prev.get(k, 0)
            entry["_stats_pid"] = pid
            entry["stats"] = snap
        else:
            entry["events"][event] = entry["events"].get(event, 0) + 1
            if event == "worker_exit" and "classification" in r:
                entry.setdefault("death_classes", []).append(
                    str(r["classification"]))
    servers = {}
    totals = {"shards": 0, "streamed_mb": 0.0, "errors": 0}
    for sid in sorted(by_server):
        entry = by_server[sid]
        stats = entry.get("stats", {})
        entry.pop("_stats_pid", None)
        base = entry.pop("_lives_base", None)
        if base:
            stats = dict(stats)
            for k, v in base.items():
                stats[k] = round(v + stats.get(k, 0), 3)
            entry["stats"] = stats
        totals["shards"] += stats.get("shards", 0)
        totals["streamed_mb"] += stats.get("streamed_mb", 0.0)
        totals["errors"] += stats.get("errors", 0)
        servers[str(sid)] = entry
    return {"servers": servers, "totals": totals,
            "n_servers": len(servers)}


def _summarize_bank(banks: list[dict]) -> dict:
    """Fold the `kind:"bank"` lifecycle stream (ISSUE 16): builder
    progress (build_start/shard_done/build_done), each replica's atomic
    dual `swap`, and the fleet's `bank_waiting`/`quarantine`/
    `bank_quarantine`/`rollback`. Event names normalize to the same
    `bank_` prefix obsd uses at ingest, so the section's counters match
    `event:bank_*` SLO objectives line for line."""
    by_event: dict[str, int] = {}
    last_swap = None
    last_build = None
    for r in banks:
        name = str(r.get("event", "unknown"))
        if not name.startswith("bank"):
            name = "bank_" + name
        by_event[name] = by_event.get(name, 0) + 1
        if name == "bank_swap":
            last_swap = r
        elif name == "bank_build_done":
            last_build = r
    sec: dict = {
        "events": dict(sorted(by_event.items())),
        "builds": by_event.get("bank_build_done", 0),
        "swaps": by_event.get("bank_swap", 0),
        "quarantines": by_event.get("bank_quarantine", 0),
        "rollbacks": by_event.get("bank_rollback", 0),
    }
    if last_build is not None:
        sec["last_build"] = {
            k: last_build[k]
            for k in ("step", "rows", "feat_dim", "shards",
                      "manifest_sha256")
            if k in last_build
        }
    if last_swap is not None:
        sec["last_swap"] = {
            k: last_swap[k]
            for k in ("step", "bank_step", "rows", "generation",
                      "agreement")
            if k in last_swap
        }
        step, bank_step = last_swap.get("step"), last_swap.get("bank_step")
        if (isinstance(step, (int, float))
                and isinstance(bank_step, (int, float))):
            sec["age_steps"] = int(step - bank_step)
    return sec


def _summarize_health(steps: list[dict], events: list[dict]) -> dict | None:
    """Fold the learning-health story (ISSUE 13): the `health` blocks the
    driver stamps onto health-stride step records (in-graph collapse
    diagnostics — telemetry/health.py documents each key) plus the
    CollapseSentinel's `health` incident/recovery events. None when the
    run carried neither (health_stride=0 and no sentinel armed)."""
    blocks = [(r.get("step"), r["health"]) for r in steps
              if isinstance(r.get("health"), dict)]
    incidents = [e for e in events if e.get("event") == "health"]
    recoveries = [e for e in events if e.get("event") == "health_recovered"]
    if not blocks and not incidents and not recoveries:
        return None
    sec: dict = {"samples": len(blocks)}
    if blocks:
        sec["last"] = dict(blocks[-1][1])
        # collapse is a FLOOR violation: the window's worst (lowest)
        # margin/std tells the story the last sample can hide
        for key in ("logit_margin", "emb_std_q", "emb_std_k",
                    "qnorm_min", "acc1"):
            vals = [b[key] for _, b in blocks
                    if isinstance(b.get(key), (int, float))]
            if vals:
                sec.setdefault("min", {})[key] = min(vals)
    if incidents or recoveries:
        sec["incidents"] = {
            "fired": len(incidents),
            "recovered": len(recoveries),
            "predicates": [
                {k: e[k] for k in ("predicate", "step", "value",
                                   "threshold", "window") if k in e}
                for e in incidents[-8:]
            ],
        }
    return sec


def _summarize_slo(slos: list[dict]) -> dict:
    """Fold the `kind:"slo"` records obsd (ISSUE 12) appended into the
    stream: per-rule alert/recovery counts + whether the LAST transition
    left the rule alerting (the stream is ordered, so last wins)."""
    by_rule: dict[str, dict] = {}
    for r in slos:
        rule = str(r.get("rule", "?"))
        entry = by_rule.setdefault(rule, {
            "alerts": 0, "recoveries": 0, "active": False,
        })
        action = r.get("action")
        if action == "alert":
            entry["alerts"] += 1
            entry["active"] = True
        elif action == "recover":
            entry["recoveries"] += 1
            entry["active"] = False
        for k in ("objective", "threshold", "severity"):
            if k in r:
                entry[k] = r[k]
        if "value_fast" in r:
            entry["last_value"] = r["value_fast"]
    return {
        "alerts": sum(e["alerts"] for e in by_rule.values()),
        "recoveries": sum(e["recoveries"] for e in by_rule.values()),
        "active": sorted(r for r, e in by_rule.items() if e["active"]),
        "by_rule": by_rule,
    }


def _summarize_resize(supervisor: list[dict]) -> dict | None:
    """Fold resize_request / resize_relaunch / mesh_change supervisor
    records into one `resize` section. None when the run saw none."""
    requests = [r for r in supervisor if r.get("event") == "resize_request"]
    relaunches = [r for r in supervisor
                  if r.get("event") == "resize_relaunch"]
    mesh_changes = [r for r in supervisor if r.get("event") == "mesh_change"]
    reverts = [r for r in supervisor if r.get("event") == "resize_revert"]
    if not (requests or relaunches or mesh_changes or reverts):
        return None
    sec: dict = {
        "requests": len(requests),
        "relaunches": len(relaunches),
        "mesh_changes": len(mesh_changes),
    }
    if reverts:
        sec["reverts"] = len(reverts)
    transitions = []
    for r in relaunches:
        t = {k: r[k] for k in ("devices_from", "devices_to", "step",
                               "grad_sync_cadence", "source") if k in r}
        transitions.append(t)
    if transitions:
        sec["transitions"] = transitions
    return sec


def _summarize_fleet(fleet: list[dict], serves: list[dict]) -> dict:
    """Fold the `kind: "fleet"` lifecycle stream (ISSUE 10) + each
    replica's own serve snapshots (grouped by the `_src` tag the
    multi-dir loader stamps) into one section."""
    by_event: dict[str, int] = {}
    per_replica: dict[int, dict] = {}
    for r in fleet:
        event = str(r.get("event", "unknown"))
        by_event[event] = by_event.get(event, 0) + 1
        idx = r.get("replica")
        if idx is None:
            continue
        rep = per_replica.setdefault(int(idx), {
            "launches": 0, "restarts": 0, "kills": 0, "ejections": 0,
            "readmissions": 0, "reloads": 0, "classifications": [],
        })
        if event == "launch":
            rep["launches"] += 1
            rep["restarts"] = max(rep["launches"] - 1, 0)
        elif event == "kill" and r.get("phase") != "sigkill":
            rep["kills"] += 1  # one kill decision, not one per signal
        elif event == "eject":
            rep["ejections"] += 1
        elif event == "readmit":
            rep["readmissions"] += 1
        elif event == "reload_replica" and r.get("status") == "ok":
            rep["reloads"] += 1
        elif event == "replica_exit":
            rep["classifications"].append(str(r.get("classification", "?")))
    sec: dict = {"events": by_event, "replicas": per_replica}
    starts = [r for r in fleet if r.get("event") == "fleet_start"]
    if starts:
        sec["size"] = starts[-1].get("replicas")
    stats = [r for r in fleet if r.get("event") == "router_stats"]
    if stats:
        last = stats[-1]
        router = {
            k: last[k]
            for k in ("requests", "ok", "retries", "retry_ok",
                      "shed_no_backend", "upstream_timeout",
                      "upstream_error", "shed_deadline_router",
                      "passthrough_non_200", "healthy",
                      # ISSUE 12 autoscaler-schema fields
                      "outstanding", "latency_ms", "window", "interval_s",
                      # ISSUE 20 tier/sharded-kNN fields
                      "requests_interactive", "requests_batch",
                      "knn_fanout", "knn_partial", "ann_shards",
                      "knn_merge_ms")
            if k in last
        }
        reqs = router.get("requests", 0)
        shed = (router.get("shed_no_backend", 0)
                + router.get("upstream_timeout", 0)
                + router.get("upstream_error", 0)
                + router.get("shed_deadline_router", 0))
        router["shed_rate"] = round(shed / reqs, 4) if reqs else 0.0
        fanout = router.get("knn_fanout", 0)
        if fanout:
            router["knn_partial_rate"] = round(
                router.get("knn_partial", 0) / fanout, 4)
        sec["router"] = router
    # autoscale lifecycle (ISSUE 20): the actions and the last reason
    scaled = [r for r in fleet
              if str(r.get("event", "")).startswith("autoscale_")]
    if scaled:
        counts: dict[str, int] = {}
        for r in scaled:
            name = str(r.get("event"))
            counts[name] = counts.get(name, 0) + 1
        sec["autoscale"] = {
            "events": counts,
            "last": {k: scaled[-1][k]
                     for k in ("event", "replica", "shard", "reason",
                               "replicas", "t")
                     if k in scaled[-1]},
        }
    reload_events = ("reload_detected", "reload_replica", "reload_done",
                     "reload_failed", "reload_quarantine",
                     "reload_bad_layout")
    history = [
        {k: r[k] for k in ("event", "step", "replica", "reason", "status",
                           "path", "t") if k in r}
        for r in fleet if r.get("event") in reload_events
    ]
    if history:
        sec["reload_history"] = history[-32:]
    # each replica's OWN last serve snapshot (cumulative): the single-file
    # `serve:` section can't tell N servers apart
    by_src: dict[str, dict] = {}
    for s in serves:
        src = s.get("_src")
        if src:
            by_src[src] = s
    if by_src:
        sec["serve_by_replica"] = {
            src: {
                k: snap[k]
                for k in ("requests", "served", "shed_overload",
                          "shed_deadline", "batches", "occupancy_mean",
                          "reloads")
                if k in snap
            }
            for src, snap in sorted(by_src.items())
        }
        sec["served_total"] = sum(
            s.get("served", 0) for s in by_src.values()
        )
    return sec


def fold_programs(summary: dict, inventory: dict) -> dict:
    """Fold a progcheck program inventory (`python -m tools.progcheck
    --inventory`, ISSUE 9) into the summary: program counts, per-mode
    gradsync payload, and the MFU cross-check — XLA `cost_analysis` FLOPs
    vs the MFUEstimator's analytic count for the same proxy program, so a
    drift in the analytic model (the numerator every reported MFU rests
    on) is visible next to the compiler's own arithmetic."""
    progs = inventory.get("programs", [])
    sec: dict = {
        "count": inventory.get("program_count", len(progs)),
        "mesh_size": inventory.get("mesh_size"),
        "by_family": inventory.get("by_family", {}),
    }
    sync = {
        p["mode"]: p["sync_bytes_per_step"]
        for p in progs
        if p.get("family") == "gradsync" and "sync_bytes_per_step" in p
    }
    if sync:
        sec["gradsync_bytes_per_step"] = sync
    cross = [
        {
            "name": p["name"],
            "cost_analysis_flops": p["flops"],
            "analytic_flops": p["analytic_flops"],
            "ratio": p.get("flops_vs_analytic"),
        }
        for p in progs
        if p.get("flops") is not None and p.get("analytic_flops")
    ]
    if cross:
        sec["mfu_cross_check"] = cross
    summary["programs"] = sec
    return summary


def render(summary: dict) -> str:
    """Human-readable report from a summarize() dict."""
    lines = []
    run = summary.get("run", {})
    if run:
        lines.append(
            "run: {name} ({variant}/{arch}) batch={batch_size} "
            "chips={n_chips} procs={n_procs}".format(
                **{k: run.get(k, "?") for k in
                   ("name", "variant", "arch", "batch_size", "n_chips",
                    "n_procs")}
            )
        )
        if run.get("peak_flops_per_chip"):
            lines.append(
                f"  MFU basis: {run['peak_flops_per_chip'] / 1e12:.0f} "
                f"TFLOP/s/chip peak, {run.get('flops_per_step', 0) / 1e9:.2f} "
                f"GFLOP/step analytic"
            )
    lines.append(
        f"records: {summary['records']} ({summary['steps']} steps, "
        f"{summary['runs']} run(s), {summary['pod_records']} pod, "
        f"{summary['skipped_lines']} unparseable line(s) skipped)"
    )
    pct = summary.get("step_time_ms")
    if pct:
        lines.append(
            f"step time: p50 {pct['p50']:.1f} ms · p95 {pct['p95']:.1f} ms "
            f"· p99 {pct['p99']:.1f} ms"
        )
        share = summary.get("phase_share", {})
        lines.append(
            f"  phase share: data {100 * share.get('data', 0):.1f}% · "
            f"host {100 * share.get('host', 0):.1f}% (rest: async device/meters)"
        )
    dev = summary.get("device_time_ms")
    if dev:
        lines.append(
            f"device drain (fenced, {dev['samples']} samples): "
            f"p50 {dev['p50']:.1f} ms · max {dev['max']:.1f} ms"
        )
    gs = summary.get("grad_sync")
    if gs:
        extras = []
        if "bucket_mb" in gs:
            extras.append(f"{gs['bucket_mb']} MiB × {gs.get('buckets', '?')} "
                          "buckets")
        if "quant_dtype" in gs:
            extras.append(str(gs["quant_dtype"]))
        if "cadence" in gs:
            extras.append(f"top-{100 * gs.get('topk', 0):.1f}% every "
                          f"{gs['cadence']} step(s)")
        lines.append(
            f"grad sync: {gs.get('mode', '?')} · "
            f"{gs.get('sync_bytes_per_step', 0) / 2**20:.2f} MiB/step/device"
            + (f" ({', '.join(extras)})" if extras else "")
        )
    comm = summary.get("comm")
    if comm:
        lines.append(
            f"  comm phase (fenced, {comm['samples']} samples): "
            f"p50 {comm['p50_ms']:.1f} ms · max {comm['max_ms']:.1f} ms · "
            f"share {100 * comm['share_mean']:.1f}%"
        )
    sh = summary.get("sharding")
    if sh:
        mesh = sh.get("mesh_shape")
        mesh_txt = ("×".join(f"{k}={v}" for k, v in mesh.items())
                    if isinstance(mesh, dict) else "?")
        lines.append(
            f"sharding: {sh.get('mode', '?')} (mesh {mesh_txt}) · "
            f"params {sh.get('param_bytes_per_device', 0) / 2**20:.2f} "
            f"MiB/device · opt "
            f"{sh.get('opt_bytes_per_device', 0) / 2**20:.2f} MiB/device"
        )
    mfu = summary.get("mfu")
    if mfu:
        label = ""
        if sh and sh.get("mode") and sh.get("mode") != "dp":
            # ISSUE 15 satellite: MFU is reported per sharding mode — the
            # FLOPs basis is layout-invariant, the label says what layout
            # achieved it
            label = f" [{sh['mode']}]"
        lines.append(f"MFU{label}: mean {100 * mfu['mean']:.2f}% · "
                     f"max {100 * mfu['max']:.2f}%")
    elif summary["steps"]:
        # only a TRAINING stream can owe an MFU; a serve-only events file
        # (zero step records) has nothing to apologize for
        lines.append(
            "MFU: n/a (no peak-FLOPs basis for this device_kind — re-run "
            "training with peak_flops_per_chip set in the config)"
        )
    thr = summary.get("imgs_per_sec")
    if thr:
        lines.append(
            f"throughput: {thr['last']:.1f} imgs/s (rolling, end of run) · "
            f"{thr['mean']:.1f} mean"
        )
    if "hbm_high_water_bytes" in summary:
        lines.append(
            f"HBM high-water: {summary['hbm_high_water_bytes'] / 2**30:.2f} GiB"
        )
    if "host_rss_high_water_bytes" in summary:
        lines.append(
            f"host RSS high-water: "
            f"{summary['host_rss_high_water_bytes'] / 2**30:.2f} GiB"
        )
    inp = summary.get("input")
    if inp:
        lines.append(
            f"input: {inp.get('staged_batches', 0)} staged batches "
            f"({inp.get('staged_mb', 0):.0f} MiB) · queue depth mean "
            f"{inp.get('queue_depth_mean', 0):.2f} · "
            f"{inp.get('workers', 1)} worker(s) busy "
            f"{100 * inp.get('worker_busy_frac', 0):.1f}%"
        )
        lines.append(
            f"  staged-batch latency: p50 "
            f"{1e3 * inp.get('staged_batch_s_p50', 0):.1f} ms · p95 "
            f"{1e3 * inp.get('staged_batch_s_p95', 0):.1f} ms"
        )
        if "cache_hit_rate" in inp:
            lines.append(
                f"  decode-once cache: {100 * inp['cache_hit_rate']:.1f}% hit "
                f"({inp.get('cache_hits', 0)} hit / "
                f"{inp.get('cache_misses', 0)} miss)"
            )
        if inp.get("wall_s"):
            lines.append(
                f"  credit stalls: {inp.get('credit_stall_s', 0):.1f} s "
                f"blocked on an empty ready queue "
                f"({100 * inp.get('credit_stall_s', 0) / inp['wall_s']:.1f}% "
                f"of {inp['wall_s']:.0f} s)"
            )
    isv = summary.get("input_servers")
    if isv:
        tot = isv.get("totals", {})
        lines.append(
            f"input service: {isv.get('n_servers', 0)} staging server(s) · "
            f"{tot.get('shards', 0)} shards "
            f"({tot.get('streamed_mb', 0):.0f} MiB streamed, "
            f"{tot.get('errors', 0)} error(s))"
        )
        for sid, entry in sorted(isv.get("servers", {}).items(),
                                 key=lambda kv: int(kv[0])):
            stats = entry.get("stats", {})
            parts = [f"  server {sid}:"]
            if stats:
                parts.append(
                    f"{stats.get('shards', 0)} shards · shard p50 "
                    f"{1e3 * stats.get('shard_s_p50', 0):.1f} ms / p95 "
                    f"{1e3 * stats.get('shard_s_p95', 0):.1f} ms · "
                    f"{stats.get('streamed_mb', 0):.0f} MiB"
                )
                if "cache_hit_rate" in stats:
                    parts.append(
                        f"· cache {100 * stats['cache_hit_rate']:.1f}% hit")
                if stats.get("decode_failures"):
                    parts.append(
                        f"· DECODE FAILURES "
                        f"{stats['decode_failures']}/"
                        f"{stats.get('decode_total', 0)} (zero canvases "
                        "served — the train host cannot see these)"
                    )
                if stats.get("wall_s"):
                    # credit_stall_s accumulates CONCURRENTLY across the
                    # client connections: normalize per connection or a
                    # healthy 4-stream run renders a nonsense 360%. Peak,
                    # not the live gauge — the final snapshot lands after
                    # clients disconnected (gauge back at 0)
                    conns = max(int(stats.get("connections_peak")
                                    or stats.get("connections", 1)
                                    or 1), 1)
                    parts.append(
                        f"· idle-for-credit "
                        f"{100 * stats.get('credit_stall_s', 0) / (stats['wall_s'] * conns):.0f}%/conn"
                    )
            ev = entry.get("events", {})
            life = []
            for key in ("launch", "eject", "kill", "worker_exit",
                        "give_up"):
                if ev.get(key):
                    life.append(f"{key}×{ev[key]}")
            if life:
                parts.append("· " + " ".join(life))
            if entry.get("death_classes"):
                parts.append(
                    "(" + ", ".join(entry["death_classes"]) + ")")
            lines.append(" ".join(parts))
    if "pod_step_spread_ms_max" in summary:
        lines.append(
            f"pod: {summary['pod_records']} records, worst cross-host step "
            f"spread {summary['pod_step_spread_ms_max']:.1f} ms"
        )
    sup = summary.get("supervisor")
    if sup:
        outcome = sup.get("outcome", "running")
        lines.append(
            f"supervisor: {sup['launches']} launch(es), {sup['restarts']} "
            f"restart(s), {sup['kills']} kill(s) — {outcome}"
            + (f" ({sup['reason']})" if sup.get("reason") else "")
        )
        if sup["classifications"]:
            counts: dict[str, int] = {}
            for c in sup["classifications"]:
                counts[c] = counts.get(c, 0) + 1
            detail = ", ".join(f"{k}×{v}" for k, v in sorted(counts.items()))
            lines.append(f"  death classifications: {detail}")
        if "budget_left" in sup:
            lines.append(f"  restart budget left: {sup['budget_left']}")
    rsz = summary.get("resize")
    if rsz:
        hops = []
        for t in rsz.get("transitions", ()):
            frm = t.get("devices_from")
            arrow = (f"{'?' if frm is None else frm}→"
                     f"{t.get('devices_to') or 'visible'}")
            if "step" in t:
                arrow += f"@{t['step']}"
            if "grad_sync_cadence" in t:
                arrow += f" (cadence {t['grad_sync_cadence']})"
            hops.append(arrow)
        lines.append(
            f"resize: {rsz['relaunches']} relaunch(es) from "
            f"{rsz['requests']} request(s)"
            + (f" — {' · '.join(hops)}" if hops else "")
            + (f" · {rsz['reverts']} reverted (unbootable argv)"
               if rsz.get("reverts") else "")
        )
        if rsz.get("mesh_changes"):
            lines.append(
                f"  mesh changes observed at relaunch preflight: "
                f"{rsz['mesh_changes']}"
            )
    srv = summary.get("serve")
    if srv:
        shed = srv.get("shed_overload", 0) + srv.get("shed_deadline", 0)
        lines.append(
            f"serve: {srv.get('requests', 0)} requests "
            f"({srv.get('served', 0)} served, {shed} shed: "
            f"{srv.get('shed_overload', 0)} overload / "
            f"{srv.get('shed_deadline', 0)} deadline, "
            f"{srv.get('batch_errors', 0)} batch error(s))"
        )
        lat = srv.get("latency_ms", {})
        if lat:
            lines.append(
                f"  latency: p50 {lat.get('p50', 0):.1f} ms · "
                f"p95 {lat.get('p95', 0):.1f} ms · p99 {lat.get('p99', 0):.1f} ms"
            )
        lines.append(
            f"  batches: {srv.get('batches', 0)} over buckets "
            f"{srv.get('buckets', [])} · occupancy mean "
            f"{100 * srv.get('occupancy_mean', 0):.1f}%"
        )
        cache = srv.get("cache")
        if cache:
            lines.append(
                f"  embed cache: {100 * cache.get('hit_rate', 0):.1f}% hit "
                f"({cache.get('hits', 0)} hit / {cache.get('misses', 0)} "
                f"miss, {cache.get('entries', 0)} entries)"
            )
        tiers = srv.get("tiers")
        if tiers:
            per = " · ".join(
                f"{t} {c.get('submitted', 0)} submitted "
                f"({c.get('shed_overload', 0)}+{c.get('shed_deadline', 0)} "
                f"shed)"
                for t, c in sorted(tiers.items())
            )
            lines.append(f"  tiers: {per}")
        ann = srv.get("ann")
        if ann:
            recall = ann.get("recall_probe")
            lines.append(
                f"ann: shard {ann.get('shard', 0)}/{ann.get('shards', 1)} "
                f"— {ann.get('owned_rows', '?')} rows in "
                f"{ann.get('cells', '?')} cells (nprobe "
                f"{ann.get('nprobe', '?')}, rerank {ann.get('rerank', '?')})"
                + (f" · recall@1 probe {recall:.4f}"
                   if isinstance(recall, (int, float)) else "")
                + f" · {ann.get('candidate_calls', 0)} candidate call(s)"
            )
    flt = summary.get("fleet")
    if flt:
        router = flt.get("router", {})
        lines.append(
            f"fleet: {flt.get('size', len(flt.get('replicas', {})))} "
            f"replica(s) · router {router.get('requests', 0)} requests "
            f"({router.get('retries', 0)} retried, shed rate "
            f"{100 * router.get('shed_rate', 0):.2f}%)"
        )
        lat = router.get("latency_ms")
        if lat:
            lines.append(
                f"  router latency (window {router.get('window', '?')}): "
                f"p50 {lat.get('p50', 0):.1f} ms · "
                f"p95 {lat.get('p95', 0):.1f} ms · "
                f"p99 {lat.get('p99', 0):.1f} ms · outstanding "
                f"{router.get('outstanding', 0)}"
            )
        if "requests_interactive" in router or "requests_batch" in router:
            lines.append(
                f"  tiers: {router.get('requests_interactive', 0)} "
                f"interactive / {router.get('requests_batch', 0)} batch"
            )
        if router.get("knn_fanout"):
            merge = router.get("knn_merge_ms") or {}
            lines.append(
                f"  knn fan-out ({router.get('ann_shards', '?')} shards): "
                f"{router['knn_fanout']} scatter(s), "
                f"{router.get('knn_partial', 0)} partial "
                f"({100 * router.get('knn_partial_rate', 0):.2f}%)"
                + (f" · merge p95 {merge.get('p95', 0):.1f} ms"
                   if merge else "")
            )
        scale = flt.get("autoscale")
        if scale:
            counts = scale.get("events", {})
            last = scale.get("last", {})
            lines.append(
                "autoscale: "
                + " · ".join(f"{k.replace('autoscale_', '')} ×{v}"
                             for k, v in sorted(counts.items()))
                + (f" — last: {last.get('event', '?')} replica "
                   f"{last.get('replica', '?')} ({last.get('reason', '')})"
                   if last else "")
            )
        for idx, rep in sorted(flt.get("replicas", {}).items()):
            counts: dict[str, int] = {}
            for c in rep["classifications"]:
                counts[c] = counts.get(c, 0) + 1
            deaths = ", ".join(f"{k}×{v}" for k, v in sorted(counts.items()))
            lines.append(
                f"  replica {idx}: {rep['launches']} launch(es), "
                f"{rep['restarts']} restart(s), {rep['kills']} kill(s), "
                f"{rep['ejections']} ejection(s)"
                + (f" — deaths: {deaths}" if deaths else "")
            )
        srv_by = flt.get("serve_by_replica")
        if srv_by:
            per = " · ".join(
                f"{src} {snap.get('served', 0)}/{snap.get('requests', 0)}"
                for src, snap in srv_by.items()
            )
            lines.append(
                f"  served (per replica, served/requests): {per} — "
                f"total {flt.get('served_total', 0)}"
            )
        history = flt.get("reload_history", [])
        done = [h for h in history if h["event"] == "reload_done"]
        quarantined = [h for h in history
                       if h["event"] == "reload_quarantine"]
        if history:
            lines.append(
                f"  reloads: {len(done)} deployed "
                f"({', '.join(str(h.get('step')) for h in done[-6:])})"
                + (f" · {len(quarantined)} quarantined "
                   f"({', '.join(str(h.get('step')) for h in quarantined[-6:])})"
                   if quarantined else "")
            )
    bank = summary.get("bank")
    if bank:
        lines.append(
            f"bank: {bank.get('builds', 0)} build(s) · "
            f"{bank.get('swaps', 0)} dual swap(s) · "
            f"{bank.get('quarantines', 0)} quarantine(s) · "
            f"{bank.get('rollbacks', 0)} rollback(s)"
        )
        lb = bank.get("last_build")
        if lb:
            lines.append(
                f"  last build: step {lb.get('step', '?')} — "
                f"{lb.get('rows', '?')} rows × {lb.get('feat_dim', '?')} "
                f"dims in {lb.get('shards', '?')} shard(s)"
            )
        ls = bank.get("last_swap")
        if ls:
            agree = ls.get("agreement")
            lines.append(
                f"  last swap: checkpoint step {ls.get('step', '?')} + "
                f"bank step {ls.get('bank_step', '?')} "
                f"(generation {ls.get('generation', '?')}"
                + (f", probe agreement {agree:.4f}"
                   if isinstance(agree, (int, float)) else "")
                + f") — bank age {bank.get('age_steps', '?')} step(s)"
            )
    health = summary.get("health")
    if health:
        last = health.get("last", {})
        parts = [f"health: {health['samples']} sample(s)"]
        if "logit_margin" in last:
            worst = health.get("min", {}).get("logit_margin")
            parts.append(
                f"margin {last['logit_margin']:.4f}"
                + (f" (min {worst:.4f})" if worst is not None else "")
            )
        if "emb_std_k" in last:
            parts.append(
                f"emb std q/k {last.get('emb_std_q', 0):.4f}/"
                f"{last['emb_std_k']:.4f}"
            )
        if "pdrift" in last:
            parts.append(f"q-k drift {last['pdrift']:.4f}")
        lines.append(" · ".join(parts))
        if "qnorm_mean" in last:
            lines.append(
                f"  queue: norm mean {last['qnorm_mean']:.4f} min "
                f"{last.get('qnorm_min', 0):.4f} · age "
                f"{last.get('qage_steps', 0):.0f} step(s)"
                + (f" · participation ratio {last['emb_pr_q']:.1f}"
                   if "emb_pr_q" in last else "")
            )
        inc = health.get("incidents")
        if inc:
            preds = ", ".join(
                f"{p.get('predicate', '?')}@{p.get('step', '?')}"
                for p in inc.get("predicates", ())
            )
            lines.append(
                f"  collapse incidents: {inc['fired']} fired"
                + (f" ({preds})" if preds else "")
                + f" · {inc['recovered']} recovered"
            )
    slo = summary.get("slo")
    if slo:
        active = slo.get("active", [])
        lines.append(
            f"slo: {slo.get('alerts', 0)} alert(s), "
            f"{slo.get('recoveries', 0)} recovery(ies)"
            + (f" — ACTIVE: {', '.join(active)}" if active
               else " — all clear")
        )
        for rule, e in sorted(slo.get("by_rule", {}).items()):
            detail = (f"{e.get('objective', '?')} vs "
                      f"{e.get('threshold', '?')}")
            if "last_value" in e:
                detail += f", last {e['last_value']}"
            lines.append(
                f"  {rule}: {e['alerts']} alert(s) / "
                f"{e['recoveries']} recovery(ies) ({detail})"
                + (" [ACTIVE]" if e.get("active") else "")
            )
    progs = summary.get("programs")
    if progs:
        fams = ", ".join(f"{k}×{v}" for k, v in
                         sorted(progs.get("by_family", {}).items()))
        lines.append(f"programs: {progs.get('count', 0)} audited ({fams})")
        sync = progs.get("gradsync_bytes_per_step")
        if sync:
            detail = " · ".join(f"{m} {b} B" for m, b in sorted(sync.items()))
            lines.append(f"  gradsync payload/step/device: {detail}")
        for c in progs.get("mfu_cross_check", ())[:4]:
            lines.append(
                f"  {c['name']}: cost_analysis "
                f"{c['cost_analysis_flops'] / 1e6:.1f} MFLOP vs analytic "
                f"{c['analytic_flops'] / 1e6:.1f} MFLOP"
                + (f" (×{c['ratio']:.2f})" if c.get("ratio") else "")
            )
    inc = summary.get("incidents", {})
    if inc:
        detail = ", ".join(f"{k}×{v}" for k, v in sorted(inc.items()))
        lines.append(f"incidents: {summary['incidents_total']} ({detail})")
    else:
        lines.append("incidents: none")
    routine = {
        k: v for k, v in summary.get("events_by_kind", {}).items()
        if k not in inc
    }
    if routine:
        detail = ", ".join(f"{k}×{v}" for k, v in sorted(routine.items()))
        lines.append(f"routine events: {detail}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# live tail (--follow)
# ---------------------------------------------------------------------------


def render_record(rec: dict) -> str | None:
    """One human line per record for the live tail; None for record kinds
    with no line-by-line story (pod vectors ride the summary)."""
    kind = rec.get("kind")
    if kind == "step":
        parts = [f"step {rec.get('step', '?'):>6}"]
        if "step_s" in rec:
            parts.append(f"{1e3 * rec['step_s']:8.1f} ms")
        share = []
        for phase in ("data_s", "host_s", "telemetry_s"):
            if phase in rec and rec.get("step_s"):
                share.append(
                    f"{phase[:-2]} {100 * rec[phase] / rec['step_s']:.0f}%"
                )
        if share:
            parts.append("(" + " · ".join(share) + ")")
        if "imgs_per_sec" in rec:
            parts.append(f"{rec['imgs_per_sec']:.1f} img/s")
        if "loss" in rec:
            parts.append(f"loss {rec['loss']:.4f}"
                         if isinstance(rec["loss"], float)
                         else f"loss {rec['loss']}")
        line = "  ".join(parts)
        health = rec.get("health")
        if isinstance(health, dict):
            # learning-health stride sample (ISSUE 13): its own tail line
            # so a margin sliding toward 0 jumps out of the step stream
            hp = [f"health: step {rec.get('step', '?'):>6}"]
            for key, label in (("logit_margin", "margin"),
                               ("emb_std_q", "std_q"),
                               ("emb_std_k", "std_k"),
                               ("qnorm_min", "qnorm_min"),
                               ("pdrift", "drift")):
                if isinstance(health.get(key), (int, float)):
                    hp.append(f"{label} {health[key]:.4f}")
            line += "\n" + "  ".join(hp)
        return line
    if kind == "event":
        name = rec.get("event", "?")
        detail = " ".join(
            f"{k}={v}" for k, v in rec.items()
            if k not in ("v", "t", "kind", "event", "msg", "run_id",
                         "trace_id")
        )
        msg = rec.get("msg", "")
        return f"[{name}] {msg}{' ' if msg and detail else ''}{detail}".rstrip()
    if kind == "supervisor":
        detail = " ".join(
            f"{k}={v}" for k, v in rec.items()
            if k not in ("v", "t", "kind", "event", "run_id", "trace_id")
        )
        event = str(rec.get("event", "?"))
        if event.startswith("resize") or event == "mesh_change":
            # elastic transitions get their own live-tail prefix (ISSUE 11
            # satellite), same as fleet lines — a resize in progress should
            # jump out of the step stream
            return f"resize: {event} {detail}".rstrip()
        return f"supervisor: {event} {detail}".rstrip()
    if kind == "fleet":
        detail = " ".join(
            f"{k}={v}" for k, v in rec.items()
            if k not in ("v", "t", "kind", "event", "run_id", "trace_id")
        )
        return f"fleet: {rec.get('event', '?')} {detail}".rstrip()
    if kind == "bank":
        # bank lifecycle (ISSUE 16): a build/swap/quarantine/rollback in
        # progress gets the fleet-style detail line
        detail = " ".join(
            f"{k}={v}" for k, v in rec.items()
            if k not in ("v", "t", "kind", "event", "run_id", "trace_id")
        )
        return f"bank: {rec.get('event', '?')} {detail}".rstrip()
    if kind == "input_server":
        # staging-server stream (ISSUE 14): stats snapshots get a compact
        # throughput line, lifecycle transitions the fleet-style detail
        sid = rec.get("server_id", "?")
        if rec.get("event") == "stats":
            return (
                f"input: server {sid} {rec.get('shards', 0)} shards · "
                f"p50 {1e3 * rec.get('shard_s_p50', 0):.1f} ms · "
                f"{rec.get('streamed_mb', 0):.0f} MiB · "
                f"{rec.get('errors', 0)} error(s)"
            )
        detail = " ".join(
            f"{k}={v}" for k, v in rec.items()
            if k not in ("v", "t", "kind", "event", "run_id", "trace_id",
                         "server_id")
        )
        return f"input: server {sid} {rec.get('event', '?')} {detail}".rstrip()
    if kind == "slo":
        # obsd transitions (ISSUE 12): an alert in progress must jump out
        # of the step stream the way resize/fleet lines do
        action = str(rec.get("action", "?")).upper()
        parts = [f"slo: {action} {rec.get('rule', '?')}"]
        if "value_fast" in rec:
            parts.append(
                f"{rec.get('objective', '?')}={rec['value_fast']} "
                f"(slow {rec.get('value_slow', '?')}) "
                f"{rec.get('op', '>')} {rec.get('threshold', '?')}"
            )
        if "run_id" in rec:
            parts.append(f"run={rec['run_id']}")
        return " ".join(parts)
    if kind == "serve":
        lat = rec.get("latency_ms") or {}
        line = (
            f"serve: {rec.get('served', 0)}/{rec.get('requests', 0)} served"
            f" · p95 {lat.get('p95', 0):.1f} ms · queue "
            f"{rec.get('queue_depth', 0)}"
        )
        ann = rec.get("ann")
        if isinstance(ann, dict):
            # sharded ANN (ISSUE 20): the recall probe rides the tail so
            # a quantizer degrading after a swap jumps out of the stream
            recall = ann.get("recall_probe")
            line += (
                f" · ann {ann.get('shard', 0)}/{ann.get('shards', 1)}"
                + (f" recall {recall:.3f}"
                   if isinstance(recall, (int, float)) else "")
            )
        return line
    if kind == "run_start":
        return (f"run_start: {rec.get('name', '?')} arch="
                f"{rec.get('arch', '?')} batch={rec.get('batch_size', '?')}"
                f" run_id={rec.get('run_id', '-')}")
    if kind == "run_end":
        return (f"run_end: {rec.get('steps', 0)} steps, "
                f"{rec.get('incidents', 0)} incident(s)")
    return None


def follow(path: str, out=None, poll_secs: float = 0.5, stop=None,
           from_start: bool = True) -> int:
    """Tail `path`, rendering records as complete lines land. Returns the
    number of records rendered (useful for tests; the CLI runs until
    interrupted). `stop` is an optional threading.Event-like object."""
    out = out or sys.stdout
    rendered = 0
    offset = 0
    buffer = b""
    if not from_start:
        try:
            offset = os.path.getsize(path)
        except OSError:
            offset = 0
    while stop is None or not stop.is_set():
        try:
            size = os.path.getsize(path)
        except OSError:
            time.sleep(poll_secs)  # not created yet (child still booting)
            continue
        if size < offset:  # truncated/rotated: start over
            offset, buffer = 0, b""
        if size > offset:
            with open(path, "rb") as f:
                f.seek(offset)
                chunk = f.read()
            offset += len(chunk)
            buffer += chunk
            # partial-line safety: only lines TERMINATED by a newline are
            # parsed; the unterminated tail waits for its next chunk
            *complete, buffer = buffer.split(b"\n")
            for raw in complete:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    rec = json.loads(raw.decode("utf-8", errors="replace"))
                except json.JSONDecodeError:
                    continue
                if not isinstance(rec, dict):
                    continue
                line = render_record(rec)
                if line is not None:
                    print(line, file=out, flush=True)
                    rendered += 1
        else:
            time.sleep(poll_secs)
    return rendered


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    parser.add_argument("events",
                        help="path to telemetry events.jsonl, or a fleet "
                             "telemetry DIRECTORY (merges its "
                             "events.jsonl + replica*/events.jsonl)")
    parser.add_argument("--json", action="store_true",
                        help="emit one machine-readable summary object")
    parser.add_argument("--follow", action="store_true",
                        help="live-tail: render step/incident/supervisor "
                             "lines as they land (ctrl-C to stop)")
    parser.add_argument("--poll-secs", type=float, default=0.5,
                        help="--follow poll cadence")
    parser.add_argument("--programs", default=None, metavar="INVENTORY",
                        help="progcheck --inventory JSON to fold in "
                             "(program counts, gradsync payload, MFU "
                             "cross-check)")
    args = parser.parse_args(argv)
    if args.follow:
        path = args.events
        if os.path.isdir(path):  # fleet dir: follow the fleet's own stream
            path = os.path.join(path, "events.jsonl")
        try:
            follow(path, poll_secs=args.poll_secs)
        except KeyboardInterrupt:
            pass
        return 0
    try:
        records, skipped = load_events_multi(expand_events_arg(args.events))
    except OSError as e:
        print(f"cannot read {args.events}: {e}", file=sys.stderr)
        return 2
    summary = summarize(records, skipped)
    if args.programs:
        try:
            with open(args.programs, encoding="utf-8") as f:
                fold_programs(summary, json.load(f))
        except (OSError, json.JSONDecodeError, ValueError) as e:
            print(f"cannot read program inventory {args.programs}: {e}",
                  file=sys.stderr)
            return 2
    if args.json:
        print(json.dumps(summary, default=float))
    else:
        print(render(summary))
    return 0 if summary["steps"] or summary["records"] else 1


if __name__ == "__main__":
    sys.exit(main())
