#!/usr/bin/env python
"""Merge spans + events from supervisor, driver and serve into ONE
Chrome-trace/Perfetto JSON timeline (ISSUE 8 tentpole part 2).

    python tools/trace_report.py runs/r1/telemetry
    python tools/trace_report.py runs/r1/telemetry runs/serve/telemetry \
        -o timeline.json
    python tools/trace_report.py runs/r1/telemetry --run <run_id> --json

Inputs are telemetry DIRS (each contributing its `spans.jsonl` and
`events.jsonl`) or explicit .jsonl files. Output:

  - a Chrome-trace JSON (`{"traceEvents": [...]}`) at `-o` (default
    `<first input dir>/trace.json`): one track per (process, thread) —
    "X" complete events for spans, "i" instant events for incidents and
    supervisor lifecycle records, "M" metadata naming each track from the
    span's `proc`/`thread` labels. Open in Perfetto (ui.perfetto.dev) or
    chrome://tracing.
  - a per-step critical-path summary on stdout (or one `--json` object):
    over the step spans, where the wall time went — data vs host vs
    telemetry vs (fenced) device/comm — and which phase dominates.

Everything joins on the `run_id` the supervisor minted and stamped down
through env vars (telemetry/trace.py): spans carry it natively, events
carry it since the registry stamp. `--run` filters to one run when a dir
accumulated several. Pure stdlib — runs anywhere the files can be copied.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

SPANS_FILENAME = "spans.jsonl"
EVENTS_FILENAME = "events.jsonl"

# events.jsonl kinds rendered as instant events on the timeline; `step`
# records are omitted (the step SPANS carry the same phases, with ids)
_INSTANT_KINDS = ("event", "supervisor", "run_start", "run_end",
                  "serve_start")


def load_jsonl(path: str) -> tuple[list[dict], int]:
    """Parse one JSONL file; (records, skipped_lines) — torn tails from a
    SIGKILL mid-flush are counted, never fatal."""
    records, skipped = [], 0
    try:
        f = open(path, encoding="utf-8")
    except OSError:
        return [], 0
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                skipped += 1
    return records, skipped


def collect(paths: list[str]) -> dict:
    """Gather spans + events from every input dir/file."""
    spans: list[dict] = []
    events: list[dict] = []
    skipped = 0
    for path in paths:
        if os.path.isdir(path):
            candidates = [os.path.join(path, SPANS_FILENAME),
                          os.path.join(path, EVENTS_FILENAME)]
        else:
            candidates = [path]
        for cand in candidates:
            records, bad = load_jsonl(cand)
            skipped += bad
            for rec in records:
                (spans if rec.get("kind") == "span" else events).append(rec)
    return {"spans": spans, "events": events, "skipped": skipped}


def _run_of(rec: dict) -> str:
    return str(rec.get("run") or rec.get("run_id") or "")


def filter_run(data: dict, run_id: str | None) -> dict:
    """Keep one run's records. Records with NO run id (events written by
    processes that predate the stamp, e.g. an old stream) are kept — a
    report must degrade, not discard evidence."""
    if not run_id:
        return data
    keep = lambda r: _run_of(r) in (run_id, "")  # noqa: E731
    return {
        "spans": [s for s in data["spans"] if keep(s)],
        "events": [e for e in data["events"] if keep(e)],
        "skipped": data["skipped"],
    }


def run_ids(data: dict) -> list[str]:
    seen: dict[str, None] = {}
    for rec in data["spans"] + data["events"]:
        rid = _run_of(rec)
        if rid:
            seen.setdefault(rid)
    return list(seen)


# ---------------------------------------------------------------------------
# Chrome-trace assembly
# ---------------------------------------------------------------------------


def to_chrome_trace(data: dict) -> dict:
    """`{"traceEvents": [...]}` — the one JSON both Perfetto and
    chrome://tracing load. Timestamps are wall-clock µs: every process
    stamped `time.time()`, so cross-process ordering is as honest as the
    host clocks (one host in this repo's topology)."""
    trace_events: list[dict] = []
    procs: dict[int, str] = {}
    threads: dict[tuple[int, int], str] = {}
    for s in data["spans"]:
        pid = int(s.get("pid", 0))
        tid = int(s.get("tid") or 0)
        procs.setdefault(pid, str(s.get("proc", f"pid {pid}")))
        threads.setdefault((pid, tid), str(s.get("thread", f"tid {tid}")))
        args = {
            "run_id": s.get("run"),
            "trace_id": s.get("trace"),
            "span_id": s.get("span"),
        }
        if s.get("parent"):
            args["parent_id"] = s["parent"]
        args.update(s.get("attrs") or {})
        dur_us = float(s.get("dur", 0.0)) * 1e6
        event = {
            "name": str(s.get("name", "?")),
            "cat": str(s.get("cat", "span")),
            "ts": float(s.get("t", 0.0)) * 1e6,
            "pid": pid,
            "tid": tid,
            "args": args,
        }
        if dur_us > 0:
            event["ph"] = "X"
            event["dur"] = dur_us
        else:  # zero-duration span (capture markers): an instant
            event["ph"] = "i"
            event["s"] = "t"
        trace_events.append(event)
    # events.jsonl incidents as process-scoped instants; the record's own
    # pid when it names one (supervisor records name the CHILD pid — keep
    # the supervisor's own records on a synthetic track per source kind)
    for e in data["events"]:
        kind = e.get("kind")
        if kind not in _INSTANT_KINDS:
            continue
        name = str(e.get("event", kind))
        pid = int(e.get("pid", 0)) if kind != "supervisor" else 0
        procs.setdefault(pid, "events" if pid == 0 else f"pid {pid}")
        threads.setdefault((pid, 0), str(kind))
        args = {k: v for k, v in e.items()
                if k not in ("v", "t", "kind") and _plain(v)}
        trace_events.append({
            "name": name,
            "cat": str(kind),
            "ph": "i",
            "s": "p",
            "ts": float(e.get("t", 0.0)) * 1e6,
            "pid": pid,
            "tid": 0,
            "args": args,
        })
    for pid, label in procs.items():
        trace_events.append({"ph": "M", "name": "process_name", "pid": pid,
                             "args": {"name": label}})
    for (pid, tid), label in threads.items():
        trace_events.append({"ph": "M", "name": "thread_name", "pid": pid,
                             "tid": tid, "args": {"name": label}})
    trace_events.sort(key=lambda ev: ev.get("ts", 0.0))
    return {"traceEvents": trace_events,
            "displayTimeUnit": "ms"}


def _plain(value) -> bool:
    return isinstance(value, (str, int, float, bool, type(None)))


# ---------------------------------------------------------------------------
# per-step critical-path summary
# ---------------------------------------------------------------------------

_PHASES = ("data_s", "host_s", "telemetry_s", "device_s", "comm_s")


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = max(0, min(len(ordered) - 1, round(q / 100.0 * (len(ordered) - 1))))
    return ordered[int(rank)]


def summarize(data: dict) -> dict:
    """Fold the merged records into the --json summary object."""
    spans = data["spans"]
    by_proc: dict[str, int] = {}
    for s in spans:
        key = str(s.get("proc", "?"))
        by_proc[key] = by_proc.get(key, 0) + 1
    step_spans = [s for s in spans if s.get("cat") == "step"]
    captures = [s for s in spans if s.get("cat") == "capture"]
    summary: dict = {
        "spans": len(spans),
        "spans_by_proc": by_proc,
        "events": len(data["events"]),
        "skipped_lines": data["skipped"],
        "run_ids": run_ids(data),
        "steps": len(step_spans),
    }
    if step_spans:
        attrs = [s.get("attrs") or {} for s in step_spans]
        step_s = [float(a.get("step_s", s.get("dur", 0.0)))
                  for a, s in zip(attrs, step_spans)]
        total = sum(step_s) or 1.0
        shares = {}
        for phase in _PHASES:
            vals = [float(a[phase]) for a in attrs if phase in a]
            if vals:
                shares[phase[:-2]] = round(sum(vals) / total, 4)
        # the phases are measured differently (data/host/telemetry are
        # wall segments of every step; device/comm are fenced drain
        # samples) — the dominant WALL segment is the critical path the
        # next perf PR should attack, with the fenced numbers as context
        wall = {k: v for k, v in shares.items()
                if k in ("data", "host", "telemetry")}
        summary["step_time_ms"] = {
            "p50": round(_percentile(step_s, 50) * 1e3, 3),
            "p95": round(_percentile(step_s, 95) * 1e3, 3),
            "p99": round(_percentile(step_s, 99) * 1e3, 3),
        }
        summary["phase_share"] = shares
        if wall:
            dominant = max(wall, key=wall.get)
            rest = 1.0 - sum(wall.values())
            summary["critical_path"] = (
                dominant if wall[dominant] >= rest else "async-device/other"
            )
    if captures:
        summary["captures"] = [
            dict({"name": s.get("name")}, **(s.get("attrs") or {}))
            for s in captures
        ]
    anomalies = [e for e in data["events"]
                 if e.get("event") == "trace_anomaly"]
    if anomalies:
        summary["anomalies"] = [
            {k: v for k, v in e.items() if k not in ("v", "kind")}
            for e in anomalies
        ]
    return summary


def render(summary: dict) -> str:
    lines = [
        f"merged {summary['spans']} span(s) from "
        + ", ".join(f"{proc}×{n}"
                    for proc, n in sorted(summary["spans_by_proc"].items()))
        + f" · {summary['events']} event record(s) · "
        f"{summary['skipped_lines']} unparseable line(s) skipped"
    ]
    rids = summary.get("run_ids", [])
    if len(rids) == 1:
        lines.append(f"run: {rids[0]}")
    elif rids:
        lines.append(f"runs: {', '.join(rids)} (use --run to isolate one)")
    pct = summary.get("step_time_ms")
    if pct:
        lines.append(
            f"steps: {summary['steps']} · p50 {pct['p50']:.1f} ms · "
            f"p95 {pct['p95']:.1f} ms · p99 {pct['p99']:.1f} ms"
        )
        share = summary.get("phase_share", {})
        parts = " · ".join(
            f"{name} {100 * share[name]:.1f}%"
            for name in ("data", "host", "telemetry") if name in share
        )
        if parts:
            lines.append(f"  wall share: {parts} (rest: async device/meters)")
        fenced = " · ".join(
            f"{name} {100 * share[name]:.1f}%"
            for name in ("device", "comm") if name in share
        )
        if fenced:
            lines.append(f"  fenced drain share: {fenced}")
        if "critical_path" in summary:
            lines.append(f"  critical path: {summary['critical_path']}")
    for cap in summary.get("captures", []):
        if cap.get("name") == "capture_start":
            lines.append(
                f"capture: {cap.get('reason', '?')} at step "
                f"{cap.get('step', '?')} "
                f"({cap.get('captures_used', '?')} used)"
            )
    for a in summary.get("anomalies", []):
        lines.append(
            f"anomaly: {a.get('anomaly', '?')} at step {a.get('step', '?')}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    parser.add_argument("paths", nargs="+",
                        help="telemetry dir(s) and/or explicit .jsonl files")
    parser.add_argument("-o", "--output", default="",
                        help="Chrome-trace JSON output path (default "
                             "<first input dir>/trace.json; '-' writes the "
                             "JSON to stdout instead of the summary)")
    parser.add_argument("--run", default="",
                        help="keep only this run_id")
    parser.add_argument("--json", action="store_true",
                        help="emit the machine-readable summary object")
    args = parser.parse_args(argv)
    data = filter_run(collect(args.paths), args.run or None)
    if not data["spans"] and not data["events"]:
        print("no spans or events found (trace_mode=off and nothing "
              "captured?)", file=sys.stderr)
        return 1
    chrome = to_chrome_trace(data)
    out = args.output
    if out == "-":
        json.dump(chrome, sys.stdout)
        return 0
    if not out:
        first_dir = (args.paths[0] if os.path.isdir(args.paths[0])
                     else os.path.dirname(args.paths[0]) or ".")
        out = os.path.join(first_dir, "trace.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(chrome, f)
    summary = summarize(data)
    summary["chrome_trace"] = out
    if args.json:
        print(json.dumps(summary, default=float))
    else:
        print(render(summary))
        print(f"chrome trace: {out} (open in ui.perfetto.dev or "
              "chrome://tracing)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
