#!/usr/bin/env python
"""obsd — the always-on telemetry aggregator + SLO engine (ISSUE 12).

    python tools/obsd.py runs/train/telemetry runs/fleet \\
        --rules slo_rules.json --port 9100

Tails every telemetry stream under the given roots (a directory
contributes its own events.jsonl plus every replica*/events.jsonl under
it — the fleet layout; a .jsonl FILE is one stream; new replica dirs are
discovered live), folds the records into per-run_id rolling windows, and
evaluates a declarative SLO rule file each tick. Alert/recovery
transitions are appended as `kind:"slo"` records into the PRODUCING
run's own events.jsonl — the same stream `telemetry_report` (its `slo:`
section and `--follow` live lines) and every other consumer already
reads. obsd is a pure READER of producer telemetry: the only write is
that one O_APPEND alert line, and no producer code path ever blocks on
obsd being up, slow, or dead.

Endpoints (one ThreadingHTTPServer):

    /metrics   Prometheus text exposition 0.0.4 (step-time percentiles,
               data-stall share, MFU, router depth/latency/sheds, serve
               latency, per-event counters, SLO states — labeled by
               run_id)
    /slo       rule spec + per-run alert state (JSON)
    /runs      every observed run: sources, record kinds, staleness,
               last step (JSON)
    /healthz   liveness

Rule-file reference and the default rule set: README "obsd" + the
`SLORule` docstring in moco_tpu/telemetry/aggregate.py. A shipped rule
file for the learning-health objectives (ISSUE 13 — health:<key> floors
over the step records' in-graph collapse diagnostics, sentinel
collapse_events) is tools/slo_rules/learning_health.json.

Pure stdlib, importable without jax/numpy (mocolint R11
`obsd-stdlib-only`, transitive): obsd must keep answering while the
runtimes it watches OOM, wedge, or crash-loop.

Exit codes: 0 clean (SIGTERM/SIGINT drain) · 45 bad flags/rule file.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moco_tpu.resilience.exitcodes import (  # noqa: E402
    EXIT_CONFIG_ERROR,
    EXIT_OK,
)
from moco_tpu.telemetry.aggregate import (  # noqa: E402
    Aggregator,
    ObsServer,
    load_rules,
)
from moco_tpu.utils.logging import info  # noqa: E402


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[1],
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("roots", nargs="+",
                   help="telemetry directories (train run, fleet dir) "
                        "or events.jsonl files to tail")
    p.add_argument("--rules", default="",
                   help="SLO rule file (JSON list or {\"rules\": [...]}); "
                        "empty = the built-in default set")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9100,
                   help="HTTP endpoint port (0 = ephemeral, printed)")
    p.add_argument("--tick-secs", type=float, default=1.0,
                   help="poll + SLO evaluation cadence")
    p.add_argument("--ring", type=int, default=2048,
                   help="per-run ring size (records kept per window)")
    p.add_argument("--retire-secs", type=float, default=6 * 3600.0,
                   help="drop a run's window + rule state once it ended "
                        "or went silent this long (and is not alerting) "
                        "— bounded state for an always-on daemon; 0 "
                        "keeps everything forever")
    p.add_argument("--no-emit", action="store_true",
                   help="do NOT append kind:\"slo\" records to producer "
                        "streams (endpoint-only mode)")
    p.add_argument("--once", action="store_true",
                   help="one poll + evaluation, print the /runs snapshot "
                        "as JSON, exit (smoke/debug)")
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    try:
        rules = load_rules(args.rules or None)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        info(f"config error: cannot load rules {args.rules!r}: {e}")
        return EXIT_CONFIG_ERROR
    if args.tick_secs <= 0:
        info(f"config error: --tick-secs must be > 0, got {args.tick_secs}")
        return EXIT_CONFIG_ERROR
    try:
        agg = Aggregator(args.roots, rules=rules, ring=args.ring,
                         emit_slo=not args.no_emit,
                         retire_after_s=args.retire_secs)
    except ValueError as e:
        info(f"config error: {e}")
        return EXIT_CONFIG_ERROR

    if args.once:
        agg.poll_once()
        print(json.dumps(agg.runs_snapshot()))
        return EXIT_OK

    server = ObsServer(agg, host=args.host, port=args.port)
    server.start()
    stop = threading.Event()

    def _drain(signum, frame):
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _drain)
    info(
        f"obsd watching {len(args.roots)} root(s) -> {server.url} "
        f"(/metrics /slo /runs; {len(rules)} rule(s), "
        f"tick {args.tick_secs}s)"
    )
    try:
        agg.run(tick_secs=args.tick_secs, stop=stop)
    finally:
        agg.poll_once()  # land anything the stop raced
        server.shutdown()
    info("obsd drained cleanly")
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
