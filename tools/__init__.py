# Marks tools/ as a package so `python -m tools.mocolint` works from the
# repo root. The scripts in this directory remain directly runnable.
