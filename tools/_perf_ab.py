"""On-chip step-time A/B for the r5-vs-r2 gap (README "open measurement
question"): times the SAME fused MoCo-v2 R50 program as bench.py's step
child under one knob setting per invocation, so the knob is applied before
any moco_tpu import (fast_bn / augment read MOCO_TPU_DISABLE_PALLAS at
trace time).

    python tools/_perf_ab.py [--disable-pallas] [--batches 128,256]
        [--stats-tile-kib N]   # override pallas_stats tile target

Prints one JSON line per batch size:
    {"ab": "...", "batch": B, "ms_per_step": T, "imgs_per_s": R}

r2's 1780 imgs/s/chip operating point was ~72 ms/step at B=128; first
contact (r5) measured 124 ms/step — this tool bisects whether the Pallas
BN-stats kernels (whose tile budget the r5 VMEM fix cut 2 MB -> 1 MB for
BOTH kernels, though only grad_sums needed it) account for the difference.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

p = argparse.ArgumentParser()
p.add_argument("--disable-pallas", action="store_true")
p.add_argument("--pallas-bn", action="store_true",
               help="opt the fast_bn stats kernels back IN (default OFF "
                    "since the r5 A/B: ~52 ms/step launch overhead)")
p.add_argument("--disable-pallas-blur", action="store_true",
               help="disable only the aug blur stencil kernel")
p.add_argument("--batches", default="128,256")
p.add_argument("--preset", default="imagenet-moco-v2",
               help="any pretrain preset; v3 presets time the queue-free "
                    "step with the asymmetric aug pair")
p.add_argument("--remat", choices=("true", "false"), default=None,
               help="force per-block rematerialization on/off (the train "
                    "driver's bool convention); default = the preset's own "
                    "value — NOTE imagenet-moco-v3-vitb defaults remat=TRUE, "
                    "so a no-remat ViT-B baseline needs --remat false "
                    "(review, r5)")
p.add_argument("--stats-tile-kib", type=int, default=0,
               help="override pallas_stats per-operand tile target (KiB)")
p.add_argument("--label", default="")
args = p.parse_args()

if args.stats_tile_kib and not (args.pallas_bn or args.disable_pallas):
    # the tile knob tunes the BN-stats kernels, which default OFF since
    # the r5 A/B — without the opt-in the sweep would time a program with
    # zero pallas_stats calls under a 'tileNk' label (review, r5)
    args.pallas_bn = True
if args.disable_pallas:
    os.environ["MOCO_TPU_DISABLE_PALLAS"] = "1"
if args.pallas_bn:
    os.environ["MOCO_TPU_PALLAS_BN"] = "1"
if args.disable_pallas_blur:
    os.environ["MOCO_TPU_DISABLE_PALLAS_BLUR"] = "1"
if args.stats_tile_kib:
    os.environ["MOCO_TPU_STATS_TILE_KIB"] = str(args.stats_tile_kib)

from moco_tpu.utils.cache import enable_persistent_cache

enable_persistent_cache()

import jax

from moco_tpu.config import get_preset
from moco_tpu.parallel.mesh import create_mesh
from moco_tpu.utils.benchkit import build_v2_fused_bench, time_fused_step

# labels COMPOSE: every active knob appears, so a combined invocation
# (e.g. --pallas-bn --stats-tile-kib 512) cannot log ambiguously
# (review, r5)
parts = []
if args.disable_pallas:
    parts.append("no_pallas")
if args.pallas_bn:
    parts.append("pallas_bn_on")
if args.disable_pallas_blur:
    parts.append("no_pallas_blur")
if args.stats_tile_kib:
    parts.append(f"tile{args.stats_tile_kib}k")
label = args.label or ("+".join(parts) if parts else "default")
# the label must reflect the EFFECTIVE remat: the vitb preset defaults
# remat=True, so a flagless run is NOT a no-remat baseline. Computed ONCE
# from the preset (remat is batch-independent) and appended
# unconditionally when effective — a substring test would let a label
# like "noremat" suppress the marker, the exact mislabel this prevents
# (review, r5)
_effective_remat = (args.remat == "true" if args.remat is not None
                    else get_preset(args.preset).remat)
if _effective_remat:
    label += "+remat"
# echo the EFFECTIVE tile at two reference shapes (R50 layer1/layer4): a
# budget that aliases the default program shows up here instead of being
# reported as a distinct sweep point (review, r5)
from moco_tpu.ops.pallas_stats import _tile_rows

print(json.dumps({"ab": label, "backend": jax.default_backend(),
                  "tile_rows_c64": _tile_rows(128 * 56 * 56, 64),
                  "tile_rows_c2048": _tile_rows(128 * 7 * 7, 2048)}),
      flush=True)

for B in (int(b) for b in args.batches.split(",")):
    mesh = create_mesh(1)
    # IDENTICAL program to bench.py's step child: the assembly and timing
    # live in moco_tpu.utils.benchkit, shared with bench.py and
    # tools/_tpu_validate.py, so the A/B cannot drift from what the bench
    # publishes (review, r5)
    config = get_preset(args.preset).replace(
        batch_size=B, dataset="synthetic", remat=_effective_remat)
    fused, state, imgs, ext = build_v2_fused_bench(config, mesh)
    best, warm_s, _loss, state = time_fused_step(
        fused, state, imgs, ext, warmup=10, steps=20, rounds=3)
    print(json.dumps({"ab": label, "batch": B,
                      "ms_per_step": round(best * 1e3, 2),
                      "imgs_per_s": round(B / best, 1),
                      "compile_warmup_s": round(warm_s, 1)}), flush=True)
