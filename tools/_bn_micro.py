import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import time
import jax, jax.numpy as jnp, numpy as np
import flax.linen as nn

def timeit(fn, args, n=30, warm=8):
    for _ in range(warm): out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    t0=time.perf_counter()
    for _ in range(n): out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    return (time.perf_counter()-t0)/n*1e3

for (B,H,W,C) in [(128,56,56,64),(128,56,56,256)]:
    nbytes = B*H*W*C*2
    x = jnp.asarray(np.random.rand(B,H,W,C), jnp.bfloat16)
    bn = nn.BatchNorm(use_running_average=False, momentum=0.9, epsilon=1e-5,
                      dtype=jnp.bfloat16, param_dtype=jnp.float32)
    v = bn.init(jax.random.key(0), x)
    params, stats = v["params"], v["batch_stats"]

    @jax.jit
    def fwd(p, s, x):
        return bn.apply({"params":p,"batch_stats":s}, x, mutable=["batch_stats"])
    t = timeit(fwd, (params, stats, x))
    print(f"[{B},{H},{W},{C}] {nbytes/1e6:.0f}MB BN fwd: {t:.2f} ms ({(2*nbytes)/t/1e6:.0f} GB/s eff 1R1W)", flush=True)

    @jax.jit
    def statpass(x):
        xf = x.astype(jnp.float32)
        return jnp.sum(xf, axis=(0,1,2)), jnp.sum(xf*xf, axis=(0,1,2))
    t3 = timeit(statpass, (x,))
    print(f"   raw sum+sumsq: {t3:.2f} ms ({nbytes/t3/1e6:.0f} GB/s read)", flush=True)
