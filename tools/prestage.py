#!/usr/bin/env python
"""prestage — decode a whole dataset once into a pre-staged epoch cache.

    python tools/prestage.py /fast/ssd/imagenet_prestage \
        --dataset imagefolder --data-dir /data/imagenet/train

Writes the mmap-able packed-canvas format of
`moco_tpu/data/service/prestage.py` (canvases.u8 / extents.i32 /
labels.i32 / meta.json, meta landing LAST as the completeness marker).
The staged canvas is a pure deterministic function of the file bytes —
every randomized transform runs ON DEVICE — so ONE prestage serves every
epoch of every run on every host at memcpy speed:

    in-process:  train.py --input-prestage /fast/ssd/imagenet_prestage
    service:     tools/staging_server.py --prestage /fast/ssd/...

This CLI is offline tooling on the numpy side (it IS the decode), so it
shares the worker's dataset flag surface verbatim and is exempt from the
control plane's stdlib-only diet.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from moco_tpu.data.service.prestage import PrestageError, write_prestage
from moco_tpu.data.service.worker import add_dataset_flags, build_worker_dataset
from moco_tpu.resilience.exitcodes import EXIT_CONFIG_ERROR, EXIT_OK
from moco_tpu.utils.logging import log_event


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="decode a dataset once into a pre-staged epoch cache",
    )
    parser.add_argument("root", help="output directory")
    add_dataset_flags(parser)
    parser.add_argument("--chunk", type=int, default=64,
                        help="decode-slice rows per memmap write")
    args = parser.parse_args(argv)
    if args.prestage:
        log_event("prestage",
                  "--prestage names an input cache; prestaging a "
                  "prestage is a copy, not a decode — refusing")
        return EXIT_CONFIG_ERROR
    try:
        dataset, _ = build_worker_dataset(args)
    except (ValueError, OSError) as e:
        # OSError, not just FileNotFoundError: --data-dir at a file or
        # unreadable is the same config class (worker.py's contract)
        log_event("prestage", f"cannot build dataset: {e}")
        return EXIT_CONFIG_ERROR

    t0 = time.perf_counter()
    state = {"last": 0.0}

    def progress(done: int, total: int) -> None:
        now = time.perf_counter()
        if now - state["last"] >= 5.0 or done == total:
            state["last"] = now
            rate = done / max(now - t0, 1e-9)
            log_event("prestage",
                      f"{done}/{total} rows ({rate:.0f} rows/s)")

    try:
        meta = write_prestage(dataset, args.root, chunk=args.chunk,
                              progress=progress)
    except PrestageError as e:
        log_event("prestage", f"refused: {e}")
        return EXIT_CONFIG_ERROR
    log_event(
        "prestage",
        f"complete: {meta['n']} rows, "
        f"{meta['canvas_bytes'] / 2**30:.2f} GiB canvases in "
        f"{time.perf_counter() - t0:.1f}s at {args.root}",
    )
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
