// Native host-side image staging pipeline (SURVEY §2.10: the TPU-native
// equivalent of the reference's C-backed input path — PIL/libjpeg-turbo
// decode inside 32 DataLoader worker PROCESSES, or the bl0-fork's DALI
// option). One shared library, a pool of decode THREADS inside the single
// controller process:
//
//   JPEG bytes --(libjpeg decode)--> RGB --(bilinear shorter-side resize)-->
//   --(center crop)--> uint8 [S, S, 3] staging tile
//
// The randomized augmentation does NOT happen here — it runs on-device
// (moco_tpu/data/augment.py). This library only turns compressed files into
// fixed-size uint8 staging tiles as fast as the host allows, the one part of
// the input path that cannot run on the TPU.
//
// C ABI (consumed via ctypes from moco_tpu/data/native_loader.py):
//   void* sl_create(int num_threads, int stage_size);
//   int   sl_load_batch(void* h, const char** paths, int n, uint8_t* out);
//         // out: n * S * S * 3 bytes; returns 0 on success, else the number
//         // of failed images (failed slots are zero-filled)
//   void  sl_destroy(void* h);

#include <cstdio>  // must precede jpeglib.h (it needs FILE declared)

#include <jpeglib.h>

#include <atomic>
#include <algorithm>
#include <cmath>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// libjpeg decode with longjmp error recovery (corrupt files must not abort)
// ---------------------------------------------------------------------------

struct JpegErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

// Decode a JPEG file to RGB. Returns false on any decode error.
bool decode_jpeg(const char* path, std::vector<uint8_t>* rgb, int* w, int* h) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_error_exit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    std::fclose(f);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;  // force 3-channel (gray/CMYK inputs too)
  jpeg_start_decompress(&cinfo);
  *w = static_cast<int>(cinfo.output_width);
  *h = static_cast<int>(cinfo.output_height);
  rgb->resize(static_cast<size_t>(*w) * *h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = rgb->data() + static_cast<size_t>(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  std::fclose(f);
  return true;
}

// ---------------------------------------------------------------------------
// bilinear shorter-side resize + center crop to S x S (uint8, RGB)
// ---------------------------------------------------------------------------

void resize_center_crop(const uint8_t* src, int w, int h, int s, uint8_t* dst) {
  const float scale = static_cast<float>(s) / std::min(w, h);
  const int rw = std::max(s, static_cast<int>(std::lround(w * scale)));
  const int rh = std::max(s, static_cast<int>(std::lround(h * scale)));
  const int x_off = (rw - s) / 2;
  const int y_off = (rh - s) / 2;
  // map output pixel -> source coordinate (align-corners=false convention)
  const float sx = static_cast<float>(w) / rw;
  const float sy = static_cast<float>(h) / rh;
  for (int y = 0; y < s; ++y) {
    const float fy = (y + y_off + 0.5f) * sy - 0.5f;
    const int y0 = std::clamp(static_cast<int>(std::floor(fy)), 0, h - 1);
    const int y1 = std::min(y0 + 1, h - 1);
    const float wy = std::clamp(fy - y0, 0.0f, 1.0f);
    for (int x = 0; x < s; ++x) {
      const float fx = (x + x_off + 0.5f) * sx - 0.5f;
      const int x0 = std::clamp(static_cast<int>(std::floor(fx)), 0, w - 1);
      const int x1 = std::min(x0 + 1, w - 1);
      const float wx = std::clamp(fx - x0, 0.0f, 1.0f);
      const uint8_t* p00 = src + (static_cast<size_t>(y0) * w + x0) * 3;
      const uint8_t* p01 = src + (static_cast<size_t>(y0) * w + x1) * 3;
      const uint8_t* p10 = src + (static_cast<size_t>(y1) * w + x0) * 3;
      const uint8_t* p11 = src + (static_cast<size_t>(y1) * w + x1) * 3;
      uint8_t* out = dst + (static_cast<size_t>(y) * s + x) * 3;
      for (int c = 0; c < 3; ++c) {
        const float top = p00[c] + (p01[c] - p00[c]) * wx;
        const float bot = p10[c] + (p11[c] - p10[c]) * wx;
        out[c] = static_cast<uint8_t>(std::lround(top + (bot - top) * wy));
      }
    }
  }
}

// ---------------------------------------------------------------------------
// thread pool
// ---------------------------------------------------------------------------

class ThreadPool {
 public:
  explicit ThreadPool(int n) {
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] { Loop(); });
    }
  }
  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }
  void Submit(std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      tasks_.push(std::move(fn));
    }
    cv_.notify_one();
  }

 private:
  void Loop() {
    for (;;) {
      std::function<void()> task;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !tasks_.empty(); });
        if (stop_ && tasks_.empty()) return;
        task = std::move(tasks_.front());
        tasks_.pop();
      }
      task();
    }
  }
  std::mutex mu_;
  std::condition_variable cv_;
  std::queue<std::function<void()>> tasks_;
  std::vector<std::thread> workers_;
  bool stop_ = false;
};

struct Loader {
  ThreadPool pool;
  int stage_size;
  Loader(int threads, int s) : pool(threads), stage_size(s) {}
};

}  // namespace

extern "C" {

void* sl_create(int num_threads, int stage_size) {
  if (num_threads < 1 || stage_size < 1) return nullptr;
  return new Loader(num_threads, stage_size);
}

int sl_load_batch(void* handle, const char** paths, int n, uint8_t* out) {
  auto* loader = static_cast<Loader*>(handle);
  const int s = loader->stage_size;
  const size_t tile = static_cast<size_t>(s) * s * 3;
  std::atomic<int> failures{0};
  // `remaining` is a plain int guarded by done_mu: the decrement must happen
  // UNDER the lock, otherwise the waiter can observe 0 (spurious wake) and
  // destroy these stack objects while the last worker is still about to
  // lock them (use-after-free).
  int remaining = n;
  std::mutex done_mu;
  std::condition_variable done_cv;
  for (int i = 0; i < n; ++i) {
    loader->pool.Submit([&, i] {
      std::vector<uint8_t> rgb;
      int w = 0, h = 0;
      if (decode_jpeg(paths[i], &rgb, &w, &h) && w > 0 && h > 0) {
        resize_center_crop(rgb.data(), w, h, s, out + i * tile);
      } else {
        std::memset(out + i * tile, 0, tile);
        failures.fetch_add(1);
      }
      {
        std::lock_guard<std::mutex> lk(done_mu);
        if (--remaining == 0) done_cv.notify_one();
      }
    });
  }
  std::unique_lock<std::mutex> lk(done_mu);
  done_cv.wait(lk, [&] { return remaining == 0; });
  return failures.load();
}

void sl_destroy(void* handle) { delete static_cast<Loader*>(handle); }

}  // extern "C"
