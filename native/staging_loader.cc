// Native host-side image staging pipeline (SURVEY §2.10: the TPU-native
// equivalent of the reference's C-backed input path — PIL/libjpeg-turbo
// decode inside 32 DataLoader worker PROCESSES, or the bl0-fork's DALI
// option). One shared library, a pool of decode THREADS inside the single
// controller process:
//
//   JPEG bytes --(libjpeg decode)--> RGB --(transpose if portrait)-->
//   --(bilinear fit-resize)--> uint8 [H, W, 3] canvas (whole image at the
//   top-left, edge-replicated padding) + int32 (valid_h, valid_w, rot) extent
//
// The WHOLE image is staged (not a center crop): the on-device
// RandomResizedCrop samples over the true image area, matching torchvision
// get_params on the original photo (VERDICT r1 weak #3). Portrait images are
// staged TRANSPOSED so one landscape canvas shape serves both orientations;
// the device pipeline transposes the crop back (the RRC ratio distribution
// is symmetric, so sampling in transposed space is equivalent).
//
// The randomized augmentation does NOT happen here — it runs on-device
// (moco_tpu/data/augment.py). This library only turns compressed files into
// fixed-size uint8 staging canvases as fast as the host allows, the one part
// of the input path that cannot run on the TPU.
//
// C ABI (consumed via ctypes from moco_tpu/data/native_loader.py):
//   int   sl_version();  // ABI/behavior revision (2 = chunked batch fan-out)
//   void* sl_create(int num_threads, int stage_h, int stage_w);
//   int   sl_load_batch(void* h, const char** paths, int n, uint8_t* out,
//                       int32_t* extents);
//         // out: n * H * W * 3 bytes; extents: n * 3 int32 (h, w, rot);
//         // returns 0 on success, else the number of failed images
//         // (failed slots are zero-filled with full-canvas extent)
//   void  sl_destroy(void* h);
//
// Scheduling (v2, ISSUE 3): the batch is fanned out as ONE task per
// CONTIGUOUS CHUNK (min(num_threads, n) chunks), not one task per image.
// Per-image tasks paid a mutex acquire + condition-variable wake per image
// (256 lock round-trips per batch), and every image re-malloc'd its decode
// buffer; chunked tasks touch the queue lock num_threads times per batch
// and reuse one RGB scratch buffer across the whole chunk. Concurrent
// sl_load_batch calls on one handle are safe: each call owns its own
// completion state, and the pool queue is the only shared structure.

#include <cstdio>  // must precede jpeglib.h (it needs FILE declared)

#include <jpeglib.h>

#include <atomic>
#include <algorithm>
#include <cmath>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// libjpeg decode with longjmp error recovery (corrupt files must not abort)
// ---------------------------------------------------------------------------

struct JpegErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

// Decode a JPEG file to RGB. Returns false on any decode error.
bool decode_jpeg(const char* path, std::vector<uint8_t>* rgb, int* w, int* h) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_error_exit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    std::fclose(f);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;  // force 3-channel (gray/CMYK inputs too)
  jpeg_start_decompress(&cinfo);
  *w = static_cast<int>(cinfo.output_width);
  *h = static_cast<int>(cinfo.output_height);
  rgb->resize(static_cast<size_t>(*w) * *h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = rgb->data() + static_cast<size_t>(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  std::fclose(f);
  return true;
}

// ---------------------------------------------------------------------------
// whole-image staging: transpose-if-portrait, bilinear fit-resize into the
// top-left of an [H, W] canvas, edge-replicate padding, record the extent
// ---------------------------------------------------------------------------

void stage_rect(const uint8_t* src, int w, int h, int H, int W, uint8_t* dst,
                int32_t* ext) {
  std::vector<uint8_t> tbuf;
  int rot = 0;
  if (h > w) {  // portrait: stage transposed (landscape canvas serves both)
    tbuf.resize(static_cast<size_t>(w) * h * 3);
    for (int y = 0; y < h; ++y) {
      const uint8_t* row = src + static_cast<size_t>(y) * w * 3;
      for (int x = 0; x < w; ++x) {
        uint8_t* o = tbuf.data() + (static_cast<size_t>(x) * h + y) * 3;
        o[0] = row[x * 3];
        o[1] = row[x * 3 + 1];
        o[2] = row[x * 3 + 2];
      }
    }
    std::swap(w, h);
    src = tbuf.data();
    rot = 1;
  }
  // Fit-DOWNSCALE only (scale capped at 1): an image that already fits the
  // canvas is staged at its ORIGINAL resolution — upsampling would burn
  // canvas bandwidth without adding information, and full-resolution staging
  // is the point (the on-device RandomResizedCrop must sample original
  // pixels, torchvision semantics). With the default shorter-side-512 canvas
  // nearly all ImageNet photos stage pixel-exact.
  const float scale = std::min(
      1.0f, std::min(static_cast<float>(H) / h, static_cast<float>(W) / w));
  const int nh = std::clamp(static_cast<int>(std::lround(h * scale)), 1, H);
  const int nw = std::clamp(static_cast<int>(std::lround(w * scale)), 1, W);
  if (nh == h && nw == w) {  // pixel-exact paste, no resample
    for (int y = 0; y < h; ++y) {
      std::memcpy(dst + static_cast<size_t>(y) * W * 3,
                  src + static_cast<size_t>(y) * w * 3,
                  static_cast<size_t>(w) * 3);
    }
  } else {
    // map output pixel -> source coordinate (align-corners=false convention)
    const float sx = static_cast<float>(w) / nw;
    const float sy = static_cast<float>(h) / nh;
    for (int y = 0; y < nh; ++y) {
      const float fy = (y + 0.5f) * sy - 0.5f;
      const int y0 = std::clamp(static_cast<int>(std::floor(fy)), 0, h - 1);
      const int y1 = std::min(y0 + 1, h - 1);
      const float wy = std::clamp(fy - y0, 0.0f, 1.0f);
      uint8_t* row = dst + static_cast<size_t>(y) * W * 3;
      for (int x = 0; x < nw; ++x) {
        const float fx = (x + 0.5f) * sx - 0.5f;
        const int x0 = std::clamp(static_cast<int>(std::floor(fx)), 0, w - 1);
        const int x1 = std::min(x0 + 1, w - 1);
        const float wx = std::clamp(fx - x0, 0.0f, 1.0f);
        const uint8_t* p00 = src + (static_cast<size_t>(y0) * w + x0) * 3;
        const uint8_t* p01 = src + (static_cast<size_t>(y0) * w + x1) * 3;
        const uint8_t* p10 = src + (static_cast<size_t>(y1) * w + x0) * 3;
        const uint8_t* p11 = src + (static_cast<size_t>(y1) * w + x1) * 3;
        uint8_t* out = row + static_cast<size_t>(x) * 3;
        for (int c = 0; c < 3; ++c) {
          const float top = p00[c] + (p01[c] - p00[c]) * wx;
          const float bot = p10[c] + (p11[c] - p10[c]) * wx;
          out[c] = static_cast<uint8_t>(std::lround(top + (bot - top) * wy));
        }
      }
    }
  }
  // edge-replicate padding so on-device crop taps at the content boundary
  // read clamped pixels (PIL semantics), never black
  for (int y = 0; y < nh; ++y) {
    uint8_t* row = dst + static_cast<size_t>(y) * W * 3;
    const uint8_t* last = row + static_cast<size_t>(nw - 1) * 3;
    for (int x = nw; x < W; ++x) {
      std::memcpy(row + static_cast<size_t>(x) * 3, last, 3);
    }
  }
  const uint8_t* last_row = dst + static_cast<size_t>(nh - 1) * W * 3;
  for (int y = nh; y < H; ++y) {
    std::memcpy(dst + static_cast<size_t>(y) * W * 3, last_row,
                static_cast<size_t>(W) * 3);
  }
  ext[0] = nh;
  ext[1] = nw;
  ext[2] = rot;
}

// ---------------------------------------------------------------------------
// thread pool
// ---------------------------------------------------------------------------

class ThreadPool {
 public:
  explicit ThreadPool(int n) {
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] { Loop(); });
    }
  }
  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }
  void Submit(std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      tasks_.push(std::move(fn));
    }
    cv_.notify_one();
  }

 private:
  void Loop() {
    for (;;) {
      std::function<void()> task;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !tasks_.empty(); });
        if (stop_ && tasks_.empty()) return;
        task = std::move(tasks_.front());
        tasks_.pop();
      }
      task();
    }
  }
  std::mutex mu_;
  std::condition_variable cv_;
  std::queue<std::function<void()>> tasks_;
  std::vector<std::thread> workers_;
  bool stop_ = false;
};

struct Loader {
  ThreadPool pool;
  int num_threads;
  int stage_h;
  int stage_w;
  Loader(int threads, int h, int w)
      : pool(threads), num_threads(threads), stage_h(h), stage_w(w) {}
};

}  // namespace

extern "C" {

int sl_version() { return 2; }

void* sl_create(int num_threads, int stage_h, int stage_w) {
  if (num_threads < 1 || stage_h < 1 || stage_w < 1) return nullptr;
  return new Loader(num_threads, stage_h, stage_w);
}

int sl_load_batch(void* handle, const char** paths, int n, uint8_t* out,
                  int32_t* extents) {
  auto* loader = static_cast<Loader*>(handle);
  const int H = loader->stage_h;
  const int W = loader->stage_w;
  const size_t tile = static_cast<size_t>(H) * W * 3;
  const int chunks = std::max(1, std::min(loader->num_threads, n));
  std::atomic<int> failures{0};
  // `remaining` is a plain int guarded by done_mu: the decrement must happen
  // UNDER the lock, otherwise the waiter can observe 0 (spurious wake) and
  // destroy these stack objects while the last worker is still about to
  // lock them (use-after-free).
  int remaining = chunks;
  std::mutex done_mu;
  std::condition_variable done_cv;
  for (int c = 0; c < chunks; ++c) {
    // balanced contiguous ranges: image i belongs to chunk i*chunks/n
    const int lo = static_cast<int>(static_cast<int64_t>(n) * c / chunks);
    const int hi = static_cast<int>(static_cast<int64_t>(n) * (c + 1) / chunks);
    loader->pool.Submit([&, lo, hi] {
      std::vector<uint8_t> rgb;  // scratch reused across the chunk's images
      int chunk_failures = 0;
      for (int i = lo; i < hi; ++i) {
        int w = 0, h = 0;
        if (decode_jpeg(paths[i], &rgb, &w, &h) && w > 0 && h > 0) {
          stage_rect(rgb.data(), w, h, H, W, out + i * tile, extents + i * 3);
        } else {
          std::memset(out + i * tile, 0, tile);
          extents[i * 3] = H;
          extents[i * 3 + 1] = W;
          extents[i * 3 + 2] = 0;
          ++chunk_failures;
        }
      }
      if (chunk_failures) failures.fetch_add(chunk_failures);
      {
        std::lock_guard<std::mutex> lk(done_mu);
        if (--remaining == 0) done_cv.notify_one();
      }
    });
  }
  std::unique_lock<std::mutex> lk(done_mu);
  done_cv.wait(lk, [&] { return remaining == 0; });
  return failures.load();
}

void sl_destroy(void* handle) { delete static_cast<Loader*>(handle); }

}  // extern "C"
