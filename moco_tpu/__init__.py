"""moco_tpu — a TPU-native Momentum-Contrast (MoCo) self-supervised learning framework.

Built from scratch in JAX/XLA for TPU hardware. Capability parity target is the
bl0/moco reference (a fork of facebookresearch/moco); see SURVEY.md at the repo
root for the full structural analysis this package is built against.

Design stance (SURVEY.md §7): the entire training step — query/key forwards,
momentum (EMA) key-encoder update, ShuffleBN collectives, the negative-key
queue enqueue, InfoNCE, gradient psum and the optimizer update — is ONE jitted
SPMD program over a `jax.sharding.Mesh`, with all state in an explicit pytree
and the queue as a donated HBM buffer. There is no DDP wrapper, no
process-per-device, no `no_grad` context: `stop_gradient` + functional updates
instead.

Package layout:
    parallel/   device mesh, distributed init, collectives (ShuffleBN)
    ops/        queue, EMA, losses, schedules, kNN, augmentation math
    models/     flax ResNet-18/34/50 and ViT-S/16 encoders + MoCo heads
    data/       input pipelines (synthetic, CIFAR-10, ImageFolder) + host loader
    evals/      linear probe and kNN evaluation drivers
    utils/      meters, logging, profiling helpers
    train_state.py / train_step.py / train.py   pretrain state + SPMD step + CLI
    config.py   dataclass configs; the five BASELINE.json presets
    checkpoint.py  Orbax checkpointing + torchvision-name exporter
"""

__version__ = "0.1.0"
