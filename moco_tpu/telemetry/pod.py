"""Pod-level aggregation (ISSUE 2 tentpole part 4).

Per-host telemetry is a lie about a pod: one slow host sets the step time
for everyone (collectives synchronize), and the interesting signals are
exactly the cross-host spread (straggler detection) and the sums
(delivered throughput). Every host builds the same fixed vector of
scalars; the driver allgathers it at the EXISTING `resilience_sync_steps`
cadence (one extra small allgather at an already-synchronizing step — no
new sync points), and process 0 folds the matrix into one `pod` record.

The vector layout is versioned by position — append only, never reorder —
so a mixed-version pod degrades to ignoring trailing fields instead of
misreading them.
"""

from __future__ import annotations

import numpy as np

# positional layout of the per-host scalar vector (append-only)
POD_FIELDS = (
    "step_s",           # most recent step wall time on this host
    "imgs_per_sec",     # rolling host throughput
    "data_s",           # most recent loader-wait time
    "hbm_peak_bytes",   # HBM high-water (0 when the backend can't report)
    "host_rss_bytes",   # host resident set
    "incidents",        # structured events this host has seen so far
)


class PodAggregator:
    """Builds the local vector; folds the allgathered matrix on process 0."""

    def __init__(self, registry, n_procs: int, process_index: int):
        self.registry = registry
        self.n_procs = int(n_procs)
        self.process_index = int(process_index)
        self._local = {name: 0.0 for name in POD_FIELDS}

    def update(self, **scalars) -> None:
        for name, value in scalars.items():
            if name in self._local and value is not None:
                self._local[name] = float(value)

    def local_vector(self) -> np.ndarray:
        return np.asarray([self._local[name] for name in POD_FIELDS], np.float64)

    def record(self, step: int, gathered: np.ndarray) -> None:
        """Fold an allgathered [n_hosts, len(POD_FIELDS)] matrix into one
        pod record (process 0 only — other hosts contribute and return)."""
        if self.process_index != 0 or self.registry is None:
            return
        g = np.asarray(gathered, np.float64).reshape(-1, len(POD_FIELDS))
        col = {name: g[:, i] for i, name in enumerate(POD_FIELDS)}
        self.registry.emit(
            "pod",
            step=int(step),
            hosts=int(g.shape[0]),
            step_s_max=round(float(col["step_s"].max()), 6),
            step_s_min=round(float(col["step_s"].min()), 6),
            data_s_max=round(float(col["data_s"].max()), 6),
            imgs_per_sec_sum=round(float(col["imgs_per_sec"].sum()), 2),
            hbm_peak_bytes_max=int(col["hbm_peak_bytes"].max()),
            host_rss_bytes_max=int(col["host_rss_bytes"].max()),
            incidents_total=int(col["incidents"].sum()),
        )
