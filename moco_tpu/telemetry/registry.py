"""Typed metric instruments + the buffered JSONL event sink (ISSUE 2 core).

Every record in `<telemetry_dir>/events.jsonl` is one JSON object per line,
stamped with `"v": SCHEMA_VERSION` and a wall-clock `"t"`, and carries a
`"kind"`:

  run_start  — one per driver pass: arch/variant/batch/mesh shape, the
               analytic per-step FLOPs and the peak-FLOPs assumption MFU
               is judged against (so a report is self-describing)
  step       — one per training step: step index, phase times
               (data_s/host_s, device_s on fenced samples), throughput
               (rolling + cumulative), MFU, loss when host-synced anyway,
               HBM + host-RSS samples at the device stride
  pod        — process-0 aggregate built from a periodic allgather of
               per-host scalars (max/min step time, summed throughput,
               max HBM/RSS high-water across hosts)
  event      — discrete incidents routed from `log_event` (preempt,
               rollback, chaos, watchdog, scalar_writer drops, ...); the
               original `[kind]` goes in the "event" field
  run_end    — final summary written at close (step count, high-water
               marks) so a truncated tail is detectable

Writes are buffered and flushed every `flush_every` records (plus on
close), each flush ending in `flush()+fsync` so a SIGKILL between flushes
loses at most one buffer — never corrupts previously-flushed lines
(append-only, newline-framed; a torn final line is skipped by the reader).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque

SCHEMA_VERSION = 1

EVENTS_FILENAME = "events.jsonl"
HEARTBEAT_FILENAME = "heartbeat.json"


class Counter:
    """Monotonic count (incidents, drops, records written). `inc` is
    locked: incident counts arrive from log_event sinks on the watchdog /
    prefetcher threads concurrently with the step loop."""

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += int(n)


class Gauge:
    """Last-observed value plus its running high-water mark (HBM, RSS)."""

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self.high_water = float("-inf")

    def set(self, value: float) -> None:
        self.value = float(value)
        self.high_water = max(self.high_water, self.value)


class Histogram:
    """Reservoir of observations with exact percentiles (step times, MFU).

    By default keeps every observation: at one float per step a multi-day
    1M-step run is ~8 MB — exactness is worth more here than a sketch,
    because the p99 regression a perf PR must catch lives in the tail.
    A LONG-LIVED process with unbounded observation rate (the serving
    path: ISSUE 5) must pass `window` instead — a bounded deque of the
    most recent N observations, so memory and per-snapshot sort cost stay
    flat forever and the percentiles describe recent behavior (which is
    what an operator watching a server wants anyway).
    """

    def __init__(self, name: str, window: int | None = None):
        self.name = name
        if window is not None and window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._values = (
            deque(maxlen=int(window)) if window is not None else []
        )

    def observe(self, value: float) -> None:
        self._values.append(float(value))

    @property
    def count(self) -> int:
        return len(self._values)

    @property
    def sum(self) -> float:
        return float(sum(self._values))

    @property
    def mean(self) -> float:
        return self.sum / self.count if self._values else 0.0

    @property
    def max(self) -> float:
        return max(self._values) if self._values else 0.0

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile, q in [0, 100]. 0.0 when empty."""
        if not self._values:
            return 0.0
        ordered = sorted(self._values)
        rank = max(0, min(len(ordered) - 1, round(q / 100.0 * (len(ordered) - 1))))
        return ordered[int(rank)]

    def percentiles_ms(self, qs=(50, 95, 99)) -> dict:
        """{"p50": ..., ...} of seconds-valued observations in ms — THE
        shared percentile-record shape (BENCH_*.json folds, serve
        snapshots, telemetry_report rendering)."""
        return {f"p{q}": round(self.percentile(q) * 1e3, 3) for q in qs}


def _json_safe(value):
    """RFC-8259-safe record values: json.dumps would happily write bare
    `NaN`/`Infinity` (invalid JSON most non-Python consumers reject) for
    exactly the interesting records — a diverged loss. Encode non-finite
    floats as their string names instead; recurse through containers, and
    coerce foreign scalars (numpy float32/int64, jax weak types — NOT
    `float` subclasses) through the same finiteness check."""
    if isinstance(value, float):
        return value if math.isfinite(value) else repr(value)  # 'nan', 'inf'
    if isinstance(value, (str, int, bool)) or value is None:
        return value
    if isinstance(value, dict):
        return {k: _json_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    try:
        return _json_safe(float(value))
    except (TypeError, ValueError):
        return str(value)  # last resort: never let dumps raise mid-run


def percentiles_ms(values, qs=(50, 95, 99)) -> dict:
    """{"p50": ..., ...} of `values` (seconds) in milliseconds — the
    free-function form of `Histogram.percentiles_ms` for callers holding
    a plain list (bench.py's BENCH_*.json folds)."""
    h = Histogram("tmp")
    for v in values:
        h.observe(float(v))
    return h.percentiles_ms(qs)


class MetricsRegistry:
    """Get-or-create registry of typed instruments + the JSONL sink.

    `path` is the events file ("" / None disables the sink: instruments
    still aggregate — non-main pod hosts run exactly this way, feeding the
    allgather without writing files). `stamp` is a small dict merged into
    EVERY record (ISSUE 8: the tracer's `run_id`/`trace_id`), so the flat
    event stream joins the span timeline — explicit record fields win on
    key collision."""

    def __init__(self, path: str | None = None, flush_every: int = 50,
                 stamp: dict | None = None):
        self._instruments: dict[str, Counter | Gauge | Histogram] = {}
        self._buffer: list[str] = []
        self._stamp = dict(stamp) if stamp else None
        self._path = path or None
        self._file = None
        # emit/flush are called from the main step loop AND from log_event
        # sinks firing on the watchdog / prefetcher threads — an unlocked
        # buffer swap would drop or duplicate exactly the stall incidents
        # telemetry exists to capture
        self._lock = threading.Lock()
        self.flush_every = max(int(flush_every), 1)
        self.records_written = 0
        if self._path:
            os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
            # a SIGKILL mid-flush can leave a torn final line with no
            # newline; appending straight after it would weld the resumed
            # run's run_start onto the fragment (two records lost instead
            # of one) — start on a fresh line if the tail is torn
            torn = False
            try:
                with open(self._path, "rb") as existing:
                    existing.seek(0, os.SEEK_END)
                    if existing.tell() > 0:
                        existing.seek(-1, os.SEEK_END)
                        torn = existing.read(1) != b"\n"
            except OSError:
                torn = False
            self._file = open(self._path, "a", encoding="utf-8")
            if torn:
                self._file.write("\n")

    # -- typed instruments --------------------------------------------------
    def _get(self, name: str, cls):
        inst = self._instruments.get(name)
        if inst is None:
            inst = self._instruments[name] = cls(name)
        elif not isinstance(inst, cls):
            raise TypeError(
                f"instrument {name!r} already registered as "
                f"{type(inst).__name__}, requested {cls.__name__}"
            )
        return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    # -- records ------------------------------------------------------------
    def emit(self, kind: str, **fields) -> bool:
        """Buffer one schema-versioned record; returns True when this call
        flushed (the driver aligns ScalarWriter.flush with that cadence).
        Thread-safe: log_event sinks fire from watchdog/loader threads."""
        if self._file is None:
            # sink-less (non-main pod hosts) or already closed: skip the
            # serialization work entirely — instruments still aggregate
            return False
        record = {"v": SCHEMA_VERSION, "t": round(time.time(), 3), "kind": kind}
        if self._stamp:
            record.update(self._stamp)
        record.update(fields)
        line = json.dumps(_json_safe(record), allow_nan=False)
        with self._lock:
            self._buffer.append(line)
            self.records_written += 1
            if len(self._buffer) >= self.flush_every:
                self._flush_locked()
                return True
        return False

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buffer:
            return
        lines, self._buffer = self._buffer, []
        if self._file is None:
            return
        self._file.write("\n".join(lines) + "\n")
        self._file.flush()
        os.fsync(self._file.fileno())

    def close(self) -> None:
        with self._lock:
            self._flush_locked()
            if self._file is not None:
                self._file.close()
                self._file = None


class Heartbeat:
    """Atomically-replaced liveness file for the run supervisor (ISSUE 4)
    and any other external watchdog.

    Monitors `stat` the file: a stale mtime (or a stale "t" inside) means
    the run stopped making progress even if the process is still alive.
    Atomic replace, never append — a reader must never see a torn write.

    The payload carries everything the supervisor's hang/progress checks
    need without log scraping: `pid` (is this beat from MY child, or a
    stale file from the previous incarnation?), `step` (monotonic progress
    for the restart-budget refund), and `phase` ("run_start" before the
    first step — cold-compile stalls there are normal — "step" once the
    loop is actually advancing, "run_end"/"preempt_exit" at the exits).

    `min_interval_secs` gates `maybe_beat` (the every-step call): one
    atomic replace per second is free, one per 100 ms step is not. `beat`
    always writes (lifecycle transitions must never be elided).

    Besides the wall-clock `t`, every beat carries a monotonic pair
    (ISSUE 12 satellite): `seq` (a per-process counter — did ANYTHING
    change since the reader's last look?) and `mono_s`
    (`time.monotonic()`, CLOCK_MONOTONIC — system-wide since boot on
    Linux, so a same-host reader can order beats against its own
    monotonic clock). Staleness/freshness readers (the run supervisor)
    prefer the pair when present: an NTP step or a manual clock change
    moves `t` but neither `seq` nor `mono_s`, so a wall jump can no
    longer read as "hung child" (backwards) or make a stale file look
    fresh (forwards)."""

    def __init__(self, path: str, min_interval_secs: float = 0.0):
        self.path = path
        self.min_interval = float(min_interval_secs)
        self._last_write = float("-inf")
        self._seq = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def beat(self, step: int, **fields) -> None:
        self._seq += 1
        payload = {"v": SCHEMA_VERSION, "t": round(time.time(), 3),
                   "seq": self._seq,
                   "mono_s": round(time.monotonic(), 3),
                   "step": int(step), "pid": os.getpid()}
        payload.update(fields)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, self.path)
        self._last_write = time.monotonic()

    def maybe_beat(self, step: int, **fields) -> bool:
        """Time-gated beat for per-step call sites: writes (and returns
        True) only when `min_interval_secs` has elapsed since the last
        write — the supervisor's staleness granularity is the max of this
        and the step time, independent of any flush cadence."""
        if time.monotonic() - self._last_write < self.min_interval:
            return False
        self.beat(step, **fields)
        return True
