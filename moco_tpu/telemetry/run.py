"""RunTelemetry — the one object the driver talks to (ISSUE 2 tentpole).

Owns the registry/sink, the phase timer, the MFU estimator, the device
monitor, the pod aggregator, and the heartbeat, and registers itself as a
`log_event` sink so every resilience incident (preempt / rollback / chaos
/ watchdog / sentinel) lands in the same events.jsonl stream it writes
step records to.

Process topology: EVERY process builds a RunTelemetry (the pod allgather
needs all hosts' vectors), but only process 0 gets a file sink and a
heartbeat — non-main registries aggregate instruments and drop record
buffers, so the call sites stay identical on every host.

Overhead contract (acceptance criterion): with telemetry off the driver
holds no RunTelemetry and none of these paths run; with it on, the only
synchronizing call is the stride-gated fence inside StepPhaseTimer —
everything else is host-side arithmetic and buffered writes.
"""

from __future__ import annotations

import os
import time

from moco_tpu.telemetry.device import DeviceMonitor
from moco_tpu.telemetry.mfu import MFUEstimator
from moco_tpu.telemetry.pod import PodAggregator
from moco_tpu.telemetry.registry import (
    EVENTS_FILENAME,
    HEARTBEAT_FILENAME,
    Heartbeat,
    MetricsRegistry,
)
from moco_tpu.data.stats import InputPipelineStats
from moco_tpu.telemetry.timing import StepPhaseTimer
from moco_tpu.telemetry.trace import SlowSampleDetector, Tracer, null_tracer
from moco_tpu.utils import logging as mlog


class RunTelemetry:
    def __init__(self, config, *, n_chips: int, n_procs: int,
                 process_index: int, steps_per_epoch: int, device=None):
        import jax

        if device is None:
            device = jax.local_devices()[0]
        is_main = process_index == 0
        run_dir = config.telemetry_dir
        self.events_path = os.path.join(run_dir, EVENTS_FILENAME)
        # span layer (ISSUE 8): process 0 only, like every file sink. The
        # tracer exists even at trace_mode="off" — that is what makes the
        # SIGUSR1 / trigger-file / anomaly capture windows reachable on a
        # run that wasn't started with tracing on.
        self.tracer = (
            Tracer(
                run_dir,
                getattr(config, "trace_mode", "off"),
                proc="driver",
                capture_steps=getattr(config, "trace_capture_steps", 50),
                capture_budget=getattr(config, "trace_capture_budget", 3),
            )
            if is_main else null_tracer()
        )
        self.tracer.install_signal()
        if is_main and getattr(config, "trace_device_profile", False):
            self.tracer.profiler_hooks = (_profiler_start, _profiler_stop)
        # anomaly detectors arming the capture window (budgeted in the
        # tracer): a slow step vs the rolling p95, and a staging stall
        # seen as a data-phase blowout (the consumer side of an empty
        # prefetch queue). Floors keep µs-scale noise on a healthy phase
        # from ever tripping them.
        # skip=3: the cold-compile/warmup steps are seconds-scale by
        # design; left in the window they put k×p95 at compile scale and
        # hide every later real anomaly. Higher input-stall floor: the
        # first step after an epoch boundary legitimately waits on a fresh
        # Prefetcher's spin-up — a sub-250 ms data wait is never the stall
        # worth spending a capture budget on.
        k = getattr(config, "trace_slow_step_k", 3.0)
        self._slow_step = SlowSampleDetector(k=k, floor_s=0.005, skip=3)
        self._input_stall = SlowSampleDetector(k=k, floor_s=0.25, skip=3)
        self.registry = MetricsRegistry(
            self.events_path if is_main else None,
            flush_every=config.telemetry_flush_steps,
            stamp={"run_id": self.tracer.run_id,
                   "trace_id": self.tracer.trace_id} if is_main else None,
        )
        self.heartbeat = (
            Heartbeat(os.path.join(run_dir, HEARTBEAT_FILENAME),
                      min_interval_secs=getattr(config, "heartbeat_secs", 1.0))
            if is_main else None
        )
        self.timer = StepPhaseTimer(stride=config.telemetry_stride)
        # input-pipeline counters (ISSUE 3): threaded into every Prefetcher
        # and CachedDataset of the run by the driver; snapshots ride the
        # step records at the device-sampling stride
        self.input_stats = InputPipelineStats()
        self.mfu = MFUEstimator.for_config(
            config, n_chips, getattr(device, "device_kind", "")
        )
        self.devices = DeviceMonitor(device)
        self.pod = PodAggregator(self.registry, n_procs, process_index)
        self.n_chips = n_chips

        self._step_hist = self.registry.histogram("step_s")
        self._mfu_hist = self.registry.histogram("mfu")
        self._hbm_gauge = self.registry.gauge("hbm_peak_bytes")
        self._incidents = self.registry.counter("incidents")
        self._grad_sync: dict | None = None
        self._closed = False
        mlog.add_event_sink(self._on_event)
        self.registry.emit(
            "run_start",
            name=config.name,
            variant=config.variant,
            arch=config.arch,
            image_size=config.image_size,
            batch_size=config.batch_size,
            steps_per_epoch=steps_per_epoch,
            n_chips=n_chips,
            n_procs=n_procs,
            sharding=getattr(config, "sharding", "dp"),
            device_kind=getattr(device, "device_kind", ""),
            peak_flops_per_chip=self.mfu.peak_flops_per_chip,
            flops_per_step=self.mfu.flops_per_step,
            flops_per_image=self.mfu.flops_per_step / max(config.batch_size, 1),
            telemetry_stride=config.telemetry_stride,
        )
        if self.heartbeat is not None:
            self.heartbeat.beat(0, phase="run_start")

    # -- incidents (log_event sink) -----------------------------------------
    def _on_event(self, kind: str, msg: str, fields: dict) -> None:
        self._incidents.inc()
        self.registry.emit("event", event=kind, msg=msg, **fields)

    def event(self, kind: str, **fields) -> None:
        """Structured non-incident event (e.g. knn_eval, epoch_summary)."""
        self.registry.emit("event", event=kind, **fields)

    def set_grad_sync(self, info: dict) -> None:
        """Record the gradient-sync plan (ISSUE 6): mode, knobs, analytic
        sync-bytes/step/device. Emitted once as a routine `grad_sync` event;
        the compressed modes (quantized/demo) also stamp the dict onto step
        records at the sampling stride, so a stream tail is self-describing
        about the bytes its step times were measured under."""
        self._grad_sync = dict(info)
        self.registry.emit("event", event="grad_sync", **info)

    def set_sharding(self, info: dict) -> None:
        """Record the sharding plan (ISSUE 15): mode, mesh shape, measured
        per-device param/optimizer bytes. One routine `sharding` event —
        the per-device footprint claim every "fsdp cuts state N-fold" row
        in a BENCH record rests on; telemetry_report renders it as the
        `sharding:` line and MFU is thereby labeled per mode."""
        self.registry.emit("event", event="sharding", **info)

    def phase_beat(self, phase: str, step: int) -> None:
        """Forced heartbeat declaring a known-long non-step phase (the
        epoch-boundary kNN eval): the supervisor widens its staleness
        window to the startup grace while the newest beat's phase is not
        "step" — the out-of-process analogue of StepWatchdog.suspended()
        (a multi-minute eval with no step beats would otherwise be killed
        as a hang)."""
        if self.heartbeat is not None:
            self.heartbeat.beat(step, phase=phase)

    # -- per-step ------------------------------------------------------------
    def on_step(self, step: int, phases: dict, throughput, loss=None,
                health: dict | None = None) -> bool:
        """Emit one step record; returns True when this step flushed the
        sink (the driver aligns ScalarWriter.flush with that cadence).

        `health` is the learning-health block (ISSUE 13): the driver
        passes the host-pulled collapse diagnostics on health-stride
        steps (None otherwise), and the record carries them under a
        `health` sub-dict — the obsd `health:<key>` objectives and the
        report's `health:` section read exactly that shape.

        Everything this method does — record building, span recording,
        capture-window ticks, detector checks — is measured and booked
        back into the phase timer as the `telemetry` sub-phase, so the
        phase-share report never blames the input pipeline for the span
        layer's own cost (ISSUE 8 satellite)."""
        t_tel0 = time.perf_counter()
        # anomaly → capture window (budgeted): check BEFORE the step span
        # records, so the capture's full-detail window starts as early as
        # the step after the anomaly
        # the anomaly event lands whenever the request was newly routed —
        # including past the capture budget, where the tick below answers
        # with one visible `denied` instead of a silent nothing
        if self._slow_step.observe(phases["step_s"]):
            if self.tracer.maybe_autocapture("slow_step"):
                self.registry.emit(
                    "event", event="trace_anomaly", anomaly="slow_step",
                    step=int(step), step_s=round(phases["step_s"], 6),
                    # pre-append snapshot: .p95() here would already
                    # contain the anomalous sample and could equal it
                    p95_s=round(self._slow_step.last_p95, 6),
                )
        if self._input_stall.observe(phases["data_s"]):
            if self.tracer.maybe_autocapture("input_stall"):
                self.registry.emit(
                    "event", event="trace_anomaly", anomaly="input_stall",
                    step=int(step), data_s=round(phases["data_s"], 6),
                    p95_s=round(self._input_stall.last_p95, 6),
                )
        self.tracer.record_step(step, phases)
        capture_evt = self.tracer.tick(step)
        if capture_evt is not None:
            self.registry.emit("event", event="trace_capture", **capture_evt)
        record = dict(step=int(step))
        for key, value in phases.items():
            record[key] = round(value, 6)
        if phases.get("step_s"):
            # the data-stall share, stamped per record (ISSUE 12): the
            # SLO rules and the live tail key on it directly instead of
            # each consumer re-deriving data_s/step_s
            record["data_share"] = round(
                phases.get("data_s", 0.0) / phases["step_s"], 4)
        rolling = throughput.rolling_imgs_per_sec
        record["imgs_per_sec"] = round(rolling, 2)
        record["imgs_per_sec_cum"] = round(throughput.imgs_per_sec, 2)
        self._step_hist.observe(phases["step_s"])
        mfu = self.mfu.mfu(phases["step_s"])
        if mfu is not None:
            record["mfu"] = round(mfu, 5)
            self._mfu_hist.observe(mfu)
        if loss is not None:
            record["loss"] = float(loss)
        if health:
            record["health"] = dict(health)
        stride = self.timer.stride or self.registry.flush_every
        if step % stride == 0:
            sampled = self.devices.sample()
            record.update(sampled)
            if "hbm_peak_bytes" in sampled:
                self._hbm_gauge.set(sampled["hbm_peak_bytes"])
            self.pod.update(**sampled)
            if self.input_stats.staged_batches:
                # queue depth / cache hit rate / staged-batch latency /
                # worker busy fraction, cumulative for the run so far
                record["input"] = self.input_stats.snapshot()
            if self._grad_sync and self._grad_sync.get("mode") in (
                    "quantized", "demo"):
                record["grad_sync"] = self._grad_sync
        self.pod.update(
            step_s=phases["step_s"], data_s=phases["data_s"],
            imgs_per_sec=rolling, incidents=self._incidents.value,
        )
        flushed = self.registry.emit("step", **record)
        if self.heartbeat is not None:
            # EVERY step, decoupled from the sink's flush cadence (ISSUE 4
            # satellite): hang-detection granularity used to be an accident
            # of telemetry_flush_steps — a 50-step flush cadence meant the
            # supervisor saw a "hang" of 50 step times. The time gate
            # (heartbeat_secs) keeps the atomic replace off the fast path.
            # `last_step_ms` + `trace` (ISSUE 8 satellite): the supervisor
            # and /healthz read "currently profiling" and the latest step
            # time straight from the beat, no events.jsonl scrape.
            self.heartbeat.maybe_beat(
                step, phase="step",
                last_step_ms=round(phases["step_s"] * 1e3, 1),
                trace=self.tracer.capture_state(),
            )
        # book everything this method cost (the tracer's tick/flush work
        # ran inside this window, so the measurement already covers it;
        # span flushes on the STAGING threads are concurrent with the
        # step and deliberately not booked — they are not main-thread
        # time) into the explicit telemetry sub-phase
        self.tracer.consume_self_time()  # drop: contained in the window
        self.timer.note_telemetry(time.perf_counter() - t_tel0)
        return flushed

    # -- pod sync (piggybacks on the resilience_sync_steps allgather) --------
    def pod_vector(self):
        return self.pod.local_vector()

    def pod_record(self, step: int, gathered) -> None:
        self.pod.record(step, gathered)

    # -- teardown ------------------------------------------------------------
    def close(self, **extra_summary) -> None:
        """Idempotent: the driver closes with the run summary in its normal
        finally; a bare safety-net close after an early abort no-ops if the
        rich close already ran."""
        if self._closed:
            return
        self._closed = True
        mlog.remove_event_sink(self._on_event)
        summary = dict(
            steps=self._step_hist.count,
            incidents=self._incidents.value,
        )
        if self._step_hist.count:
            summary.update(
                step_s_p50=round(self._step_hist.percentile(50), 6),
                step_s_p95=round(self._step_hist.percentile(95), 6),
                step_s_p99=round(self._step_hist.percentile(99), 6),
            )
        if self._mfu_hist.count:
            summary["mfu_mean"] = round(self._mfu_hist.mean, 5)
        if self._hbm_gauge.high_water > float("-inf"):
            summary["hbm_peak_bytes"] = int(self._hbm_gauge.high_water)
        if self.input_stats.staged_batches:
            summary["input"] = self.input_stats.snapshot()
        if self.tracer.captures_used or self.tracer.spans_recorded:
            summary["trace"] = dict(
                self.tracer.capture_state(),
                spans_recorded=self.tracer.spans_recorded,
            )
        summary.update(extra_summary)
        self.registry.emit("run_end", **summary)
        if self.heartbeat is not None:
            # the final heartbeat is the supervisor's progress record for
            # the restart-budget refund: last completed step + this pid,
            # phase distinguishing a preemption exit (relaunch expected)
            # from a natural end
            phase = "run_end"
            if summary.get("preempted"):
                phase = "preempt_exit"
            elif summary.get("resized"):
                # a resize exit expects a relaunch onto a NEW mesh; any
                # non-"step" phase already widens the supervisor's
                # staleness window during the elastic checkpoint
                phase = "resize_exit"
            self.heartbeat.beat(
                summary.get("last_step", self._step_hist.count),
                phase=phase,
                trace=self.tracer.capture_state(),
            )
        self.registry.close()
        self.tracer.close()


def _profiler_start(trace_dir: str) -> None:
    """Capture-window device-trace hook (config: trace_device_profile).
    Lazy jax import: trace.py itself must stay jax-free, so the hooks are
    injected from this (already jax-coupled) module."""
    import jax

    jax.profiler.start_trace(trace_dir)


def _profiler_stop() -> None:
    import jax

    jax.profiler.stop_trace()
