"""Structured run telemetry (ISSUE 2 tentpole).

The measurement foundation every perf PR is judged against: step-phase
timing (data/host/device), analytic-FLOPs MFU, HBM/host-memory tracking,
pod-aggregated JSONL events, a heartbeat for external watchdogs, and the
`log_event` bridge that lands resilience incidents in the same stream.

Offline consumer: `tools/telemetry_report.py` renders p50/p95/p99 step
time, MFU, throughput, HBM high-water and incident counts from an
events.jsonl. Schema notes: registry.py module docstring + README
"Observability".
"""

from moco_tpu.telemetry.device import DeviceMonitor, host_rss_bytes
from moco_tpu.telemetry.mfu import (
    MFUEstimator,
    detect_peak_flops,
    model_fwd_flops,
    resnet_fwd_flops,
    train_step_flops,
    vit_fwd_flops,
)
from moco_tpu.telemetry.pod import POD_FIELDS, PodAggregator
from moco_tpu.telemetry.registry import (
    EVENTS_FILENAME,
    HEARTBEAT_FILENAME,
    SCHEMA_VERSION,
    Counter,
    Gauge,
    Heartbeat,
    Histogram,
    MetricsRegistry,
    percentiles_ms,
)
from moco_tpu.telemetry.run import RunTelemetry
from moco_tpu.telemetry.timing import StepPhaseTimer

__all__ = [
    "Counter",
    "DeviceMonitor",
    "EVENTS_FILENAME",
    "Gauge",
    "HEARTBEAT_FILENAME",
    "Heartbeat",
    "Histogram",
    "MFUEstimator",
    "MetricsRegistry",
    "POD_FIELDS",
    "PodAggregator",
    "RunTelemetry",
    "SCHEMA_VERSION",
    "StepPhaseTimer",
    "detect_peak_flops",
    "host_rss_bytes",
    "model_fwd_flops",
    "percentiles_ms",
    "resnet_fwd_flops",
    "train_step_flops",
    "vit_fwd_flops",
]
