"""Structured run telemetry (ISSUE 2 tentpole) + distributed tracing
(ISSUE 8).

The measurement foundation every perf PR is judged against: step-phase
timing (data/host/device), analytic-FLOPs MFU, HBM/host-memory tracking,
pod-aggregated JSONL events, a heartbeat for external watchdogs, the
`log_event` bridge that lands resilience incidents in the same stream,
and the cross-process span layer (`telemetry/trace.py`) that joins
supervisor, driver, staging workers and serve into one timeline.

Offline consumers: `tools/telemetry_report.py` renders p50/p95/p99 step
time, MFU, throughput, HBM high-water and incident counts from an
events.jsonl; `tools/trace_report.py` merges spans + events into one
Chrome-trace JSON. Schema notes: registry.py module docstring + README
"Observability" / "Tracing & profiling".

This __init__ is LAZY (PEP 562): the out-of-process supervisor imports
`moco_tpu.telemetry.trace` — which executes this package body — and must
stay importable without jax or numpy (mocolint R12 + the R11
supervisor-stdlib-only boundary). Eagerly importing `pod`/`run` here
would drag numpy (and, through the data package, jax) into every
supervisor process; instead each public name resolves its submodule on
first attribute access, so `from moco_tpu.telemetry import RunTelemetry`
keeps working unchanged while `import moco_tpu.telemetry.trace` touches
nothing heavy.
"""

from __future__ import annotations

import importlib

# public name -> submodule that defines it
_EXPORTS = {
    "Aggregator": "aggregate",
    "ObsServer": "aggregate",
    "PercentileWindow": "aggregate",
    "SLOEngine": "aggregate",
    "SLORule": "aggregate",
    "StreamTailer": "aggregate",
    "load_rules": "aggregate",
    "DeviceMonitor": "device",
    "host_rss_bytes": "device",
    "MFUEstimator": "mfu",
    "detect_peak_flops": "mfu",
    "model_fwd_flops": "mfu",
    "resnet_fwd_flops": "mfu",
    "train_step_flops": "mfu",
    "vit_fwd_flops": "mfu",
    "POD_FIELDS": "pod",
    "PodAggregator": "pod",
    "EVENTS_FILENAME": "registry",
    "HEARTBEAT_FILENAME": "registry",
    "SCHEMA_VERSION": "registry",
    "Counter": "registry",
    "Gauge": "registry",
    "Heartbeat": "registry",
    "Histogram": "registry",
    "MetricsRegistry": "registry",
    "percentiles_ms": "registry",
    "RunTelemetry": "run",
    "StepPhaseTimer": "timing",
    "Tracer": "trace",
    "SlowSampleDetector": "trace",
    "SpikeDetector": "trace",
    "SPANS_FILENAME": "trace",
    "TRIGGER_FILENAME": "trace",
    "TRACES_DIRNAME": "trace",
    "TRACE_MODES": "trace",
    "null_tracer": "trace",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        submodule = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    value = getattr(
        importlib.import_module(f"{__name__}.{submodule}"), name
    )
    globals()[name] = value  # cache: later accesses skip __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
