"""HBM + host-memory sampling (ISSUE 2 tentpole part 3).

`jax.Device.memory_stats()` is a PJRT call that returns allocator
statistics on TPU/GPU backends (`bytes_in_use`, `peak_bytes_in_use`,
`bytes_limit`) and None / raises on backends without an allocator API
(CPU, some relay transports) — sampling is therefore best-effort and the
absence of HBM keys in a record means "backend can't report", not zero.

Host RSS comes from /proc/self/statm (Linux; current resident set), with
`resource.getrusage` ru_maxrss (peak, kB) as the portable fallback — both
are cheap enough to sample at the device stride.
"""

from __future__ import annotations

import os
import resource
import sys

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

# memory_stats keys → our schema names
_HBM_KEYS = (
    ("bytes_in_use", "hbm_bytes_in_use"),
    ("peak_bytes_in_use", "hbm_peak_bytes"),
    ("bytes_limit", "hbm_bytes_limit"),
)


def host_rss_bytes() -> int:
    """Current resident set size (Linux /proc); off-Linux the fallback is
    ru_maxrss — the PEAK, not current, so the off-Linux curve is monotone
    — in the platform's native unit (bytes on macOS, kilobytes elsewhere:
    a blind *1024 would report terabytes on a Mac dev box)."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(rss if sys.platform == "darwin" else rss * 1024)


class DeviceMonitor:
    """Samples one device's allocator stats + this host's RSS.

    A backend that errors once on memory_stats is not asked again (the
    relay can raise on every call — that must not tax the step loop)."""

    def __init__(self, device=None):
        if device is None:
            import jax

            device = jax.local_devices()[0]
        self.device = device
        self._hbm_supported = True

    def sample(self) -> dict:
        out = {"host_rss_bytes": host_rss_bytes()}
        if self._hbm_supported:
            try:
                stats = self.device.memory_stats()
            except Exception:  # noqa: BLE001 — relay/backends raise freely here
                stats = None
                self._hbm_supported = False
            if stats:
                for src, dst in _HBM_KEYS:
                    if src in stats:
                        out[dst] = int(stats[src])
            else:
                self._hbm_supported = False
        return out
