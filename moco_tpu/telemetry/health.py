"""In-graph learning-health diagnostics (ISSUE 13 tentpole part a).

Systems observability (tracing, obsd) says whether the machine is
healthy; nothing so far says whether MoCo is *learning*. The failure
modes the paper's mechanism admits — representation collapse (every
input maps to one feature), a frozen/diverged key encoder, a queue full
of stale or degenerate negatives — are SILENT: the loss keeps moving
against a degenerate contrast set while the features rot. This module
computes the cheap in-graph signals that make those modes visible:

  per-dim embedding std      mean over dims of the per-dim std across
                             the (local) batch; a collapsed encoder
                             drives it to ~0 while loss still "trains"
  participation ratio        tr(C)^2 / tr(C^2) of the embedding
                             covariance — the effective number of
                             dimensions the batch actually occupies
                             (1 = rank-one collapse, D = isotropic);
                             computed without an eigendecomposition
  logit margin               pos_sim − mean neg_sim (both ×T): the
                             contrast the loss is actually working
                             with. A margin pinned at ~0 means the
                             positives are indistinguishable from the
                             negatives — collapse, or a degenerate
                             queue
  queue feature-norm stats   rows are L2-normalized at enqueue, so a
                             norm drifting from 1 (or ~0: a crushed
                             encoder's eps-floored zero vector) marks
                             degenerate entries
  ptr-derived queue age      how many steps ago the OLDEST live queue
                             row was enqueued (each step advances the
                             ptr by the global batch, so a full queue
                             is K/B steps deep): the staleness of the
                             negative set relative to the encoder
  query↔key parameter drift  ‖θ_q − θ_k‖ / ‖θ_q‖ over the EMA-covered
                             subtree: ~0 means the EMA collapsed onto
                             the query encoder (or nothing is moving)
  grad norm by layer group   global grad L2 + first/last top-level
                             parameter group — a vanishing head (or
                             stem) gradient is the earliest signal of
                             a dead loss

Contract (the step builders enforce it; tests pin it):

  - `health_stride == 0` (the default): none of the gated diagnostics
    trace — only the two always-on standard metrics (below) exist, as
    extra scalars in the metrics reduce the step already runs.
  - `health_stride = N`: the diagnostics are traced into the step under
    ONE `lax.cond` on `step % N == 0`; off-stride steps select the
    cheap zero branch, and the scalars ride the EXISTING per-step
    metrics reduction — no new collectives, no host callbacks
    (progcheck audits the instrumented variants).
  - diagnostics are observational: they read state/activations and
    contribute nothing to the loss/update path, so the parameter
    trajectory with health on is BITWISE the trajectory with it off.

`neg_sim`/`logit_margin` are standard step metrics (always on, like
`pos_sim` — they reuse the already-computed logits), popped by the
driver like the gradsync probe scalars and consumed by the
CollapseSentinel (resilience/sentinel.py) and the telemetry `health`
record block.

`crush_key_params` is the chaos `collapse_at_step` payload: it rewrites
the key-encoder params so its features degenerate to one constant
vector — the injected collapse every layer above (sentinel, obsd SLO,
serve reload guard) is drilled against.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# step-metric keys the driver pops before meters/scalar-writer see them
# (the gradsync gs_comm_* convention); the h_-prefixed ones exist only
# when health_stride > 0 and carry zeros on off-stride steps
HEALTH_PREFIX = "h_"
STANDARD_KEYS = ("neg_sim", "logit_margin")

# the canonical "on" stride (config default is 0 = off): what bench.py's
# health_overhead row measures against and the README documents — chosen
# so the amortized diagnostics cost stays well under 1% of step time
# while the sentinel still sees a fresh emb-std sample every few seconds
DEFAULT_STRIDE = 10


def neg_sim_mean(logits: jax.Array, labels: jax.Array,
                 temperature: float) -> jax.Array:
    """Mean negative-pair similarity ×T over the logit matrix, excluding
    each row's positive (the `labels` column). Works for both layouts:
    v1/v2 puts the positive at column 0 (labels are zeros), v3 at the
    global-batch diagonal offset."""
    total = jnp.sum(logits, dtype=jnp.float32)
    pos = jnp.sum(
        jnp.take_along_axis(logits, labels[:, None].astype(jnp.int32),
                            axis=-1),
        dtype=jnp.float32,
    )
    n, m = logits.shape
    return (total - pos) / (n * (m - 1)) * temperature


def embedding_stats(z: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(mean per-dim std, participation ratio) of a `[B, D]` embedding
    batch. The participation ratio tr(C)^2 / tr(C^2) needs only the
    covariance traces — one `[D, B] x [B, D]` matmul, no eig."""
    z = z.astype(jnp.float32)
    centered = z - jnp.mean(z, axis=0, keepdims=True)
    var = jnp.mean(jnp.square(centered), axis=0)            # [D]
    mean_std = jnp.mean(jnp.sqrt(var))
    cov = centered.T @ centered / z.shape[0]                # [D, D]
    tr = jnp.sum(var)
    tr_sq = jnp.sum(jnp.square(cov))
    pr = jnp.square(tr) / jnp.maximum(tr_sq, 1e-20)
    return mean_std, pr


def grad_group_norms(grads) -> dict[str, jax.Array]:
    """Global grad L2 norm + the first/last top-level parameter group's
    (sorted key order — deterministic for a given arch). Local per-device
    grads: the metrics pmean averages the per-device norms."""

    def _norm(tree) -> jax.Array:
        leaves = [jnp.sum(jnp.square(g.astype(jnp.float32)))
                  for g in jax.tree.leaves(tree)]
        total = sum(leaves) if leaves else jnp.zeros((), jnp.float32)
        return jnp.sqrt(total)

    out = {"h_gnorm": _norm(grads)}
    if isinstance(grads, dict) and grads:
        keys = sorted(grads)
        out["h_gnorm_first"] = _norm(grads[keys[0]])
        out["h_gnorm_last"] = _norm(grads[keys[-1]])
    return out


def _gated(step: jax.Array, stride: int, compute) -> dict[str, jax.Array]:
    """Trace `compute()` under ONE lax.cond on the health stride:
    off-stride steps select a same-structure zero branch, so the
    expensive diagnostics execute only every `stride` steps. The cond is
    a plain control-flow primitive — no collective, no callback — and
    its outputs join the step's EXISTING metrics reduction. The real
    branch is traced INSIDE the cond (only `eval_shape`d here for the
    zero branch's structure), so XLA never hoists the diagnostics onto
    the every-step path."""
    shapes = jax.eval_shape(compute)

    def zeros():
        return {k: jnp.zeros(v.shape, v.dtype) for k, v in shapes.items()}

    return lax.cond(step % stride == 0, compute, zeros)


def region_health(q: jax.Array, k: jax.Array, grads, step: jax.Array,
                  stride: int) -> dict[str, jax.Array]:
    """The shard_map-region diagnostics (per-device batch slice, averaged
    by the caller's metrics pmean): embedding std/participation ratio on
    the query AND key embeddings, grad norms by layer group."""

    def compute():
        std_q, pr_q = embedding_stats(q)
        std_k, _ = embedding_stats(k)
        out = {"h_emb_std_q": std_q, "h_emb_pr_q": pr_q,
               "h_emb_std_k": std_k}
        out.update(grad_group_norms(grads))
        return out

    return _gated(step, stride, compute)


def queue_health(queue: jax.Array, step: jax.Array, global_batch: int,
                 stride: int) -> dict[str, jax.Array]:
    """Queue-side diagnostics, computed at the OUTER jit level where the
    queue is replicated (no collective): row-norm mean/min + the
    ptr-derived age in steps of the oldest live entry (the enqueue
    advances the ptr by the global batch each step, so a warm queue is
    exactly K/B steps deep; before that the age is the step count)."""
    k_slots = queue.shape[0]
    depth = max(k_slots // max(global_batch, 1), 1)

    def compute():
        norms = jnp.sqrt(jnp.sum(
            jnp.square(queue.astype(jnp.float32)), axis=-1))
        return {
            "h_qnorm_mean": jnp.mean(norms),
            "h_qnorm_min": jnp.min(norms),
            "h_qage_steps": jnp.minimum(
                step, depth).astype(jnp.float32),
        }

    return _gated(step, stride, compute)


def param_drift(params_q, params_k, step: jax.Array,
                stride: int) -> dict[str, jax.Array]:
    """Relative query↔key parameter drift ‖θ_q − θ_k‖ / ‖θ_q‖ over the
    EMA-covered subtree (the caller passes the matching trees — v3 drops
    the predictor). Outer-level, replicated: no collective."""

    def compute():
        diff_sq = q_sq = jnp.zeros((), jnp.float32)
        for gq, gk in zip(jax.tree.leaves(params_q),
                          jax.tree.leaves(params_k)):
            gq = gq.astype(jnp.float32)
            diff_sq = diff_sq + jnp.sum(jnp.square(gq - gk.astype(jnp.float32)))
            q_sq = q_sq + jnp.sum(jnp.square(gq))
        return {"h_pdrift": jnp.sqrt(diff_sq)
                / jnp.maximum(jnp.sqrt(q_sq), 1e-12)}

    return _gated(step, stride, compute)


def crush_key_params(params_k):
    """The chaos `collapse_at_step` payload: a key-encoder param tree
    whose forward maps EVERY input to one constant feature vector —
    kernels (≥2-D leaves) AND normalization `scale` leaves zeroed,
    remaining 1-D leaves (biases/shifts) set to one, so every block
    emits a constant and the final layer's bias alone decides the
    output. Zeroing the BN/LN scales matters: the step's own EMA leaks
    (1−m)·θ_q back in BEFORE the key forward, and batch norm rescales
    any nonzero kernel back to O(1) input-dependent activations — with
    the scales at ~(1−m) that leak is attenuated to noise instead. The
    driver re-applies the crush after every step at/after the fault: the
    fault models a persistently-wedged momentum update, not a one-off
    corruption."""

    def crush(path, x):
        name = getattr(path[-1], "key", "") if path else ""
        if name == "scale" or x.ndim != 1:
            return jnp.zeros_like(x)
        return jnp.ones_like(x)

    return jax.tree_util.tree_map_with_path(crush, params_k)
