"""obsd core: fleet-wide metrics aggregation + the SLO/burn-rate engine
(ISSUE 12 tentpole).

The repo's processes each write rich per-process telemetry (events.jsonl
step/serve/fleet/supervisor records, heartbeat.json), but nothing WATCHES
a deployment: `telemetry_report` is an after-the-fact fold, and the
autoscaler (ROADMAP 2b) needs a rolling signal, not a last-snapshot one.
This module is that always-on layer, and it obeys the supervisor import
contract: PURE stdlib, importable without jax or numpy (mocolint R11
`obsd-stdlib-only` pins it, transitively) — the aggregator must outlive
the runtimes it observes.

Pieces, bottom-up:

  - `PercentileWindow` — ring-buffered percentile sketch over the most
    recent N observations (the `Histogram(window=...)` idea without the
    numpy-adjacent registry coupling; `FleetRouter` uses it for the
    router_stats latency window).
  - `StreamTailer` — incremental, partial-line-safe reads of one
    events.jsonl (`--follow`'s discipline: only newline-terminated lines
    parse; the torn tail waits; truncation resets; a missing file is
    "not yet", never an error). obsd is a PURE READER of producer
    streams — no producer code path ever blocks on it.
  - `RunWindow` — one run_id's rolling state: step-time/MFU/phase-share
    sketches, event timestamps by name, router_stats + serve snapshot
    history for window deltas. `metric(name, window_s)` resolves the
    objective names SLO rules key on (table in `metric.__doc__`).
  - `SLORule` / `SLOEngine` — declarative rules (JSON file): an
    objective is violated only when BOTH the fast and the slow window
    exceed the threshold (multi-window burn rate: the fast window says
    "it is happening now", the slow one "it is not a blip"), sustained
    for `for_s` before alerting and clear for `clear_s` before
    recovering (hysteresis — a flapping metric produces one alert, not
    one per tick).
  - `Aggregator` — tails every stream under N telemetry roots (a fleet
    root contributes its own events.jsonl + every replica*/ one, and new
    replica dirs are discovered live), folds records into per-run
    windows, evaluates the rules each tick, appends `kind:"slo"`
    alert/recovery records back into the producing run's OWN stream
    (single O_APPEND line — safe to interleave with the producer's
    appends), and snapshots for the HTTP endpoints.
  - `ObsServer` — ThreadingHTTPServer: `/metrics` (Prometheus text
    exposition 0.0.4), `/slo` + `/runs` (JSON), `/healthz`.

`tools/obsd.py` is the CLI; `tools/telemetry_report.py` renders the
`slo:` section from the records this module appends.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from moco_tpu.telemetry.registry import Histogram

SCHEMA_VERSION = 1

EVENTS_FILENAME = "events.jsonl"
HEARTBEAT_FILENAME = "heartbeat.json"

# telemetry subdirectory families a root can contain: `replica*` (the
# serve-fleet layout, ISSUE 10) and `staging_server*` (the input-service
# layout, ISSUE 14). THE definition — telemetry_report discovers the
# same dirs obsd tails, so the next family lands in both at once
TELEMETRY_SUBDIR_PREFIXES = ("replica", "staging_server")

SLO_KIND = "slo"

# event names that count as "rollback/NaN trouble" for the default rule
ROLLBACK_EVENTS = ("rollback", "sentinel")
# fleet events that count as reload failures (quarantine included: a
# corrupt export IS a deploy failure even though the fleet survived it;
# a bank-pair quarantine is the ISSUE 16 flavor of the same outcome)
RELOAD_FAILURE_EVENTS = ("reload_failed", "reload_quarantine",
                         "reload_watch_error", "reload_bad_layout",
                         "bank_quarantine")


# ---------------------------------------------------------------------------
# percentile sketch (ring-buffered; shared with FleetRouter's latency window)
# ---------------------------------------------------------------------------


class PercentileWindow(Histogram):
    """`registry.Histogram` pinned to its bounded-`window` mode — the
    ring shape the router latency window and the run windows need, with
    ONE copy of the nearest-rank math. `observe` is a `deque.append`
    (GIL-atomic — concurrent HTTP handler threads may observe without a
    lock); `percentile` sorts a snapshot copy, so a concurrent append
    during the sort costs at most one sample of skew."""

    def __init__(self, size: int = 512):
        super().__init__("window", window=int(size))


def percentile(values, q: float) -> float:
    """Nearest-rank percentile of a plain iterable (one-shot form of
    `Histogram.percentile`, same rank math by construction)."""
    h = Histogram("tmp")
    for v in values:
        h.observe(float(v))
    return h.percentile(q)


# ---------------------------------------------------------------------------
# stream tailing (the --follow read discipline, as a reusable object)
# ---------------------------------------------------------------------------


class StreamTailer:
    """Incrementally read complete JSONL records from one events file.

    Each `poll()` returns the records whose terminating newline landed
    since the last poll. Partial-line-safe: bytes after the last newline
    stay buffered until their newline arrives (the producer's buffered
    multi-line appends can be caught mid-write). A missing file means
    "producer not up yet"; shrinkage means truncation/rotation — reset
    and re-read. Unparseable lines are counted, never fatal.

    Content that already exists when the tailer is CREATED is flagged as
    catch-up (`polled_catchup` True for polls still inside it): the
    aggregator folds it into counters/meta but not into the rolling
    windows — a restarted obsd must not replay yesterday's incident as
    if it were happening now (and then append a duplicate alert)."""

    def __init__(self, path: str, from_start: bool = True):
        self.path = path
        self._offset = 0
        self._buffer = b""
        self.skipped = 0
        self.records_read = 0
        try:
            self.preexisting = os.path.getsize(path)
        except OSError:
            self.preexisting = 0
        self.polled_catchup = False  # last poll began inside preexisting
        if not from_start:
            self._offset = self.preexisting

    def poll(self) -> list[dict]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []  # not created yet
        if size < self._offset:  # truncated/rotated: start over — the
            self._offset, self._buffer = 0, b""
            self.preexisting = 0  # rewritten content is NEW, not history
        self.polled_catchup = self._offset < self.preexisting
        if size <= self._offset:
            return []
        try:
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                chunk = f.read()
        except OSError:
            return []  # vanished between stat and open: next poll decides
        self._offset += len(chunk)
        self._buffer += chunk
        *complete, self._buffer = self._buffer.split(b"\n")
        records = []
        for raw in complete:
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw.decode("utf-8", errors="replace"))
            except json.JSONDecodeError:
                self.skipped += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
                self.records_read += 1
            else:
                self.skipped += 1
        return records


def discover_streams(roots) -> dict:
    """`{label: events_path}` for the given telemetry roots. A FILE
    argument is one stream; a DIRECTORY contributes its own events.jsonl
    plus every `replica*/events.jsonl` (the fleet layout) and
    `staging_server*/events.jsonl` (the input-service layout, ISSUE 14)
    under it — called every poll, so replica/server dirs that appear
    later join live."""
    streams: dict[str, str] = {}
    for root in roots:
        if os.path.isfile(root) or root.endswith(".jsonl"):
            streams[root] = root
            continue
        own = os.path.join(root, EVENTS_FILENAME)
        streams[root] = own
        try:
            names = sorted(os.listdir(root))
        except OSError:
            continue
        for name in names:
            sub = os.path.join(root, name, EVENTS_FILENAME)
            if (name.startswith(TELEMETRY_SUBDIR_PREFIXES)
                    and os.path.exists(sub)):
                streams[os.path.join(root, name)] = sub
    return streams


# ---------------------------------------------------------------------------
# per-run rolling windows
# ---------------------------------------------------------------------------


class RunWindow:
    """One run_id's rolling telemetry state.

    Entries are (mono_seen, ...) tuples on ring-buffered deques —
    bounded memory no matter how long the run — and every window metric
    filters by OBSERVATION time on the aggregator's own monotonic
    clock, so a producer's wall-clock step can never fake freshness or
    staleness (the same lesson as the heartbeat's seq/mono_s pair)."""

    def __init__(self, run_id: str, ring: int = 2048):
        self.run_id = run_id
        self.srcs: set[str] = set()
        self.kinds: dict[str, int] = {}
        self.meta: dict = {}
        self.ended = False
        self.last_wall_t: float | None = None
        self.first_seen = float("inf")   # mono of the first ingest (any)
        self.last_seen = float("-inf")   # mono of the newest LIVE record
        self.home_path: str | None = None  # stream slo records append to
        self.steps_total = 0
        self.incidents: dict[str, int] = {}
        self.slo_events = 0
        # rings: (mono, payload...)
        self._steps: deque = deque(maxlen=ring)       # (mono, step_s,
                                                      #  data_s, mfu)
        self._events: deque = deque(maxlen=ring)      # (mono, name)
        self._router: deque = deque(maxlen=256)       # (mono, record)
        self._serve: deque = deque(maxlen=256)        # (mono, record)
        self._health: deque = deque(maxlen=256)       # (mono, block, step)
        self._input: deque = deque(maxlen=256)        # (mono, input snap)
        self.last_step: dict | None = None
        self.last_router: dict | None = None
        self.last_serve: dict | None = None
        self.last_health: dict | None = None
        self.last_bank: dict | None = None

    # -- ingest --------------------------------------------------------------
    def ingest(self, rec: dict, src: str, path: str, now: float,
               historical: bool = False) -> None:
        """Fold one record. `historical=True` marks catch-up content
        that predates this aggregator (a restarted obsd re-reading the
        file): it feeds counters, meta and incident totals — the /runs
        story — but NEVER the time-windowed rings, because stamping old
        records at observation-time `now` would replay yesterday's
        incident as live and fire a duplicate alert into the stream."""
        self.first_seen = min(self.first_seen, now)
        kind = str(rec.get("kind", "?"))
        if kind == SLO_KIND:
            # our own (or a previous obsd incarnation's) output: count it,
            # never feed it back into the windows it was computed from
            self.slo_events += 1
            return
        self.srcs.add(src)
        self.kinds[kind] = self.kinds.get(kind, 0) + 1
        if not historical:
            # historical records must not make a long-dead run look
            # live: stale_s stays anchored to genuinely observed appends
            self.last_seen = now
        if isinstance(rec.get("t"), (int, float)):
            self.last_wall_t = rec["t"]
        if self.home_path is None:
            self.home_path = path
        if kind == "step":
            self.steps_total += 1
            self.last_step = rec
            try:
                step_no = int(rec.get("step", self.steps_total))
            except (TypeError, ValueError):
                step_no = self.steps_total
            health = rec.get("health")
            if isinstance(health, dict):
                self.last_health = health
            if not historical:
                self._steps.append((
                    now,
                    float(rec.get("step_s") or 0.0),
                    float(rec.get("data_s") or 0.0),
                    rec.get("mfu"),
                    step_no,
                ))
                if isinstance(health, dict):
                    self._health.append((now, health, step_no))
                # cumulative input-pipeline snapshot (ISSUE 14): the
                # credit_stall_s/wall_s pair feeds the windowed
                # input_credit_stall_rate delta
                if isinstance(rec.get("input"), dict):
                    self._input.append((now, rec["input"]))
        elif kind == "event":
            name = str(rec.get("event", "unknown"))
            self.incidents[name] = self.incidents.get(name, 0) + 1
            if not historical:
                self._events.append((now, name))
        elif kind == "input_server":
            # staging-server stream (ISSUE 14): periodic `stats` records
            # are routine cumulative snapshots, lifecycle transitions
            # (launch/eject/kill/worker_exit/give_up) are incidents like
            # their fleet twins
            name = str(rec.get("event", "unknown"))
            if name != "stats":
                self.incidents[name] = self.incidents.get(name, 0) + 1
                if not historical:
                    self._events.append((now, name))
        elif kind in ("supervisor", "fleet"):
            name = str(rec.get("event", "unknown"))
            if name == "router_stats":
                self.last_router = rec
                if not historical:
                    self._router.append((now, rec))
            else:
                self.incidents[name] = self.incidents.get(name, 0) + 1
                if not historical:
                    self._events.append((now, name))
        elif kind == "bank":
            # bank lifecycle stream (ISSUE 16): builder progress
            # (build_start/shard_done/build_done), the service's dual
            # `swap`, and the fleet's `bank_waiting`/`quarantine`/
            # `bank_quarantine`/`rollback`. Event names normalize to a
            # `bank_` prefix so `event:bank_rollback` reads the same
            # whether the producer already prefixed or not; shard_done
            # is routine build progress, not an incident
            name = str(rec.get("event", "unknown"))
            if not name.startswith("bank"):
                name = "bank_" + name
            if name in ("bank_swap", "bank_waiting"):
                # the records that carry freshness: swap pins age to
                # step - bank_step, bank_waiting carries age_steps
                self.last_bank = rec
            if name != "bank_shard_done":
                self.incidents[name] = self.incidents.get(name, 0) + 1
                if not historical:
                    self._events.append((now, name))
        elif kind == "serve":
            self.last_serve = rec
            if not historical:
                self._serve.append((now, rec))
        elif kind == "run_start":
            self.meta = {
                k: rec[k] for k in ("name", "variant", "arch",
                                    "batch_size", "n_chips")
                if k in rec
            }
            self.ended = False
        elif kind == "run_end":
            self.ended = True

    # -- window folds --------------------------------------------------------
    def _step_window(self, window_s: float, now: float,
                     min_step: int = 0) -> list:
        cut = now - window_s
        return [s for s in self._steps
                if s[0] >= cut and s[4] > min_step]

    def event_count(self, names, window_s: float, now: float) -> int:
        cut = now - window_s
        names = set(names)
        return sum(1 for (mono, n) in self._events
                   if mono >= cut and n in names)

    def _counter_delta(self, ring: deque, window_s: float, now: float,
                       fold) -> tuple[float, float] | None:
        """(delta_numer, delta_denom) between the oldest and newest
        cumulative-counter snapshot inside the window; None without two
        snapshots. `fold(rec) -> (numer, denom)`."""
        cut = now - window_s
        inside = [rec for (mono, rec) in ring if mono >= cut]
        if len(inside) < 2:
            return None
        n0, d0 = fold(inside[0])
        n1, d1 = fold(inside[-1])
        return max(n1 - n0, 0.0), max(d1 - d0, 0.0)

    def metric(self, name: str, window_s: float, now: float,
               min_step: int = 0):
        """Resolve one SLO objective over `window_s` trailing seconds.
        `min_step` drops step records with step index <= it from the
        step-derived objectives (the rule-level `min_step` knob: cold
        compile/warmup steps are seconds-scale BY DESIGN — the
        SlowSampleDetector `skip` lesson — and must not page anyone).

        Objectives (None = no data in the window; a rule never fires on
        silence — staleness is its own objective):

          step_time_ms_p50|p95|p99|max  windowed step-time percentiles
          data_share                    sum(data_s)/sum(step_s)
          mfu_mean                      windowed mean MFU
          shed_rate                     router window delta: sheds/requests
          serve_shed_rate               serve-snapshot delta: sheds/requests
          outstanding                   last router_stats outstanding depth
          router_latency_ms_p95         last router_stats window p95
          serve_latency_ms_p95          last serve snapshot p95
          input_credit_stall_rate       input-snapshot delta (ISSUE 14):
                                        credit_stall_s/wall_s — the
                                        fraction of wall time the train
                                        host spent blocked on an empty
                                        ready queue; a sustained high
                                        rate IS a starving train host
          reload_failures               reload_* failure events in window
                                        (bank_quarantine included: a
                                        refused pair IS a failed deploy)
          rollback_events               rollback/sentinel events in window
          bank_age_steps                promoted-checkpoint step minus
                                        serving-bank step, from the last
                                        bank swap/bank_waiting record
                                        (ISSUE 16) — a growing age means
                                        checkpoints are landing without
                                        paired banks and the fleet is
                                        pinned on an aging pair
          resize_relaunches             resize_relaunch records in window
          ann_recall_probe              last serve snapshot's seeded
                                        ANN-vs-exact recall@1 probe
                                        (ISSUE 20) — the quantizer's
                                        standing quality gauge; absent
                                        on exact-only services
          knn_partial_rate              router window delta (ISSUE 20):
                                        partial fan-out answers /
                                        fan-outs — sustained partials
                                        mean a shard can't make the
                                        deadline
          autoscale_events              autoscale_up + autoscale_down
                                        actions in window (flapping
                                        capacity is its own incident)
          stale_s                       seconds since the newest record
          event:<name>                  count of that event name in window
          health:<key>                  windowed MEAN of that key in the
                                        step records' learning-health
                                        block (ISSUE 13; keys as written
                                        by the driver: logit_margin,
                                        emb_std_q, emb_std_k, emb_pr_q,
                                        qnorm_min, pdrift, ...)
          health_min:<key> /            windowed MIN / MAX of the same —
          health_max:<key>              collapse is a floor violation, and
                                        a window MEAN would let healthy
                                        history mask a fresh collapse
          collapse_events               sentinel `health` incidents in
                                        window (alias of event:health)
        """
        if name.startswith("event:"):
            return float(self.event_count((name[6:],), window_s, now))
        if name == "collapse_events":
            return float(self.event_count(("health",), window_s, now))
        for prefix, fold in (("health:", None), ("health_min:", min),
                             ("health_max:", max)):
            if name.startswith(prefix):
                key = name[len(prefix):]
                cut = now - window_s
                vals = [h[key] for (mono, h, step_no) in self._health
                        if mono >= cut and step_no > min_step
                        and isinstance(h.get(key), (int, float))]
                if not vals:
                    return None
                if fold is None:
                    return sum(vals) / len(vals)
                return float(fold(vals))
        if name in ("step_time_ms_p50", "step_time_ms_p95",
                    "step_time_ms_p99", "step_time_ms_max"):
            steps = self._step_window(window_s, now, min_step)
            if not steps:
                return None
            times = [s[1] for s in steps]
            if name.endswith("max"):
                return max(times) * 1e3
            return percentile(times, float(name.rsplit("p", 1)[1])) * 1e3
        if name == "data_share":
            steps = self._step_window(window_s, now, min_step)
            total = sum(s[1] for s in steps)
            if total <= 0.0:
                return None
            return sum(s[2] for s in steps) / total
        if name == "mfu_mean":
            mfus = [s[3] for s in self._step_window(window_s, now, min_step)
                    if isinstance(s[3], (int, float))]
            if not mfus:
                return None
            return sum(mfus) / len(mfus)
        if name == "shed_rate":
            delta = self._counter_delta(
                self._router, window_s, now,
                lambda r: (float(r.get("shed_no_backend", 0)
                                 + r.get("upstream_timeout", 0)
                                 + r.get("upstream_error", 0)
                                 + r.get("shed_deadline_router", 0)),
                           float(r.get("requests", 0))))
            if delta is None:
                return None
            sheds, requests = delta
            return sheds / requests if requests else 0.0
        if name == "serve_shed_rate":
            delta = self._counter_delta(
                self._serve, window_s, now,
                lambda r: (float(r.get("shed_overload", 0)
                                 + r.get("shed_deadline", 0)),
                           float(r.get("requests", 0))))
            if delta is None:
                return None
            sheds, requests = delta
            return sheds / requests if requests else 0.0
        if name == "input_credit_stall_rate":
            delta = self._counter_delta(
                self._input, window_s, now,
                lambda r: (float(r.get("credit_stall_s", 0.0)),
                           float(r.get("wall_s", 0.0))))
            if delta is None:
                return None
            stalled, wall = delta
            return stalled / wall if wall else 0.0
        if name == "outstanding":
            if self.last_router is None:
                return None
            return float(self.last_router.get("outstanding", 0))
        if name == "router_latency_ms_p95":
            lat = (self.last_router or {}).get("latency_ms")
            return float(lat["p95"]) if isinstance(lat, dict) \
                and "p95" in lat else None
        if name == "serve_latency_ms_p95":
            lat = (self.last_serve or {}).get("latency_ms")
            return float(lat["p95"]) if isinstance(lat, dict) \
                and "p95" in lat else None
        if name == "reload_failures":
            return float(self.event_count(RELOAD_FAILURE_EVENTS,
                                          window_s, now))
        if name == "rollback_events":
            return float(self.event_count(ROLLBACK_EVENTS, window_s, now))
        if name == "bank_age_steps":
            if self.last_bank is None:
                return None
            age = self.last_bank.get("age_steps")
            if isinstance(age, (int, float)):
                return float(age)
            step = self.last_bank.get("step")
            bank_step = self.last_bank.get("bank_step")
            if (isinstance(step, (int, float))
                    and isinstance(bank_step, (int, float))):
                return float(step) - float(bank_step)
            return None
        if name == "resize_relaunches":
            return float(self.event_count(("resize_relaunch",),
                                          window_s, now))
        if name == "ann_recall_probe":
            ann = (self.last_serve or {}).get("ann")
            if isinstance(ann, dict) and isinstance(
                    ann.get("recall_probe"), (int, float)):
                return float(ann["recall_probe"])
            return None
        if name == "knn_partial_rate":
            delta = self._counter_delta(
                self._router, window_s, now,
                lambda r: (float(r.get("knn_partial", 0)),
                           float(r.get("knn_fanout", 0))))
            if delta is None:
                return None
            partial, fanout = delta
            return partial / fanout if fanout else 0.0
        if name == "autoscale_events":
            return float(self.event_count(
                ("autoscale_up", "autoscale_down"), window_s, now))
        if name == "stale_s":
            if self.last_seen == float("-inf"):
                return None
            return max(now - self.last_seen, 0.0)
        raise ValueError(f"unknown SLO objective {name!r}")

    def snapshot(self, now: float) -> dict:
        """The /runs payload for this run."""
        snap: dict = {
            "run_id": self.run_id,
            "srcs": sorted(self.srcs),
            "kinds": dict(sorted(self.kinds.items())),
            "steps": self.steps_total,
            "ended": self.ended,
            "slo_events": self.slo_events,
        }
        if self.meta:
            snap["run"] = self.meta
        if self.last_wall_t is not None:
            snap["last_t"] = self.last_wall_t
        if self.last_seen != float("-inf"):
            snap["stale_s"] = round(max(now - self.last_seen, 0.0), 3)
        if self.last_step is not None:
            snap["last_step"] = {
                k: self.last_step[k]
                for k in ("step", "step_s", "data_share", "mfu",
                          "imgs_per_sec")
                if k in self.last_step
            }
        if self.incidents:
            snap["events"] = dict(sorted(self.incidents.items()))
        if self.last_health is not None:
            snap["health"] = self.last_health
        if self.last_bank is not None:
            snap["bank"] = {
                k: self.last_bank[k]
                for k in ("event", "step", "bank_step", "age_steps",
                          "rows", "generation", "agreement")
                if k in self.last_bank
            }
        return snap


# ---------------------------------------------------------------------------
# SLO rules + burn-rate engine
# ---------------------------------------------------------------------------

_OPS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}

# The default rule set (README "obsd" documents each): thresholds are
# deliberately conservative — an operator tunes them per deployment via
# the rule file; the count-objective rules (reload/rollback/resize) are
# meaningful everywhere as shipped.
DEFAULT_RULES = (
    {"name": "step_time_p95", "objective": "step_time_ms_p95",
     "op": ">", "threshold": 2000.0,
     "fast_window_s": 60.0, "slow_window_s": 300.0},
    {"name": "data_stall_share", "objective": "data_share",
     "op": ">", "threshold": 0.6,
     "fast_window_s": 60.0, "slow_window_s": 300.0},
    {"name": "shed_rate", "objective": "shed_rate",
     "op": ">", "threshold": 0.05,
     "fast_window_s": 60.0, "slow_window_s": 300.0},
    {"name": "input_credit_stall", "objective": "input_credit_stall_rate",
     "op": ">", "threshold": 0.25,
     "fast_window_s": 60.0, "slow_window_s": 300.0},
    {"name": "reload_failure", "objective": "reload_failures",
     "op": ">=", "threshold": 1.0,
     "fast_window_s": 300.0, "slow_window_s": 900.0},
    {"name": "nonfinite_loss", "objective": "rollback_events",
     "op": ">=", "threshold": 1.0,
     "fast_window_s": 300.0, "slow_window_s": 900.0},
    {"name": "resize_loop", "objective": "resize_relaunches",
     "op": ">=", "threshold": 3.0,
     "fast_window_s": 600.0, "slow_window_s": 1800.0},
)


class SLORule:
    """One declarative objective. JSON fields (rule-file reference):

      name           unique id (required)
      objective      a RunWindow.metric name (required)
      op             ">" | ">=" | "<" | "<=" (default ">")
      threshold      violation bound (required)
      fast_window_s  burn-rate fast window (default 60)
      slow_window_s  burn-rate slow window (default 5 × fast)
      fast_threshold / slow_threshold
                     per-window overrides of `threshold` (classic
                     multi-burn-rate: a steeper bar on the fast window)
      for_s          violation must be sustained this long before the
                     alert fires (default 0: first confirmed tick)
      clear_s        fast window must be clean this long before the
                     recovery fires (default 2 s — hysteresis: a metric
                     hovering at its threshold flaps once, not once per
                     tick)
      min_step       ignore step records with step <= this for the
                     step-derived objectives (default 3: cold-compile
                     steps are seconds-scale by design)
      severity       "page" | "ticket" | ... (annotation only)
    """

    def __init__(self, spec: dict):
        try:
            self.name = str(spec["name"])
            self.objective = str(spec["objective"])
            self.threshold = float(spec["threshold"])
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"bad SLO rule {spec!r}: {e}") from None
        self.op = str(spec.get("op", ">"))
        if self.op not in _OPS:
            raise ValueError(
                f"rule {self.name!r}: unknown op {self.op!r} "
                f"(choose from {sorted(_OPS)})"
            )
        self.fast_window_s = float(spec.get("fast_window_s", 60.0))
        self.slow_window_s = float(
            spec.get("slow_window_s", 5.0 * self.fast_window_s))
        if self.fast_window_s <= 0 or self.slow_window_s < self.fast_window_s:
            raise ValueError(
                f"rule {self.name!r}: need 0 < fast_window_s <= "
                f"slow_window_s"
            )
        self.fast_threshold = float(spec.get("fast_threshold",
                                             self.threshold))
        self.slow_threshold = float(spec.get("slow_threshold",
                                             self.threshold))
        self.for_s = float(spec.get("for_s", 0.0))
        self.clear_s = float(spec.get("clear_s", 2.0))
        self.min_step = int(spec.get("min_step", 3))
        self.severity = str(spec.get("severity", "ticket"))

    def violated(self, window: RunWindow, now: float) -> tuple | None:
        """(fast_value, slow_value, violating) — None when the objective
        has no data in EITHER window (silence never burns budget)."""
        fast = window.metric(self.objective, self.fast_window_s, now,
                             self.min_step)
        slow = window.metric(self.objective, self.slow_window_s, now,
                             self.min_step)
        if fast is None or slow is None:
            return None
        op = _OPS[self.op]
        return (fast, slow,
                op(fast, self.fast_threshold)
                and op(slow, self.slow_threshold))


def load_rules(path: str | None) -> list[SLORule]:
    """Rule file -> rules; None/"" -> the default set. Accepts either a
    bare JSON list or {"rules": [...]}."""
    if not path:
        return [SLORule(dict(s)) for s in DEFAULT_RULES]
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        data = data.get("rules")
    if not isinstance(data, list) or not data:
        raise ValueError(f"{path}: expected a JSON list of rules "
                         '(or {"rules": [...]})')
    rules = [SLORule(s) for s in data]
    names = [r.name for r in rules]
    if len(set(names)) != len(names):
        raise ValueError(f"{path}: duplicate rule names in {names}")
    return rules


class _RuleState:
    """Alert state machine for one (rule, run) pair."""

    __slots__ = ("alerting", "violating_since", "clean_since",
                 "alerts", "recoveries", "last_fast", "last_slow",
                 "since_wall")

    def __init__(self):
        self.alerting = False
        self.violating_since: float | None = None
        self.clean_since: float | None = None
        self.alerts = 0
        self.recoveries = 0
        self.last_fast: float | None = None
        self.last_slow: float | None = None
        self.since_wall: float | None = None


class SLOEngine:
    """Evaluate every rule against every run window each tick; return
    alert/recovery TRANSITIONS (the aggregator lands them as records).

    Burn-rate + hysteresis semantics, per (rule, run):
      ok -> alert   when fast AND slow windows violate, sustained for
                    `for_s` (a one-tick blip inside `for_s` re-arms)
      alert -> ok   when the fast window stops violating (or goes
                    data-less) for `clear_s` — the slow window is
                    deliberately NOT required to clear: it can stay
                    poisoned for its whole width after a real incident,
                    and recovery means "not happening NOW"
    """

    def __init__(self, rules: list[SLORule]):
        self.rules = list(rules)
        self._state: dict[tuple[str, str], _RuleState] = {}

    def state_for(self, rule_name: str, run_id: str) -> _RuleState:
        return self._state.setdefault((rule_name, run_id), _RuleState())

    def evaluate(self, windows: dict, now: float) -> list[dict]:
        transitions = []
        for rule in self.rules:
            for run_id, window in windows.items():
                res = rule.violated(window, now)
                if res is None and (rule.name, run_id) not in self._state:
                    # an objective this run has NEVER produced data for
                    # (a step-time rule over a serve fleet): no state, no
                    # /slo row — silence is absence, not "ok"
                    continue
                st = self.state_for(rule.name, run_id)
                if res is not None:
                    st.last_fast, st.last_slow = res[0], res[1]
                violating = bool(res and res[2])
                if violating:
                    st.clean_since = None
                    if st.violating_since is None:
                        st.violating_since = now
                    if (not st.alerting
                            and now - st.violating_since >= rule.for_s):
                        st.alerting = True
                        st.alerts += 1
                        st.since_wall = time.time()
                        transitions.append(self._transition(
                            "alert", rule, run_id, st))
                else:
                    st.violating_since = None
                    if st.alerting:
                        if st.clean_since is None:
                            st.clean_since = now
                        if now - st.clean_since >= rule.clear_s:
                            st.alerting = False
                            st.recoveries += 1
                            transitions.append(self._transition(
                                "recover", rule, run_id, st))
                            st.since_wall = None
        return transitions

    def _transition(self, action: str, rule: SLORule, run_id: str,
                    st: _RuleState) -> dict:
        rec = {
            "action": action,
            "rule": rule.name,
            "objective": rule.objective,
            "op": rule.op,
            "threshold": rule.threshold,
            "severity": rule.severity,
            "run_id": run_id,
            "fast_window_s": rule.fast_window_s,
            "slow_window_s": rule.slow_window_s,
        }
        if st.last_fast is not None:
            rec["value_fast"] = round(st.last_fast, 6)
        if st.last_slow is not None:
            rec["value_slow"] = round(st.last_slow, 6)
        return rec

    def snapshot(self, windows: dict) -> dict:
        """The /slo payload: per-rule spec + per-run state."""
        out: dict = {"rules": []}
        for rule in self.rules:
            entry: dict = {
                "name": rule.name,
                "objective": rule.objective,
                "op": rule.op,
                "threshold": rule.threshold,
                "fast_window_s": rule.fast_window_s,
                "slow_window_s": rule.slow_window_s,
                "for_s": rule.for_s,
                "clear_s": rule.clear_s,
                "severity": rule.severity,
                "runs": {},
            }
            for run_id in windows:
                st = self._state.get((rule.name, run_id))
                if st is None:
                    continue
                run_state: dict = {
                    "state": "alert" if st.alerting else "ok",
                    "alerts": st.alerts,
                    "recoveries": st.recoveries,
                }
                if st.last_fast is not None:
                    run_state["value_fast"] = round(st.last_fast, 6)
                if st.last_slow is not None:
                    run_state["value_slow"] = round(st.last_slow, 6)
                if st.since_wall is not None:
                    run_state["since"] = round(st.since_wall, 3)
                entry["runs"][run_id] = run_state
            out["rules"].append(entry)
        return out


# ---------------------------------------------------------------------------
# the aggregator
# ---------------------------------------------------------------------------


class Aggregator:
    """Tail N telemetry roots into per-run windows + the SLO engine.

    `poll_once()` is the whole unit of work (tests drive it directly;
    `run()` loops it on `tick_secs`): tail every stream, ingest, evaluate
    rules, append transitions as `kind:"slo"` records to each producing
    run's home stream. Thread-safety: `poll_once` runs on ONE thread
    (the collector); HTTP handlers read snapshots under `_lock`."""

    def __init__(self, roots, *, rules: list[SLORule] | None = None,
                 ring: int = 2048, emit_slo: bool = True,
                 retire_after_s: float = 6 * 3600.0):
        self.roots = [str(r) for r in roots]
        self.engine = SLOEngine(rules if rules is not None
                                else load_rules(None))
        self.emit_slo = emit_slo
        self.ring = int(ring)
        self.retire_after_s = float(retire_after_s)
        self.retired = 0
        self.windows: dict[str, RunWindow] = {}
        self._tailers: dict[str, StreamTailer] = {}
        self._lock = threading.Lock()
        self.polls = 0
        self.records_total = 0
        self.slo_written = 0
        self.started_wall = time.time()

    # -- ingest + evaluate ---------------------------------------------------
    def poll_once(self, now: float | None = None) -> list[dict]:
        """One tick; returns the SLO transitions it produced."""
        now = time.monotonic() if now is None else now
        streams = discover_streams(self.roots)
        batches = []
        for label, path in streams.items():
            tailer = self._tailers.get(label)
            if tailer is None:
                tailer = self._tailers[label] = StreamTailer(path)
            recs = tailer.poll()
            for rec in recs:
                batches.append((label, path, rec, tailer.polled_catchup))
        with self._lock:
            for label, path, rec, historical in batches:
                self.records_total += 1
                run_id = str(rec.get("run_id") or rec.get("run") or "-")
                window = self.windows.get(run_id)
                if window is None:
                    window = self.windows[run_id] = RunWindow(
                        run_id, ring=self.ring)
                window.ingest(rec, label, path, now,
                              historical=historical)
            transitions = self.engine.evaluate(self.windows, now)
            self._retire_windows(now)
            self.polls += 1
        for tr in transitions:
            self._write_slo(tr)
        return transitions

    def _retire_windows(self, now: float) -> None:
        """Bounded state for an always-on daemon (caller holds _lock):
        a run that ENDED (run_end seen) or went silent past
        `retire_after_s` is dropped — window, engine state, everything —
        once no rule is still alerting for it (retiring mid-alert would
        orphan the alert without its recovery record). run_ids churn
        with every supervisor relaunch; without this, windows and rule
        states grow forever and every tick re-evaluates dead runs."""
        if self.retire_after_s <= 0:
            return
        for run_id in list(self.windows):
            window = self.windows[run_id]
            # a history-only window never updates last_seen: fall back
            # to its ingest time so it can still age out
            anchor = max(window.last_seen, window.first_seen)
            silent_for = now - anchor if anchor != float("inf") else 0.0
            if not (window.ended or silent_for >= self.retire_after_s):
                continue
            states = {k: st for k, st in self.engine._state.items()
                      if k[1] == run_id}
            if any(st.alerting for st in states.values()):
                continue  # recovery (or its record) first
            # a freshly-ended run lingers a grace period so /slo and
            # /runs still answer for it right after run_end
            if window.ended and silent_for < 60.0:
                continue
            del self.windows[run_id]
            for key in states:
                del self.engine._state[key]
            self.retired += 1

    def _write_slo(self, transition: dict) -> None:
        """Append one `kind:"slo"` record to the producing run's own
        stream (its home events.jsonl): ONE newline-terminated line via
        an O_APPEND handle, the same interleave-safe discipline as the
        span layer's multi-process spans file. This is the aggregator's
        ONLY write into producer directories."""
        window = self.windows.get(transition["run_id"])
        path = window.home_path if window is not None else None
        record = {"v": SCHEMA_VERSION, "t": round(time.time(), 3),
                  "kind": SLO_KIND}
        record.update(transition)
        if not self.emit_slo or path is None:
            # endpoint-only mode still counts the event on the window
            # (the tail-read normally does this when the line comes back)
            if window is not None:
                window.slo_events += 1
            return
        try:
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(record) + "\n")
                f.flush()
        except OSError:
            return  # an unwritable producer dir must not kill the watcher
        self.slo_written += 1

    # -- snapshots (HTTP side; also handy for tests) -------------------------
    def runs_snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {
                "v": SCHEMA_VERSION,
                "roots": self.roots,
                "streams": len(self._tailers),
                "records": self.records_total,
                "skipped_lines": sum(t.skipped
                                     for t in self._tailers.values()),
                "polls": self.polls,
                "slo_written": self.slo_written,
                "retired_runs": self.retired,
                "runs": [w.snapshot(now)
                         for w in self.windows.values()],
            }

    def slo_snapshot(self) -> dict:
        with self._lock:
            snap = self.engine.snapshot(self.windows)
        snap["v"] = SCHEMA_VERSION
        return snap

    def prometheus(self) -> str:
        """Prometheus text exposition (0.0.4) of every run window's
        gauges/counters + the aggregator's own meta-metrics."""
        now = time.monotonic()
        lines: list[str] = []

        def emit(name, mtype, help_text, samples):
            # samples: [(labels_dict, value)] — emitted only when any
            # sample exists, so the exposition never carries NaN filler
            if not samples:
                return
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {mtype}")
            for labels, value in samples:
                label_s = ",".join(
                    f'{k}="{_escape_label(v)}"'
                    for k, v in sorted(labels.items())
                )
                label_s = f"{{{label_s}}}" if label_s else ""
                lines.append(f"{name}{label_s} {_format_value(value)}")

        with self._lock:
            per_run = [(w.run_id, w) for w in self.windows.values()]
            step_pcts, data_share, mfu, steps_tot, stale = [], [], [], [], []
            incidents, router_g, router_lat, serve_lat = [], [], [], []
            health_g: list = []
            input_stall: list = []
            bank_age: list = []
            router_counters: dict[str, list] = {}
            for run_id, w in per_run:
                lab = {"run_id": run_id}
                steps_tot.append((lab, w.steps_total))
                if w.last_seen != float("-inf"):
                    stale.append((lab, max(now - w.last_seen, 0.0)))
                for q in ("50", "95", "99"):
                    v = w.metric(f"step_time_ms_p{q}", 300.0, now)
                    if v is not None:
                        step_pcts.append((dict(lab, quantile=f"p{q}"), v))
                v = w.metric("data_share", 300.0, now)
                if v is not None:
                    data_share.append((lab, v))
                v = w.metric("mfu_mean", 300.0, now)
                if v is not None:
                    mfu.append((lab, v))
                v = w.metric("input_credit_stall_rate", 300.0, now)
                if v is not None:
                    input_stall.append((lab, v))
                v = w.metric("bank_age_steps", 300.0, now)
                if v is not None:
                    bank_age.append((lab, v))
                if w.last_health:
                    for key in sorted(w.last_health):
                        v = w.metric(f"health:{key}", 300.0, now)
                        if v is not None:
                            health_g.append((dict(lab, key=key), v))
                for name, count in w.incidents.items():
                    incidents.append((dict(lab, event=name), count))
                if w.last_router is not None:
                    r = w.last_router
                    router_g.append((lab, r.get("outstanding", 0)))
                    for key in ("requests", "ok", "retries",
                                "shed_no_backend", "upstream_timeout",
                                "upstream_error", "shed_deadline_router",
                                "passthrough_non_200"):
                        if key in r:
                            router_counters.setdefault(key, []).append(
                                (lab, r[key]))
                    lat = r.get("latency_ms")
                    if isinstance(lat, dict):
                        for q, v in lat.items():
                            router_lat.append(
                                (dict(lab, quantile=q), v))
                if w.last_serve is not None:
                    lat = w.last_serve.get("latency_ms")
                    if isinstance(lat, dict):
                        for q, v in lat.items():
                            serve_lat.append((dict(lab, quantile=q), v))
            slo_state, slo_alerts = [], []
            for (rule_name, run_id), st in self.engine._state.items():
                lab = {"rule": rule_name, "run_id": run_id}
                slo_state.append((lab, 1 if st.alerting else 0))
                slo_alerts.append((lab, st.alerts))
            meta = [({}, self.records_total)]
            skipped = [({}, sum(t.skipped
                                for t in self._tailers.values()))]
            streams = [({}, len(self._tailers))]

        emit("moco_tpu_steps_total", "counter",
             "training step records ingested per run", steps_tot)
        emit("moco_tpu_step_time_ms", "gauge",
             "windowed (300s) step-time percentiles", step_pcts)
        emit("moco_tpu_data_share", "gauge",
             "windowed (300s) input-stall share of step time", data_share)
        emit("moco_tpu_mfu", "gauge",
             "windowed (300s) mean model FLOPs utilization", mfu)
        emit("moco_tpu_health", "gauge",
             "windowed (300s) mean learning-health diagnostics by key",
             health_g)
        emit("moco_tpu_input_credit_stall_rate", "gauge",
             "windowed (300s) fraction of wall time the train host spent "
             "blocked on an empty input ready queue", input_stall)
        emit("moco_tpu_bank_age_steps", "gauge",
             "promoted-checkpoint step minus serving kNN-bank step "
             "(last bank swap/bank_waiting record)", bank_age)
        emit("moco_tpu_run_stale_seconds", "gauge",
             "seconds since the run's newest record was observed", stale)
        emit("moco_tpu_events_total", "counter",
             "event records ingested by name", incidents)
        emit("moco_tpu_router_outstanding", "gauge",
             "router in-flight depth (last router_stats)", router_g)
        for key, samples in router_counters.items():
            emit(f"moco_tpu_router_{key}_total", "counter",
                 f"router cumulative {key} (last router_stats)", samples)
        emit("moco_tpu_router_latency_ms", "gauge",
             "router latency window percentiles (last router_stats)",
             router_lat)
        emit("moco_tpu_serve_latency_ms", "gauge",
             "serve latency percentiles (last serve snapshot)", serve_lat)
        emit("moco_tpu_slo_alert", "gauge",
             "1 while the rule is alerting for the run", slo_state)
        emit("moco_tpu_slo_alerts_total", "counter",
             "alerts fired per rule per run", slo_alerts)
        emit("moco_tpu_obsd_records_total", "counter",
             "records ingested by this obsd", meta)
        emit("moco_tpu_obsd_skipped_lines_total", "counter",
             "unparseable lines skipped by this obsd", skipped)
        emit("moco_tpu_obsd_streams", "gauge",
             "streams currently tailed", streams)
        return "\n".join(lines) + "\n"

    # -- loop ----------------------------------------------------------------
    def run(self, tick_secs: float = 1.0,
            stop: threading.Event | None = None) -> None:
        stop = stop or threading.Event()
        while not stop.is_set():
            self.poll_once()
            stop.wait(tick_secs)


def _escape_label(value) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _format_value(value) -> str:
    f = float(value)
    return str(int(f)) if f == int(f) else repr(f)


# ---------------------------------------------------------------------------
# the HTTP endpoints
# ---------------------------------------------------------------------------


class _ObsHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    request_queue_size = 32  # scrape traffic, not user traffic


def _make_handler(agg: Aggregator):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: D102
            pass  # scrapes at 1/s would drown stderr

        def _send(self, status: int, body: bytes, ctype: str) -> None:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/metrics":
                self._send(200, agg.prometheus().encode("utf-8"),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif self.path == "/slo":
                self._send(200,
                           json.dumps(agg.slo_snapshot()).encode("utf-8"),
                           "application/json")
            elif self.path == "/runs":
                self._send(200,
                           json.dumps(agg.runs_snapshot()).encode("utf-8"),
                           "application/json")
            elif self.path == "/healthz":
                self._send(200, b'{"status": "ok"}', "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": "not_found", "path": self.path}
                ).encode("utf-8"), "application/json")

    return Handler


class ObsServer:
    """Owns the ThreadingHTTPServer; `port=0` binds an ephemeral port
    exposed as `.port` (tests, parallel obsds)."""

    def __init__(self, agg: Aggregator, host: str = "127.0.0.1",
                 port: int = 0):
        self.server = _ObsHTTPServer((host, port), _make_handler(agg))
        self.host, self.port = self.server.server_address[:2]
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True, name="obsd-http"
        )
        self._thread.start()

    def shutdown(self) -> None:
        if self._thread is not None:
            self.server.shutdown()
            self._thread.join(timeout=5.0)
            self._thread = None
        self.server.server_close()
