"""Step-phase wall-clock splitting (ISSUE 2 tentpole part 2).

A training step's wall time decomposes into:

  data_s    — loader wait: the host blocked on the next batch (prefetch
              misses, decode stalls, filesystem hiccups)
  host_s    — dispatch: staging arrays + tracing-cache lookup + enqueue of
              the jitted program; in a healthy async pipeline this is the
              ONLY host cost per step
  device_s  — device-compute drain, measured ONLY on fenced samples: every
              `stride` steps the timer calls `block_until_ready` on a step
              output and times dispatch-return → ready. This measures the
              device backlog (the step itself plus anything still queued),
              which is the honest number for "is the device the
              bottleneck" — and the fence is what a comm/compute-overlap
              PR will move, so it must stay OFF the steady-state path
              (stride=0 never fences; off-stride steps stay fully async).
  comm_s    — gradient-sync tail (ISSUE 6), measured only on the SAME fenced
              samples: the step emits two probe scalars (gradsync's
              grads-ready psum and a reading of the reduced grads) and the
              fence drains them in order — comm_s is drain(reduced) −
              drain(grads-ready), i.e. how long the step sat between "local
              grads exist" and "the sync is visible". Honest caveat: on
              backends that materialize all program outputs atomically
              (CPU, and the relay on scalar transfers) both probes drain
              together and comm_s reads ~0 — the analytic sync-bytes/step
              in the `grad_sync` records is the backend-independent signal.
  telemetry_s — span-layer/telemetry self-time (ISSUE 8 satellite fix):
              record-keeping the telemetry stack itself paid inside this
              step's window — span flushes, trigger-file polls, capture
              transitions, the on_step bookkeeping. Booked explicitly via
              `note_telemetry` and SUBTRACTED from the window it would
              otherwise pollute, so a capture window (which makes the
              span layer temporarily expensive on purpose) cannot
              masquerade as a data/host-phase regression in the
              phase-share report. In this driver the telemetry work runs
              between one step's finish and the next step's loader wait,
              so the polluted window is the NEXT step's `data_s`.
  step_s    — the whole iteration (data_s + host_s + meters + everything);
              on fenced steps it includes the fence wait.

Usage per iteration (driver order):
    timer.epoch_start()                  # aligns the first data window
    ... loader yields ...
    timer.mark_data()
    ... fused_step dispatch returns ...
    timer.mark_dispatch()
    timer.maybe_fence(step, sync_obj)    # stride-gated block_until_ready
    phases = timer.finish_step()         # {"data_s", "host_s", ...}
"""

from __future__ import annotations

import time


class StepPhaseTimer:
    def __init__(self, stride: int = 0):
        self.stride = max(int(stride), 0)
        self.fences = 0  # how many steps actually paid a fence (tests pin
                         # that this NEVER exceeds steps/stride)
        self._t_iter = None
        self._t_data = None
        self._t_dispatch = None
        self._device_s = None
        self._comm_s = None
        self._telemetry_s = 0.0

    def epoch_start(self) -> None:
        now = time.perf_counter()
        self._t_iter = now
        self._t_data = self._t_dispatch = None
        self._device_s = None
        self._comm_s = None
        # telemetry time booked after the previous epoch's last step falls
        # outside every step window — dropping it is correct, carrying it
        # would over-subtract from the new epoch's first data phase
        self._telemetry_s = 0.0

    def note_telemetry(self, seconds: float) -> None:
        """Book span-layer/telemetry self-time into the CURRENT iteration
        window (the driver calls this right after its per-step telemetry
        work, which runs between finish_step and the next loader wait)."""
        self._telemetry_s += max(float(seconds), 0.0)

    def mark_data(self) -> None:
        self._t_data = time.perf_counter()

    def mark_dispatch(self) -> None:
        self._t_dispatch = time.perf_counter()

    def maybe_fence(self, step: int, sync_obj, comm_pre=None,
                    comm_post=None) -> float | None:
        """Stride-gated device fence; returns device_s on sampled steps.

        `sync_obj` is any step output (the loss array); draining it fences
        this step's program and everything queued before it. The sync is a
        real device→host TRANSFER (`float`) when the object is scalar:
        `block_until_ready` does not reliably synchronize on the
        experimental axon PJRT relay (moco_tpu/utils/benchkit.py) — a
        fence that returns early would record a near-zero device phase and
        tell the exact lie this telemetry exists to prevent.
        `block_until_ready` remains the fallback for non-scalar outputs.

        `comm_pre`/`comm_post` are the gradient-sync probe scalars (ISSUE
        6): when both are present on a fenced step they are drained FIRST,
        in order, and their gap is recorded as the `comm_s` phase — see the
        module docstring for what that number can and cannot claim."""
        if self.stride <= 0 or step % self.stride != 0:
            return None
        if self._t_dispatch is None:  # fence without a dispatch mark
            return None
        if comm_pre is not None and comm_post is not None:
            try:
                float(comm_pre)
                t_pre = time.perf_counter()
                float(comm_post)
                self._comm_s = max(time.perf_counter() - t_pre, 0.0)
            except (TypeError, ValueError):
                self._comm_s = None  # non-scalar probes: no comm sample
        try:
            float(sync_obj)
        except (TypeError, ValueError):
            import jax

            jax.block_until_ready(sync_obj)
        self._device_s = time.perf_counter() - self._t_dispatch
        self.fences += 1
        return self._device_s

    def finish_step(self) -> dict:
        """Close the iteration; returns the phase dict and re-arms for the
        next step (the next data window starts now)."""
        now = time.perf_counter()
        t0 = self._t_iter if self._t_iter is not None else now
        t_data = self._t_data if self._t_data is not None else t0
        t_disp = self._t_dispatch if self._t_dispatch is not None else t_data
        # carve the booked telemetry self-time OUT of the phase it landed
        # in (the loader-wait window, see the class docstring) into its
        # own bucket: data_s + host_s + telemetry_s still sums within
        # step_s, and the phase-share report stops blaming the input
        # pipeline for capture-window overhead
        telemetry_s = min(self._telemetry_s, max(t_data - t0, 0.0))
        phases = {
            "step_s": now - t0,
            "data_s": max(t_data - t0 - telemetry_s, 0.0),
            "host_s": t_disp - t_data,
        }
        if telemetry_s > 0.0:
            phases["telemetry_s"] = telemetry_s
        if self._device_s is not None:
            phases["device_s"] = self._device_s
        if self._comm_s is not None:
            phases["comm_s"] = self._comm_s
        self._t_iter = now
        self._t_data = self._t_dispatch = None
        self._device_s = None
        self._comm_s = None
        self._telemetry_s = 0.0
        return phases
