"""Analytic-FLOPs MFU estimation (ISSUE 2 tentpole part 2).

MFU = achieved FLOP/s ÷ peak FLOP/s. The numerator comes from an ANALYTIC
count of the model's matmul/conv FLOPs (the standard convention: 2 FLOPs
per multiply-add, convs + dense layers only — BN/activations/pooling are
bandwidth, not FLOPs, and would flatter the number), scaled by the MoCo
step's encoder-pass structure:

  v1/v2 — query encoder forward+backward (3 fwd-equivalents, the standard
          1+2 fwd/bwd accounting) + key encoder forward (1): 4× per image
  v3    — BOTH crops through both encoders: query fwd+bwd on 2 crops (6)
          + momentum forward on 2 crops (2): 8× per image

Projection heads ARE counted (they are dense layers); the v3
predictor/projector MLPs beyond the configured head are not — they are
<0.5% of a ResNet-50/ViT step and the estimate documents itself as
backbone-dominated via `flops_per_image` in the run_start record.

The denominator is a per-chip peak-FLOPs table keyed on
`device.device_kind` (bf16 peaks from the Cloud TPU docs), overridable via
`config.peak_flops_per_chip` — the only honest option on CPU or unlisted
hardware, where auto-detection yields None and MFU is omitted rather than
fabricated.
"""

from __future__ import annotations

# (substring of device_kind lowercased, peak bf16 FLOP/s per chip).
# Ordered: more specific entries first — "v5p" must win over "v5".
PEAK_FLOPS_BF16 = (
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),   # some jax versions report v5e as "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def detect_peak_flops(device_kind: str) -> float | None:
    """Peak bf16 FLOP/s for a `device.device_kind` string, None if unknown
    (CPU, GPU, future TPUs) — callers must then rely on the config
    override or skip MFU."""
    kind = (device_kind or "").lower()
    for key, peak in PEAK_FLOPS_BF16:
        if key in kind:
            return peak
    return None


def _conv_flops(h_out: int, w_out: int, k: int, c_in: int, c_out: int) -> float:
    return 2.0 * h_out * w_out * k * k * c_in * c_out


def _conv_out(size: int, k: int, stride: int, pad: int) -> int:
    return (size + 2 * pad - k) // stride + 1


# mirrors models/resnet.py: (stage_sizes, bottleneck?, width)
_RESNET_SPECS = {
    "resnet18": ((2, 2, 2, 2), False, 64),
    "resnet34": ((3, 4, 6, 3), False, 64),
    "resnet50": ((3, 4, 6, 3), True, 64),
    "resnet101": ((3, 4, 23, 3), True, 64),
    "resnet152": ((3, 8, 36, 3), True, 64),
    "resnet_tiny": ((1, 1), False, 16),
}

# mirrors models/vit.py: (width, depth, patch_size)
_VIT_SPECS = {
    "vit_small": (384, 12, 16),
    "vit_base": (768, 12, 16),
    "vit_large": (1024, 24, 16),
    "vit_huge": (1280, 32, 14),
    "vit_tiny": (64, 2, 16),
}


def resnet_fwd_flops(arch: str, image_size: int, cifar_stem: bool = False) -> float:
    """Forward conv FLOPs per image for the flax ResNet in models/resnet.py
    (2·H·W·K²·Cin·Cout per conv, including downsample projections;
    excludes BN/ReLU/pool and any head — see head_fwd_flops)."""
    stage_sizes, bottleneck, width = _RESNET_SPECS[arch]
    flops = 0.0
    if cifar_stem:
        size = image_size  # 3x3/1 conv, no pool
        flops += _conv_flops(size, size, 3, 3, width)
    else:
        size = _conv_out(image_size, 7, 2, 3)
        flops += _conv_flops(size, size, 7, 3, width)
        size = _conv_out(size, 3, 2, 1)  # max-pool: no FLOPs, changes size
    expansion = 4 if bottleneck else 1
    c_in = width
    for i, num_blocks in enumerate(stage_sizes):
        filters = width * 2**i
        c_out = filters * expansion
        for j in range(num_blocks):
            stride = 2 if i > 0 and j == 0 else 1
            out_size = _conv_out(size, 3, stride, 1)
            if bottleneck:
                flops += _conv_flops(size, size, 1, c_in, filters)          # conv1 1x1
                flops += _conv_flops(out_size, out_size, 3, filters, filters)  # conv2 3x3/s
                flops += _conv_flops(out_size, out_size, 1, filters, c_out)    # conv3 1x1
            else:
                flops += _conv_flops(out_size, out_size, 3, c_in, filters)  # conv1 3x3/s
                flops += _conv_flops(out_size, out_size, 3, filters, filters)  # conv2 3x3
            if stride != 1 or c_in != c_out:  # downsample projection
                flops += _conv_flops(out_size, out_size, 1, c_in, c_out)
            c_in, size = c_out, out_size
    return flops


def vit_fwd_flops(arch: str, image_size: int) -> float:
    """Forward matmul FLOPs per image for the flax ViT in models/vit.py:
    patch embed + per-block (qkv, scores, attn·V, proj, 4x MLP); excludes
    LayerNorm/GELU and any head."""
    width, depth, patch = _VIT_SPECS[arch]
    grid = image_size // patch
    n = grid * grid + 1  # patch tokens + class token
    d = width
    flops = 2.0 * (grid * grid) * (patch * patch * 3) * d  # patch embed conv
    per_block = (
        2.0 * n * d * (3 * d)      # qkv projection
        + 2.0 * n * n * d          # Q·Kᵀ scores
        + 2.0 * n * n * d          # scores·V
        + 2.0 * n * d * d          # output projection
        + 2.0 * 2 * n * d * (4 * d)  # MLP fc1 + fc2 (ratio 4)
    )
    return flops + depth * per_block


def head_fwd_flops(arch: str, embed_dim: int, mlp_head: bool) -> float:
    """Projection-head dense FLOPs per image (fc, or the v2 2-layer MLP)."""
    from moco_tpu.models.resnet import FEATURE_DIMS

    if arch in _VIT_SPECS:
        feat = _VIT_SPECS[arch][0]
    else:
        feat = FEATURE_DIMS[arch]
    if mlp_head:
        return 2.0 * feat * feat + 2.0 * feat * embed_dim
    return 2.0 * feat * embed_dim


def model_fwd_flops(arch: str, image_size: int, *, cifar_stem: bool = False,
                    embed_dim: int = 128, mlp_head: bool = False) -> float:
    """Backbone + head forward FLOPs per image for any supported arch."""
    if arch in _VIT_SPECS:
        body = vit_fwd_flops(arch, image_size)
    elif arch in _RESNET_SPECS:
        body = resnet_fwd_flops(arch, image_size, cifar_stem)
    else:
        raise ValueError(f"no analytic FLOPs model for arch {arch!r}")
    return body + head_fwd_flops(arch, embed_dim, mlp_head)


# fwd-equivalent encoder passes per image: fwd+bwd = 3 fwd (standard 1+2
# accounting), momentum fwd = 1
_STEP_MULTIPLIER = {"v1": 3 + 1, "v2": 3 + 1, "v3": 2 * 3 + 2 * 1}


def train_step_flops(config) -> float:
    """Analytic FLOPs for ONE global-batch training step of `config`."""
    per_image = model_fwd_flops(
        config.arch, config.image_size, cifar_stem=config.cifar_stem,
        embed_dim=config.embed_dim, mlp_head=config.mlp_head,
    )
    return per_image * _STEP_MULTIPLIER[config.variant] * config.batch_size


class MFUEstimator:
    """step wall time → model-FLOPs utilization fraction.

    `peak_flops_per_chip` None/0 disables (mfu() returns None) — never
    fabricate a denominator."""

    def __init__(self, flops_per_step: float, n_chips: int,
                 peak_flops_per_chip: float | None, sharding: str = "dp"):
        self.flops_per_step = float(flops_per_step)
        self.n_chips = max(int(n_chips), 1)
        self.peak_flops_per_chip = (
            float(peak_flops_per_chip) if peak_flops_per_chip else None
        )
        # the sharding mode the MFU is reported under (ISSUE 15): the
        # analytic FLOPs are layout-invariant — fsdp changes per-device
        # PARAM BYTES (the telemetry `sharding` event carries the measured
        # inventory) and the collective schedule, never the model math —
        # so the estimator carries the label rather than a different count
        self.sharding = sharding

    @classmethod
    def for_config(cls, config, n_chips: int, device_kind: str = ""):
        peak = config.peak_flops_per_chip or detect_peak_flops(device_kind)
        return cls(train_step_flops(config), n_chips, peak,
                   sharding=getattr(config, "sharding", "dp"))

    def mfu(self, step_s: float) -> float | None:
        if not self.peak_flops_per_chip or step_s <= 0:
            return None
        achieved = self.flops_per_step / step_s
        return achieved / (self.peak_flops_per_chip * self.n_chips)
