"""Distributed tracing + on-demand capture windows (ISSUE 8 tentpole).

The repo runs as a small distributed system — supervisor → train driver →
staging workers → device step, plus a serve stack — and its telemetry was
flat per-process JSONL: no way to follow one step or one request across a
process boundary, and no way to grab a profile *when* the slow step
actually happens. This module is the span layer every process shares:

  - `Tracer.span(name)` is a context manager that records one timed span
    into a lock-free ring buffer (a `deque.append` under the GIL — no
    lock, no syscall on the fast path) and flushes batches of spans as
    JSONL lines to `<telemetry_dir>/spans.jsonl` with O_APPEND one-line
    writes, safe to interleave across processes sharing the file.
  - Every span carries `run`/`trace`/`span`/`parent` ids. The ids
    propagate ACROSS processes through two env vars (`MOCO_TPU_RUN_ID`,
    `MOCO_TPU_TRACE_PARENT`): the supervisor stamps its child's env from
    inside its per-launch span, the child's Tracer picks the parent up at
    construction, and thread-side spans (staging workers) continue a
    coordinator span through an explicit `parent=span.context()`.
  - `trace_mode` knob, off by default: `off` records nothing, `steps`
    records the coarse spans (one per step / staged batch / serve flush /
    supervisor launch), `full` additionally records the detail spans
    (worker decode slices, per-shard H2D puts, engine calls).
  - On-demand and anomaly-triggered CAPTURE: SIGUSR1 or a
    `<telemetry_dir>/trace.trigger` file arms a bounded window during
    which the effective mode is `full` (and, when hooks are installed, a
    jax.profiler device trace lands under `<telemetry_dir>/traces/`).
    Anomaly detectors (`SlowSampleDetector` for step-time / staging-stall
    blowouts, `SpikeDetector` for serve shed spikes) arm the same window
    through `maybe_autocapture`, bounded by a per-run capture budget — a
    3 a.m. slowdown leaves a profile behind without anyone watching.

This module MUST stay importable without jax (and without numpy): the
out-of-process supervisor imports it, and the supervisor's whole contract
is surviving the failures that kill the jax runtime (mocolint R12 pins
both the import discipline and the context-manager-only span API).
`tools/trace_report.py` merges spans + events from every process of a run
into one Chrome-trace/Perfetto JSON.
"""

from __future__ import annotations

import os
import signal
import threading
import time
import uuid
from collections import deque

SCHEMA_VERSION = 1

SPANS_FILENAME = "spans.jsonl"
TRIGGER_FILENAME = "trace.trigger"
TRACES_DIRNAME = "traces"

ENV_RUN_ID = "MOCO_TPU_RUN_ID"
ENV_TRACE_PARENT = "MOCO_TPU_TRACE_PARENT"  # "<trace_id>:<span_id>"

TRACE_MODES = ("off", "steps", "full")
_LEVEL = {"off": 0, "steps": 1, "full": 2}


def new_id() -> str:
    """16-hex-char id (64 random bits): short enough to read in a report,
    long enough that a run's span set never collides."""
    return uuid.uuid4().hex[:16]


def parse_parent(value: str | None) -> tuple[str, str] | None:
    """`"<trace_id>:<span_id>"` → tuple; None on absent/malformed (a
    malformed env var must degrade to a fresh trace, never crash the
    child at import time)."""
    if not value:
        return None
    trace_id, sep, span_id = value.partition(":")
    if not sep or not trace_id or not span_id:
        return None
    return trace_id, span_id


# ---------------------------------------------------------------------------
# anomaly detectors (stdlib, shared by driver / loader / serve call sites)
# ---------------------------------------------------------------------------


class SlowSampleDetector:
    """Rolling-window tail detector: `observe(x)` returns True when `x`
    exceeds `k` × the window's p95 (with at least `min_samples` PRIOR
    samples, and `x` above `floor_s` so microsecond-scale noise on a fast
    phase can never trip it). The current sample is checked BEFORE it
    joins the window, so one anomaly does not raise the bar for the next.
    The first `skip` observations are DISCARDED entirely: cold-compile /
    warmup steps are seconds-scale by design, and two of them in the
    window put the p95 itself at warmup scale — every later real anomaly
    would hide under k × (compile time). Not thread-safe by design — each
    caller owns one detector."""

    def __init__(self, k: float = 3.0, window: int = 64,
                 min_samples: int = 8, floor_s: float = 0.0,
                 skip: int = 0):
        self.k = float(k)
        self.min_samples = int(min_samples)
        self.floor_s = float(floor_s)
        self._skip = int(skip)
        self.last_p95 = 0.0  # the threshold the last observe() compared
                             # against — snapshotted BEFORE the sample
                             # joined the window, so an anomaly report can
                             # name the p95 it actually violated
        self._window: deque = deque(maxlen=int(window))

    def p95(self) -> float:
        if not self._window:
            return 0.0
        ordered = sorted(self._window)
        rank = max(0, min(len(ordered) - 1,
                          round(0.95 * (len(ordered) - 1))))
        return ordered[rank]

    def observe(self, value: float) -> bool:
        if self._skip > 0:
            self._skip -= 1
            return False
        value = float(value)
        self.last_p95 = self.p95()
        anomalous = (
            len(self._window) >= self.min_samples
            and value > self.floor_s
            and value > self.k * self.last_p95
        )
        self._window.append(value)
        return anomalous


class SpikeDetector:
    """Event-rate spike detector for discrete bad events (serve sheds):
    `note()` returns True when at least `min_events` landed within the
    trailing `window_s` seconds. After firing, the window is cleared so
    one sustained spike arms one capture, not one per shed. Thread-safe:
    sheds arrive from concurrent HTTP handler threads."""

    def __init__(self, min_events: int = 8, window_s: float = 5.0):
        self.min_events = int(min_events)
        self.window_s = float(window_s)
        self._times: deque = deque()
        self._lock = threading.Lock()

    def note(self, now: float | None = None) -> bool:
        if self.min_events <= 0:
            return False
        now = time.monotonic() if now is None else now
        with self._lock:
            self._times.append(now)
            while self._times and now - self._times[0] > self.window_s:
                self._times.popleft()
            if len(self._times) >= self.min_events:
                self._times.clear()
                return True
        return False


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


class _NullSpan:
    """The no-op span: returned whenever the tracer is off or the span's
    detail level is filtered — the fast path is one attribute check and
    this singleton's trivial __enter__/__exit__."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass

    def context(self):
        return None


NULL_SPAN = _NullSpan()


class Span:
    """One live span (handle of `Tracer.span(...)`). Only ever used as a
    context manager (mocolint R12): __enter__ stamps the start and pushes
    onto the opening thread's span stack (so nested spans parent
    automatically), __exit__ records the completed span into the ring."""

    __slots__ = ("_tracer", "name", "cat", "trace_id", "span_id",
                 "parent_id", "attrs", "_t_wall", "_t0", "_entered")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 parent: tuple[str, str] | None, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        if parent is None:
            parent = tracer.current_context()
        self.trace_id = parent[0] if parent else tracer.trace_id
        self.parent_id = parent[1] if parent else tracer.root_parent
        self.span_id = new_id()
        self.attrs = attrs
        self._t_wall = 0.0
        self._t0 = 0.0
        self._entered = False

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def context(self) -> tuple[str, str]:
        """(trace_id, span_id) — the handle a worker thread (or a child
        process, via `Tracer.child_env`) parents its own spans under."""
        return (self.trace_id, self.span_id)

    def __enter__(self):
        self._t_wall = time.time()
        self._t0 = time.perf_counter()
        self._entered = True
        self._tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer._pop(self)
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        self._tracer._record(
            self.name, self.cat, self._t_wall,
            time.perf_counter() - self._t0,
            self.trace_id, self.span_id, self.parent_id, self.attrs,
        )
        return False


class _NullTracer:
    """Shared do-nothing tracer so call sites never branch on `tracer is
    None` in hot loops: every method is a constant-return no-op."""

    run_id = ""
    trace_id = ""
    root_parent = None
    mode = "off"
    captures_used = 0
    capture_budget = 0
    spans_recorded = 0
    spans_written = 0
    profiler_hooks = None

    def span(self, name, *, cat="span", detail=False, parent=None, **attrs):
        return NULL_SPAN

    def instant(self, name, *, cat="instant", parent=None, **attrs):
        return None

    def record_span(self, *a, **kw):
        return None

    def record_step(self, *a, **kw):
        return None

    def tick(self, step=None):
        return None

    def maybe_autocapture(self, reason):
        return False

    def request_capture(self, reason):
        pass

    def capture_state(self):
        return None

    def current_context(self):
        return None

    def child_env(self):
        return {}

    def consume_self_time(self):
        return 0.0

    def install_signal(self):
        return False

    def flush(self):
        pass

    def close(self):
        pass


_NULL_TRACER = _NullTracer()


def null_tracer() -> _NullTracer:
    return _NULL_TRACER


# ---------------------------------------------------------------------------
# the tracer
# ---------------------------------------------------------------------------


class Tracer:
    """Per-process span recorder + capture-window state machine.

    `telemetry_dir` is where `spans.jsonl` (O_APPEND, shared with every
    other process of the run), the `trace.trigger` file and the
    `traces/` profiler dir live; None disables recording entirely.
    `mode` is the configured `trace_mode`; a capture window elevates the
    EFFECTIVE level to `full` without touching the configured one.
    `proc` labels this process's track in the merged timeline
    ("supervisor" / "driver" / "serve" / ...).

    Overhead contract: recording one span is a dict build plus a
    `deque.append` (GIL-atomic, lock-free); the ring drains to disk only
    when `flush_every` spans accumulated (or at capture end / close), and
    that drain time — plus everything else the span layer does off the
    hot path (trigger-file polls, capture transitions) — is accumulated
    into `consume_self_time()` so the step-phase report can book it as
    the explicit `telemetry` sub-phase instead of skewing data/host."""

    def __init__(self, telemetry_dir: str | None, mode: str = "off", *,
                 proc: str = "proc", run_id: str | None = None,
                 parent: tuple[str, str] | None = None,
                 capture_steps: int = 50, capture_budget: int = 3,
                 ring_size: int = 4096, flush_every: int = 256,
                 trigger_poll_secs: float = 1.0):
        if mode not in TRACE_MODES:
            raise ValueError(
                f"unknown trace_mode {mode!r}; choose from {TRACE_MODES}"
            )
        self.mode = mode
        self.proc = proc
        self.pid = os.getpid()
        self.run_id = run_id or os.environ.get(ENV_RUN_ID) or new_id()
        env_parent = parent or parse_parent(os.environ.get(ENV_TRACE_PARENT))
        if env_parent is not None:
            self.trace_id, self.root_parent = env_parent
        else:
            self.trace_id, self.root_parent = new_id(), None
        self.capture_steps = max(int(capture_steps), 1)
        self.capture_budget = max(int(capture_budget), 0)
        self.captures_used = 0
        self.spans_recorded = 0
        self.spans_written = 0
        self._capturing = False
        self._capture_left = 0
        self._capture_reason = ""
        # set from signal handlers / other threads: plain assignments only
        self._pending_reason: str | None = None
        self._denied_reported = False
        self._ring: deque = deque(maxlen=max(int(ring_size), 2))
        self._flush_every = max(int(flush_every), 1)
        self._io_lock = threading.Lock()
        self._tls = threading.local()
        self._self_s = 0.0
        self._self_lock = threading.Lock()
        self._trigger_poll_secs = float(trigger_poll_secs)
        self._last_trigger_poll = float("-inf")
        self._prev_sigusr1 = None
        self.profiler_hooks: tuple | None = None  # (start(dir), stop())
        self.profiler_error: str | None = None
        self._profiler_active = False
        self._path = None
        self._trigger_path = None
        self._traces_dir = None
        if telemetry_dir:
            os.makedirs(telemetry_dir, exist_ok=True)
            self._path = os.path.join(telemetry_dir, SPANS_FILENAME)
            self._trigger_path = os.path.join(telemetry_dir, TRIGGER_FILENAME)
            self._traces_dir = os.path.join(telemetry_dir, TRACES_DIRNAME)

    # -- levels --------------------------------------------------------------
    def _level(self) -> int:
        if self._path is None:
            return 0
        if self._capturing:
            return 2
        return _LEVEL[self.mode]

    # -- span API (context-manager only: mocolint R12) -----------------------
    def span(self, name: str, *, cat: str = "span", detail: bool = False,
             parent: tuple[str, str] | None = None, **attrs):
        """Open one span as a context manager. `detail=True` marks a
        fine-grained span recorded only at `full` level (or inside a
        capture window); coarse spans record from `steps` up."""
        lvl = self._level()
        if lvl == 0 or (detail and lvl < 2):
            return NULL_SPAN
        return Span(self, name, cat, parent, attrs)

    def instant(self, name: str, *, cat: str = "instant",
                parent: tuple[str, str] | None = None, **attrs):
        """Zero-duration marker (rendered as an instant event)."""
        return self.record_span(name, time.time(), 0.0, cat=cat,
                                parent=parent, **attrs)

    def record_span(self, name: str, t_start_wall: float, dur_s: float, *,
                    cat: str = "span", detail: bool = False,
                    parent: tuple[str, str] | None = None,
                    trace_id: str | None = None,
                    span_id: str | None = None, **attrs) -> str | None:
        """Retroactive span: record an already-measured interval (the step
        spans are derived from StepPhaseTimer after the fact — zero
        context-manager overhead inside the hot loop; serve request spans
        are stamped at resolve time). Same `detail` filtering as `span`.
        Returns the span id so callers can parent further retroactive
        children under it."""
        lvl = self._level()
        if lvl == 0 or (detail and lvl < 2):
            return None
        if parent is None:
            parent = self.current_context()
        sid = span_id or new_id()
        self._record(
            name, cat, t_start_wall, dur_s,
            trace_id or (parent[0] if parent else self.trace_id),
            sid,
            parent[1] if parent else self.root_parent,
            attrs,
        )
        return sid

    def record_step(self, step: int, phases: dict, **attrs) -> str | None:
        """One training step as a span tree, derived from the phase dict
        (`step_s`/`data_s`/`host_s`/...): the step span at `steps` level,
        plus sequential data/host/telemetry child segments at `full`
        level. `device_s`/`comm_s` are drain measurements, not wall
        segments — they ride as attrs, not child spans."""
        lvl = self._level()
        if lvl == 0:
            return None
        step_s = float(phases.get("step_s", 0.0))
        t0 = time.time() - step_s
        span_attrs = {k: round(float(v), 6) for k, v in phases.items()}
        span_attrs.update(attrs)
        span_attrs["step"] = int(step)
        sid = self.record_span("step", t0, step_s, cat="step", **span_attrs)
        if lvl >= 2 and sid is not None:
            parent = (self.trace_id, sid)
            cursor = t0
            for seg in ("telemetry_s", "data_s", "host_s"):
                seg_s = float(phases.get(seg, 0.0))
                if seg_s > 0.0:
                    self.record_span(seg[:-2], cursor, seg_s, cat="phase",
                                     parent=parent, step=int(step))
                    cursor += seg_s
        return sid

    # -- parenting -----------------------------------------------------------
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()
        elif span in stack:  # exotic unwind order: drop it wherever it is
            stack.remove(span)

    def current_context(self) -> tuple[str, str] | None:
        """(trace_id, span_id) of this thread's innermost open span, else
        the process root context inherited from the parent process."""
        stack = getattr(self._tls, "stack", None)
        if stack:
            return stack[-1].context()
        if self.root_parent is not None:
            return (self.trace_id, self.root_parent)
        return None

    def child_env(self) -> dict:
        """Env vars that make a child process continue this trace: its
        tracer adopts our run id and parents its spans under the CURRENT
        span of the calling thread (the supervisor calls this inside its
        per-launch span)."""
        ctx = self.current_context() or (self.trace_id, "")
        env = {ENV_RUN_ID: self.run_id}
        if ctx[1]:
            env[ENV_TRACE_PARENT] = f"{ctx[0]}:{ctx[1]}"
        return env

    # -- recording / flushing ------------------------------------------------
    def _record(self, name, cat, t_wall, dur_s, trace_id, span_id,
                parent_id, attrs) -> None:
        thread = threading.current_thread()
        rec = {
            "v": SCHEMA_VERSION,
            "kind": "span",
            "name": name,
            "cat": cat,
            "run": self.run_id,
            "trace": trace_id,
            "span": span_id,
            "t": round(t_wall, 6),
            "dur": round(max(dur_s, 0.0), 6),
            "pid": self.pid,
            "proc": self.proc,
            "tid": thread.ident,
            "thread": thread.name,
        }
        if parent_id:
            rec["parent"] = parent_id
        if attrs:
            rec["attrs"] = attrs
        self._ring.append(rec)  # lock-free fast path (GIL-atomic append)
        self.spans_recorded += 1
        if len(self._ring) >= self._flush_every:
            self.flush()

    def flush(self) -> None:
        """Drain the ring to spans.jsonl (one O_APPEND write of all
        pending lines — safe to interleave with other processes appending
        to the same file). Flush time is booked as span-layer self-time."""
        if self._path is None:
            return
        t0 = time.perf_counter()
        with self._io_lock:
            lines = []
            while True:
                try:
                    rec = self._ring.popleft()
                except IndexError:
                    break
                lines.append(_dumps(rec))
            if lines:
                with open(self._path, "a", encoding="utf-8") as f:
                    f.write("\n".join(lines) + "\n")
                self.spans_written += len(lines)
        self._note_self(time.perf_counter() - t0)

    # -- capture windows -----------------------------------------------------
    def request_capture(self, reason: str) -> None:
        """Arm a capture window at the next `tick`. Signal-safe: a plain
        assignment, no locks, no I/O — callable straight from a SIGUSR1
        handler or any thread."""
        self._pending_reason = reason

    def maybe_autocapture(self, reason: str) -> bool:
        """Anomaly-detector entry: route a capture request unless one is
        already running/pending. Returns True when this call newly routed
        it — the caller then logs the anomaly. Deliberately NOT gated on
        the budget here: a budget-exhausted anomaly must still be visible
        (the next tick reports it through the once-only `denied` event)
        rather than vanish without a trace; spam is self-limiting because
        anomalous samples join the detector window and raise its p95."""
        if (self._path is None or self._capturing
                or self._pending_reason is not None):
            return False
        self._pending_reason = reason
        return True

    def tick(self, step=None) -> dict | None:
        """Advance the capture state machine one unit (a train step, a
        serve flush). Returns a small event dict on transitions (capture
        start / end / budget-denied) for the caller to land in
        events.jsonl, else None. Also polls the trigger file, time-gated
        so the stat() never rides every step."""
        t0 = time.perf_counter()
        evt = self._tick_inner(step)
        self._note_self(time.perf_counter() - t0)
        return evt

    def _tick_inner(self, step) -> dict | None:
        if self._path is None:
            return None
        now = time.monotonic()
        if (self._trigger_path is not None
                and now - self._last_trigger_poll >= self._trigger_poll_secs):
            self._last_trigger_poll = now
            if os.path.exists(self._trigger_path):
                try:
                    os.remove(self._trigger_path)  # re-touch re-arms
                except OSError:
                    pass
                # also while a window is ACTIVE: the file is consumed
                # either way, so the request must queue (it starts on the
                # first tick after the current window ends) — deleting it
                # without arming would silently drop the operator's touch
                if self._pending_reason is None:
                    self._pending_reason = "trigger_file"
        if self._pending_reason is not None and not self._capturing:
            reason, self._pending_reason = self._pending_reason, None
            if self.captures_used >= self.capture_budget:
                if self._denied_reported:
                    return None
                self._denied_reported = True
                return {"action": "denied", "reason": reason,
                        "captures_used": self.captures_used,
                        "capture_budget": self.capture_budget}
            self.captures_used += 1
            self._capturing = True
            self._capture_left = self.capture_steps
            self._capture_reason = reason
            self.instant("capture_start", cat="capture", reason=reason,
                         step=step, captures_used=self.captures_used)
            self._start_profiler(reason, step)
            return {"action": "start", "reason": reason, "step": step,
                    "window_steps": self.capture_steps,
                    "captures_used": self.captures_used,
                    "capture_budget": self.capture_budget}
        if self._capturing:
            self._capture_left -= 1
            if self._capture_left <= 0:
                reason = self._capture_reason
                self._stop_profiler()
                self.instant("capture_end", cat="capture", reason=reason,
                             step=step)
                self._capturing = False
                self._capture_reason = ""
                self.flush()  # land the window's full-detail spans NOW
                return {"action": "end", "reason": reason, "step": step}
        return None

    def capture_state(self) -> dict:
        """The heartbeat/healthz payload: is a capture running, how much
        window is left, how much budget is spent."""
        return {
            "capturing": self._capturing,
            "window_steps_left": self._capture_left if self._capturing else 0,
            "captures_used": self.captures_used,
            "capture_budget": self.capture_budget,
        }

    def _start_profiler(self, reason: str, step) -> None:
        if self.profiler_hooks is None or self._traces_dir is None:
            return
        tag = f"{int(time.time())}-{reason}"
        if step is not None:
            tag += f"-s{step}"
        trace_dir = os.path.join(self._traces_dir, tag)
        try:
            os.makedirs(trace_dir, exist_ok=True)
            self.profiler_hooks[0](trace_dir)
            self._profiler_active = True
        except Exception as e:  # device profiler failure must not end the
            # run — the span capture still happens; the failure is visible
            # in the timeline and on `profiler_error`
            self._profiler_active = False
            self.profiler_error = repr(e)
            self.instant("profiler_error", cat="capture", error=repr(e))

    def _stop_profiler(self) -> None:
        if not self._profiler_active:
            return
        self._profiler_active = False
        try:
            self.profiler_hooks[1]()
        except Exception as e:  # ending the window must never end the run
            self.profiler_error = repr(e)
            self.instant("profiler_error", cat="capture", error=repr(e))

    # -- self-time accounting (the `telemetry` sub-phase) --------------------
    def _note_self(self, seconds: float) -> None:
        with self._self_lock:
            self._self_s += seconds

    def consume_self_time(self) -> float:
        """Span-layer self-time (flushes, trigger polls, capture
        transitions) accumulated since the last call — booked by the
        driver into StepPhaseTimer's `telemetry` sub-phase so a capture
        window cannot masquerade as a data/host regression."""
        with self._self_lock:
            s, self._self_s = self._self_s, 0.0
        return s

    # -- signals -------------------------------------------------------------
    def install_signal(self) -> bool:
        """SIGUSR1 → arm a capture window. Main-thread only (CPython
        restriction); returns False elsewhere. The previous handler is
        chained and restored by close()."""
        if threading.current_thread() is not threading.main_thread():
            return False
        prev = signal.getsignal(signal.SIGUSR1)

        def _handler(signum, frame):
            self.request_capture("sigusr1")  # assignment only: signal-safe
            if callable(prev):
                prev(signum, frame)

        self._prev_sigusr1 = prev
        signal.signal(signal.SIGUSR1, _handler)
        return True

    # -- teardown ------------------------------------------------------------
    def close(self) -> None:
        """Idempotent: stop any open capture, flush the ring, restore the
        signal disposition."""
        if self._capturing:
            self._stop_profiler()
            self.instant("capture_end", cat="capture",
                         reason=self._capture_reason, truncated=True)
            self._capturing = False
        self.flush()
        if self._prev_sigusr1 is not None:
            try:
                signal.signal(signal.SIGUSR1, self._prev_sigusr1)
            except ValueError:
                pass  # not the main thread anymore (interpreter teardown)
            self._prev_sigusr1 = None


def _dumps(rec: dict) -> str:
    """JSON without importing json at call time is not worth it — but the
    import IS stdlib; kept in a helper so a future binary format has one
    seam."""
    import json

    try:
        return json.dumps(rec)
    except (TypeError, ValueError):
        # foreign attr values (a numpy scalar from a caller): stringify
        # rather than lose the span
        return json.dumps({k: (v if isinstance(
            v, (str, int, float, bool, dict, list, type(None))) else str(v))
            for k, v in rec.items()}, default=str)
