"""Flat dataclass configs + the five BASELINE.json presets (SURVEY §5.6).

The reference's config system is one flat argparse namespace per driver
(`main_moco.py:≈L28-100`, re-declared with different defaults in
`main_lincls.py:≈L40-90`); the v1→v2 switch is three booleans and a
temperature on the CLI. We keep that shape — a flat dataclass per driver,
argparse front-end in the drivers — and name the five BASELINE configs as
presets.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass


@dataclass
class PretrainConfig:
    # experiment
    name: str = "moco"
    variant: str = "v2"               # "v1" | "v2" | "v3"
    seed: int = 0
    # model (reference flags -a/--arch, --moco-dim/k/m/t, --mlp)
    arch: str = "resnet50"            # resnet18/34/50/101/152 | vit_small/base/large/huge
    embed_dim: int = 128              # --moco-dim
    num_negatives: int = 65536        # --moco-k (ignored for v3)
    momentum_ema: float = 0.999       # --moco-m (v3: base for cosine ramp, 0.99)
    temperature: float = 0.07         # --moco-t (v2 runs use 0.2)
    mlp_head: bool = False            # --mlp
    cifar_stem: bool = False
    shuffle_mode: str = "permute"     # ShuffleBN flavor: "permute" (faithful
                                      # all-gather + shared-RNG perm) | "ring"
                                      # (single ppermute rotation, cheaper)
    compute_dtype: str = "float32"    # "bfloat16" on TPU
    sync_bn: bool = False             # per-device BN is the MoCo default
    remat: bool = False               # per-block rematerialization (ViT
                                      # blocks / ResNet residual blocks):
                                      # trades recompute for HBM traffic
    zero_sharding: bool = False       # ZeRO-1: shard optimizer state over
                                      # the data axis (HBM/N footprint, one
                                      # all-gather of updates per step;
                                      # identical numerics — parallel/zero)
    # scale-out sharding (ISSUE 15; parallel/fsdp.py — see README
    # "Sharding modes" for the mode table and composition matrix)
    sharding: str = "dp"              # "dp" (seed layout: 1-D mesh, params
                                      # replicated — bitwise the pre-ISSUE-15
                                      # program) | "fsdp" (v3 only: params +
                                      # optimizer state sharded 1/N over the
                                      # fsdp mesh axis, all-gather-on-use,
                                      # grads reduce-scattered through
                                      # GradSync) | "fsdp_tp" (2-D hybrid:
                                      # shard over the fast inner axis,
                                      # replicate over the slow outer one;
                                      # quantized grad_sync upgrades to the
                                      # DynamiQ-style multi-hop reduce)
    sharding_axis_size: int = 0       # fsdp-axis (inner/fast) device count
                                      # for fsdp_tp; 0 = derive (all devices
                                      # for fsdp, largest proper divisor for
                                      # fsdp_tp). Must divide the device
                                      # count.
    collective_chunks: int = 1        # FAST-style chunked scheduling for
                                      # the ShuffleBN / v3 key-gather
                                      # all-to-alls: split each gather into
                                      # N barrier-chained chunk collectives
                                      # that pipeline with compute.
                                      # Bit-identical reassembly; 1 = one
                                      # monolithic gather (seed behavior)
    # gradient sync (ISSUE 6; parallel/gradsync.py — see README "Gradient
    # sync modes" for the mode table and convergence caveats)
    grad_sync: str = "fused"          # "fused" (exact DP, one tree pmean —
                                      # the seed program, bitwise) |
                                      # "bucketed" (per-bucket psums chained
                                      # with optimization_barrier: reduce
                                      # overlaps backprop, bitwise-equal
                                      # numerics) | "quantized" (int8/bf16
                                      # compress→psum→dequant per bucket +
                                      # per-device error feedback) | "demo"
                                      # (DeMo-style local momentum, top-k
                                      # sparse sync at a cadence)
    grad_sync_bucket_mb: float = 4.0  # bucketed/quantized: target bucket
                                      # payload (MiB of wire bytes per
                                      # all-reduce issue)
    grad_sync_quant_dtype: str = "int8"  # quantized wire dtype: "int8"
                                      # (shared-scale symmetric, int32
                                      # carrier) | "bfloat16"
    grad_sync_cadence: int = 1        # demo: sync every N steps (off-steps
                                      # carry no gradient payload — only
                                      # the constant probe-scalar psum)
    grad_sync_topk: float = 0.01      # demo: fraction of each leaf's
                                      # momentum synced per sync step
    grad_sync_demo_beta: float = 0.9  # demo: local momentum decay
    grad_allreduce_dtype: str = "float32"  # fused/bucketed wire-dtype
                                      # policy: "bfloat16" halves the grad
                                      # all-reduce's ICI bytes (EQuARX-style
                                      # in its simplest lossy form, NO error
                                      # feedback — grad_sync="quantized" is
                                      # the EF-corrected version; the master
                                      # update still runs in f32). Per-leaf
                                      # policy: float leaves reduce in bf16
                                      # and cast back to their OWN dtype,
                                      # integer leaves are summed exactly,
                                      # never cast (gradsync.leaf_wire_dtype)
    fused_bn_conv: bool = False       # interior bn→relu→conv passes through
                                      # Pallas fused kernels on TPU: the
                                      # Bottleneck 1x1 tail + stride-1 3x3
                                      # mids, and BasicBlock's conv2
                                      # (identical params and math;
                                      # models/fused_block). Default OFF
                                      # until tools/_fused_validate.py has
                                      # proven numerics+speed on a real
                                      # chip (r3 shipped it ON unmeasured —
                                      # VERDICT r3 weak #2; the r3 tunnel
                                      # outage left it chip-unvalidated)
    # data
    dataset: str = "synthetic"        # synthetic | cifar10 | imagefolder
    data_dir: str = ""
    image_size: int = 224
    aug_plus: bool = False            # --aug-plus (v2 aug stack)
    crop_min: float = 0.0             # v3 --crop-min (0 = variant default:
                                      # 0.08 for ViT, the R50 recipe uses 0.2)
    num_workers: int = 0              # host-side loader threads (-j);
                                      # 0 = dataset default (8)
    stage_size: int = 0               # ImageFolder staging-canvas shorter
                                      # side; 0 = dataset default (512 —
                                      # stages typical ImageNet photos at
                                      # ORIGINAL resolution so the on-device
                                      # RRC samples original pixels)
    # input pipeline (ISSUE 3: parallel sharded staging, decode-once cache,
    # overlapped H2D — see README "Input pipeline" for tuning)
    prefetch_depth: int = 2           # device batches staged ahead of the
                                      # consumer (Prefetcher queue capacity;
                                      # each slot pins one batch of HBM)
    staging_workers: int = 4          # host staging threads per Prefetcher:
                                      # each decodes a disjoint sub-slice of
                                      # the per-host batch into a pooled
                                      # canvas (bit-identical to 1 worker)
    input_cache_mb: int = 0           # decode-once canvas cache budget in
                                      # MiB (LRU over uint8 canvases +
                                      # extents; 0 = off). Sound because the
                                      # randomized augmentation runs ON
                                      # DEVICE over the staging canvas, so
                                      # the decoded canvas is deterministic
                                      # per image — epochs >= 2 pay memcpy
                                      # instead of JPEG decode
    h2d_trim: bool = False            # slice each staged batch to its max
                                      # content extent (rounded up to 64)
                                      # before the device transfer: fewer
                                      # H2D bytes + cheaper on-device aug
                                      # for content that underfills the
                                      # canvas. Single-host only; each new
                                      # trimmed shape compiles once
    # disaggregated input service (ISSUE 14 — see README "Input service")
    input_service: str = ""           # "host:port,host:port" staging-server
                                      # data endpoints: epoch batches are
                                      # fetched from standalone decode
                                      # servers (ServiceClient) instead of
                                      # decoded in-process — bit-identical
                                      # to in-process staging on the same
                                      # seed/epoch. "" = in-process.
                                      # Rejected with h2d_trim: trimming
                                      # is a client-side canvas slice whose
                                      # shape grid the remote shard frames
                                      # do not carry — progcheck P9's
                                      # bounded-compile-set contract stays
                                      # with the in-process path
    input_prestage: str = ""          # pre-staged epoch cache directory
                                      # (tools/prestage.py output) served
                                      # by the IN-PROCESS Prefetcher: the
                                      # dataset becomes mmap row gathers —
                                      # decode-once for the whole cluster.
                                      # (Staging servers take the same
                                      # directory via --prestage.)
    input_request_timeout_s: float = 30.0
                                      # one service shard round-trip bound
                                      # before the client tears the link
                                      # and re-lands the shard elsewhere.
                                      # Size ABOVE the slowest honest
                                      # shard decode: a timeout restarts
                                      # the decode from scratch on the
                                      # next server, so a bound below it
                                      # exhausts retries deterministically
    # optimization (reference: SGD momentum .9, wd 1e-4, lr .03, batch 256)
    optimizer: str = "sgd"            # sgd | adamw | lars
    lr: float = 0.03                  # absolute lr; 0.0 = derive from base_lr
    base_lr: float = 0.0              # lr-per-256: effective lr is
                                      # base_lr × batch/256 (moco-v3 semantics,
                                      # `main_moco.py` there: `args.lr *
                                      # args.batch_size / 256`), resolved at
                                      # step-build time so a --batch-size
                                      # override rescales the lr with it
    batch_size: int = 256             # GLOBAL batch
    epochs: int = 200
    warmup_epochs: int = 0            # v3: 40
    schedule: tuple[int, ...] = (120, 160)  # --schedule milestones (v1 path)
    cos: bool = False                 # --cos
    sgd_momentum: float = 0.9
    weight_decay: float = 1e-4
    momentum_ramp: bool = False       # v3 cosine m→1 ramp
    # bookkeeping / observability (SURVEY §5.1, §5.5)
    print_freq: int = 10              # -p
    tb_dir: str = ""                  # tensorboard scalar logdir ("" = off)
    profile_dir: str = ""             # jax.profiler trace logdir ("" = off)
    profile_start: int = 10           # trace window [start, stop) in steps
    profile_stop: int = 20
    debug_nans: bool = False          # jax_debug_nans + finite-loss guard (§5.2)
    # structured run telemetry (telemetry/; ISSUE 2) — machine-readable
    # step-phase timing, MFU, HBM tracking, pod-aggregated JSONL events
    telemetry_dir: str = ""           # events.jsonl + heartbeat.json land
                                      # here ("" = telemetry off; no step-
                                      # loop overhead when off)
    telemetry_flush_steps: int = 50   # buffered-record flush cadence, in
                                      # step records
    heartbeat_secs: float = 1.0       # min seconds between heartbeat.json
                                      # writes (beaten every step, time-
                                      # gated; the supervisor's hang-
                                      # detection granularity — independent
                                      # of the flush cadence above)
    telemetry_stride: int = 16        # device-fence sampling stride: every
                                      # N steps block_until_ready measures
                                      # the device-compute phase and HBM is
                                      # sampled; all other steps stay fully
                                      # async (0 = never fence)
    peak_flops_per_chip: float = 0.0  # MFU denominator override; 0 = look
                                      # up device_kind in the bf16 peak
                                      # table (telemetry/mfu.py; unknown
                                      # hardware ⇒ MFU omitted, never
                                      # fabricated)
    # distributed tracing + on-demand profiling (telemetry/trace.py;
    # ISSUE 8 — see README "Tracing & profiling")
    trace_mode: str = "off"           # "off" (capture windows still
                                      # armable) | "steps" (one span per
                                      # step / staged batch / supervisor
                                      # launch) | "full" (+ worker decode
                                      # slices, H2D puts, phase segments)
    trace_capture_steps: int = 50     # capture-window length, in steps:
                                      # SIGUSR1 / trace.trigger / anomaly
                                      # detectors elevate to full detail
                                      # (+ optional device trace) for this
                                      # many steps
    trace_capture_budget: int = 3     # max capture windows per run (auto-
                                      # triggers can never profile-storm a
                                      # multi-day run; 0 = captures off)
    trace_slow_step_k: float = 3.0    # arm a capture when step_s (or the
                                      # data phase) exceeds k × its own
                                      # rolling p95
    trace_device_profile: bool = False  # capture windows also record a
                                      # jax.profiler device trace into
                                      # <telemetry_dir>/traces/
    # learning-health diagnostics (telemetry/health.py; ISSUE 13 — see
    # README "Learning health" for formulas and sentinel semantics)
    health_stride: int = 0            # 0 = off (no diagnostics traced;
                                      # the health-on parameter trajectory
                                      # is bitwise the health-off one);
                                      # N = trace the in-graph
                                      # collapse diagnostics (embedding
                                      # std/participation ratio, queue
                                      # norm/age, q↔k param drift, grad
                                      # group norms) under one lax.cond
                                      # firing every N steps, recorded as
                                      # the step records' `health` block.
                                      # neg_sim/logit_margin are standard
                                      # metrics regardless of this knob.
    collapse_window: int = 50         # CollapseSentinel window W, in
                                      # OBSERVATIONS (per-step for
                                      # margin/acc1, per-health-stride
                                      # sample for embedding std)
    collapse_min_step: int = 0        # sentinel predicates evaluate only
                                      # past this step (init-time acc1 IS
                                      # chance and the margin is still
                                      # forming — an early window must
                                      # not page anyone)
    collapse_acc1: float = 0.0        # predicate: max acc1 over a full
                                      # window < this floor (%; 0 = off)
    collapse_emb_std: float = 0.0     # predicate: every sampled
                                      # embedding std in a full window
                                      # <= this epsilon (0 = off; needs
                                      # health_stride > 0 to see samples)
    collapse_margin: float = 0.0      # predicate: max logit margin over
                                      # a full window <= this (0 = off)
    collapse_rollback: bool = False   # opt-in: a fired predicate raises
                                      # CollapseError into the bounded
                                      # NaN-rollback path (restore last
                                      # good checkpoint + data-window
                                      # advance, max_rollbacks-capped);
                                      # default is a structured `health`
                                      # incident only
    ckpt_dir: str = "checkpoints"
    ckpt_every_epochs: int = 1
    resume: str = ""                  # path | "auto"
    export_path: str = ""             # write encoder_q (.safetensors/.npz) at end
    steps_per_epoch: int | None = None  # derived from dataset unless set
    # fault tolerance (resilience/; preemptible-VM pretraining survives
    # SIGTERM, corrupt checkpoints, NaN losses, and flaky reads unattended)
    loss_sentinel: bool = True        # every-step non-finite-loss check
                                      # (one-step lag — no pipeline bubble)
    max_rollbacks: int = 3            # consecutive NaN rollbacks before the
                                      # run aborts (0 = never roll back:
                                      # a non-finite loss raises immediately)
    watchdog_secs: float = 0.0        # flag when no step completes within
                                      # this window (0 = watchdog off)
    loader_retries: int = 3           # transient data-read retries per batch
                                      # (Prefetcher, exponential backoff)
    loader_backoff_secs: float = 0.5  # base backoff delay between retries
    decode_abort_rate: float = 0.5    # abort (DataQualityError) when the
                                      # cumulative decode-failure rate
                                      # exceeds this after the first host
                                      # batch (0 = never abort; failures are
                                      # still metered either way)
    resilience_sync_steps: int = 16   # multi-host only: cadence (in steps)
                                      # at which per-host fault signals
                                      # (SIGTERM flag, decode counters) are
                                      # allgathered so every host acts on
                                      # them identically — one host breaking
                                      # alone hangs the rest in the next
                                      # collective (0 disables the sync,
                                      # and with it preemption handling and
                                      # the decode abort on multi-host runs)
    chaos: str = ""                   # fault-injection spec for drills/tests,
                                      # e.g. "sigterm_at_step=100" or
                                      # "nan_at_step=3,loader_error_at_batch=7"
                                      # (resilience/chaos.py; also via the
                                      # MOCO_TPU_CHAOS env var)
    knn_monitor: bool = False         # periodic kNN top-1 during pretrain
    knn_every_epochs: int = 1         # monitor cadence (the eval costs ~160 s
                                      # on the 1-core sandbox — long CPU runs
                                      # thin it out; the final epoch always
                                      # reports so gates see a fresh number)
    knn_bank_size: int = 4096         # monitor bank cap (train-subset size)
    num_classes: int = 1000           # dataset classes (kNN/eval only)

    def __post_init__(self):
        # config-BUILD-time validation (runs again on every replace()): a
        # bad depth/worker count must fail where it was written, not as a
        # wedged queue half an epoch into a run
        if self.prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {self.prefetch_depth}"
            )
        if self.staging_workers < 1:
            raise ValueError(
                f"staging_workers must be >= 1, got {self.staging_workers}"
            )
        if self.input_cache_mb < 0:
            raise ValueError(
                f"input_cache_mb must be >= 0, got {self.input_cache_mb}"
            )
        # input-service knobs (ISSUE 14): a typo'd endpoint list must fail
        # where it was written, not as an unreachable-server stall mid-run.
        # The parser lives in the stdlib service protocol module — a
        # function-level import, so config stays importable without jax
        if self.input_request_timeout_s <= 0:
            raise ValueError(
                "input_request_timeout_s must be > 0, got "
                f"{self.input_request_timeout_s}"
            )
        if self.input_service:
            from moco_tpu.data.service.protocol import parse_endpoints

            parse_endpoints(self.input_service)  # raises ValueError
            if self.h2d_trim:
                raise ValueError(
                    "input_service and h2d_trim are mutually exclusive: "
                    "extent-trimming slices the staged canvas CLIENT-side "
                    "into a shape grid the remote shard frames do not "
                    "carry — run the service with full canvases (the "
                    "remote decode is what h2d_trim's savings came from) "
                    "or trim in-process"
                )
            if self.input_prestage:
                raise ValueError(
                    "input_service and input_prestage are mutually "
                    "exclusive on the train host: the service loader "
                    "would feed training while the prestage sat unused "
                    "as a len() source — a same-length-different-data "
                    "server pool would pass the meta check and silently "
                    "train off the pinned cache. Point the staging "
                    "servers at it instead "
                    "(tools/staging_server.py --prestage <dir>)"
                )
        # sharding knobs (ISSUE 15): literals kept in sync with
        # parallel/mesh.SHARDING_MODES — config must stay importable
        # without jax
        if self.sharding not in ("dp", "fsdp", "fsdp_tp"):
            raise ValueError(
                f"unknown sharding {self.sharding!r}; choose from "
                "dp/fsdp/fsdp_tp"
            )
        if self.sharding != "dp" and self.variant != "v3":
            raise ValueError(
                f"sharding={self.sharding!r} requires variant='v3': the "
                "queue-based v1/v2 step needs the replicated queue's "
                "identical-enqueue invariant (and its encoders fit "
                "per-chip) — FSDP targets the queue-free large-batch v3 "
                "regime"
            )
        if self.sharding_axis_size < 0:
            raise ValueError(
                f"sharding_axis_size must be >= 0, got "
                f"{self.sharding_axis_size}"
            )
        if self.sharding != "dp" and self.zero_sharding:
            raise ValueError(
                "zero_sharding and sharding=fsdp/fsdp_tp are mutually "
                "exclusive: fsdp already shards the optimizer state over "
                "the fsdp axis — re-placing it with the ZeRO-1 data-axis "
                "layout would silently re-replicate the shards"
            )
        if self.collective_chunks < 1:
            raise ValueError(
                f"collective_chunks must be >= 1, got "
                f"{self.collective_chunks}"
            )
        # grad-sync knobs (ISSUE 6): literals kept in sync with
        # parallel/gradsync.GRAD_SYNC_MODES — config must stay importable
        # without jax (the serve/stdlib processes)
        if self.grad_sync not in ("fused", "bucketed", "quantized", "demo"):
            raise ValueError(
                f"unknown grad_sync {self.grad_sync!r}; choose from "
                "fused/bucketed/quantized/demo"
            )
        if self.grad_sync_bucket_mb <= 0:
            raise ValueError(
                f"grad_sync_bucket_mb must be > 0, got {self.grad_sync_bucket_mb}"
            )
        if self.grad_sync_quant_dtype not in ("int8", "bfloat16"):
            raise ValueError(
                f"unknown grad_sync_quant_dtype {self.grad_sync_quant_dtype!r}"
            )
        if self.grad_sync_cadence < 1:
            raise ValueError(
                f"grad_sync_cadence must be >= 1, got {self.grad_sync_cadence}"
            )
        if not 0.0 < self.grad_sync_topk <= 1.0:
            raise ValueError(
                f"grad_sync_topk must be in (0, 1], got {self.grad_sync_topk}"
            )
        if not 0.0 <= self.grad_sync_demo_beta < 1.0:
            raise ValueError(
                f"grad_sync_demo_beta must be in [0, 1), got "
                f"{self.grad_sync_demo_beta}"
            )
        # tracing knobs (ISSUE 8): literals kept in sync with
        # telemetry/trace.TRACE_MODES — config stays importable without
        # the telemetry stack loaded
        if self.trace_mode not in ("off", "steps", "full"):
            raise ValueError(
                f"unknown trace_mode {self.trace_mode!r}; choose from "
                "off/steps/full"
            )
        if self.trace_capture_steps < 1:
            raise ValueError(
                f"trace_capture_steps must be >= 1, got "
                f"{self.trace_capture_steps}"
            )
        if self.trace_capture_budget < 0:
            raise ValueError(
                f"trace_capture_budget must be >= 0, got "
                f"{self.trace_capture_budget}"
            )
        if self.trace_slow_step_k <= 1.0:
            raise ValueError(
                f"trace_slow_step_k must be > 1, got {self.trace_slow_step_k}"
            )
        # learning-health knobs (ISSUE 13): config stays importable
        # without jax — literals only, like the gradsync/trace blocks
        if self.health_stride < 0:
            raise ValueError(
                f"health_stride must be >= 0, got {self.health_stride}"
            )
        if self.collapse_window < 1:
            raise ValueError(
                f"collapse_window must be >= 1, got {self.collapse_window}"
            )
        if self.collapse_min_step < 0:
            raise ValueError(
                f"collapse_min_step must be >= 0, got {self.collapse_min_step}"
            )
        for knob in ("collapse_acc1", "collapse_emb_std", "collapse_margin"):
            if getattr(self, knob) < 0:
                raise ValueError(
                    f"{knob} must be >= 0 (0 disables the predicate), "
                    f"got {getattr(self, knob)}"
                )
        if self.collapse_emb_std and not self.health_stride:
            raise ValueError(
                "collapse_emb_std needs health_stride > 0: the embedding-"
                "std predicate consumes the stride-sampled in-graph "
                "diagnostics and would otherwise watch an empty stream"
            )

    def replace(self, **kw) -> "PretrainConfig":
        return dataclasses.replace(self, **kw)

    @property
    def effective_lr(self) -> float:
        return _effective_lr(self)


def _effective_lr(config) -> float:
    """`lr` if set, else the batch-scaled `base_lr × batch/256`. An explicit
    `--lr` always wins (reference CLI semantics); presets that follow the
    linear-scaling rule ship `lr=0.0` + `base_lr` so batch overrides stay
    on-recipe (VERDICT r2 weak #4)."""
    if config.lr:
        return config.lr
    if not config.base_lr:
        raise ValueError("config needs lr or base_lr (both are 0)")
    return config.base_lr * config.batch_size / 256


@dataclass
class EvalConfig:
    """Linear probe (`main_lincls.py` defaults) + kNN settings."""

    arch: str = "resnet50"
    pretrained: str = ""              # --pretrained checkpoint path
    dataset: str = "imagefolder"
    data_dir: str = ""
    image_size: int = 224
    cifar_stem: bool = False
    num_classes: int = 1000
    num_workers: int = 0              # host-side loader threads (-j); 0 = default (8)
    stage_size: int = 0               # staging canvas shorter side (0 = default)
    prefetch_depth: int = 2           # batches staged ahead (epoch_loader)
    staging_workers: int = 4          # host staging threads per Prefetcher
    seed: int = 0
    # lincls recipe: lr 30, epochs 100, milestones 60/80, wd 0, batch 256
    lr: float = 30.0                  # absolute lr; 0.0 = derive from base_lr
    base_lr: float = 0.0              # lr-per-256 (moco-v3 lincls scales lr by
                                      # batch/256; see `_effective_lr`)
    batch_size: int = 256
    epochs: int = 100
    schedule: tuple[int, ...] = (60, 80)
    cos: bool = False
    sgd_momentum: float = 0.9
    weight_decay: float = 0.0
    # kNN protocol (SURVEY §2.5): top-200 neighbors, T=0.07
    knn_k: int = 200
    knn_temperature: float = 0.07
    knn_bank_chunk: int = 65536       # bank rows per streamed top-k slice
                                      # (caps sims at [batch, chunk]; 0 = off)
    print_freq: int = 10
    ckpt_dir: str = "lincls_checkpoints"  # probe checkpoints ("" = off)
    resume: str = ""                      # "" | "auto" (latest probe ckpt)
    evaluate: bool = False                # -e/--evaluate: validate the
                                          # (resumed) probe and exit, no
                                          # training (`main_lincls.py:≈L95`)

    def __post_init__(self):
        if self.prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {self.prefetch_depth}"
            )
        if self.staging_workers < 1:
            raise ValueError(
                f"staging_workers must be >= 1, got {self.staging_workers}"
            )

    def replace(self, **kw) -> "EvalConfig":
        return dataclasses.replace(self, **kw)

    @property
    def effective_lr(self) -> float:
        return _effective_lr(self)


@dataclass
class ServeConfig:
    """Online embedding service (moco_tpu/serve/; ISSUE 5). One flat
    dataclass like the drivers', exposed by tools/serve.py as `--flags`."""

    pretrained: str = ""              # exported encoder (.safetensors/.npz),
                                      # any dialect in checkpoint.CHECKPOINT_DIALECTS
    arch: str = "resnet50"
    image_size: int = 224
    cifar_stem: bool = False
    host: str = "127.0.0.1"
    port: int = 8080                  # 0 = ephemeral (tests/bench)
    # micro-batcher (serve/batcher.py): flush on bucket-full OR deadline
    buckets: tuple[int, ...] = (1, 8, 32, 128)  # padded compile shapes; the
                                      # jitted apply compiles exactly these
    flush_ms: float = 10.0            # max coalesce wait before a partial
                                      # bucket flushes (the latency a lone
                                      # request pays to help the next one)
    max_queue: int = 256              # admission-queue depth; beyond it
                                      # requests shed with `overloaded`
    request_deadline_ms: float = 2000.0  # per-request budget; expired-in-
                                      # queue requests shed with
                                      # `deadline_exceeded`, never stall
    embed_cache_mb: int = 64          # content-hash embedding LRU budget
                                      # (serve/cache.py; 0 = off)
    # observability (same events.jsonl stream as training)
    telemetry_dir: str = ""           # "" = telemetry off
    snapshot_every: int = 25          # serve-record cadence, in batches
    # distributed tracing (ISSUE 8): request/flush spans + capture windows
    trace_mode: str = "off"           # off | steps | full (README table)
    trace_capture_steps: int = 50     # capture-window length, in FLUSHED
                                      # batches (the serve tick unit)
    trace_capture_budget: int = 3     # max capture windows per process
    trace_shed_spike: int = 8         # arm a capture when this many
                                      # overload sheds land within 5 s
                                      # (0 = shed-spike detector off)
    # optional kNN-classify endpoint over a precomputed feature bank
    knn_bank: str = ""                # npz with `features` [N,D] + `labels` [N]
    knn_k: int = 200
    knn_temperature: float = 0.07
    num_classes: int = 0              # 0 = derive from bank labels
    drain_timeout_s: float = 60.0     # SIGTERM: max wait for in-flight work
    # hot-reload drift guard (ISSUE 13): before swapping a reloaded
    # engine in, embed a fixed probe batch on old+new and refuse (409
    # reload_collapsed — the fleet quarantines the step) a checkpoint
    # whose probe embeddings are degenerate
    reload_probe: int = 8             # probe rows (0 = guard off)
    reload_min_spread: float = 1e-4   # refuse when 1-‖mean unit row‖ of
                                      # the NEW engine's probe embeddings
                                      # falls below this (rank-one
                                      # collapse as seen from serving)
    # dual swap (ISSUE 16): mean probe-row cosine between a paired
    # bank's recorded probe features and the NEW engine's embedding of
    # the same rows must clear this floor or the pair is refused
    # (409 reload_bank_mismatch — the fleet quarantines the pair)
    bank_agreement_min: float = 0.98
    # sharded ANN index (ISSUE 20): ann_cells > 0 requires a verified
    # paired index next to the bank (tools/bank_build.py --ann-cells)
    # and replaces the exact /v1/knn vote with the IVF probe; 0 keeps
    # the exact path bit-identical to before
    ann_cells: int = 0                # coarse-quantizer cells (0 = exact)
    ann_nprobe: int = 8               # cells probed per query
    ann_rerank: int = 0               # candidates kept per probe
                                      # (0 = knn_k)
    ann_shard: int = 0                # this replica's cell partition ...
    ann_shards: int = 1               # ... of how many (cell % shards)
    # tiered admission (ISSUE 20): interactive vs batch lanes
    admission_tiers: bool = True      # False folds "batch" onto the
                                      # interactive lane
    batch_max_queue: int = 1024       # batch-lane admission depth
    batch_deadline_ms: float = 30000.0  # batch-lane default deadline

    def __post_init__(self):
        # the ONE bucket-ladder rule, shared with the runtime's own check
        # (serve/batcher.py is numpy+stdlib — safe at config-import time)
        from moco_tpu.serve.batcher import validate_buckets

        b = validate_buckets(self.buckets)
        if self.max_queue < b[-1]:
            raise ValueError(
                f"max_queue ({self.max_queue}) must hold at least one full "
                f"bucket ({b[-1]})"
            )
        if self.flush_ms < 0 or self.request_deadline_ms <= 0:
            raise ValueError(
                "flush_ms must be >= 0 and request_deadline_ms > 0"
            )
        if self.embed_cache_mb < 0:
            raise ValueError(
                f"embed_cache_mb must be >= 0, got {self.embed_cache_mb}"
            )
        if self.reload_probe < 0 or self.reload_min_spread < 0:
            raise ValueError(
                "reload_probe and reload_min_spread must be >= 0 "
                f"(0 disables the guard), got {self.reload_probe} / "
                f"{self.reload_min_spread}"
            )
        if not -1.0 <= self.bank_agreement_min <= 1.0:
            raise ValueError(
                "bank_agreement_min is a cosine floor in [-1, 1], got "
                f"{self.bank_agreement_min}"
            )
        if self.trace_mode not in ("off", "steps", "full"):
            raise ValueError(
                f"unknown trace_mode {self.trace_mode!r}; choose from "
                "off/steps/full"
            )
        if self.trace_capture_steps < 1 or self.trace_capture_budget < 0 \
                or self.trace_shed_spike < 0:
            raise ValueError(
                "trace_capture_steps must be >= 1, trace_capture_budget "
                "and trace_shed_spike >= 0"
            )
        if self.ann_cells < 0 or self.ann_nprobe < 1 or self.ann_rerank < 0:
            raise ValueError(
                "need ann_cells >= 0 (0 = exact), ann_nprobe >= 1, "
                f"ann_rerank >= 0 (0 = knn_k); got {self.ann_cells} / "
                f"{self.ann_nprobe} / {self.ann_rerank}"
            )
        if self.ann_shards < 1 or not 0 <= self.ann_shard < self.ann_shards:
            raise ValueError(
                f"need 0 <= ann_shard < ann_shards, got "
                f"{self.ann_shard} / {self.ann_shards}"
            )
        if self.ann_cells and not self.knn_bank:
            raise ValueError(
                "ann_cells > 0 needs a --knn-bank (the index pairs with "
                "a versioned bank)"
            )
        if self.batch_max_queue < b[-1]:
            raise ValueError(
                f"batch_max_queue ({self.batch_max_queue}) must hold at "
                f"least one full bucket ({b[-1]})"
            )
        if self.batch_deadline_ms <= 0:
            raise ValueError(
                f"batch_deadline_ms must be > 0, got "
                f"{self.batch_deadline_ms}"
            )

    def replace(self, **kw) -> "ServeConfig":
        return dataclasses.replace(self, **kw)


# ---------------------------------------------------------------------------
# The five BASELINE.json target configs as named presets.
# ---------------------------------------------------------------------------

PRESETS: dict[str, PretrainConfig | EvalConfig] = {
    # 1. MoCo-v1 ResNet-18 CIFAR-10, K=4096, single-process (CPU smoke ref)
    "cifar10-moco-v1": PretrainConfig(
        name="cifar10-moco-v1",
        variant="v1",
        arch="resnet18",
        num_negatives=4096,
        temperature=0.07,
        cifar_stem=True,
        dataset="cifar10",
        image_size=32,
        batch_size=256,
        epochs=200,
        cos=False,
        knn_monitor=True,
        num_classes=10,
    ),
    # 0. MoCo-v1 ResNet-50 ImageNet-1k — the reference's DEFAULT run
    #    (no MLP, no aug+, no cosine; T=0.07, milestones 120/160; the 60.6%
    #    linear-probe row in BASELINE.md)
    "imagenet-moco-v1": PretrainConfig(
        name="imagenet-moco-v1",
        variant="v1",
        arch="resnet50",
        dataset="imagefolder",
        compute_dtype="bfloat16",
    ),
    # 2. MoCo-v2 ResNet-50 ImageNet-1k, K=65536, MLP head, cosine LR
    "imagenet-moco-v2": PretrainConfig(
        name="imagenet-moco-v2",
        variant="v2",
        arch="resnet50",
        num_negatives=65536,
        temperature=0.2,
        mlp_head=True,
        aug_plus=True,
        cos=True,
        dataset="imagefolder",
        compute_dtype="bfloat16",
    ),
    # 4. Linear-probe + kNN eval on frozen MoCo-v2 features
    "imagenet-lincls": EvalConfig(),
    # 4b. MoCo-v3 linear probe (sibling repo's `main_lincls.py` recipe: SGD
    #     lr 3·batch/256, 90 epochs, cosine, wd 0 — its README linear-probe
    #     command for ViT). Probes BACKBONE features of a v3 export.
    "imagenet-lincls-v3": EvalConfig(
        arch="vit_small",
        lr=0.0,
        base_lr=3.0,
        batch_size=1024,
        epochs=90,
        schedule=(),
        cos=True,
    ),
    # 5. MoCo-v3 ViT-S/16, queue-free large-batch contrastive
    "imagenet-moco-v3-vits": PretrainConfig(
        name="imagenet-moco-v3-vits",
        variant="v3",
        arch="vit_small",
        embed_dim=256,
        momentum_ema=0.99,
        momentum_ramp=True,
        temperature=0.2,
        optimizer="adamw",
        lr=0.0,
        base_lr=1.5e-4,
        weight_decay=0.1,
        batch_size=4096,
        epochs=300,
        warmup_epochs=40,
        cos=True,
        aug_plus=True,
        dataset="imagefolder",
        compute_dtype="bfloat16",
    ),
    # 5a. MoCo-v3 ViT-B/16 — the sibling repo's larger ViT run (same AdamW
    #     recipe as ViT-S: lr 1.5e-4·b/256, wd 0.1, batch 4096, 40-epoch
    #     warmup; only the backbone width/depth changes). remat on by
    #     default: ViT-B at per-chip batch 512 needs it to fit HBM.
    "imagenet-moco-v3-vitb": PretrainConfig(
        name="imagenet-moco-v3-vitb",
        variant="v3",
        arch="vit_base",
        embed_dim=256,
        momentum_ema=0.99,
        momentum_ramp=True,
        temperature=0.2,
        optimizer="adamw",
        lr=0.0,
        base_lr=1.5e-4,
        weight_decay=0.1,
        batch_size=4096,
        epochs=300,
        warmup_epochs=40,
        cos=True,
        aug_plus=True,
        remat=True,
        dataset="imagefolder",
        compute_dtype="bfloat16",
    ),
    # 5b. MoCo-v3 ResNet-50 leg (sibling repo's `MoCo_ResNet`; SURVEY §2.9
    #     "ResNet recipe uses LARS"): LARS, lr 0.3·batch/256, wd 1.5e-6,
    #     100 ep / 10 warmup, T=1.0 (moco-v3 default), crop-min 0.2,
    #     m=0.99 cosine-ramped — the repo's R50 README command.
    "imagenet-moco-v3-r50": PretrainConfig(
        name="imagenet-moco-v3-r50",
        variant="v3",
        arch="resnet50",
        embed_dim=256,
        momentum_ema=0.99,
        momentum_ramp=True,
        temperature=1.0,
        optimizer="lars",
        lr=0.0,
        base_lr=0.3,
        weight_decay=1.5e-6,
        batch_size=4096,
        epochs=100,
        warmup_epochs=10,
        cos=True,
        crop_min=0.2,
        dataset="imagefolder",
        compute_dtype="bfloat16",
    ),
}


# 3. Same recipe, ShuffleBN across 8 chips (v3-8) — identical step program by
# construction (derived, so the two can never silently fork); the mesh size
# comes from the hardware.
PRESETS["imagenet-moco-v2-8chip"] = PRESETS["imagenet-moco-v2"].replace(
    name="imagenet-moco-v2-8chip"
)


# fields whose default is None but which must parse as ints
_INT_NONE_FIELDS = {"steps_per_epoch"}


def add_config_flags(parser, config_cls) -> None:
    """Expose every dataclass field as a `--flag` (the reference's flat
    argparse surface). Shared by the train/lincls/knn drivers."""
    for f in dataclasses.fields(config_cls):
        name = "--" + f.name.replace("_", "-")
        if isinstance(f.default, bool):
            parser.add_argument(
                name,
                type=lambda s: s.lower() in ("1", "true", "yes"),
                default=None,
            )
        elif isinstance(f.default, tuple):
            # int-tuple fields (schedule milestones, serve buckets):
            # space-separated on the CLI, retupled in collect_overrides
            parser.add_argument(name, type=int, nargs="*", default=None)
        else:
            caster = (
                int
                if f.name in _INT_NONE_FIELDS
                else type(f.default)
                if f.default is not None
                else str
            )
            parser.add_argument(name, type=caster, default=None)


def collect_overrides(args, config_cls) -> dict:
    """Non-None parsed flags → dataclass replace() kwargs."""
    overrides = {
        f.name: getattr(args, f.name)
        for f in dataclasses.fields(config_cls)
        if getattr(args, f.name, None) is not None
    }
    for f in dataclasses.fields(config_cls):
        if isinstance(f.default, tuple) and f.name in overrides:
            overrides[f.name] = tuple(overrides[f.name])
    return overrides


def get_preset(name: str):
    if name not in PRESETS:
        raise ValueError(f"unknown preset {name!r}; choose from {sorted(PRESETS)}")
    return PRESETS[name]
