"""MoCo v3 — queue-free, symmetric, large-batch contrastive step
(BASELINE config 5; SURVEY §2.9 / §3.5, sibling repo `moco-v3`).

Differences from the v1/v2 step (train_step.py), per the reference:
- No queue, no ShuffleBN. Negatives are the OTHER in-batch samples,
  all-gathered across the data mesh.
- Both crops go through BOTH encoders; the loss is symmetric:
  `ctr(q1, k2) + ctr(q2, k1)`, each scaled by 2·T.
- The query model adds a 2-layer PREDICTOR on top of the projector; the
  momentum encoder is backbone+projector only. EMA therefore covers the
  params_q subtree MINUS the predictor.
- Momentum ramps 0.99 → 1.0 on a cosine over training.
- ViT: the patch-projection is frozen at random init — `stop_gradient` in
  the model (models/vit.py) plus an optimizer mask here so weight decay
  cannot move the frozen params either (== `requires_grad=False`).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from moco_tpu.config import PretrainConfig
from moco_tpu.models.heads import V3Predictor, V3Projector
from moco_tpu.ops.ema import ema_update, momentum_schedule
from moco_tpu.ops.losses import l2_normalize, v3_contrastive_loss
from moco_tpu.parallel.collectives import all_gather_batch
from moco_tpu.parallel.mesh import DATA_AXIS
from moco_tpu.telemetry import health
from moco_tpu.train_state import TrainState
from moco_tpu.utils.compat import shard_map

PREDICTOR_KEY = "predictor"


class V3Model(nn.Module):
    """backbone → projector (→ predictor when `predict=True`).

    One module serves both roles: the key encoder applies it with
    `predict=False` and a params tree lacking the predictor subtree.
    """

    backbone: nn.Module
    embed_dim: int = 256
    hidden_dim: int = 4096

    @nn.compact
    def __call__(self, x, train: bool = True, predict: bool = False):
        f = self.backbone(x, train=train)
        z = V3Projector(self.hidden_dim, self.embed_dim, name="projector")(f, train=train)
        if predict:
            z = V3Predictor(self.hidden_dim, self.embed_dim, name=PREDICTOR_KEY)(
                z, train=train
            )
        return z


def encoder_subtree(tree):
    """Drop the predictor subtree — the part of params_q the EMA covers."""
    return {k: v for k, v in tree.items() if k != PREDICTOR_KEY}


def patch_embed_trainable_mask(params) -> Any:
    """Optimizer mask: False for every leaf under a `patch_embed` module."""

    def is_trainable(path, _leaf):
        return not any(
            getattr(entry, "key", None) == "patch_embed" for entry in path
        )

    return jax.tree_util.tree_map_with_path(is_trainable, params)


def create_v3_train_state(
    rng: jax.Array, model: V3Model, tx: optax.GradientTransformation, input_shape
) -> TrainState:
    """Init query model (with predictor); key tree = encoder subtree copy."""
    init_key, state_key = jax.random.split(rng)
    variables = model.init(
        init_key, jnp.zeros(input_shape, jnp.float32), train=False, predict=True
    )
    params_q = variables["params"]
    batch_stats_q = variables.get("batch_stats", {})
    params_k = jax.tree.map(jnp.copy, encoder_subtree(params_q))
    batch_stats_k = jax.tree.map(jnp.copy, encoder_subtree(batch_stats_q))
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params_q=params_q,
        params_k=params_k,
        batch_stats_q=batch_stats_q,
        batch_stats_k=batch_stats_k,
        opt_state=tx.init(params_q),
        queue=None,
        queue_ptr=None,
        rng=state_key,
    )


def _build_apply(model: V3Model):
    def apply(params, stats, x, predict):
        out, mut = model.apply(
            {"params": params, "batch_stats": stats},
            x,
            train=True,
            predict=predict,
            mutable=["batch_stats"],
        )
        return l2_normalize(out), mut["batch_stats"]

    return apply


def _build_momentum_keys(model: V3Model):
    """The momentum-encoder branch, shared by the spmd_region and
    `build_v3_grad_probe` (ISSUE 9): keys for both crops (running stats
    chained through the two forwards, as two sequential reference forward
    calls would), stop-gradded — the v3 contract that no gradient reaches
    the momentum encoder."""
    apply = _build_apply(model)

    def momentum_keys(params_k, stats_k, x1, x2):
        k1, stats_k = apply(params_k, stats_k, x1, predict=False)
        k2, stats_k = apply(params_k, stats_k, x2, predict=False)
        k1 = lax.stop_gradient(k1)
        k2 = lax.stop_gradient(k2)
        return k1, k2, stats_k

    return momentum_keys


def _build_query_loss(model: V3Model, temperature: float,
                      batch_axis=DATA_AXIS, chunks: int = 1):
    """The symmetric v3 contrastive core, shared by the spmd_region's
    value_and_grad and the grad-flow probe. `batch_axis` is the data axis
    (or the 2-D mesh's axis tuple — ISSUE 15); `chunks` routes the key
    gathers through the FAST-style chunked schedule."""
    apply = _build_apply(model)

    def query_loss(pq, stats_q, x1, x2, k1, k2):
        q1, s = apply(pq, stats_q, x1, predict=True)
        q2, s = apply(pq, s, x2, predict=True)
        loss = v3_contrastive_loss(q1, k2, temperature, batch_axis, chunks) + \
               v3_contrastive_loss(q2, k1, temperature, batch_axis, chunks)
        return loss, (s, q1)

    return query_loss


def build_v3_grad_probe(config: PretrainConfig, model: V3Model, mesh):
    """The v3 differentiable audit surface (ISSUE 9, tools/progcheck P1):
    shard_map'd `(params_q, params_k, stats_q, stats_k, x1, x2) ->
    (g_q, g_k)` differentiating the SAME momentum-key + symmetric-loss code
    the v3 step traces, w.r.t. the query AND momentum params. The momentum
    branch ends in stop_gradient, so `g_k` must be structurally zero —
    progcheck proves it from the jaxpr. Grads route through the fused
    GradSync reduce (lint R7)."""
    from jax.sharding import PartitionSpec as P

    from moco_tpu.parallel.gradsync import GradSync

    momentum_keys = _build_momentum_keys(model)
    query_loss = _build_query_loss(model, config.temperature)
    gradsync = GradSync(config.replace(grad_sync="fused"), mesh.size)

    def probe(params_q, params_k, stats_q, stats_k, x1, x2):
        def loss_of(pq, pk):
            k1, k2, _ = momentum_keys(pk, stats_k, x1, x2)
            loss, _aux = query_loss(pq, stats_q, x1, x2, k1, k2)
            return loss

        grads = jax.grad(loss_of, argnums=(0, 1))(params_q, params_k)
        reduced, _, _probe = gradsync.region_reduce(grads, {}, jnp.int32(0))
        return reduced

    return shard_map(
        probe,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
    )


def build_v3_train_step(
    config: PretrainConfig, model: V3Model, tx, mesh, steps_per_epoch: int,
    sched=None, state=None,
):
    """Jitted `(state, x1, x2) -> (state', metrics)`, state donated.

    With `config.sharding != "dp"` (ISSUE 15) the step is FSDP-sharded:
    `state` (an example TrainState — abstract shapes suffice) is required
    so the per-leaf shard axes are fixed at build time; params enter the
    region as fsdp shards, are all-gathered on use, and the GradSync-
    reduced gradient is sliced back to the shard before it leaves the
    region. The dp path is byte-for-byte the pre-ISSUE-15 program.
    """
    from moco_tpu.parallel.collectives import batch_axis_index
    from moco_tpu.parallel.fsdp import plan_for
    from moco_tpu.parallel.gradsync import GradSync
    from moco_tpu.train_step import lr_schedule

    temperature = config.temperature
    total_steps = config.epochs * steps_per_epoch
    if sched is None:
        sched = lr_schedule(config, steps_per_epoch)
    plan = plan_for(config, mesh)
    if plan is None:
        batch_axis = DATA_AXIS
        gradsync = GradSync(config, mesh.size)
    else:
        if state is None:
            raise ValueError(
                f"sharding={config.sharding!r} needs the example `state` at "
                "step-build time (the per-leaf shard axes come from its "
                "shapes) — the driver passes the freshly-created TrainState"
            )
        batch_axis = plan.batch_axes
        gradsync = GradSync.for_mesh(config, mesh)
        q_axes = plan.axis_tree(state.params_q)
        k_axes = plan.axis_tree(state.params_k)
        q_specs = plan.specs(state.params_q)
        k_specs = plan.specs(state.params_k)
    chunks = int(getattr(config, "collective_chunks", 1))
    momentum_keys = _build_momentum_keys(model)
    query_loss = _build_query_loss(model, temperature, batch_axis, chunks)

    def spmd_region(params_q, params_k, stats_q, stats_k, gs_state, x1, x2,
                    step):
        if plan is not None:
            # all-gather-on-use: the full weights exist only inside the
            # region's forward/backward window
            params_q = plan.gather(params_q, q_axes)
            params_k = plan.gather(params_k, k_axes)
        k1, k2, stats_k = momentum_keys(params_k, stats_k, x1, x2)

        def loss_fn(pq):
            return query_loss(pq, stats_q, x1, x2, k1, k2)

        (loss, (new_stats_q, q1)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params_q)
        payload, gs_new, gs_probe = gradsync.region_reduce(grads, gs_state, step)
        if plan is not None and gradsync.mode != "demo":
            # reduce-scatter: the reduced full grads leave the region as
            # this device's shard (demo's sparse payload merges outside)
            payload = plan.scatter(payload, q_axes)
        new_stats_q = lax.pmean(new_stats_q, batch_axis)
        new_stats_k = lax.pmean(stats_k, batch_axis)
        # monitoring: in-batch top-1 for the q1·k2 direction
        k2_all = all_gather_batch(k2, batch_axis, chunks)
        logits = jnp.einsum("nc,mc->nm", q1, k2_all, preferred_element_type=jnp.float32)
        labels = jnp.arange(q1.shape[0]) + batch_axis_index(batch_axis) * q1.shape[0]
        acc1 = 100.0 * jnp.mean(jnp.argmax(logits, axis=-1) == labels)
        # positive-pair alignment, same frozen-encoder detector as the
        # v1/v2 step's pos_sim (q1/k2 are L2-normalized, so the row-dot is
        # the cosine of the local positive pair)
        pos_sim = jnp.mean(jnp.sum(q1 * k2, axis=-1))
        # ISSUE 13 standard metrics: the monitoring logits are raw
        # cosines (no /T), so neg_sim_mean's ×T runs at T=1 here
        neg_sim = health.neg_sim_mean(logits, labels, 1.0)
        metrics = {"loss": loss, "acc1": acc1, "pos_sim": pos_sim,
                   "neg_sim": neg_sim, "logit_margin": pos_sim - neg_sim}
        if config.health_stride:
            # stride-gated collapse diagnostics (queue-free v3: no queue
            # stats) riding the SAME metrics pmean — no new collectives
            metrics.update(health.region_health(
                q1, k2, grads, step, config.health_stride))
        metrics = lax.pmean(metrics, batch_axis)
        return payload, gs_new, gs_probe, new_stats_q, new_stats_k, metrics

    if plan is None:
        in_specs = (P(), P(), P(), P(), P(DATA_AXIS), P(DATA_AXIS),
                    P(DATA_AXIS), P())
        out_specs = (gradsync.payload_specs(P), P(DATA_AXIS), P(), P(), P(),
                     P())
    else:
        batch_spec = P(plan.batch_axes)
        payload_spec = (gradsync.payload_specs(P)
                        if gradsync.mode == "demo" else q_specs)
        in_specs = (q_specs, k_specs, P(), P(), batch_spec, batch_spec,
                    batch_spec, P())
        out_specs = (payload_spec, batch_spec, P(), P(), P(), P())
    region = shard_map(
        spmd_region,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
    )

    def train_step(state: TrainState, x1, x2):
        if config.momentum_ramp:
            m = momentum_schedule(config.momentum_ema, state.step, total_steps)
        else:
            m = config.momentum_ema
        params_k = ema_update(state.params_k, encoder_subtree(state.params_q), m)
        payload, gs_new, gs_probe, stats_q, stats_k, metrics = region(
            state.params_q, params_k, state.batch_stats_q, state.batch_stats_k,
            state.gradsync, x1, x2, state.step,
        )
        grads = gradsync.finalize(payload, state.step)
        updates, opt_state = tx.update(grads, state.opt_state, state.params_q)
        params_q = optax.apply_updates(state.params_q, updates)
        metrics = dict(
            metrics, lr=sched(state.step), momentum=m,
            gs_comm_pre=gs_probe, gs_comm_post=gradsync.probe_post(grads),
        )
        if config.health_stride:
            # q↔k drift over the EMA-covered subtree (the predictor is
            # query-only); outer level, replicated: no collective
            metrics.update(health.param_drift(
                encoder_subtree(state.params_q), params_k, state.step,
                config.health_stride))
        return (
            state.replace(
                step=state.step + 1,
                params_q=params_q,
                params_k=params_k,
                batch_stats_q=stats_q,
                batch_stats_k=stats_k,
                opt_state=opt_state,
                gradsync=gs_new,
            ),
            metrics,
        )

    return jax.jit(train_step, donate_argnums=(0,))
