"""Decode-once canvas cache (ISSUE 3 tentpole part 3).

The staged canvas is AUGMENTATION-INDEPENDENT: every randomized transform
(crop, flip, jitter, blur) runs on device over the staging canvas
(data/augment.py), so host decode of image i is a pure deterministic
function of the file bytes — decode it once, and every later epoch pays a
memcpy instead of a JPEG decode. `CachedDataset` wraps any dataset with
the `(images, labels, extents)` batch protocol in a byte-budgeted LRU of
per-image `(canvas, extent, label)` entries.

Correctness invariants:
  - bit-identical: a cache-hit batch equals the freshly-decoded batch
    exactly (test-enforced). Entries are immutable by convention; lookups
    COPY rows into the output, so consumers can never corrupt the cache.
  - resume/rollback-safe by construction: the cache is keyed by DATASET
    INDEX, not batch position, so `skip_batches` fast-forward and the NaN
    rollback's data-window skip simply never consult the skipped indices —
    there is no positional state to invalidate.
  - failures are never frozen: if the inner dataset's decode-failure
    counter moved during a miss fill, none of that fill is inserted — a
    transient storage blip must not pin zero canvases for the whole run
    (the per-batch PIL retry / driver abort-rate machinery keeps working).

Thread-safe: staging workers fill disjoint sub-slices of a batch
concurrently; the lock guards only dict bookkeeping, copies happen
outside it.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np


class CachedDataset:
    """LRU canvas cache in front of `dataset`. Budget is `cache_mb` MiB of
    canvas+extent bytes; an entry larger than the whole budget is simply
    never cached. Unknown attributes (labels, num_classes, decode
    counters, stage geometry) delegate to the inner dataset, so the driver
    meters and eval paths see the wrapper as the dataset itself."""

    def __init__(self, dataset, cache_mb: int, stats=None):
        if cache_mb <= 0:
            raise ValueError(f"cache_mb must be positive, got {cache_mb}")
        self.dataset = dataset
        self.budget_bytes = int(cache_mb) * 2**20
        self._stats = stats
        self._lock = threading.Lock()
        self._entries: OrderedDict[int, tuple[np.ndarray, np.ndarray, int]] = (
            OrderedDict()
        )
        self._bytes = 0
        # local counters mirrored into `stats` (when given): benches and
        # tests read them without a telemetry registry
        self.hits = 0
        self.misses = 0

    def __len__(self):
        return len(self.dataset)

    def __getattr__(self, name):
        # only called for attributes NOT found on the wrapper: live
        # delegation, so decode_failures/decode_total read current values
        return getattr(self.dataset, name)

    # -- internals ----------------------------------------------------------
    def _lookup(self, indices) -> dict[int, tuple]:
        """Hit entries for `indices` (refreshing LRU recency), under lock."""
        found = {}
        with self._lock:
            for i in indices:
                entry = self._entries.get(i)
                if entry is not None:
                    self._entries.move_to_end(i)
                    found[i] = entry
        return found

    def _insert(self, idx: int, canvas: np.ndarray, extent: np.ndarray,
                label) -> None:
        cost = canvas.nbytes + extent.nbytes
        if cost > self.budget_bytes:
            return
        with self._lock:
            old = self._entries.pop(idx, None)
            if old is not None:
                self._bytes -= old[0].nbytes + old[1].nbytes
            while self._bytes + cost > self.budget_bytes and self._entries:
                _, (ev_c, ev_e, _) = self._entries.popitem(last=False)
                self._bytes -= ev_c.nbytes + ev_e.nbytes
            self._entries[idx] = (canvas, extent, label)
            self._bytes += cost

    def _fill_misses(self, miss_idx: list[int]):
        """Decode the missing indices through the inner dataset; returns its
        (imgs, labels, extents). Inserts into the cache only when the inner
        decode-failure counter did not move."""
        before = getattr(self.dataset, "decode_failures", 0)
        imgs, labels, extents = self.dataset.get_batch(np.asarray(miss_idx))
        clean = getattr(self.dataset, "decode_failures", 0) == before
        if clean:
            for j, i in enumerate(miss_idx):
                # row copies: a row VIEW would pin the whole miss batch's
                # array in memory for the life of one cached image
                self._insert(i, np.array(imgs[j]), np.array(extents[j]),
                             labels[j])
        return imgs, labels, extents

    def _account(self, hits: int, misses: int) -> None:
        self.hits += hits
        self.misses += misses
        if self._stats is not None:
            self._stats.note_cache(hits, misses)

    # -- batch protocol -----------------------------------------------------
    def get_batch(self, indices):
        idx = [int(i) for i in np.asarray(indices)]
        found = self._lookup(idx)
        miss_idx = [i for i in idx if i not in found]
        if not miss_idx:  # pure-hit fast path: assemble straight from cache
            imgs = np.stack([found[i][0] for i in idx])
            extents = np.stack([found[i][1] for i in idx])
            labels = np.asarray([found[i][2] for i in idx])
            self._account(len(idx), 0)
            return imgs, labels, extents
        m_imgs, m_labels, m_extents = self._fill_misses(miss_idx)
        if not found:  # pure-miss fast path: no assembly copy needed
            self._account(0, len(idx))
            return m_imgs, m_labels, m_extents
        imgs = np.empty((len(idx),) + m_imgs.shape[1:], m_imgs.dtype)
        extents = np.empty((len(idx),) + m_extents.shape[1:], m_extents.dtype)
        labels = np.empty((len(idx),), np.asarray(m_labels).dtype)
        pos_of_miss = iter(range(len(miss_idx)))
        for j, i in enumerate(idx):
            if i in found:
                canvas, extent, label = found[i]
                imgs[j], extents[j], labels[j] = canvas, extent, label
            else:
                k = next(pos_of_miss)
                imgs[j], extents[j], labels[j] = m_imgs[k], m_extents[k], m_labels[k]
        self._account(len(found), len(miss_idx))
        return imgs, labels, extents

    def get_batch_into(self, indices, out_imgs, out_extents):
        """Staging-canvas protocol (see `ImageFolder.get_batch_into`): fill
        caller-owned rows, return labels. Hits memcpy straight from the
        cache; misses decode through the inner dataset and populate it."""
        idx = [int(i) for i in np.asarray(indices)]
        found = self._lookup(idx)
        miss_idx = [i for i in idx if i not in found]
        if not found and hasattr(self.dataset, "get_batch_into"):
            # pure-miss fast path (the steady state whenever the budget is
            # smaller than the dataset): decode straight into the caller's
            # pooled rows — no intermediate batch allocation — and insert
            # copies only of what the cache keeps
            before = getattr(self.dataset, "decode_failures", 0)
            labels = self.dataset.get_batch_into(idx, out_imgs, out_extents)
            if getattr(self.dataset, "decode_failures", 0) == before:
                for j, i in enumerate(idx):
                    self._insert(i, np.array(out_imgs[j]),
                                 np.array(out_extents[j]), labels[j])
            self._account(0, len(idx))
            return labels
        labels = np.empty((len(idx),), np.int32)
        if miss_idx:
            m_imgs, m_labels, m_extents = self._fill_misses(miss_idx)
            pos_of_miss = {i: k for k, i in enumerate(miss_idx)}
        for j, i in enumerate(idx):
            if i in found:
                canvas, extent, label = found[i]
                out_imgs[j], out_extents[j], labels[j] = canvas, extent, label
            else:
                k = pos_of_miss[i]
                out_imgs[j] = m_imgs[k]
                out_extents[j] = m_extents[k]
                labels[j] = m_labels[k]
        self._account(len(found), len(miss_idx))
        return labels

    # -- introspection ------------------------------------------------------
    @property
    def cached_entries(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def cached_bytes(self) -> int:
        with self._lock:
            return self._bytes
