"""Datasets (layer L2). The reference uses `torchvision.datasets.ImageFolder`
(+ CIFAR-10 for the smoke config); equivalents here, torch-free:

- `SyntheticDataset` — class-structured random images, for tests/benches and
  environments with no data mounted (each class = a fixed low-frequency
  pattern + per-sample noise, so contrastive learning has real signal and
  kNN can beat chance; BASELINE config-1 success criterion).
- `CIFAR10` — reads the standard `cifar-10-batches-py` pickle layout from
  disk (no network, no torch).
- `ImageFolder` — class-per-subdirectory JPEG tree, decoded on host (C++
  thread pool or PIL) into fixed-size uint8 staging canvases holding the
  WHOLE image plus a `(valid_h, valid_w, rot)` extent; all randomized
  cropping happens later on device (data/augment.py) over the true image
  area.

All datasets expose the SAME batch protocol:
`get_batch(indices) -> (images [B,H,W,3] uint8, labels int32, extents
[B,3] int32)` where extents is `(valid_h, valid_w, rot)` per sample —
full-canvas for in-memory square datasets, the true staged geometry for
ImageFolder. The host never does float math.
"""

from __future__ import annotations

import os
import pickle
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np


def full_extents(n: int, h: int, w: int) -> np.ndarray:
    """`[n, 3] (valid_h, valid_w, rot)` covering the whole canvas."""
    return np.tile(np.asarray([h, w, 0], np.int32), (n, 1))


class SyntheticDataset:
    """Deterministic clusterable fake data in memory."""

    def __init__(
        self,
        num_samples: int = 2048,
        image_size: int = 32,
        num_classes: int = 10,
        seed: int = 0,
        noise: float = 0.15,
    ):
        rng = np.random.RandomState(seed)
        self.num_classes = num_classes
        self.image_size = image_size
        # low-frequency class prototypes: random 4x4 upsampled to full size.
        # Prototypes come from a FIXED seed so two instances with different
        # `seed`s (train vs val split) sample the same classes
        protos = np.random.RandomState(12345).rand(num_classes, 4, 4, 3)
        reps = image_size // 4
        protos = protos.repeat(reps, axis=1).repeat(reps, axis=2)
        labels = rng.randint(0, num_classes, size=num_samples)
        imgs = protos[labels] + noise * rng.randn(num_samples, image_size, image_size, 3)
        self.images = (np.clip(imgs, 0, 1) * 255).astype(np.uint8)
        self.labels = labels.astype(np.int32)

    def __len__(self):
        return len(self.images)

    def get_batch(self, indices: np.ndarray):
        return (
            self.images[indices],
            self.labels[indices],
            full_extents(len(indices), self.image_size, self.image_size),
        )


class SyntheticTextureDataset:
    """Clusterable fake data that an UNTRAINED network cannot solve.

    `SyntheticDataset`'s one-prototype-per-class design is separable by
    random-init features (epoch-0 kNN ~86% — VERDICT r3 weak #3), so its
    curves cannot distinguish learning from initialization. Here the class
    signal and the dominant pixel variance are split adversarially:

    - class signal: a class-specific high-frequency grayscale 8x8 tile,
      tiled across the image with a random per-sample phase roll (default
      amplitude 0.4 — random-init kNN measured ~6.8% vs 6.25% chance). Stable under the contrastive augmentations (crops keep
      the texture statistics; color jitter/grayscale are channel-wise maps
      that preserve a channel-shared pattern).
    - nuisance (dominates pixel distance): strong per-sample random RGB
      gain/bias (color cast) + brightness offset + pixel noise — exactly
      what the v1/v2 aug stacks randomize away between views.

    Random-init conv features inherit pixel geometry, so their nearest
    neighbors follow the class-independent cast → kNN near chance
    (1/num_classes). Features trained to be augmentation-invariant must
    discard the cast, leaving the texture as the stable cue → kNN well
    above chance. The gap IS the learning signal.

    Class tiles come from a FIXED seed so train/val instances with
    different `seed`s share the same classes (same convention as
    `SyntheticDataset`).
    """

    def __init__(
        self,
        num_samples: int = 16384,
        image_size: int = 32,
        num_classes: int = 16,
        seed: int = 0,
        texture_amp: float = 0.4,
        cast_strength: float = 0.5,
    ):
        """`cast_strength` scales the nuisance color cast: 1.0 = gain
        U[0.4,1.6] — stronger than the jitter augmentation's ±40%, so the
        cast partially SURVIVES augmentation; measured r4: MoCo then learns
        cast-dominated features and class clustering never emerges at
        micro-batch scale (kNN drifts to 4-5%, i.e. below chance). The 0.5
        default = gain U[0.7,1.3], within the jitter's destruction range,
        so the cast is useless for instance discrimination and the texture
        is the only aug-stable cue. Untrained-baseline kNN measured on a
        random-init resnet18: 6.6-7.6% at cast 1.0, 8.3% at cast 0.5
        (chance 6.25%; the predecessor dataset scored 100%)."""
        assert image_size % 8 == 0, "tile period 8 must divide image_size"
        self.num_classes = num_classes
        self.image_size = image_size
        self.seed = seed  # the monitor derives a held-out val seed from it
        # recorded so the monitor's val split can mirror the train
        # distribution exactly (non-default knobs included)
        self.texture_amp = texture_amp
        self.cast_strength = cast_strength
        g = np.random.RandomState(7777)
        tiles = g.rand(num_classes, 8, 8).astype(np.float32)
        tiles -= tiles.mean(axis=(1, 2), keepdims=True)  # zero-mean signal
        # exposed so held-out-split construction is PINNABLE: train/val
        # instances must share these regardless of `seed` (the eval
        # val-split bug r5 fixed scored a probe against a different
        # generator's labels — tests/test_evals.py)
        self.class_tiles = tiles
        rng = np.random.RandomState(seed)
        labels = rng.randint(0, num_classes, size=num_samples)
        reps = image_size // 8
        # f32 throughout: the default 16384-sample build transiently peaks
        # >1 GB in f64, for an output that is quantized to uint8 anyway
        tex = np.tile(tiles[labels], (1, reps, reps))
        # random texture phase per sample: classes must be recognized by the
        # pattern, not by its absolute pixel position
        for i in range(num_samples):
            dy, dx = rng.randint(0, 8, size=2)
            tex[i] = np.roll(tex[i], (dy, dx), axis=(0, 1))
        g, b = 1.2 * cast_strength, 0.5 * cast_strength
        gain = (1.0 - g / 2) + g * rng.rand(num_samples, 1, 1, 3).astype(np.float32)
        imgs = (0.5 + texture_amp * tex[..., None]) * gain  # (N, H, W, 3) f32
        imgs += -b / 2 + b * rng.rand(num_samples, 1, 1, 3).astype(np.float32)
        imgs += 0.04 * rng.randn(
            num_samples, image_size, image_size, 3
        ).astype(np.float32)
        self.images = (np.clip(imgs, 0, 1) * 255).astype(np.uint8)
        self.labels = labels.astype(np.int32)

    def __len__(self):
        return len(self.images)

    def get_batch(self, indices: np.ndarray):
        return (
            self.images[indices],
            self.labels[indices],
            full_extents(len(indices), self.image_size, self.image_size),
        )


class CIFAR10:
    """`cifar-10-batches-py` reader (binary pickle layout, 50k train / 10k test)."""

    def __init__(self, data_dir: str, train: bool = True):
        batch_dir = data_dir
        if os.path.isdir(os.path.join(data_dir, "cifar-10-batches-py")):
            batch_dir = os.path.join(data_dir, "cifar-10-batches-py")
        names = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
        xs, ys = [], []
        for n in names:
            path = os.path.join(batch_dir, n)
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"CIFAR-10 batch {path} not found — place the "
                    "'cifar-10-batches-py' directory under data_dir"
                )
            with open(path, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.extend(d[b"labels"])
        x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        self.images = np.ascontiguousarray(x)
        self.labels = np.asarray(ys, np.int32)
        self.num_classes = 10
        self.image_size = 32

    def __len__(self):
        return len(self.images)

    def get_batch(self, indices: np.ndarray):
        return (
            self.images[indices],
            self.labels[indices],
            full_extents(len(indices), 32, 32),
        )


@dataclass
class _ImageEntry:
    path: str
    label: int


class ImageFolder:
    """Class-per-subdir image tree; decodes the WHOLE image into a fixed
    `[stage_size, 2*stage_size]` landscape uint8 canvas on the host
    (transpose-if-portrait + bilinear fit-resize + edge-replicated padding),
    with a per-image `(valid_h, valid_w, rot)` extent. The on-device
    RandomResizedCrop then samples over the true image area — matching
    torchvision get_params on the original photo (`main_moco.py:≈L232`) —
    instead of a pre-cropped central square."""

    def __init__(
        self,
        root: str,
        stage_size: int = 512,
        num_workers: int = 8,
        backend: str = "auto",  # auto | native | pil
    ):
        from PIL import Image  # lazy: torch-free PIL dependency

        self._Image = Image
        self.stage_size = stage_size
        self.stage_h = stage_size
        self.stage_w = stage_size * 2  # aspect ≤ 2:1 keeps shorter side at full res
        self.image_size = stage_size
        self._native = None
        self._backend = backend
        self._native_workers = num_workers
        # cumulative decode telemetry (read by the train driver every step):
        # failures substitute zero canvases, which poison training silently —
        # the driver meters the rate and aborts past config.decode_abort_rate.
        # Locked: staging workers (ISSUE 3) decode disjoint sub-slices of one
        # batch concurrently, and a lost increment would understate the very
        # failure rate the abort threshold watches.
        self.decode_failures = 0
        self.decode_total = 0
        self._meter_lock = threading.Lock()
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        if not classes:
            raise FileNotFoundError(f"no class subdirectories under {root!r}")
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.num_classes = len(classes)
        self.entries: list[_ImageEntry] = []
        exts = {".jpg", ".jpeg", ".png", ".bmp", ".webp"}
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if os.path.splitext(fname)[1].lower() in exts:
                    self.entries.append(
                        _ImageEntry(os.path.join(cdir, fname), self.class_to_idx[c])
                    )
        self.labels = np.asarray([e.label for e in self.entries], np.int32)
        self._pool = ThreadPoolExecutor(max_workers=num_workers)
        # native decode path only pays off (and only works) for JPEG trees —
        # don't compile/spawn the C++ loader for PNG/BMP/WebP datasets
        has_jpeg = any(
            e.path.lower().endswith((".jpg", ".jpeg")) for e in self.entries
        )
        if self._backend in ("auto", "native") and has_jpeg:
            try:
                from moco_tpu.data.native_loader import NativeStagingLoader

                self._native = NativeStagingLoader(
                    self.stage_h, self.stage_w, self._native_workers
                )
            except (RuntimeError, OSError):
                if self._backend == "native":
                    raise
        elif self._backend == "native" and not has_jpeg:
            raise RuntimeError("backend='native' requires JPEG images")

    def __len__(self):
        return len(self.entries)

    def _load_one(self, idx: int) -> tuple[np.ndarray, np.ndarray]:
        img = self._Image.open(self.entries[idx].path).convert("RGB")
        arr = np.asarray(img, np.uint8)
        rot = 0
        if arr.shape[0] > arr.shape[1]:  # portrait: stage transposed
            arr = np.ascontiguousarray(np.swapaxes(arr, 0, 1))
            rot = 1
        h, w = arr.shape[:2]
        # fit-DOWNSCALE only (scale capped at 1, matching the native path):
        # an image that already fits the canvas stages at ORIGINAL resolution
        # so the on-device RandomResizedCrop samples original pixels
        # (torchvision-on-the-photo semantics; VERDICT r2 missing #3)
        scale = min(1.0, self.stage_h / h, self.stage_w / w)
        # int(x + 0.5), not round(): Python rounds half-to-even, the native
        # path uses lround (half away from zero) — sizes must agree exactly
        nh = min(max(1, int(h * scale + 0.5)), self.stage_h)
        nw = min(max(1, int(w * scale + 0.5)), self.stage_w)
        if (nh, nw) == (h, w):
            resized = arr  # pixel-exact paste
        else:
            resized = np.asarray(
                self._Image.fromarray(arr).resize((nw, nh), self._Image.BILINEAR),
                np.uint8,
            )
        canvas = np.empty((self.stage_h, self.stage_w, 3), np.uint8)
        canvas[:nh, :nw] = resized
        # edge-replicate padding: crop taps at the content boundary read
        # clamped pixels (PIL semantics), never black
        canvas[:nh, nw:] = resized[:, -1:]
        canvas[nh:, :] = canvas[nh - 1 : nh, :]
        return canvas, np.asarray([nh, nw, rot], np.int32)

    def _load_one_tolerant(self, idx: int):
        """`_load_one` that degrades a per-image decode failure into a zero
        canvas + counted failure instead of killing the epoch — one corrupt
        file in a million-image tree must not end a multi-day run; the
        driver-level failure-rate threshold (`decode_abort_rate`) catches
        the systemic case."""
        try:
            canvas, extent = self._load_one(idx)
            return canvas, extent, 0
        except (OSError, ValueError) as e:
            from moco_tpu.utils.logging import log_event

            log_event(
                "data",
                f"decode failed for {self.entries[idx].path!r} "
                f"({type(e).__name__}: {e}); substituting a zero canvas",
            )
            canvas = np.zeros((self.stage_h, self.stage_w, 3), np.uint8)
            extent = np.asarray([self.stage_h, self.stage_w, 0], np.int32)
            return canvas, extent, 1

    def get_batch(self, indices: np.ndarray):
        out = np.empty(
            (len(indices), self.stage_h, self.stage_w, 3), np.uint8
        )
        extents = np.empty((len(indices), 3), np.int32)
        labels = self.get_batch_into(indices, out, extents)
        return out, labels, extents

    def get_batch_into(self, indices, out_imgs: np.ndarray,
                       out_extents: np.ndarray) -> np.ndarray:
        """Decode `indices` INTO caller-owned rows (ISSUE 3 staging-canvas
        protocol); returns the labels. `out_imgs` is `[n, stage_h, stage_w,
        3] uint8`, `out_extents` `[n, 3] int32` — typically disjoint row
        ranges of a pooled staging canvas, so the native path's decode
        threads write the final bytes in place (zero assembly copies).
        Thread-safe: concurrent calls for disjoint rows share the native
        pool and the decode meters."""
        idx = [int(i) for i in indices]
        paths = [self.entries[i].path for i in idx]
        with self._meter_lock:
            self.decode_total += len(idx)
        if self._native is not None and all(
            p.lower().endswith((".jpg", ".jpeg")) for p in paths
        ):
            _, _, failures = self._native.load_batch(
                paths, out=out_imgs, extents=out_extents
            )
            if failures == 0:
                return self.labels[np.asarray(idx)]
            # native failures: retry the whole batch via PIL — it decodes
            # some streams libjpeg rejects, and pinpoints the bad file(s)
        staged = list(self._pool.map(self._load_one_tolerant, idx))
        failed = sum(s[2] for s in staged)
        if failed:
            with self._meter_lock:
                self.decode_failures += failed
        for j, s in enumerate(staged):
            out_imgs[j] = s[0]
            out_extents[j] = s[1]
        return self.labels[np.asarray(idx)]


def build_dataset(
    name: str,
    data_dir: str = "",
    image_size: int = 32,
    stage_size: int = 0,
    num_workers: int = 0,
    **kw,
):
    """`stage_size`/`num_workers` are the ImageFolder staging knobs (the
    reference's `-j` and the staging-canvas resolution); 0 = class default.
    In-memory datasets (synthetic/CIFAR) have no staging and ignore both."""
    if name == "synthetic":
        return SyntheticDataset(image_size=image_size, **kw)
    if name == "synthetic_texture":
        return SyntheticTextureDataset(image_size=image_size, **kw)
    if name == "cifar10":
        return CIFAR10(data_dir, **kw)
    if name == "imagefolder":
        sub = os.path.join(data_dir, "train")
        root = sub if os.path.isdir(sub) else data_dir
        if stage_size:
            kw["stage_size"] = stage_size
        if num_workers:
            kw["num_workers"] = num_workers
        return ImageFolder(root, **kw)
    raise ValueError(f"unknown dataset {name!r}")
