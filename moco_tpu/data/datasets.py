"""Datasets (layer L2). The reference uses `torchvision.datasets.ImageFolder`
(+ CIFAR-10 for the smoke config); equivalents here, torch-free:

- `SyntheticDataset` — class-structured random images, for tests/benches and
  environments with no data mounted (each class = a fixed low-frequency
  pattern + per-sample noise, so contrastive learning has real signal and
  kNN can beat chance; BASELINE config-1 success criterion).
- `CIFAR10` — reads the standard `cifar-10-batches-py` pickle layout from
  disk (no network, no torch).
- `ImageFolder` — class-per-subdirectory JPEG tree, PIL-decoded on host by a
  thread pool into fixed-size uint8 staging arrays; all randomized cropping
  happens later on device (data/augment.py).

All datasets expose `images_u8()`-style batched access returning
`[B, H, W, 3] uint8` + int labels; the host never does float math.
"""

from __future__ import annotations

import os
import pickle
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np


class SyntheticDataset:
    """Deterministic clusterable fake data in memory."""

    def __init__(
        self,
        num_samples: int = 2048,
        image_size: int = 32,
        num_classes: int = 10,
        seed: int = 0,
        noise: float = 0.15,
    ):
        rng = np.random.RandomState(seed)
        self.num_classes = num_classes
        self.image_size = image_size
        # low-frequency class prototypes: random 4x4 upsampled to full size.
        # Prototypes come from a FIXED seed so two instances with different
        # `seed`s (train vs val split) sample the same classes
        protos = np.random.RandomState(12345).rand(num_classes, 4, 4, 3)
        reps = image_size // 4
        protos = protos.repeat(reps, axis=1).repeat(reps, axis=2)
        labels = rng.randint(0, num_classes, size=num_samples)
        imgs = protos[labels] + noise * rng.randn(num_samples, image_size, image_size, 3)
        self.images = (np.clip(imgs, 0, 1) * 255).astype(np.uint8)
        self.labels = labels.astype(np.int32)

    def __len__(self):
        return len(self.images)

    def get_batch(self, indices: np.ndarray):
        return self.images[indices], self.labels[indices]


class CIFAR10:
    """`cifar-10-batches-py` reader (binary pickle layout, 50k train / 10k test)."""

    def __init__(self, data_dir: str, train: bool = True):
        batch_dir = data_dir
        if os.path.isdir(os.path.join(data_dir, "cifar-10-batches-py")):
            batch_dir = os.path.join(data_dir, "cifar-10-batches-py")
        names = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
        xs, ys = [], []
        for n in names:
            path = os.path.join(batch_dir, n)
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"CIFAR-10 batch {path} not found — place the "
                    "'cifar-10-batches-py' directory under data_dir"
                )
            with open(path, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.extend(d[b"labels"])
        x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        self.images = np.ascontiguousarray(x)
        self.labels = np.asarray(ys, np.int32)
        self.num_classes = 10
        self.image_size = 32

    def __len__(self):
        return len(self.images)

    def get_batch(self, indices: np.ndarray):
        return self.images[indices], self.labels[indices]


@dataclass
class _ImageEntry:
    path: str
    label: int


class ImageFolder:
    """Class-per-subdir image tree; decodes to a fixed `stage_size` square
    uint8 staging array on the host (shorter-side resize + center crop —
    the final random crop happens on device with full scale range)."""

    def __init__(
        self,
        root: str,
        stage_size: int = 256,
        num_workers: int = 8,
        backend: str = "auto",  # auto | native | pil
    ):
        from PIL import Image  # lazy: torch-free PIL dependency

        self._Image = Image
        self.stage_size = stage_size
        self.image_size = stage_size
        self._native = None
        self._backend = backend
        self._native_workers = num_workers
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        if not classes:
            raise FileNotFoundError(f"no class subdirectories under {root!r}")
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.num_classes = len(classes)
        self.entries: list[_ImageEntry] = []
        exts = {".jpg", ".jpeg", ".png", ".bmp", ".webp"}
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if os.path.splitext(fname)[1].lower() in exts:
                    self.entries.append(
                        _ImageEntry(os.path.join(cdir, fname), self.class_to_idx[c])
                    )
        self.labels = np.asarray([e.label for e in self.entries], np.int32)
        self._pool = ThreadPoolExecutor(max_workers=num_workers)
        # native decode path only pays off (and only works) for JPEG trees —
        # don't compile/spawn the C++ loader for PNG/BMP/WebP datasets
        has_jpeg = any(
            e.path.lower().endswith((".jpg", ".jpeg")) for e in self.entries
        )
        if self._backend in ("auto", "native") and has_jpeg:
            try:
                from moco_tpu.data.native_loader import NativeStagingLoader

                self._native = NativeStagingLoader(stage_size, self._native_workers)
            except (RuntimeError, OSError):
                if self._backend == "native":
                    raise
        elif self._backend == "native" and not has_jpeg:
            raise RuntimeError("backend='native' requires JPEG images")

    def __len__(self):
        return len(self.entries)

    def _load_one(self, idx: int) -> np.ndarray:
        img = self._Image.open(self.entries[idx].path).convert("RGB")
        w, h = img.size
        s = self.stage_size
        scale = s / min(w, h)
        img = img.resize((max(s, round(w * scale)), max(s, round(h * scale))))
        w, h = img.size
        left, top = (w - s) // 2, (h - s) // 2
        img = img.crop((left, top, left + s, top + s))
        return np.asarray(img, np.uint8)

    def get_batch(self, indices: np.ndarray):
        idx = [int(i) for i in indices]
        paths = [self.entries[i].path for i in idx]
        if self._native is not None and all(
            p.lower().endswith((".jpg", ".jpeg")) for p in paths
        ):
            imgs, failures = self._native.load_batch(paths)
            if failures == 0:
                return imgs, self.labels[indices]
            # corrupt files: fall through to PIL for a precise error surface
        imgs = list(self._pool.map(self._load_one, idx))
        return np.stack(imgs), self.labels[indices]


def build_dataset(name: str, data_dir: str = "", image_size: int = 32, **kw):
    if name == "synthetic":
        return SyntheticDataset(image_size=image_size, **kw)
    if name == "cifar10":
        return CIFAR10(data_dir, **kw)
    if name == "imagefolder":
        sub = os.path.join(data_dir, "train")
        root = sub if os.path.isdir(sub) else data_dir
        return ImageFolder(root, **kw)
    raise ValueError(f"unknown dataset {name!r}")
