"""Decode worker: the numpy half of one staging server (ISSUE 14).

Runs as a SUBPROCESS of `tools/staging_server.py` (never imported by the
stdlib control plane — the supervisor half must outlive a wedged decode
runtime, so the split is a process boundary, not a module boundary):
binds the DATA port, builds the dataset once (ImageFolder's native
chunked C++ pool, a `--prestage` mmap, synthetic — whatever the argv
names), and serves the frame protocol: each client connection is one
thread running recv(shard) → decode into a reused scratch →
send(data).

Bit-identity is by construction: the client ships the exact dataset
indices it would have decoded locally, and the worker runs the SAME
dataset code over them — the bytes that come back are the bytes
in-process staging would have produced.

Chaos (`MOCO_TPU_CHAOS` on the server process): `kill_at_shard=N`
self-SIGKILLs before answering the N-th served shard (fire-once across
supervisor relaunches via MOCO_TPU_CHAOS_STATE); `stall_at_shard=N,
stall_ms=M` holds one answer for M ms. Injected loader faults
(`loader_error_at_batch`) surface as retryable `error` frames and
re-enter the client's PR 1 retry budget.

Telemetry: a `kind:"input_server"` stats record (shard latency p50/p95,
bytes streamed, credit stalls, cache-hit rate) lands in the server's
events.jsonl on a time cadence, `serve_shard` trace spans continue the
client coordinator's `stage_batch` span ids across the process boundary,
and every `pong` carries the live stats snapshot (the supervisor's
/stats source).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time

import numpy as np

from moco_tpu.data.service import protocol
from moco_tpu.data.stats import _percentile
from moco_tpu.resilience.chaos import active_chaos
from moco_tpu.resilience.exitcodes import (
    EXIT_CONFIG_ERROR,
    EXIT_OK,
    EXIT_STAGING_BIND,
)
from moco_tpu.telemetry.trace import Tracer, null_tracer, parse_parent
from moco_tpu.utils.logging import log_event

# rolling shard-latency window (sorted under the stats lock at snapshot
# time — same bound/discipline as data/stats.py)
_LATENCY_WINDOW = 4096


class WorkerStats:
    """Cumulative, thread-safe counters for one worker process. The
    snapshot is the wire/stats schema: consumers (pong answers, the
    periodic `input_server` record, telemetry_report's per-server rows,
    obsd) all read the same dict."""

    def __init__(self, server_id: int):
        self._lock = threading.Lock()
        self._created = time.perf_counter()
        self.server_id = server_id
        self.shards = 0
        self.bytes_streamed = 0
        self.errors = 0
        self._shard_s: list[float] = []
        self._decode_s = 0.0
        self._credit_stall_s = 0.0
        self.connections = 0
        self.connections_peak = 0

    def note_shard(self, decode_s: float, total_s: float,
                   nbytes: int) -> None:
        with self._lock:
            self.shards += 1
            self.bytes_streamed += int(nbytes)
            self._decode_s += float(decode_s)
            self._shard_s.append(float(total_s))
            if len(self._shard_s) > 2 * _LATENCY_WINDOW:
                del self._shard_s[:-_LATENCY_WINDOW]

    def note_error(self) -> None:
        with self._lock:
            self.errors += 1

    def note_credit_stall(self, seconds: float) -> None:
        """Server-side credit stall: a connection sat idle between
        answering one shard and receiving the next request — the CLIENT
        held the credit (device-bound pipeline, healthy). Near-zero
        stalls with saturated decode mean the train host is the starved
        side (its own client-side counter is the SLO input)."""
        with self._lock:
            self._credit_stall_s += float(seconds)

    def note_connection(self, delta: int) -> None:
        with self._lock:
            self.connections += delta
            # peak, not the live gauge: the FINAL stats snapshot lands
            # after clients disconnected (connections back at 0), and
            # the report needs the concurrency credit_stall_s actually
            # accumulated across to normalize idle-for-credit
            self.connections_peak = max(self.connections_peak,
                                        self.connections)

    def snapshot(self, dataset=None) -> dict:
        with self._lock:
            wall = max(time.perf_counter() - self._created, 1e-9)
            ordered = sorted(self._shard_s)
            snap = {
                "server_id": self.server_id,
                "shards": self.shards,
                "streamed_mb": round(self.bytes_streamed / 2**20, 1),
                "shard_s_p50": round(_percentile(ordered, 50), 6),
                "shard_s_p95": round(_percentile(ordered, 95), 6),
                "decode_s": round(self._decode_s, 3),
                "credit_stall_s": round(self._credit_stall_s, 3),
                "wall_s": round(wall, 3),
                "errors": self.errors,
                "connections": self.connections,
                "connections_peak": self.connections_peak,
            }
        hits = getattr(dataset, "hits", None)
        misses = getattr(dataset, "misses", None)
        if isinstance(hits, int) and isinstance(misses, int) \
                and hits + misses:
            snap["cache_hit_rate"] = round(hits / (hits + misses), 4)
        # server-side zero-canvas substitutions: the train host's dataset
        # is None under input_service, so its decode_abort_rate guard
        # cannot see these — the stats record/pong is the ONLY channel
        # that makes silent data poisoning visible to an operator
        fails = getattr(dataset, "decode_failures", None)
        total = getattr(dataset, "decode_total", None)
        if isinstance(fails, int) and isinstance(total, int) and total:
            snap["decode_failures"] = fails
            snap["decode_total"] = total
        return snap


class ProbeDecodeError(RuntimeError):
    """The row-0 probe decode at construction hit a read fault. A
    DISTINCT type on purpose: main() maps construction OSErrors to
    EXIT_STAGING_BIND (fatal — the supervisor abandons, reschedule
    beats racing the socket), but a flaky-storage EIO on one probe read
    is the transient class the retry machinery survives everywhere else
    — it must exit as a plain restartable crash, not a give_up."""


class DecodeWorker:
    """The data-port server. `serve_forever()` blocks; `stop()` (any
    thread / signal handler) drains: the listener closes, in-flight
    shards finish, later requests answer `error: shutdown` (retryable —
    the client re-lands them on another server)."""

    def __init__(self, dataset, host: str, port: int, *,
                 server_id: int = 0, telemetry_dir: str = "",
                 stats_every_secs: float = 10.0, tracer=None,
                 prestaged: bool = False):
        self.dataset = dataset
        self.server_id = server_id
        self.telemetry_dir = telemetry_dir
        self.stats_every_secs = float(stats_every_secs)
        self.stats = WorkerStats(server_id)
        self.prestaged = prestaged
        # null-object, never None: span call sites stay branch-free (the
        # Prefetcher pattern) and lint R12 keeps its with-statement shape
        self._tracer = tracer if tracer is not None else null_tracer()
        self._stop = threading.Event()
        self._conn_threads: list[threading.Thread] = []
        self._shard_count = 0          # served-shard chaos counter
        self._count_lock = threading.Lock()
        self._last_stats_emit = 0.0
        self._events_path = (
            os.path.join(telemetry_dir, "events.jsonl")
            if telemetry_dir else ""
        )
        # probe one row for the wire meta (also warms the native pool /
        # faults the mmap header pages before the first real shard)
        try:
            imgs, labels, _extents = dataset.get_batch(np.asarray([0]))
        except OSError as e:
            raise ProbeDecodeError(
                f"probe decode of row 0 failed: {type(e).__name__}: {e}"
            ) from e
        self._img_shape = tuple(int(d) for d in imgs.shape[1:])
        self._img_dtype = str(imgs.dtype)
        self._label_dtype = str(np.asarray(labels).dtype)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))   # OSError -> EXIT_STAGING_BIND in main
        self._sock.listen(32)
        self.host, self.port = self._sock.getsockname()[:2]

    # -- wire meta -----------------------------------------------------------
    def _meta(self) -> dict:
        return {
            "op": protocol.OP_META,
            "proto": protocol.PROTO_VERSION,
            "server_id": self.server_id,
            "n": len(self.dataset),
            "img_shape": list(self._img_shape),
            "img_dtype": self._img_dtype,
            "label_dtype": self._label_dtype,
            "prestaged": self.prestaged,
        }

    # -- serving -------------------------------------------------------------
    def serve_forever(self) -> None:
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed under us: stop()
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="staging-conn")
            t.start()
            self._conn_threads.append(t)
            self._conn_threads = [t for t in self._conn_threads
                                  if t.is_alive()]
        self._sock.close()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        deadline = time.monotonic() + timeout_s
        for t in self._conn_threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.05))
        self._emit_stats(final=True)

    def _serve_conn(self, conn: socket.socket) -> None:
        self.stats.note_connection(+1)
        scratch: dict = {}  # per-connection reused decode buffers
        try:
            conn.settimeout(30.0)
            header, _ = protocol.recv_frame(conn)
            if header.get("op") != protocol.OP_HELLO:
                protocol.send_frame(conn, {
                    "op": protocol.OP_ERROR,
                    "code": protocol.ERR_PROTOCOL,
                    "detail": f"expected hello, got {header.get('op')!r}",
                    "retryable": False,
                })
                return
            protocol.send_frame(conn, self._meta())
            # t_wait0 marks when we LAST finished answering: it survives
            # the socket-timeout retries below so a 95 s client pause
            # books 95 s of credit stall, not just the tail < timeout
            t_wait0 = time.perf_counter()
            while not self._stop.is_set():
                try:
                    header, payload = protocol.recv_frame(conn)
                except socket.timeout:
                    continue  # idle probe/client connection: keep it
                # idle gap between requests on a live client connection =
                # the client held the credit (we were NOT the bottleneck)
                if header.get("op") == protocol.OP_SHARD:
                    self.stats.note_credit_stall(
                        time.perf_counter() - t_wait0)
                    self._serve_shard(conn, header, payload, scratch)
                elif header.get("op") == protocol.OP_PING:
                    protocol.send_frame(conn, {
                        "op": protocol.OP_PONG,
                        "stats": self.stats.snapshot(self.dataset),
                    })
                elif header.get("op") == protocol.OP_BYE:
                    return
                else:
                    protocol.send_frame(conn, {
                        "op": protocol.OP_ERROR,
                        "code": protocol.ERR_PROTOCOL,
                        "detail": f"unknown op {header.get('op')!r}",
                        "retryable": False,
                    })
                    return
                t_wait0 = time.perf_counter()  # next wait starts now
        except (ConnectionError, protocol.FrameError, socket.timeout,
                OSError):
            pass  # client went away: its retry machinery owns the story
        finally:
            self.stats.note_connection(-1)
            try:
                conn.close()
            except OSError:
                pass

    def _serve_shard(self, conn, header, payload, scratch) -> None:
        t0 = time.perf_counter()
        with self._count_lock:
            self._shard_count += 1
            n_shard = self._shard_count
        plan = active_chaos()
        try:
            # request parsing INSIDE the try: a malformed header field
            # or a payload that is not a whole number of <i8 indices
            # must answer an error frame, not kill this connection
            # thread with an unclassified traceback
            batch = int(header.get("batch", -1))
            lo = int(header.get("lo", 0))
            hi = int(header.get("hi", 0))
            if len(payload) % 8:
                raise protocol.RemoteShardError(
                    protocol.ERR_BAD_REQUEST,
                    f"shard payload of {len(payload)} bytes is not a "
                    "whole number of <i8 indices",
                    False,
                )
            indices = np.frombuffer(payload, dtype="<i8")
            rows = hi - lo
            if rows <= 0 or len(indices) != rows:
                raise protocol.RemoteShardError(
                    protocol.ERR_BAD_REQUEST,
                    f"shard rows [{lo}:{hi}) vs {len(indices)} indices",
                    False,
                )
            if len(self.dataset) and (
                    int(indices.max(initial=0)) >= len(self.dataset)
                    or int(indices.min(initial=0)) < 0):
                # negative indices would WRAP via numpy fancy indexing —
                # silently-wrong rows, the exact failure bad_request is for
                raise protocol.RemoteShardError(
                    protocol.ERR_BAD_REQUEST,
                    f"index range [{int(indices.min())}, "
                    f"{int(indices.max())}] outside dataset length "
                    f"{len(self.dataset)} — client/server dataset drift",
                    False,
                )
            if self._stop.is_set():
                raise protocol.RemoteShardError(
                    protocol.ERR_SHUTDOWN, "server draining", True)
            imgs, extents, labels, decode_s = self._decode(
                batch, indices, rows, scratch, header)
        except protocol.RemoteShardError as e:
            self.stats.note_error()
            protocol.send_frame(conn, {
                "op": protocol.OP_ERROR, "code": e.code,
                "detail": e.detail, "retryable": e.retryable,
            })
            return
        except OSError as e:
            # transient storage/read fault (incl. chaos TransientDataError):
            # the client's retry-with-backoff budget owns it — PR 1 contract
            self.stats.note_error()
            protocol.send_frame(conn, {
                "op": protocol.OP_ERROR, "code": protocol.ERR_TRANSIENT,
                "detail": f"{type(e).__name__}: {e}", "retryable": True,
            })
            return
        except (ValueError, TypeError, KeyError, IndexError) as e:
            # garbage request fields or a deterministic decode fault:
            # non-retryable (the same request would fail on every
            # server) — surfaced to the client instead of retried
            # blindly round after round
            self.stats.note_error()
            protocol.send_frame(conn, {
                "op": protocol.OP_ERROR, "code": protocol.ERR_BAD_REQUEST,
                "detail": f"{type(e).__name__}: {e}", "retryable": False,
            })
            return
        if plan is not None:
            # drills fire between decode and answer: the client observes a
            # stalled (then answered) or torn-mid-request connection
            plan.maybe_stall_shard(n_shard)
            plan.maybe_kill_shard(n_shard)
        # multi-chunk payload: the arrays stream straight from the
        # decode scratch — no imgs+extents+labels concatenation copy on
        # the serving hot path (a TPU-shape shard is ~256 MiB)
        nbytes = imgs.nbytes + extents.nbytes + labels.nbytes
        protocol.send_frame(conn, {
            "op": protocol.OP_DATA, "batch": batch, "lo": lo, "hi": hi,
            "shapes": {"imgs": list(imgs.shape),
                       "extents": list(extents.shape),
                       "labels": list(labels.shape)},
            "dtypes": {"imgs": str(imgs.dtype),
                       "extents": str(extents.dtype),
                       "labels": str(labels.dtype)},
        }, (imgs, extents, labels))
        self.stats.note_shard(decode_s, time.perf_counter() - t0,
                              nbytes)
        self._maybe_emit_stats()

    def _decode(self, batch, indices, rows, scratch, header):
        """Decode `indices` into the connection's reused scratch rows.
        Returns (imgs, extents, labels, decode_seconds)."""
        plan = active_chaos()
        if plan is not None:
            plan.maybe_loader_error(batch)
        t0 = time.perf_counter()
        with self._tracer.span("serve_shard", cat="input",
                               parent=parse_parent(header.get("trace")),
                               batch=batch, rows=rows,
                               server=self.server_id):
            if ("imgs" not in scratch
                    or scratch["imgs"].shape[0] < rows):
                scratch["imgs"] = np.empty(
                    (rows,) + self._img_shape, np.dtype(self._img_dtype))
                scratch["extents"] = np.empty((rows, 3), np.int32)
            imgs = scratch["imgs"][:rows]
            extents = scratch["extents"][:rows]
            if hasattr(self.dataset, "get_batch_into"):
                labels = self.dataset.get_batch_into(indices, imgs,
                                                     extents)
            else:
                b_imgs, labels, b_extents = self.dataset.get_batch(
                    indices)
                imgs[:] = b_imgs
                extents[:] = b_extents
        labels = np.ascontiguousarray(np.asarray(labels))
        return imgs, extents, labels, time.perf_counter() - t0

    # -- telemetry -----------------------------------------------------------
    def _maybe_emit_stats(self) -> None:
        now = time.monotonic()
        if now - self._last_stats_emit < self.stats_every_secs:
            return
        self._last_stats_emit = now
        self._emit_stats()

    def _emit_stats(self, final: bool = False) -> None:
        if not self._events_path:
            return
        record = {
            "v": 1,
            "t": round(time.time(), 3),
            "kind": "input_server", "event": "stats", "final": final,
            # per-life marker: a relaunch changes the pid, so the report
            # detects counter resets exactly instead of heuristically
            "pid": os.getpid(),
        }
        if self._tracer.run_id:
            record["run_id"] = self._tracer.run_id
        record.update(self.stats.snapshot(self.dataset))
        try:
            protocol.append_jsonl(self._events_path, record)
        except OSError as e:
            log_event("input_server",
                      f"stats record write failed (non-fatal): {e}")


def build_worker_dataset(args) -> tuple[object, bool]:
    """(dataset, prestaged?) from the worker argv. `--prestage` wins: a
    hit epoch is then a pure mmap gather. `--cache-mb` wraps a decoding
    dataset in the decode-once canvas LRU, so epochs >= 2 serve at
    memcpy speed even without a prestage."""
    from moco_tpu.data.canvas_cache import CachedDataset
    from moco_tpu.data.datasets import build_dataset
    from moco_tpu.data.service.prestage import PrestagedDataset

    if args.prestage:
        return PrestagedDataset(args.prestage), True
    kw = {}
    if args.dataset.startswith("synthetic"):
        kw["num_samples"] = args.num_samples
        kw["seed"] = args.seed
    dataset = build_dataset(
        args.dataset, data_dir=args.data_dir, image_size=args.image_size,
        stage_size=args.stage_size, num_workers=args.loader_workers, **kw
    )
    if args.cache_mb:
        dataset = CachedDataset(dataset, args.cache_mb)
    return dataset, False


def add_dataset_flags(parser: argparse.ArgumentParser) -> None:
    """Dataset/decode argv shared verbatim by tools/staging_server.py
    (which forwards them here) — one flag surface, no drift."""
    parser.add_argument("--dataset", default="synthetic")
    parser.add_argument("--data-dir", default="")
    parser.add_argument("--image-size", type=int, default=32)
    parser.add_argument("--stage-size", type=int, default=0)
    parser.add_argument("--loader-workers", type=int, default=8)
    parser.add_argument("--num-samples", type=int, default=2048,
                        help="synthetic datasets only")
    parser.add_argument("--seed", type=int, default=0,
                        help="synthetic datasets only")
    parser.add_argument("--prestage", default="",
                        help="serve this pre-staged epoch cache instead "
                             "of decoding (tools/prestage.py output)")
    parser.add_argument("--cache-mb", type=int, default=0,
                        help="decode-once canvas cache budget (MiB)")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    add_dataset_flags(parser)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--server-id", type=int, default=0)
    parser.add_argument("--telemetry-dir", default="")
    parser.add_argument("--stats-every-secs", type=float, default=10.0)
    parser.add_argument("--trace-mode", default="off",
                        choices=["off", "steps", "full"])
    args = parser.parse_args(argv)

    try:
        dataset, prestaged = build_worker_dataset(args)
    except (ValueError, OSError) as e:
        # OSError, not just FileNotFoundError: --data-dir at a file
        # (NotADirectoryError) or unreadable (PermissionError) is the
        # same config class — without the exit code the supervisor
        # relaunch-loops a misconfigured worker through its whole budget
        log_event("input_server", f"cannot build dataset: {e}")
        return EXIT_CONFIG_ERROR

    tracer = None
    if args.telemetry_dir:
        tracer = Tracer(args.telemetry_dir, args.trace_mode,
                        proc=f"staging{args.server_id}")
    try:
        worker = DecodeWorker(
            dataset, args.host, args.port, server_id=args.server_id,
            telemetry_dir=args.telemetry_dir,
            stats_every_secs=args.stats_every_secs, tracer=tracer,
            prestaged=prestaged,
        )
    except ProbeDecodeError as e:
        # transient-class read fault, NOT a bind: exit as a plain crash
        # so the supervisor restarts within its budget instead of the
        # fatal staging_bind give_up
        log_event("input_server", str(e))
        return 1
    except OSError as e:
        log_event("input_server",
                  f"cannot bind {args.host}:{args.port}: {e}")
        return EXIT_STAGING_BIND

    import signal as _signal

    def _drain(signum, frame):
        worker.stop()

    _signal.signal(_signal.SIGTERM, _drain)
    log_event(
        "input_server",
        f"serving shards on {worker.host}:{worker.port} "
        f"(server {args.server_id}, {len(dataset)} samples, "
        f"{'prestaged' if prestaged else args.dataset})",
    )
    worker.serve_forever()
    if tracer is not None:
        tracer.close()
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
