"""moco_tpu.data.service — the disaggregated input service (ISSUE 14).

    protocol.py   length-prefixed frame protocol + probe ping (stdlib)
    worker.py     decode worker subprocess: data port, numpy + native
                  chunked pool, chaos hooks, per-server stats/spans
    server.py     stdlib supervisor half: health HTTP endpoint, worker
                  lifecycle (probe / staleness kill / budgeted restart)
    client.py     ServiceClient — Prefetcher's drop-in sibling on the
                  train host (bit-identical staging over sockets)
    prestage.py   mmap-able pre-staged epoch cache (decode-once format)
    fleet.py      local N-server pool helper (tests, bench, drills)

LAZY (PEP 562, the serve/telemetry __init__ pattern): the control plane
(`server.py`, `tools/staging_server.py`) is stdlib-only by contract
(mocolint R11 `staging-server-stdlib-only` walks ancestor __init__s), so
nothing here may eagerly import the numpy/jax halves."""

from __future__ import annotations

import importlib

_EXPORTS = {
    "FrameError": "protocol",
    "RemoteShardError": "protocol",
    "parse_endpoints": "protocol",
    "ServiceClient": "client",
    "ServiceConfigError": "client",
    "service_epoch_loader": "client",
    "PrestageError": "prestage",
    "PrestagedDataset": "prestage",
    "write_prestage": "prestage",
    "DecodeWorker": "worker",
    "StagingServer": "server",
    "LocalServerPool": "fleet",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        submodule = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    value = getattr(
        importlib.import_module(f"{__name__}.{submodule}"), name
    )
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
