"""Pre-staged epoch cache: decode-once, mmap-served canvases (ISSUE 14).

The staged canvas is a pure deterministic function of the file bytes
(every randomized transform runs ON DEVICE — the canvas_cache.py
argument), so the degenerate cache-everything case is to decode the
WHOLE dataset once, offline, into a packed fixed-shape memmap that every
epoch of every run on every host then serves at memcpy speed:

    <root>/
        canvases.u8     [N, H, W, 3] uint8, C-order     (np.memmap)
        extents.i32     [N, 3] int32 (valid_h, valid_w, rot)
        labels.i32      [N] int32
        meta.json       geometry + counts + fingerprint — written LAST
                        (atomic rename), so its presence IS the
                        completeness marker (the integrity-manifest
                        convention: a killed writer leaves no meta, and
                        a loader refuses the directory loudly)

`PrestagedDataset` speaks the repo's standard batch protocol
(`get_batch` / `get_batch_into` / `labels` / `__len__`), so it plugs
into BOTH consumers unchanged: the in-process `Prefetcher` (point
`--input-prestaged` at the root) and the staging server's decode worker
(`tools/staging_server.py --prestage`). Rows are stored in DATASET INDEX
order — not permutation order — so ONE prestage serves every epoch,
every `skip_batches` fast-forward and every NaN-rollback data-window
advance: an epoch is just row gathers against the mmap.

Bit-identity: a prestaged batch equals the freshly-decoded batch exactly
(test-enforced) because the bytes ARE the decode output, copied once.
"""

from __future__ import annotations

import json
import os

import numpy as np

META_FILENAME = "meta.json"
CANVASES_FILENAME = "canvases.u8"
EXTENTS_FILENAME = "extents.i32"
LABELS_FILENAME = "labels.i32"

FORMAT_VERSION = 1


class PrestageError(ValueError):
    """The directory is not a complete, consistent prestage (missing
    meta, truncated payload, geometry mismatch). Deliberately loud: a
    half-written prestage silently decoded as zeros would poison a run
    the way the decode-failure meter exists to prevent."""


def _paths(root: str) -> dict:
    return {name: os.path.join(root, fname) for name, fname in (
        ("meta", META_FILENAME), ("canvases", CANVASES_FILENAME),
        ("extents", EXTENTS_FILENAME), ("labels", LABELS_FILENAME),
    )}


def write_prestage(dataset, root: str, *, chunk: int = 64,
                   progress=None) -> dict:
    """Decode `dataset` (standard batch protocol) into a prestage at
    `root`. Decodes in `chunk`-row slices straight into the memmap —
    `get_batch_into` when the dataset supports it (the native C++ path
    then writes the final bytes in place), else `get_batch` + copy.
    Returns the meta dict. `progress(done, total)` is an optional
    callback (the CLI's progress line).

    A decode FAILURE anywhere aborts the write: a prestage is a
    whole-cluster artifact consumed for months — one zero canvas frozen
    into it would out-poison any runtime blip (`decode_abort_rate`
    guards runtime decode; the offline writer holds the stricter line).
    """
    n = len(dataset)
    if n == 0:
        raise PrestageError("refusing to prestage an empty dataset")
    probe, _labels, _extents = dataset.get_batch(np.asarray([0]))
    img_shape = tuple(int(d) for d in probe.shape[1:])
    if probe.dtype != np.uint8:
        raise PrestageError(
            f"prestage expects uint8 canvases, got {probe.dtype}"
        )
    os.makedirs(root, exist_ok=True)
    paths = _paths(root)
    if os.path.exists(paths["meta"]):
        raise PrestageError(
            f"{root!r} already holds a complete prestage; remove it "
            "first (never silently overwrite a whole-cluster artifact)"
        )
    canvases = np.lib.format.open_memmap(
        paths["canvases"], mode="w+", dtype=np.uint8,
        shape=(n,) + img_shape,
    )
    extents = np.lib.format.open_memmap(
        paths["extents"], mode="w+", dtype=np.int32, shape=(n, 3),
    )
    labels = np.lib.format.open_memmap(
        paths["labels"], mode="w+", dtype=np.int32, shape=(n,),
    )
    fail_before = getattr(dataset, "decode_failures", 0)
    into = hasattr(dataset, "get_batch_into")
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        idx = np.arange(lo, hi)
        if into:
            labels[lo:hi] = dataset.get_batch_into(
                idx, canvases[lo:hi], extents[lo:hi]
            )
        else:
            imgs, labs, exts = dataset.get_batch(idx)
            canvases[lo:hi] = imgs
            extents[lo:hi] = exts
            labels[lo:hi] = labs
        if progress is not None:
            progress(hi, n)
    failed = getattr(dataset, "decode_failures", 0) - fail_before
    if failed:
        raise PrestageError(
            f"{failed} decode failure(s) during prestage — refusing to "
            "freeze zero canvases into a whole-cluster artifact"
        )
    canvases.flush()
    extents.flush()
    labels.flush()
    meta = {
        "v": FORMAT_VERSION,
        "n": n,
        "img_shape": list(img_shape),
        "img_dtype": "uint8",
        "num_classes": int(getattr(dataset, "num_classes", 0)),
        "image_size": int(getattr(dataset, "image_size", img_shape[0])),
        "stage_h": int(getattr(dataset, "stage_h", img_shape[0])),
        "stage_w": int(getattr(dataset, "stage_w", img_shape[1])),
        "canvas_bytes": int(canvases.nbytes),
        "source": type(getattr(dataset, "dataset", dataset)).__name__,
    }
    tmp = paths["meta"] + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, paths["meta"])  # meta lands LAST, atomically
    return meta


class PrestagedDataset:
    """Serve a prestage directory through the standard batch protocol.

    Canvases are an `np.memmap` (`mmap=True`, the default): the OS page
    cache is the only copy, shared across every Prefetcher, staging
    server and eval loader on the host — a "hit epoch" costs one memcpy
    per row and zero decode. `mmap=False` loads everything into
    anonymous memory up front (small datasets, or hosts whose storage
    is slower than RAM refills)."""

    def __init__(self, root: str, *, mmap: bool = True):
        paths = _paths(root)
        if not os.path.exists(paths["meta"]):
            raise PrestageError(
                f"{root!r} has no {META_FILENAME} — not a (complete) "
                "prestage; the writer lands meta last, so a missing "
                "meta means a killed or still-running write_prestage"
            )
        with open(paths["meta"], encoding="utf-8") as f:
            self.meta = json.load(f)
        if self.meta.get("v") != FORMAT_VERSION:
            raise PrestageError(
                f"prestage format v{self.meta.get('v')} != "
                f"v{FORMAT_VERSION} reader"
            )
        self.root = root
        mode = "r"
        self.images = np.load(paths["canvases"],
                              mmap_mode=mode if mmap else None)
        self._extents = np.load(paths["extents"],
                                mmap_mode=mode if mmap else None)
        self.labels = np.asarray(np.load(paths["labels"]), np.int32)
        n = int(self.meta["n"])
        shape = (n,) + tuple(self.meta["img_shape"])
        if (self.images.shape != shape or self.images.dtype != np.uint8
                or self._extents.shape != (n, 3)
                or self.labels.shape != (n,)):
            raise PrestageError(
                f"prestage payload disagrees with meta: canvases "
                f"{self.images.shape}/{self.images.dtype} vs {shape}/"
                f"uint8, extents {self._extents.shape}, labels "
                f"{self.labels.shape}"
            )
        self.num_classes = int(self.meta.get("num_classes", 0))
        self.image_size = int(self.meta.get("image_size", shape[1]))
        self.stage_h = int(self.meta.get("stage_h", shape[1]))
        self.stage_w = int(self.meta.get("stage_w", shape[2]))

    def __len__(self):
        return int(self.meta["n"])

    def get_batch(self, indices):
        idx = np.asarray(indices)
        # fancy-indexing a memmap materializes real arrays (the one copy)
        return (
            np.asarray(self.images[idx]),
            self.labels[idx],
            np.asarray(self._extents[idx]),
        )

    def get_batch_into(self, indices, out_imgs: np.ndarray,
                       out_extents: np.ndarray) -> np.ndarray:
        """Memcpy rows straight into caller-owned canvas rows (the
        staging-canvas protocol): the steady state the service's "hit
        epoch" promise is made of."""
        idx = [int(i) for i in indices]
        for j, i in enumerate(idx):
            out_imgs[j] = self.images[i]
            out_extents[j] = self._extents[i]
        return self.labels[np.asarray(idx)]
