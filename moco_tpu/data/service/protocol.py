"""Wire protocol of the disaggregated input service (ISSUE 14).

One frame = an 8-byte prefix (`!II`: header length, payload length), a
UTF-8 JSON header, and an opaque payload. The header carries the message
`op` plus its small fields; the payload carries raw canvas/extent/label
bytes — staged image data never round-trips through JSON.

Ops (client → server):
    hello   first frame of a connection: {"op": "hello", "role":
            "client"|"probe", "credits": N, "proto": 1}. `credits` is
            the flow-control window the client announces — sized by
            `prefetch_depth`. Enforcement is structural, not policed:
            the server's per-connection serve loop is strictly
            request→answer (one in-flight shard per stream), so the
            client's stream count × its ready-queue depth bounds how
            much decoded data is ever in flight — the train host, not
            the server, holds the credits. The announced value rides in
            the hello for diagnostics.
    shard   {"op": "shard", "batch": b, "epoch": e, "lo": r0, "hi": r1,
            "trace": "tid:sid"?} + payload = the shard's dataset indices
            as little-endian int64 — the client computes the epoch
            permutation (resume/rollback fast-forward included) and the
            server decodes exactly the indices it is handed, so
            bit-identity to in-process staging is by construction, not
            by re-derived seeding.
    ping    probe liveness: answered with `pong` + the server's stats
            snapshot (the staging supervisor's probe — an ANSWER is the
            heartbeat, the serve-fleet rule).
    bye     clean connection close.

Ops (server → client):
    meta    hello answer: canvas geometry + dtypes + dataset length, so
            the client can build its pooled canvases before the first
            shard and refuse a server whose dataset disagrees with its
            own config.
    data    shard answer: header {"batch", "lo", "hi", "shapes",
            "dtypes"} + payload = imgs‖extents‖labels bytes,
            concatenated in that order.
    pong    ping answer: {"stats": {...}}.
    error   structured failure: {"code": str, "detail": str,
            "retryable": bool}. Retryable errors (a transient read
            fault, chaos-injected `TransientDataError`) re-enter the
            client's retry-with-backoff budget — the PR 1 contract;
            non-retryable ones (protocol violation, index out of range)
            surface immediately.

Pure stdlib by contract (mocolint R11 `staging-server-stdlib-only`):
both halves of the staging server and the supervisor-side probes import
this module; numpy array (de)serialization stays with the caller, which
hands raw bytes in and takes raw bytes out.
"""

from __future__ import annotations

import json
import os
import socket
import struct

PROTO_VERSION = 1

# frame prefix: header length, payload length (network byte order)
_PREFIX = struct.Struct("!II")

# sanity bounds: a corrupt/foreign prefix must fail loudly, not allocate
# gigabytes. 1 GiB payload admits a ~680-row shard of 512×1024 uint8
# canvases; the client chunks its shard requests (client.MAX_SHARD_BYTES,
# 256 MiB) so a data answer never approaches this bound.
MAX_HEADER_BYTES = 1 << 20
MAX_PAYLOAD_BYTES = 1 << 30

OP_HELLO = "hello"
OP_SHARD = "shard"
OP_PING = "ping"
OP_BYE = "bye"
OP_META = "meta"
OP_DATA = "data"
OP_PONG = "pong"
OP_ERROR = "error"

ERR_TRANSIENT = "transient"      # retryable decode/read fault
ERR_PROTOCOL = "protocol"        # malformed frame / credit violation
ERR_BAD_REQUEST = "bad_request"  # out-of-range indices, wrong shapes
ERR_SHUTDOWN = "shutdown"        # server draining: retry elsewhere


class FrameError(ConnectionError):
    """Malformed or out-of-bounds frame; subclasses ConnectionError on
    purpose — the client's retry-on-another-server path treats a peer
    speaking garbage exactly like a peer hanging up mid-frame."""


class RemoteShardError(OSError):
    """A structured `error` frame, surfaced client-side. Subclasses
    OSError so a retryable server-side fault enters the SAME
    retry-with-backoff path as a local flaky read (the PR 1 contract);
    `retryable=False` errors are re-raised past the budget immediately."""

    def __init__(self, code: str, detail: str, retryable: bool):
        super().__init__(f"{code}: {detail}")
        self.code = code
        self.detail = detail
        self.retryable = bool(retryable)


def send_frame(sock: socket.socket, header: dict,
               payload=b"") -> None:
    """One frame (header dict -> JSON). `payload` is bytes-like OR a
    sequence of contiguous buffer-protocol chunks (numpy arrays
    included): multi-chunk payloads stream as back-to-back sendalls so
    a 256 MiB shard answer never materializes a concatenated copy —
    the receiver sees one contiguous payload either way."""
    raw = json.dumps(header).encode("utf-8")
    if isinstance(payload, (bytes, bytearray, memoryview)):
        payload = (payload,)
    parts = []
    for chunk in payload:
        view = memoryview(chunk)
        parts.append(view if view.format == "B" and view.ndim == 1
                     else view.cast("B"))
    total = sum(p.nbytes for p in parts)
    if len(raw) > MAX_HEADER_BYTES or total > MAX_PAYLOAD_BYTES:
        raise FrameError(
            f"frame exceeds protocol bounds (header {len(raw)} B, "
            f"payload {total} B)"
        )
    sock.sendall(_PREFIX.pack(len(raw), total) + raw)
    for part in parts:
        if part.nbytes:
            sock.sendall(part)


def _recv_exact(sock: socket.socket, n: int,
                mid_frame: bool = False) -> bytes:
    """Read exactly n bytes or raise ConnectionError (a torn frame is a
    dead peer as far as the retry machinery is concerned). A
    socket.timeout at a FRAME BOUNDARY (nothing read yet, not
    `mid_frame`) propagates as-is — an idle connection the serve loop
    keeps; once any byte of a frame is consumed, a timeout means the
    stream is desynchronized and only tearing the connection is safe."""
    chunks = []
    remaining = n
    while remaining:
        try:
            chunk = sock.recv(min(remaining, 1 << 20))
        except socket.timeout:
            if mid_frame or remaining != n:
                raise ConnectionError(
                    f"timeout mid-frame ({n - remaining}/{n} bytes) — "
                    "stream desynchronized"
                ) from None
            raise
        if not chunk:
            raise ConnectionError(
                f"peer closed mid-frame ({n - remaining}/{n} bytes)"
            )
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> tuple[dict, bytes]:
    """(header, payload) of the next frame; ConnectionError on a closed
    peer, FrameError on garbage."""
    prefix = _recv_exact(sock, _PREFIX.size)
    header_len, payload_len = _PREFIX.unpack(prefix)
    if header_len > MAX_HEADER_BYTES or payload_len > MAX_PAYLOAD_BYTES:
        raise FrameError(
            f"frame prefix out of bounds (header {header_len} B, "
            f"payload {payload_len} B) — not this protocol"
        )
    try:
        header = json.loads(_recv_exact(sock, header_len,
                                        mid_frame=True).decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise FrameError(f"unparseable frame header: {e}") from e
    if not isinstance(header, dict) or "op" not in header:
        raise FrameError(f"frame header is not an op dict: {header!r}")
    payload = (_recv_exact(sock, payload_len, mid_frame=True)
               if payload_len else b"")
    return header, payload


def raise_if_error(header: dict) -> None:
    """Surface a structured `error` frame as RemoteShardError."""
    if header.get("op") == OP_ERROR:
        raise RemoteShardError(
            str(header.get("code", "unknown")),
            str(header.get("detail", "")),
            bool(header.get("retryable", False)),
        )


def parse_endpoints(spec: str) -> list[tuple[str, int]]:
    """`"host:port,host:port"` (";" also accepted) → [(host, port)].
    Loud on malformed entries — a typo'd endpoint that silently vanishes
    would turn a two-server deployment into an unnoticed single point of
    failure."""
    endpoints = []
    for part in spec.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        host, sep, port = part.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"input-service endpoint {part!r} is not host:port"
            )
        try:
            endpoints.append((host, int(port)))
        except ValueError:
            raise ValueError(
                f"input-service endpoint {part!r} has a non-integer port"
            ) from None
    if not endpoints:
        raise ValueError(f"no endpoints in input-service spec {spec!r}")
    return endpoints


def append_jsonl(path: str, record: dict) -> None:
    """One whole-line O_APPEND write + fsync. THE event-emit discipline
    for the per-server events.jsonl: the supervisor half (another
    process) and the decode worker both append to the same file, and
    whole-line appends interleave safely. Shared here (stdlib, inside
    the R11 boundary) so the two halves of one stream cannot drift."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(record) + "\n")
        f.flush()
        os.fsync(f.fileno())


def fetch_meta(host: str, port: int, timeout_s: float = 2.0) -> dict | None:
    """One hello round-trip as a probe: the server's meta answer header
    (dataset length `n`, canvas geometry, `prestaged`), or None on any
    failure. The cheap way for a train host to learn the dataset length
    without building — or even mounting — the dataset locally; drift
    between servers is still caught per-connection by the client's
    meta check."""
    try:
        with socket.create_connection((host, port),
                                      timeout=timeout_s) as sock:
            send_frame(sock, {"op": OP_HELLO, "role": "probe",
                              "proto": PROTO_VERSION})
            header, _ = recv_frame(sock)
            if header.get("op") != OP_META:
                return None
            return header
    except (OSError, FrameError, ValueError):
        return None


def ping(host: str, port: int, timeout_s: float = 2.0) -> dict | None:
    """One probe round-trip: connect, hello(role=probe), ping, read
    pong. Returns the server's stats dict, or None on any failure (the
    caller treats None as a missed heartbeat)."""
    try:
        with socket.create_connection((host, port),
                                      timeout=timeout_s) as sock:
            send_frame(sock, {"op": OP_HELLO, "role": "probe",
                              "proto": PROTO_VERSION})
            header, _ = recv_frame(sock)
            if header.get("op") != OP_META:
                return None
            send_frame(sock, {"op": OP_PING})
            header, _ = recv_frame(sock)
            if header.get("op") != OP_PONG:
                return None
            stats = header.get("stats")
            return stats if isinstance(stats, dict) else {}
    except (OSError, FrameError, ValueError):
        return None
