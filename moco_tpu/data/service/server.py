"""Staging-server control plane: the stdlib supervisor half (ISSUE 14).

One staging server is TWO processes, split exactly like the run
supervisor (PR 4) and the serve fleet (PR 10) split theirs:

    tools/staging_server.py  →  StagingServer (THIS module, pure stdlib)
                                  ├── health HTTP endpoint (/healthz,
                                  │   /stats) — the serve-replica probe
                                  │   surface, so any fleet supervisor /
                                  │   k8s probe speaks to it unchanged
                                  └── DECODE WORKER subprocess
                                      (`python -m moco_tpu.data.service.
                                      worker`): numpy + the native
                                      chunked pool, binds the DATA port

The supervisor half never imports numpy/jax — not even transitively
(mocolint R11 `staging-server-stdlib-only`): a wedged native decode, an
OOM'd worker or a poisoned import must leave a live process that still
answers /healthz 503, classifies the death, and relaunches within a
budget. Supervision reuses the serve-fleet machinery: `FleetPolicy`
knobs, `ReplicaState` bookkeeping, probe-answer-is-the-heartbeat
liveness (a `ping` frame on the data port — it exercises the REAL
serving path, so accepting-but-not-answering wedges are caught), the
SIGTERM → grace → SIGKILL escalation, `classify_exit` death
classification, and restart budgets refunded on a healthy life.

Lifecycle transitions land as `kind:"input_server"` records in the
server's events.jsonl — the same stream the worker appends its `stats`
records to (O_APPEND whole lines interleave safely across the two
processes), so telemetry_report folds one per-server story.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from moco_tpu.data.service import protocol
from moco_tpu.resilience.exitcodes import (
    EXIT_CONFIG_ERROR,
    EXIT_STAGING_BIND,
)
from moco_tpu.resilience.supervisor import (
    CLASS_CLEAN,
    CLASS_CONFIG_ERROR,
    CLASS_STAGING_BIND,
    FATAL_CLASSES,
    classify_exit,
)
from moco_tpu.serve.fleet import FleetPolicy, ReplicaState, pick_free_port
from moco_tpu.telemetry.trace import Tracer
from moco_tpu.utils.logging import log_event

EVENTS_FILENAME = "events.jsonl"

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


class _HealthServer(ThreadingHTTPServer):
    daemon_threads = True
    request_queue_size = 32


def _make_health_handler(server: "StagingServer"):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _send(self, status: int, obj: dict) -> None:
            body = json.dumps(obj).encode("utf-8")
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                healthy = server.worker_healthy()
                self._send(200 if healthy else 503, {
                    "status": "ok" if healthy else "worker_unhealthy",
                    "data_port": server.data_port,
                    "server_id": server.server_id,
                })
            elif self.path == "/stats":
                self._send(200, server.stats())
            else:
                self._send(404, {"error": "not_found", "path": self.path})

    return Handler


class StagingServer:
    """Supervise one decode-worker subprocess behind a health endpoint.

    `worker_args` is the dataset/decode argv tail forwarded verbatim to
    `python -m moco_tpu.data.service.worker` (the CLI builds it from its
    own flags; tests pass it directly). `data_port=0` picks a free port
    — announced via `/healthz`, `/stats` and `self.data_port`."""

    def __init__(self, worker_args: list[str], *, host: str = "127.0.0.1",
                 data_port: int = 0, health_port: int = 0,
                 telemetry_dir: str = "", server_id: int = 0,
                 policy: FleetPolicy | None = None,
                 env: dict | None = None, worker_python: str | None = None):
        self.worker_args = list(worker_args)
        self.host = host
        self.server_id = int(server_id)
        self.telemetry_dir = telemetry_dir or os.path.join(
            ".", f"staging_server{server_id}")
        self.policy = policy or FleetPolicy()
        self._env = env
        self._python = worker_python or sys.executable
        self.data_port = data_port or pick_free_port(host)
        self.events_path = os.path.join(self.telemetry_dir,
                                        EVENTS_FILENAME)
        os.makedirs(self.telemetry_dir, exist_ok=True)
        self.tracer = Tracer(self.telemetry_dir, "steps",
                             proc=f"staging-sup{server_id}")
        self.run_id = self.tracer.run_id
        self._lock = threading.Lock()
        self._emit_lock = threading.Lock()
        self.worker = ReplicaState(self.server_id, host, self.data_port,
                                   self.telemetry_dir,
                                   self.policy.max_restarts)
        self.last_worker_stats: dict = {}
        self.incidents: list[dict] = []
        self._stop = threading.Event()
        self._monitor: threading.Thread | None = None
        self._rng = random.Random()  # system entropy: no fleet lockstep
        # health endpoint binds FIRST: an occupied port must fail the CLI
        # with EXIT_STAGING_BIND before any subprocess exists
        self.health = _HealthServer((host, health_port),
                                    _make_health_handler(self))
        self.health_port = self.health.server_address[1]
        self._health_thread: threading.Thread | None = None

    # -- events --------------------------------------------------------------
    def _emit(self, event: str, **fields) -> None:
        record = {"v": 1, "t": round(time.time(), 3),
                  "kind": "input_server", "event": event,
                  "server_id": self.server_id, "run_id": self.run_id}
        record.update(fields)
        with self._emit_lock:
            self.incidents.append(record)
            protocol.append_jsonl(self.events_path, record)
        detail = " ".join(f"{k}={v}" for k, v in fields.items())
        log_event("input_server", f"{event} {detail}".strip())

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._emit("server_start", data_port=self.data_port,
                   health_port=self.health_port)
        self._launch()
        self._health_thread = threading.Thread(
            target=self.health.serve_forever, daemon=True,
            name="staging-health")
        self._health_thread.start()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="staging-monitor")
        self._monitor.start()

    def stop(self, timeout_s: float = 15.0) -> None:
        """Drain-stop: SIGTERM the worker (it finishes in-flight shards),
        escalate a straggler, release the health port."""
        if self._stop.is_set():
            return
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        r = self.worker
        with self._lock:
            r.expected_exit = True
        if r.alive():
            r.proc.terminate()
            deadline = time.monotonic() + timeout_s
            while r.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if r.proc.poll() is None:
                r.proc.kill()
                r.proc.wait()
        self._emit("server_stop", launches=r.launches)
        if self._health_thread is not None:
            self.health.shutdown()
            self._health_thread.join(timeout=5.0)
            self._health_thread = None
        self.health.server_close()
        self.tracer.close()

    # R4 coverage (ISSUE 14 satellite): server constructions close in a
    # finally like loader constructions do — same names, same rule
    def close(self) -> None:
        self.stop()

    def close_quietly(self) -> None:
        try:
            self.stop()
        except Exception as e:  # noqa: BLE001 — teardown must not unwind
            log_event("input_server", f"stop failed (ignored): {e!r}")

    # -- worker lifecycle ----------------------------------------------------
    def _worker_argv(self) -> list[str]:
        return [self._python, "-m", "moco_tpu.data.service.worker",
                *self.worker_args,
                "--host", self.host, "--port", str(self.data_port),
                "--server-id", str(self.server_id),
                "--telemetry-dir", self.telemetry_dir]

    def _launch(self) -> None:
        r = self.worker
        env = dict(os.environ if self._env is None else self._env)
        env["PYTHONPATH"] = _REPO_ROOT + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        env.update(self.tracer.child_env())
        log_file = open(os.path.join(self.telemetry_dir, "worker.log"),
                        "ab")
        try:
            proc = subprocess.Popen(self._worker_argv(), stdout=log_file,
                                    stderr=subprocess.STDOUT, env=env)
        finally:
            log_file.close()
        now = time.monotonic()
        with self._lock:
            r.proc = proc
            r.pid = proc.pid
            r.launches += 1
            r.launched_at = now
            r.last_ok_life = None
            r.ever_healthy_life = False
            r.healthy = False
            r.kill_phase = None
            r.relaunch_at = None
            r.expected_exit = False
        self._emit("launch", attempt=r.launches - 1, pid=proc.pid,
                   data_port=self.data_port, budget_left=r.budget)

    def _handle_exit(self) -> None:
        r = self.worker
        rc = r.proc.returncode
        hang = r.kill_phase is not None
        cls, detail = classify_exit(rc, hang_killed=hang)
        now = time.monotonic()
        with self._lock:
            expected = r.expected_exit
            progressed = r.ever_healthy_life
            pid = r.pid
            r.proc = None
            r.healthy = False
            r.kill_phase = None
            r.expected_exit = False
            r.classifications.append(cls)
        self._emit("worker_exit", pid=pid, returncode=rc,
                   classification=cls, detail=detail,
                   progressed=progressed, expected=expected)
        if expected:
            return
        if cls in FATAL_CLASSES and cls != CLASS_CLEAN:
            # a staging server exists to serve: an unexpected clean exit
            # restarts (the fleet rule), real fatals abandon
            with self._lock:
                r.abandoned = True
            self._emit("give_up", reason=f"fatal class {cls}",
                       returncode=rc)
            return
        delay = 0.0
        with self._lock:
            if progressed:
                r.budget = self.policy.max_restarts
                r.consecutive_failures = 0
            else:
                r.consecutive_failures += 1
                if r.budget <= 0:
                    r.abandoned = True
                else:
                    r.budget -= 1
                    delay = self.policy.backoff_secs(
                        r.consecutive_failures, self._rng)
            abandoned = r.abandoned
            if not abandoned:
                r.relaunch_at = now + delay
        if abandoned:
            self._emit("give_up",
                       reason=(f"restart budget exhausted: "
                               f"{r.consecutive_failures} consecutive "
                               f"never-healthy deaths"))
        elif delay:
            self._emit("backoff", secs=round(delay, 3),
                       budget_left=r.budget)

    def _probe_and_update(self) -> None:
        r = self.worker
        stats = protocol.ping(self.host, self.data_port,
                              timeout_s=self.policy.probe_timeout_s)
        now = time.monotonic()
        if stats is not None:
            with self._lock:
                r.last_ok_life = now
                newly = not r.healthy
                r.healthy = True
                was_ever = r.ever_healthy_life
                r.ever_healthy_life = True
                self.last_worker_stats = stats
            if newly:
                self._emit("readmit" if was_ever else "worker_healthy",
                           pid=r.pid, shards=stats.get("shards", 0))
        else:
            with self._lock:
                was = r.healthy
                r.healthy = False
            if was:
                self._emit("eject", reason="probe failed")

    def _check_staleness(self, now: float) -> None:
        r = self.worker
        if r.expected_exit or not r.alive():
            return
        if r.kill_phase == "term":
            if now - r.term_at > self.policy.term_grace_secs:
                self._emit("kill", pid=r.pid, phase="sigkill",
                           reason="probe_stale")
                r.proc.kill()
                with self._lock:
                    r.kill_phase = "kill"
            return
        if r.kill_phase is not None:
            return
        ref = r.last_ok_life if r.last_ok_life is not None else r.launched_at
        window = (self.policy.health_stale_secs
                  if r.last_ok_life is not None
                  else self.policy.startup_grace_secs)
        stale_for = now - ref
        if stale_for > window:
            self._emit("kill", pid=r.pid, phase="sigterm",
                       reason="probe_stale",
                       stale_secs=round(stale_for, 3))
            r.proc.terminate()
            with self._lock:
                r.kill_phase = "term"
                r.term_at = now

    def _monitor_loop(self) -> None:
        poll = max(min(self.policy.probe_secs / 2.0, 0.5), 0.02)
        last_probe = 0.0
        while not self._stop.is_set():
            now = time.monotonic()
            r = self.worker
            if r.abandoned:
                self._stop.wait(poll)
                continue
            if r.proc is None:
                with self._lock:
                    due = (r.relaunch_at is not None
                           and now >= r.relaunch_at)
                if due:
                    try:
                        self._launch()
                    except OSError as e:
                        with self._lock:
                            r.abandoned = True
                        self._emit("give_up",
                                   reason=f"relaunch failed to spawn: {e}")
            elif r.proc.poll() is not None:
                self._handle_exit()
            else:
                if now - last_probe >= self.policy.probe_secs:
                    last_probe = now
                    self._probe_and_update()
                self._check_staleness(time.monotonic())
            self._stop.wait(poll)

    # -- introspection -------------------------------------------------------
    def worker_healthy(self) -> bool:
        with self._lock:
            return self.worker.healthy and not self.worker.abandoned

    def abandoned_class(self) -> str | None:
        """The worker's terminal classification once abandoned (the CLI's
        exit-code source), else None."""
        with self._lock:
            if not self.worker.abandoned:
                return None
            return (self.worker.classifications[-1]
                    if self.worker.classifications else "abandoned")

    def exit_code(self) -> int:
        """Map an abandoned worker to the CLI's own exit code: the
        supervisor speaks for the server it fronts."""
        cls = self.abandoned_class()
        if cls == CLASS_STAGING_BIND:
            return EXIT_STAGING_BIND
        if cls == CLASS_CONFIG_ERROR:
            return EXIT_CONFIG_ERROR
        return 1

    def stats(self) -> dict:
        with self._lock:
            return {
                "run_id": self.run_id,
                "server_id": self.server_id,
                "data_port": self.data_port,
                "worker": self.worker.snapshot(),
                "worker_stats": dict(self.last_worker_stats),
            }

    def wait_healthy(self, timeout_s: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.worker_healthy():
                return True
            if self.abandoned_class() is not None:
                return False
            time.sleep(0.05)
        return False
