"""ServiceClient: the train-host side of the input service (ISSUE 14).

A drop-in sibling of `Prefetcher` — literally a subclass: the
coordinator, canvas pool, per-device-shard early-put plan, ready queue,
close semantics and the `__iter__`-pops-device-arrays contract are all
inherited unchanged. The ONLY thing that changes is where canvas rows
come from: instead of decoding locally, each sub-slice is fetched from a
staging server over the frame protocol (`data/service/protocol.py`).
Because the client ships the exact dataset indices it would have decoded
itself and the server runs the same dataset code, service-fed batches
are BIT-IDENTICAL to in-process staging on the same seed/epoch
(test-enforced) — including the device placement path.

Flow control: the ready queue (`prefetch_depth` device batches) plus the
double-buffered canvas pool bound how many batches are ever in flight;
the shard REQUESTS are the credits, announced in the hello frame —
a server never decodes ahead of what the train host asked for. Time the
consumer spends blocked on an empty ready queue is booked as
`credit_stall_s` into `InputPipelineStats` — the obsd
`input_credit_stall_rate` objective that pages on a starving train host.

Failure contract (PR 1): a retryable server error (transient read fault,
chaos-injected `TransientDataError`) or a dead/torn/stalled connection
retries the SAME shard on ANOTHER server immediately; once every server
has failed an attempt, the exponential backoff kicks in between further
rounds, up to the loader's ordinary `retries` budget. Batches are never
reordered or duplicated — a retry lands the same rows in the same canvas
slice.
"""

from __future__ import annotations

import itertools
import socket
import threading
import time

import numpy as np

from moco_tpu.data.loader import (
    Prefetcher,
    epoch_permutation,
    host_shard,
)
from moco_tpu.data.service import protocol
from moco_tpu.resilience.chaos import active_chaos
from moco_tpu.utils.logging import log_event


class ServiceConfigError(ValueError):
    """Client/server configuration drift (dataset length or canvas
    geometry disagreement) or no reachable server at construction —
    loud, immediately: a mis-pointed service must fail where it was
    configured, not as silently-wrong training data."""


# One shard request never asks for more than this many payload bytes:
# comfortably under protocol.MAX_PAYLOAD_BYTES (1 GiB) so a data answer
# can never trip the frame bound. At the shipping TPU shape (512x1024x3
# uint8 = 1.5 MiB/row) this is ~170 rows per shard; the whole-batch
# shape-discovery fetch (1024 rows/host) chunks instead of dying.
MAX_SHARD_BYTES = 256 << 20


class _Link:
    """One per-(thread, endpoint) connection. Thread-confined: the
    owning worker thread is the only user, so no lock."""

    __slots__ = ("sock", "meta")

    def __init__(self, sock: socket.socket, meta: dict):
        self.sock = sock
        self.meta = meta


class ServiceClient(Prefetcher):
    """Iterate device-sharded batches staged by remote servers.

    `endpoints` is `[(host, port), ...]` data-port addresses (or the
    `"host:port,host:port"` string form). `streams` is the number of
    concurrent fetch threads (the in-flight shard window — the
    `staging_workers` knob reused); `request_timeout_s` bounds one shard
    round-trip before the client gives up on that server and re-lands
    the shard elsewhere."""

    def __init__(self, endpoints, indices: np.ndarray,
                 batch_per_host: int, mesh, *, depth: int = 2,
                 retries: int = 3, backoff_secs: float = 0.5,
                 join_timeout: float = 5.0, streams: int = 4,
                 stats=None, tracer=None, request_timeout_s: float = 30.0,
                 connect_timeout_s: float = 5.0,
                 expected_len: int | None = None,
                 max_shard_rows: int | None = None):
        if isinstance(endpoints, str):
            endpoints = protocol.parse_endpoints(endpoints)
        self.endpoints = [(str(h), int(p)) for h, p in endpoints]
        if not self.endpoints:
            raise ServiceConfigError("ServiceClient needs >= 1 endpoint")
        self._request_timeout_s = float(request_timeout_s)
        self._connect_timeout_s = float(connect_timeout_s)
        self._credits = max(int(depth), 1)
        self._tls = threading.local()
        self._socks_lock = threading.Lock()
        self._open_socks: set = set()
        self._rr = itertools.count()
        self.meta = self._handshake_meta(expected_len)
        # per-shard row cap from the wire geometry: one data answer is
        # imgs + extents + labels bytes per row, and must stay under the
        # frame payload bound regardless of batch size
        row_bytes = (
            int(np.prod(self.meta["img_shape"]))
            * np.dtype(self.meta["img_dtype"]).itemsize
            + 3 * np.dtype(np.int32).itemsize
            + np.dtype(self.meta["label_dtype"]).itemsize
        )
        self._max_shard_rows = int(max_shard_rows) if max_shard_rows \
            else max(1, MAX_SHARD_BYTES // max(row_bytes, 1))
        try:
            super().__init__(
                None, indices, batch_per_host, mesh, depth=depth,
                retries=retries, backoff_secs=backoff_secs,
                join_timeout=join_timeout, workers=streams, stats=stats,
                trim_h2d=False, tracer=tracer,
            )
        except BaseException:
            # construction failed AFTER the handshake: close() will
            # never run, so release the handshake socket here or it
            # (and the server's conn thread) outlives the refused client
            self._close_all_socks()
            raise

    # -- construction-time validation ---------------------------------------
    def _handshake_meta(self, expected_len: int | None) -> dict:
        """First reachable server's meta (dataset length, canvas
        geometry). Every endpoint is tried once; total unreachability is
        a configuration error, not a transient."""
        errors = []
        for host, port in self.endpoints:
            try:
                sock = self._connect(host, port)
            except (OSError, protocol.FrameError,
                    ServiceConfigError) as e:
                errors.append(f"{host}:{port}: {e}")
                continue
            meta = self._link_of(sock).meta
            if expected_len is not None and meta["n"] != expected_len:
                # __init__ aborts here, so close() never runs: release
                # the handshake socket now or it (and the server's conn
                # thread) outlives the refused client
                self._drop_link((host, port))
                raise ServiceConfigError(
                    f"staging server {host}:{port} serves {meta['n']} "
                    f"samples but this run's dataset has {expected_len} "
                    "— client and server must be pointed at the same "
                    "data"
                )
            return meta
        raise ServiceConfigError(
            "no staging server reachable: " + "; ".join(errors)
        )

    # -- connections ---------------------------------------------------------
    def _connect(self, host: str, port: int) -> socket.socket:
        sock = socket.create_connection(
            (host, port), timeout=self._connect_timeout_s)
        try:
            sock.settimeout(self._request_timeout_s)
            protocol.send_frame(sock, {
                "op": protocol.OP_HELLO, "role": "client",
                "credits": self._credits,
                "proto": protocol.PROTO_VERSION,
            })
            header, _ = protocol.recv_frame(sock)
            protocol.raise_if_error(header)
            if header.get("op") != protocol.OP_META:
                raise protocol.FrameError(
                    f"expected meta, got {header.get('op')!r}")
        except BaseException:
            sock.close()
            raise
        links = getattr(self._tls, "links", None)
        if links is None:
            links = self._tls.links = {}
        link = _Link(sock, {
            "n": int(header.get("n", 0)),
            "img_shape": tuple(header.get("img_shape", ())),
            "img_dtype": str(header.get("img_dtype", "uint8")),
            "label_dtype": str(header.get("label_dtype", "int32")),
            "server_id": header.get("server_id"),
            "prestaged": bool(header.get("prestaged", False)),
        })
        links[(host, port)] = link
        with self._socks_lock:
            self._open_socks.add(sock)
        # EVERY server must agree with the handshake meta, not just the
        # first reachable one: a same-length-different-data server would
        # otherwise silently serve wrong rows into the round-robin
        expected = getattr(self, "meta", None)
        if expected is not None:
            for key in ("n", "img_shape", "img_dtype", "label_dtype"):
                if link.meta[key] != expected[key]:
                    self._drop_link((host, port))
                    raise ServiceConfigError(
                        f"staging server {host}:{port} disagrees on "
                        f"{key} ({link.meta[key]!r} vs "
                        f"{expected[key]!r}) — all servers must serve "
                        "the same dataset/geometry"
                    )
        return sock

    def _link_of(self, sock: socket.socket) -> _Link:
        for link in getattr(self._tls, "links", {}).values():
            if link.sock is sock:
                return link
        raise KeyError("socket has no link (internal)")

    def _get_link(self, endpoint) -> _Link:
        links = getattr(self._tls, "links", None)
        if links is None:
            links = self._tls.links = {}
        link = links.get(endpoint)
        if link is None:
            self._connect(*endpoint)
            link = links[endpoint]
        return link

    def _drop_link(self, endpoint) -> None:
        links = getattr(self._tls, "links", None)
        link = links.pop(endpoint, None) if links else None
        if link is not None:
            with self._socks_lock:
                self._open_socks.discard(link.sock)
            try:
                link.sock.close()
            except OSError:
                pass

    # -- the remote fetch ----------------------------------------------------
    def _fetch_once(self, endpoint, b: int, lo: int, hi: int,
                    idx: np.ndarray, trace_ctx) -> tuple:
        """One shard round-trip against one server. Raises
        ConnectionError/timeout/RemoteShardError on failure; the caller
        owns retry placement."""
        link = self._get_link(endpoint)
        header = {"op": protocol.OP_SHARD, "batch": int(b),
                  "lo": int(lo), "hi": int(hi)}
        if trace_ctx:
            # the server's serve_shard span continues the coordinator's
            # stage_batch / decode_slice parent across the process edge
            header["trace"] = f"{trace_ctx[0]}:{trace_ctx[1]}"
        payload = np.ascontiguousarray(idx, dtype="<i8").tobytes()
        try:
            protocol.send_frame(link.sock, header, payload)
            answer, data = protocol.recv_frame(link.sock)
        except (ConnectionError, socket.timeout, OSError):
            self._drop_link(endpoint)
            raise
        protocol.raise_if_error(answer)
        if answer.get("op") != protocol.OP_DATA:
            self._drop_link(endpoint)
            raise protocol.FrameError(
                f"expected data, got {answer.get('op')!r}")
        try:
            shapes = answer["shapes"]
            dtypes = answer["dtypes"]
            n_img = int(np.prod(shapes["imgs"])) * np.dtype(
                dtypes["imgs"]).itemsize
            n_ext = int(np.prod(shapes["extents"])) * np.dtype(
                dtypes["extents"]).itemsize
            imgs = np.frombuffer(data, dtype=dtypes["imgs"],
                                 count=int(np.prod(shapes["imgs"]))
                                 ).reshape(shapes["imgs"])
            extents = np.frombuffer(
                data[n_img:], dtype=dtypes["extents"],
                count=int(np.prod(shapes["extents"]))
            ).reshape(shapes["extents"])
            labels = np.frombuffer(data[n_img + n_ext:],
                                   dtype=dtypes["labels"],
                                   count=int(np.prod(shapes["labels"])))
        except (KeyError, ValueError, TypeError) as e:
            # a malformed data answer (missing/garbage shapes or dtypes,
            # payload shorter than they imply) is a peer speaking
            # garbage: the same retry-on-another-server class as a torn
            # frame, and the link may be desynced — drop it
            self._drop_link(endpoint)
            raise protocol.FrameError(
                f"malformed data answer: {type(e).__name__}: {e}") from e
        if imgs.shape[0] != len(idx) or len(labels) != len(idx):
            self._drop_link(endpoint)
            raise protocol.FrameError(
                f"server answered {imgs.shape[0]} rows / "
                f"{len(labels)} labels for a {len(idx)}-row shard")
        return imgs, labels, extents

    def _fetch_rows(self, b: int, lo: int, hi: int, idx: np.ndarray,
                    trace_ctx=None) -> tuple:
        """Fetch rows with the retry contract: immediate
        retry-on-another-server per failure; exponential backoff only
        once a whole round of servers has failed; `retries` bounds the
        ROUNDS (matching the in-process per-sub-slice budget)."""
        plan = active_chaos()
        n = len(self.endpoints)
        round_no = 0
        last: BaseException | None = None
        start = next(self._rr)
        while True:
            for j in range(n):
                endpoint = self.endpoints[(start + j) % n]
                t0 = time.perf_counter()
                try:
                    # chaos polls INSIDE the retried region, exactly like
                    # Prefetcher._read_slice_into: an injected
                    # TransientDataError (an OSError) re-enters this
                    # attempt's budget instead of crossing the contract
                    if plan is not None:
                        plan.maybe_loader_error(b)
                    out = self._fetch_once(endpoint, b, lo, hi, idx,
                                           trace_ctx)
                    if self._stats is not None:
                        self._stats.note_worker_busy(
                            time.perf_counter() - t0)
                    return out
                except protocol.RemoteShardError as e:
                    if not e.retryable:
                        raise
                    last = e
                except (ConnectionError, socket.timeout, OSError) as e:
                    last = e
                if self._stats is not None:
                    self._stats.note_worker_busy(
                        time.perf_counter() - t0)
                log_event(
                    "loader",
                    f"shard batch {b} rows [{lo}:{hi}) failed on "
                    f"{endpoint[0]}:{endpoint[1]} "
                    f"({type(last).__name__}: {last}); trying another "
                    "server",
                )
            round_no += 1
            if round_no > self.retries:
                raise last if last is not None else ConnectionError(
                    "shard fetch failed with no recorded error")
            delay = self.backoff_secs * (2 ** (round_no - 1))
            log_event(
                "loader",
                f"all {n} staging server(s) failed batch {b} rows "
                f"[{lo}:{hi}); retry round {round_no}/{self.retries} "
                f"in {delay:.2f}s",
            )
            if self._stop.wait(delay):
                from moco_tpu.data.loader import _CloseRequested

                raise _CloseRequested() from last

    # -- Prefetcher overrides ------------------------------------------------
    def _read_batch(self, b: int):
        """Whole-batch fetch (shape-discovery first batch / streams=1
        path), chunked to `_max_shard_rows` so a large per-host batch
        (1024 rows at the 512 canvas is ~1.5 GiB) never builds a data
        answer past the frame payload bound."""
        idx = self.indices[b * self.batch: (b + 1) * self.batch]
        ctx = self._tracer.current_context()
        parts = []
        for lo in range(0, len(idx), self._max_shard_rows):
            hi = min(lo + self._max_shard_rows, len(idx))
            parts.append(self._fetch_rows(b, lo, hi, idx[lo:hi],
                                          trace_ctx=ctx))
        if len(parts) == 1:
            return parts[0]
        return tuple(np.concatenate([p[k] for p in parts])
                     for k in range(3))

    def _read_slice_into(self, b, idx, canvas, lo, hi, trace_ctx=None):
        for off in range(0, hi - lo, self._max_shard_rows):
            c_lo = lo + off
            c_hi = min(c_lo + self._max_shard_rows, hi)
            imgs, labels, extents = self._fetch_rows(
                b, c_lo, c_hi, idx[off:off + (c_hi - c_lo)],
                trace_ctx=trace_ctx)
            canvas.imgs[c_lo:c_hi] = imgs
            canvas.labels[c_lo:c_hi] = labels
            canvas.extents[c_lo:c_hi] = extents

    def _close_all_socks(self) -> None:
        with self._socks_lock:
            socks, self._open_socks = list(self._open_socks), set()
        for sock in socks:
            try:
                sock.close()
            except OSError:
                pass

    def close(self):
        # sockets first: a fetch thread blocked in recv (up to
        # request_timeout_s) cannot observe _stop, so joining before
        # closing would stall close() by join_timeout per thread and log
        # a spurious wedged-read incident. _stop gates new fetch rounds;
        # a thread mid-retry may reconnect once, so sweep again after.
        self._stop.set()
        self._close_all_socks()
        try:
            super().close()
        finally:
            self._close_all_socks()


def service_epoch_loader(
    endpoints, dataset_len: int, epoch: int, seed: int,
    global_batch: int, mesh, skip_batches: int = 0, retries: int = 3,
    backoff_secs: float = 0.5, depth: int = 2, streams: int = 4,
    stats=None, tracer=None, request_timeout_s: float = 30.0,
) -> ServiceClient:
    """`epoch_loader`'s service twin: the SAME deterministic epoch
    permutation, host shard and resume fast-forward — computed client-
    side, so every resume/rollback path works unchanged — feeding a
    ServiceClient instead of local decode. The server is validated
    against `dataset_len` at construction (config-drift guard)."""
    import jax

    perm = epoch_permutation(dataset_len, epoch, seed, global_batch)
    local = host_shard(perm, global_batch)
    per_host = global_batch // jax.process_count()
    if skip_batches:
        local = local[skip_batches * per_host:]
    return ServiceClient(
        endpoints, local, per_host, mesh, depth=depth, retries=retries,
        backoff_secs=backoff_secs, streams=streams, stats=stats,
        tracer=tracer, request_timeout_s=request_timeout_s,
        expected_len=dataset_len,
    )
