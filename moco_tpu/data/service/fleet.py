"""LocalServerPool: N staging servers on this host (ISSUE 14).

The multi-server deployments tests, bench.py's e2e child and the chaos
drills need, without asking anyone to run N `tools/staging_server.py`
terminals: each pool member is one full `StagingServer` (stdlib
supervisor + decode-worker subprocess), so everything the drills exercise
— probe liveness, budgeted relaunch, EXIT_STAGING_BIND classification —
is the SAME code path a production deployment runs; nothing is stubbed.

`per_server_env` injects env overlays by server index, which is how a
drill poisons exactly ONE server with `MOCO_TPU_CHAOS=kill_at_shard=N`
(+ a per-server MOCO_TPU_CHAOS_STATE dir, so the supervisor's relaunch
is never re-poisoned) while its peers stay healthy.

Pure stdlib by contract (mocolint R11 `staging-server-stdlib-only`):
the pool is control-plane code — it must outlive the numpy/jax runtimes
it supervises.
"""

from __future__ import annotations

import os
import time

from moco_tpu.data.service.server import StagingServer
from moco_tpu.serve.fleet import FleetPolicy
from moco_tpu.utils.logging import log_event


class LocalServerPool:
    """Spawn and own `n` StagingServers with auto-picked ports.

    `worker_args` is the dataset/decode argv tail every server forwards
    to its decode worker (one flag surface — see
    `worker.add_dataset_flags`). Every construction closes in a
    `finally` (lint R4: the pool counts as a loader construction)."""

    def __init__(self, n: int, worker_args: list[str], *,
                 host: str = "127.0.0.1", telemetry_root: str = "",
                 policy: FleetPolicy | None = None,
                 per_server_env: dict[int, dict] | None = None,
                 worker_python: str | None = None):
        if n < 1:
            raise ValueError(f"pool needs >= 1 server, got {n}")
        self.servers: list[StagingServer] = []
        per_server_env = per_server_env or {}
        try:
            for i in range(n):
                env = None
                overlay = per_server_env.get(i)
                if overlay is not None:
                    env = dict(os.environ)
                    env.update(overlay)
                self.servers.append(StagingServer(
                    list(worker_args), host=host, server_id=i,
                    telemetry_dir=(os.path.join(
                        telemetry_root, f"staging_server{i}")
                        if telemetry_root else ""),
                    policy=policy, env=env, worker_python=worker_python,
                ))
        except BaseException:
            self.close_quietly()
            raise

    def start(self) -> None:
        for server in self.servers:
            server.start()

    def wait_healthy(self, timeout_s: float = 60.0) -> bool:
        """True when EVERY server answered a probe. A server that went
        terminal (abandoned) fails the wait immediately — a pool that
        silently came up short would turn a two-server drill into an
        unnoticed single point of failure. ONE shared deadline: servers
        come up concurrently, so a dead pool reports in timeout_s, not
        n x timeout_s."""
        deadline = time.monotonic() + timeout_s
        return all(
            s.wait_healthy(max(deadline - time.monotonic(), 0.05))
            for s in self.servers)

    def endpoints(self) -> list[tuple[str, int]]:
        return [(s.host, s.data_port) for s in self.servers]

    def endpoints_spec(self) -> str:
        """The `"host:port,host:port"` form PretrainConfig.input_service
        takes."""
        return ",".join(f"{h}:{p}" for h, p in self.endpoints())

    def worker_pids(self) -> list[int | None]:
        """Live decode-worker pids by server index (drills SIGKILL one)."""
        return [s.worker.pid if s.worker.alive() else None
                for s in self.servers]

    def close(self) -> None:
        for server in self.servers:
            server.close_quietly()

    def close_quietly(self) -> None:
        try:
            self.close()
        except Exception as e:  # noqa: BLE001 — teardown must not unwind
            log_event("input_server", f"pool stop failed (ignored): {e!r}")
