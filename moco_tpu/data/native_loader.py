"""ctypes binding for the native C++ staging loader (native/staging_loader.cc).

The reference leans on native code for its input path — PIL/libjpeg decode in
32 worker processes (`main_moco.py:≈L260-270`), or NVIDIA DALI in the bl0
fork (SURVEY §2.10). This is the TPU-native equivalent: a C++ thread pool in
the single controller process that turns JPEG files into fixed-size uint8
staging canvases (decode → transpose-if-portrait → bilinear fit-resize of
the WHOLE image + edge-replicated padding, with a per-image
`(valid_h, valid_w, rot)` extent); the randomized augmentation then runs ON
DEVICE (data/augment.py) over the true image area.

The shared library is compiled on first use (g++ + libjpeg, both in the
image); if the toolchain is unavailable, `ImageFolder` silently falls back
to the PIL path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from moco_tpu.utils.logging import log_event

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libstaging_loader.so"))
_build_lock = threading.Lock()


def _ensure_built() -> str | None:
    """Compile the library if needed; None if the build is impossible."""
    with _build_lock:
        src = os.path.join(_NATIVE_DIR, "staging_loader.cc")
        if os.path.exists(_LIB_PATH) and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(src):
            return _LIB_PATH
        try:
            subprocess.run(
                ["make", "-C", os.path.abspath(_NATIVE_DIR), "libstaging_loader.so"],
                check=True,
                capture_output=True,
            )
            return _LIB_PATH
        except (subprocess.CalledProcessError, FileNotFoundError):
            return None


class NativeStagingLoader:
    """Threaded JPEG→staging-canvas batch loader. Raises RuntimeError if the
    native library cannot be built (callers fall back to PIL)."""

    def __init__(self, stage_h: int, stage_w: int, num_threads: int | None = None):
        path = _ensure_built()
        if path is None:
            raise RuntimeError("native staging loader unavailable (build failed)")
        self._lib = ctypes.CDLL(path)
        self._lib.sl_create.restype = ctypes.c_void_p
        self._lib.sl_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int]
        self._lib.sl_load_batch.restype = ctypes.c_int
        self._lib.sl_load_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
        ]
        self._lib.sl_destroy.argtypes = [ctypes.c_void_p]
        try:
            self._lib.sl_version.restype = ctypes.c_int
            self.version = int(self._lib.sl_version())
        except AttributeError:  # pre-v2 .so without the symbol
            self.version = 1
        if num_threads is None:
            num_threads = max(os.cpu_count() or 1, 1)
        self.num_threads = num_threads
        self.stage_h = stage_h
        self.stage_w = stage_w
        # cumulative decode telemetry: a zero-canvas batch poisoning training
        # must be VISIBLE (metered by the driver, ISSUE 1 satellite), not a
        # discarded return value. Locked: staging workers (ISSUE 3) call
        # load_batch concurrently for disjoint sub-slices of one batch.
        self.total_images = 0
        self.total_failures = 0
        self._meter_lock = threading.Lock()
        self._handle = self._lib.sl_create(num_threads, stage_h, stage_w)
        if not self._handle:
            raise RuntimeError("sl_create failed")

    def load_batch(
        self,
        paths: list[str],
        out: np.ndarray | None = None,
        extents: np.ndarray | None = None,
    ) -> tuple[np.ndarray, np.ndarray, int]:
        """Decode `paths` in parallel →
        (`[n, H, W, 3] uint8`, `[n, 3] int32 (h, w, rot)`, n_failures).
        Failed images come back as zero canvases with full-canvas extent.

        `out`/`extents` let the caller own the destination (ISSUE 3: staging
        workers hand in disjoint row ranges of a shared pooled canvas, so the
        decode writes land in place with no per-image Python round-trips and
        no assembly copy). They must be C-contiguous with the exact shapes
        below; omitted, fresh arrays are allocated."""
        n = len(paths)
        if out is None:
            out = np.empty((n, self.stage_h, self.stage_w, 3), dtype=np.uint8)
        if extents is None:
            extents = np.empty((n, 3), dtype=np.int32)
        if out.shape != (n, self.stage_h, self.stage_w, 3) or out.dtype != np.uint8:
            raise ValueError(
                f"out must be uint8 [{n}, {self.stage_h}, {self.stage_w}, 3], "
                f"got {out.dtype} {out.shape}"
            )
        if extents.shape != (n, 3) or extents.dtype != np.int32:
            raise ValueError(f"extents must be int32 [{n}, 3], got "
                             f"{extents.dtype} {extents.shape}")
        if not out.flags.c_contiguous or not extents.flags.c_contiguous:
            raise ValueError("out/extents must be C-contiguous")
        arr = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
        failures = self._lib.sl_load_batch(
            self._handle,
            arr,
            n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            extents.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        failures = int(failures)
        with self._meter_lock:
            self.total_images += n
            if failures:
                self.total_failures += failures
        if failures:
            log_event(
                "data",
                f"native decode: {failures}/{n} failure(s) in batch "
                f"(cumulative {self.total_failures}/{self.total_images})",
            )
        return out, extents, failures

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.sl_destroy(handle)
            self._handle = None
