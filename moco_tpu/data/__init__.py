"""moco_tpu.data — input pipelines (datasets, host staging, augmentation)
plus the disaggregated input service (ISSUE 14) under `data/service/`.

This __init__ is LAZY (PEP 562, the telemetry/serve __init__ pattern):
the input-service control plane (`data/service/server.py`,
`tools/staging_server.py`) is PURE stdlib by contract — the mocolint R11
`staging-server-stdlib-only` boundary walks ancestor __init__s, and an
eager `from moco_tpu.data.augment import ...` here would drag jax into
every staging-server supervisor process. Each public name resolves its
submodule on first attribute access, so `from moco_tpu.data import
epoch_loader` keeps working unchanged while `import
moco_tpu.data.service.protocol` touches nothing heavy."""

from __future__ import annotations

import importlib

# public name -> submodule that defines it
_EXPORTS = {
    "AugConfig": "augment",
    "augment_batch": "augment",
    "build_two_crops_sharded": "augment",
    "aug_config_for": "augment",
    "eval_aug_config": "augment",
    "two_crops": "augment",
    "v1_aug_config": "augment",
    "v2_aug_config": "augment",
    "v3_aug_configs": "augment",
    "CachedDataset": "canvas_cache",
    "CIFAR10": "datasets",
    "ImageFolder": "datasets",
    "SyntheticDataset": "datasets",
    "build_dataset": "datasets",
    "Prefetcher": "loader",
    "epoch_loader": "loader",
    "epoch_permutation": "loader",
    "host_shard": "loader",
    "InputPipelineStats": "stats",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        submodule = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    value = getattr(
        importlib.import_module(f"{__name__}.{submodule}"), name
    )
    globals()[name] = value  # cache: later accesses skip __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
