from moco_tpu.data.augment import (
    AugConfig,
    augment_batch,
    build_two_crops_sharded,
    aug_config_for,
    eval_aug_config,
    two_crops,
    v1_aug_config,
    v2_aug_config,
    v3_aug_configs,
)
from moco_tpu.data.canvas_cache import CachedDataset
from moco_tpu.data.datasets import CIFAR10, ImageFolder, SyntheticDataset, build_dataset
from moco_tpu.data.loader import Prefetcher, epoch_loader, epoch_permutation, host_shard
from moco_tpu.data.stats import InputPipelineStats

__all__ = [
    "AugConfig",
    "augment_batch",
    "build_two_crops_sharded",
    "aug_config_for",
    "eval_aug_config",
    "two_crops",
    "v1_aug_config",
    "v2_aug_config",
    "v3_aug_configs",
    "CachedDataset",
    "CIFAR10",
    "ImageFolder",
    "InputPipelineStats",
    "SyntheticDataset",
    "build_dataset",
    "Prefetcher",
    "epoch_loader",
    "epoch_permutation",
    "host_shard",
]
