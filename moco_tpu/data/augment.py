"""On-device MoCo augmentation stacks (layer L2; rebuild of
`main_moco.py:≈L216-244` + `moco/loader.py`).

The reference runs PIL transforms in 32 DataLoader worker processes —
SURVEY §7 ranks that host pipeline the likely wall-clock bottleneck at TPU
throughput. TPU-first redesign: the host only decodes/stages uint8 images;
ALL randomized augmentation (random-resized-crop, flip, color jitter,
grayscale, Gaussian blur, normalize) runs on device as one vmapped, jitted,
static-shaped program fused by XLA — and `TwoCropsTransform`'s two
independent draws (`moco/loader.py:≈L8-18`) become two calls with split PRNG
keys.

Reproduced parameterizations:
- v1 aug (`main_moco.py:≈L232-244`): RRC(scale 0.2-1) + grayscale p=.2 +
  jitter(.4,.4,.4,.4) always + hflip.
- v2 `--aug-plus` (`≈L216-231`, SimCLR-style): RRC + jitter(.4,.4,.4,.1)
  p=.8 + grayscale p=.2 + blur(sigma U(.1,2)) p=.5 + hflip.
- Normalize with ImageNet mean/std.

Static-shape tricks: the variable-size crop is `jax.image.scale_and_translate`
(crop+resize in one fixed-shape bilinear op); blur uses a fixed-width
separable kernel whose WEIGHTS carry the per-sample sigma.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# numpy (not jnp): module-level device arrays would initialize the JAX
# backend at import time, breaking late force_cpu_devices() platform selection
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


class AugConfig(NamedTuple):
    out_size: int = 224
    min_scale: float = 0.2
    max_scale: float = 1.0
    brightness: float = 0.4
    contrast: float = 0.4
    saturation: float = 0.4
    hue: float = 0.4              # v2 uses 0.1
    jitter_prob: float = 1.0      # v2 uses 0.8
    grayscale_prob: float = 0.2
    blur_prob: float = 0.0        # v2 uses 0.5
    blur_sigma: tuple[float, float] = (0.1, 2.0)
    flip_prob: float = 0.5
    deterministic: bool = False   # eval: fixed-aspect center crop, no randomness


def v1_aug_config(out_size: int = 224) -> AugConfig:
    return AugConfig(out_size=out_size)


def v2_aug_config(out_size: int = 224) -> AugConfig:
    return AugConfig(out_size=out_size, hue=0.1, jitter_prob=0.8, blur_prob=0.5)


def eval_aug_config(out_size: int = 224) -> AugConfig:
    """Deterministic eval transform: resize(256/224 ratio) + center crop —
    approximated as a fixed full-ish center crop; randomness disabled."""
    return AugConfig(
        out_size=out_size, min_scale=0.875**2, max_scale=0.875**2,
        jitter_prob=0.0, grayscale_prob=0.0, blur_prob=0.0, flip_prob=0.0,
        brightness=0.0, contrast=0.0, saturation=0.0, hue=0.0,
        deterministic=True,
    )


# --------------------------------------------------------------------------
# color helpers (single image [H, W, 3], float32 in [0, 1])
# --------------------------------------------------------------------------


def _rgb_to_hsv(rgb):
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = jnp.max(rgb, axis=-1)
    minc = jnp.min(rgb, axis=-1)
    v = maxc
    delta = maxc - minc
    safe_delta = jnp.where(delta == 0, 1.0, delta)
    s = jnp.where(maxc == 0, 0.0, delta / jnp.where(maxc == 0, 1.0, maxc))
    rc = (maxc - r) / safe_delta
    gc = (maxc - g) / safe_delta
    bc = (maxc - b) / safe_delta
    h = jnp.where(
        maxc == r, bc - gc, jnp.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc)
    )
    h = jnp.where(delta == 0, 0.0, h / 6.0) % 1.0
    return jnp.stack([h, s, v], axis=-1)


def _hsv_to_rgb(hsv):
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    i = jnp.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(jnp.int32) % 6

    def pick(c0, c1, c2, c3, c4, c5):
        # select chain, NOT jnp.choose: choose lowers to per-element gathers,
        # which measured ~35x slower than vectorized selects on TPU
        return jnp.where(
            i == 0, c0,
            jnp.where(i == 1, c1,
                      jnp.where(i == 2, c2,
                                jnp.where(i == 3, c3, jnp.where(i == 4, c4, c5)))),
        )

    r = pick(v, q, p, p, t, v)
    g = pick(t, v, v, q, p, p)
    b = pick(p, p, t, v, v, q)
    return jnp.stack([r, g, b], axis=-1)


def _color_jitter(img, key, cfg: AugConfig):
    kb, kc, ks, kh, kp = jax.random.split(key, 5)
    # torchvision samples each factor from U(max(0,1-x), 1+x)
    def factor(k, x):
        return jax.random.uniform(k, (), minval=max(0.0, 1.0 - x), maxval=1.0 + x)

    out = img * factor(kb, cfg.brightness)                      # brightness
    mean_gray = jnp.mean(_grayscale(out))
    out = (out - mean_gray) * factor(kc, cfg.contrast) + mean_gray  # contrast
    gray = _grayscale(out)[..., None]
    out = (out - gray) * factor(ks, cfg.saturation) + gray      # saturation
    if cfg.hue > 0:
        shift = jax.random.uniform(kh, (), minval=-cfg.hue, maxval=cfg.hue)
        hsv = _rgb_to_hsv(jnp.clip(out, 0.0, 1.0))
        hsv = hsv.at[..., 0].set((hsv[..., 0] + shift) % 1.0)
        out = _hsv_to_rgb(hsv)
    out = jnp.clip(out, 0.0, 1.0)
    apply = jax.random.uniform(kp, ()) < cfg.jitter_prob
    return jnp.where(apply, out, img)


def _grayscale(img):
    # ITU-R 601-2 luma, the PIL 'L' conversion torchvision uses
    return img[..., 0] * 0.299 + img[..., 1] * 0.587 + img[..., 2] * 0.114


def _random_grayscale(img, key, cfg: AugConfig):
    apply = jax.random.uniform(key, ()) < cfg.grayscale_prob
    gray = jnp.broadcast_to(_grayscale(img)[..., None], img.shape)
    return jnp.where(apply, gray, img)


def _gaussian_blur(img, key, cfg: AugConfig):
    ksig, kp = jax.random.split(key)
    sigma = jax.random.uniform(
        ksig, (), minval=cfg.blur_sigma[0], maxval=cfg.blur_sigma[1]
    )
    radius = max(1, int(0.05 * cfg.out_size))  # fixed width; weights carry sigma
    offs = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    kernel = jnp.exp(-0.5 * (offs / sigma) ** 2)
    kernel = kernel / jnp.sum(kernel)
    # Separable blur as weighted shifted-adds over STATIC slices. Two designs
    # were measured and rejected on the v5e: slice-stack + einsum fuses the
    # whole upstream jitter chain into every tap (~20x recompute), and a
    # grouped `conv_general_dilated` autotunes nondeterministically (12 ms or
    # 180 ms depending on compilation). Shifted-adds behind an
    # optimization_barrier are deterministic ALU/bandwidth work.
    img_b = jax.lax.optimization_barrier(img)

    def conv1d(x, axis):
        pad = [(0, 0)] * 3
        pad[axis] = (radius, radius)
        padded = jnp.pad(x, pad, mode="edge")
        acc = jnp.zeros_like(x)
        n = x.shape[axis]
        for i in range(2 * radius + 1):
            sl = [slice(None)] * 3
            sl[axis] = slice(i, i + n)
            acc = acc + kernel[i] * padded[tuple(sl)]
        return acc

    blurred = conv1d(conv1d(img_b, 0), 1)
    apply = jax.random.uniform(kp, ()) < cfg.blur_prob
    return jnp.where(apply, blurred, img)


def _random_resized_crop(img, key, cfg: AugConfig):
    """torchvision RandomResizedCrop semantics (scale=(s0,s1), ratio 3/4..4/3)
    as a single fixed-shape `scale_and_translate` (crop+bilinear resize)."""
    h, w = img.shape[0], img.shape[1]
    karea, kaspect, ky, kx = jax.random.split(key, 4)
    area = h * w * jax.random.uniform(
        karea, (), minval=cfg.min_scale, maxval=cfg.max_scale
    )
    if cfg.deterministic:
        ratio = jnp.asarray(1.0)
    else:
        log_ratio = jax.random.uniform(
            kaspect, (), minval=jnp.log(3.0 / 4.0), maxval=jnp.log(4.0 / 3.0)
        )
        ratio = jnp.exp(log_ratio)
    cw = jnp.clip(jnp.sqrt(area * ratio), 1.0, w)
    ch = jnp.clip(jnp.sqrt(area / ratio), 1.0, h)
    if cfg.deterministic:
        y0, x0 = (h - ch) / 2.0, (w - cw) / 2.0  # center crop
    else:
        y0 = jax.random.uniform(ky, (), minval=0.0, maxval=1.0) * (h - ch)
        x0 = jax.random.uniform(kx, (), minval=0.0, maxval=1.0) * (w - cw)
    s = cfg.out_size
    scale = jnp.array([s / ch, s / cw])
    translation = jnp.array([-y0 * s / ch, -x0 * s / cw])
    return jax.image.scale_and_translate(
        img,
        (s, s, img.shape[2]),
        (0, 1),
        scale,
        translation,
        method="linear",
        antialias=True,
    )


def _random_flip(img, key, cfg: AugConfig):
    apply = jax.random.uniform(key, ()) < cfg.flip_prob
    return jnp.where(apply, img[:, ::-1, :], img)


def _augment_one(img_u8, key, cfg: AugConfig):
    img = img_u8.astype(jnp.float32) / 255.0
    kcrop, kjit, kgray, kblur, kflip = jax.random.split(key, 5)
    img = _random_resized_crop(img, kcrop, cfg)
    if cfg.jitter_prob > 0:
        img = _color_jitter(img, kjit, cfg)
    if cfg.grayscale_prob > 0:
        img = _random_grayscale(img, kgray, cfg)
    if cfg.blur_prob > 0:
        img = _gaussian_blur(img, kblur, cfg)
    img = _random_flip(img, kflip, cfg)
    return (img - IMAGENET_MEAN) / IMAGENET_STD


@functools.partial(jax.jit, static_argnames=("cfg",))
def augment_batch(images_u8: jax.Array, key: jax.Array, cfg: AugConfig) -> jax.Array:
    """`[B, H, W, 3] uint8 → [B, S, S, 3] float32` — one independent random
    draw per sample (vmapped keys)."""
    keys = jax.random.split(key, images_u8.shape[0])
    return jax.vmap(_augment_one, in_axes=(0, 0, None))(images_u8, keys, cfg)


@functools.partial(jax.jit, static_argnames=("cfg",))
def two_crops(images_u8: jax.Array, key: jax.Array, cfg: AugConfig):
    """The `TwoCropsTransform`: two INDEPENDENT draws of the same pipeline
    (`moco/loader.py:≈L8-18`) → `(im_q, im_k)`, one jitted program.

    Deliberately two [B] vmapped draws, NOT a concatenated [2B] pass: with
    the batch sharded P('data'), `concatenate([x, x], 0)` makes GSPMD
    reshard the whole batch across chips every step (measured: 12
    collective-permutes + 20 all-to-alls in the compiled HLO vs ZERO for
    this form)."""
    kq, kk = jax.random.split(key)
    return augment_batch(images_u8, kq, cfg), augment_batch(images_u8, kk, cfg)
