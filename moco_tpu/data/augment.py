"""On-device MoCo augmentation stacks (layer L2; rebuild of
`main_moco.py:≈L216-244` + `moco/loader.py`).

The reference runs PIL transforms in 32 DataLoader worker processes —
SURVEY §7 ranks that host pipeline the likely wall-clock bottleneck at TPU
throughput. TPU-first redesign: the host only decodes/stages uint8 images;
ALL randomized augmentation (random-resized-crop, flip, color jitter,
grayscale, Gaussian blur, normalize) runs on device as one vmapped, jitted,
static-shaped program fused by XLA — and `TwoCropsTransform`'s two
independent draws (`moco/loader.py:≈L8-18`) become two calls with split PRNG
keys.

Reproduced parameterizations:
- v1 aug (`main_moco.py:≈L232-244`): RRC(scale 0.2-1) + grayscale p=.2 +
  jitter(.4,.4,.4,.4) always + hflip.
- v2 `--aug-plus` (`≈L216-231`, SimCLR-style): RRC + jitter(.4,.4,.4,.1)
  p=.8 + grayscale p=.2 + blur(sigma U(.1,2)) p=.5 + hflip.
- Normalize with ImageNet mean/std.

Static-shape tricks: the variable-size crop is dense-matmul resampling on
the MXU (`ops/matmul_resize.py`, crop+antialiased-bilinear resize as two
fixed-shape contractions); blur uses a fixed-width separable kernel whose
WEIGHTS carry the per-sample sigma.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from moco_tpu.utils.compat import optimization_barrier, shard_map
import numpy as np

# numpy (not jnp): module-level device arrays would initialize the JAX
# backend at import time, breaking late force_cpu_devices() platform selection
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)
IMAGENET_INV_STD = (1.0 / IMAGENET_STD).astype(np.float32)


class AugConfig(NamedTuple):
    out_size: int = 224
    min_scale: float = 0.2
    max_scale: float = 1.0
    brightness: float = 0.4
    contrast: float = 0.4
    saturation: float = 0.4
    hue: float = 0.4              # v2 uses 0.1
    jitter_prob: float = 1.0      # v2 uses 0.8
    grayscale_prob: float = 0.2
    blur_prob: float = 0.0        # v2 uses 0.5
    blur_sigma: tuple[float, float] = (0.1, 2.0)
    flip_prob: float = 0.5
    solarize_prob: float = 0.0    # v3's second view uses 0.2 (threshold 0.5)
    deterministic: bool = False   # eval: fixed-aspect center crop, no randomness
    pallas_blur: str = "auto"     # auto (TPU only) | on | off — see ops/pallas_blur.py
    grayscale_first: bool = False  # v1 applies RandomGrayscale BEFORE ColorJitter
    rrc_trials: int = 10          # torchvision get_params rejection-sampling draws
    crop_frac: float = 0.875      # deterministic eval: center-crop fraction of
                                  # min(h, w) — 224/256 for the ImageNet protocol,
                                  # 1.0 for the community CIFAR protocol
    dtype: str = "float32"        # image math dtype; "bfloat16" halves the
                                  # pipeline's HBM traffic on TPU (quantization
                                  # ~2^-8 ≈ the u8 source precision; per-pixel
                                  # HSV math stays f32 inside fusions)


def v1_aug_config(out_size: int = 224) -> AugConfig:
    # v1 op order (`main_moco.py:≈L232-244`): RRC → RandomGrayscale →
    # ColorJitter(always) → flip — grayscale BEFORE jitter, unlike v2
    return AugConfig(out_size=out_size, grayscale_first=True)


def v2_aug_config(out_size: int = 224) -> AugConfig:
    return AugConfig(out_size=out_size, hue=0.1, jitter_prob=0.8, blur_prob=0.5)


def aug_config_for(config):
    """The ONE variant→aug-recipe selection, shared by the train driver and
    benchkit so a benchmark can never time an aug stack the driver would
    not run (review, r5): v3 → asymmetric pair (crop_min is the repo's
    --crop-min knob), v2/aug_plus → blur+hue stack, else the v1 recipe."""
    if config.variant == "v3":
        return v3_aug_configs(config.image_size,
                              min_scale=config.crop_min or 0.08)
    if config.aug_plus:
        return v2_aug_config(config.image_size)
    return v1_aug_config(config.image_size)


def v3_aug_configs(
    out_size: int = 224, min_scale: float = 0.08
) -> tuple[AugConfig, AugConfig]:
    """moco-v3's ASYMMETRIC per-view recipes (BYOL-style; sibling repo
    `main_moco.py` augmentation1/augmentation2): both views use
    jitter(.4,.4,.2,.1) p=.8 + grayscale .2 + flip, but view 1 always blurs
    (p=1.0) while view 2 rarely blurs (p=.1) and solarizes (p=.2).
    `min_scale` is the repo's `--crop-min` (0.08 ViT default, 0.2 for R50)."""
    base = AugConfig(
        out_size=out_size, min_scale=min_scale, saturation=0.2, hue=0.1,
        jitter_prob=0.8, grayscale_prob=0.2,
    )
    return (
        base._replace(blur_prob=1.0),
        base._replace(blur_prob=0.1, solarize_prob=0.2),
    )


def eval_aug_config(out_size: int = 224, crop_frac: float = 0.875) -> AugConfig:
    """Deterministic eval transform. `crop_frac=0.875` reproduces
    resize(256) → center-crop(224) exactly: that pipeline crops the centered
    square of side `min(h, w) * 224/256` from the original image. CIFAR-style
    protocols evaluate the FULL image — pass `crop_frac=1.0`
    (`default_eval_crop_frac` keys this off the image size)."""
    return AugConfig(
        out_size=out_size, crop_frac=crop_frac,
        jitter_prob=0.0, grayscale_prob=0.0, blur_prob=0.0, flip_prob=0.0,
        brightness=0.0, contrast=0.0, saturation=0.0, hue=0.0,
        deterministic=True,
    )


def default_eval_crop_frac(image_size: int) -> float:
    """Community protocol split: small-image datasets (CIFAR) evaluate the
    full image; ImageNet-scale uses the 224/256 center crop."""
    return 1.0 if image_size < 96 else 0.875


def with_dtype(cfg, dtype: str):
    """Set the pipeline dtype on a single AugConfig or a v3 view pair.
    (AugConfig IS a NamedTuple — the isinstance check must come first.)"""
    if isinstance(cfg, AugConfig):
        return cfg._replace(dtype=dtype)
    return tuple(c._replace(dtype=dtype) for c in cfg)


# --------------------------------------------------------------------------
# color helpers (single image [H, W, 3], float32 in [0, 1])
# --------------------------------------------------------------------------


def _rgb_to_hsv(rgb):
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = jnp.max(rgb, axis=-1)
    minc = jnp.min(rgb, axis=-1)
    v = maxc
    delta = maxc - minc
    safe_delta = jnp.where(delta == 0, 1.0, delta)
    s = jnp.where(maxc == 0, 0.0, delta / jnp.where(maxc == 0, 1.0, maxc))
    rc = (maxc - r) / safe_delta
    gc = (maxc - g) / safe_delta
    bc = (maxc - b) / safe_delta
    h = jnp.where(
        maxc == r, bc - gc, jnp.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc)
    )
    h = jnp.where(delta == 0, 0.0, h / 6.0) % 1.0
    return jnp.stack([h, s, v], axis=-1)


def _hsv_to_rgb(hsv):
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    i = jnp.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(jnp.int32) % 6

    def pick(c0, c1, c2, c3, c4, c5):
        # select chain, NOT jnp.choose: choose lowers to per-element gathers,
        # which measured ~35x slower than vectorized selects on TPU
        return jnp.where(
            i == 0, c0,
            jnp.where(i == 1, c1,
                      jnp.where(i == 2, c2,
                                jnp.where(i == 3, c3, jnp.where(i == 4, c4, c5)))),
        )

    r = pick(v, q, p, p, t, v)
    g = pick(t, v, v, q, p, p)
    b = pick(p, p, t, v, v, q)
    return jnp.stack([r, g, b], axis=-1)


def _jitter_ops(factors, hue_shift, use_hue: bool):
    """The four ColorJitter sub-ops as closures over their sampled factors.
    Each clamps to [0, 1] like torchvision's `_blend` (float path). Same
    dtype discipline as the fast path: blends in the pipeline dtype,
    contrast mean and the HSV round-trip in f32."""
    fb, fc, fs = factors

    def brightness(x):
        return jnp.clip(x * fb.astype(x.dtype), 0.0, 1.0)

    def contrast(x):
        m = jnp.mean(_grayscale(x), dtype=jnp.float32).astype(x.dtype)
        return jnp.clip((x - m) * fc.astype(x.dtype) + m, 0.0, 1.0)

    def saturation(x):
        g = _grayscale(x)[..., None]
        return jnp.clip((x - g) * fs.astype(x.dtype) + g, 0.0, 1.0)

    if use_hue:
        def hue(x):
            hsv = _rgb_to_hsv(x.astype(jnp.float32))
            hsv = hsv.at[..., 0].set((hsv[..., 0] + hue_shift) % 1.0)
            return _hsv_to_rgb(hsv).astype(x.dtype)
    else:
        def hue(x):
            return x

    return [brightness, contrast, saturation, hue]


def _apply_jitter_ops(img, factors, hue_shift, perm, use_hue: bool):
    """REFERENCE implementation: apply the 4 sub-ops in `perm` order via
    `lax.switch`. Semantically exact but slow under vmap (every slot computes
    all four candidates, incl. 4 HSV round-trips) — production uses
    `_apply_jitter_ops_fast`, pinned equivalent by
    tests/test_data.py::test_fast_jitter_matches_switch_form."""
    ops = _jitter_ops(factors, hue_shift, use_hue)
    out = img
    for step in range(4):
        out = jax.lax.switch(perm[step], ops, out)
    return out


def _apply_jitter_ops_fast(img, factors, hue_shift, perm, use_hue: bool):
    """Same math as `_apply_jitter_ops`, restructured for the vmapped/TPU
    path. A uniform randperm(4) factors exactly into (position of hue,
    order of the 3 cheap ops); hue — the only expensive op (two HSV
    conversions) — then runs exactly ONCE, and the cheap ops collapse into a
    unified blend `clip(f·x + (1-f)·m)` with `m ∈ {0, mean_gray, gray}`
    (torchvision's `_blend` targets for brightness/contrast/saturation),
    applied conditionally by folding inactive slots to `f=1`."""
    fb, fc, fs = factors
    # chain order: positions of the cheap ops among the 4 slots, in order;
    # h_rank = how many cheap ops precede hue
    cheap_pos = jnp.argsort(jnp.where(perm == 3, 99, jnp.arange(4)))[:3]
    c_ops = perm[cheap_pos]
    h_rank = jnp.argmax(perm == 3)
    f_by_op = jnp.stack([fb, fc, fs])

    def cheap_apply(x, op, active):
        g = _grayscale(x)
        # contrast's mean in f32 (bf16 mean over ~50k pixels loses bits),
        # cast back so the blend stays in the pipeline dtype
        mean_g = jnp.mean(g, dtype=jnp.float32).astype(x.dtype)
        m = jnp.where(
            op == 0, x.dtype.type(0.0), jnp.where(op == 1, mean_g, x.dtype.type(0.0))
        ) + jnp.where(op == 2, x.dtype.type(1.0), x.dtype.type(0.0)) * g[..., None]
        f = jnp.where(active, f_by_op[op], 1.0).astype(x.dtype)
        return jnp.clip(f * x + (1.0 - f) * m, 0.0, 1.0)

    out = img
    for j in range(3):
        out = cheap_apply(out, c_ops[j], j < h_rank)
    if use_hue:
        # HSV math in f32 (piecewise selects are precision-sensitive); the
        # converts fuse — no extra HBM traffic
        hsv = _rgb_to_hsv(out.astype(jnp.float32))
        hsv = hsv.at[..., 0].set((hsv[..., 0] + hue_shift) % 1.0)
        out = _hsv_to_rgb(hsv).astype(img.dtype)
    for j in range(3):
        out = cheap_apply(out, c_ops[j], j >= h_rank)
    return out


def _color_jitter(img, key, cfg: AugConfig):
    kb, kc, ks, kh, kp, kperm = jax.random.split(key, 6)

    # torchvision samples each factor from U(max(0,1-x), 1+x)
    def factor(k, x):
        return jax.random.uniform(k, (), minval=max(0.0, 1.0 - x), maxval=1.0 + x)

    factors = (
        factor(kb, cfg.brightness),
        factor(kc, cfg.contrast),
        factor(ks, cfg.saturation),
    )
    use_hue = cfg.hue > 0
    hue_shift = (
        jax.random.uniform(kh, (), minval=-cfg.hue, maxval=cfg.hue)
        if use_hue
        else jnp.float32(0.0)
    )
    # torchvision's ColorJitter draws randperm(4) per call — the sub-op ORDER
    # is part of the augmentation distribution (VERDICT r1 weak #3)
    perm = jax.random.permutation(kperm, 4)
    out = _apply_jitter_ops_fast(img, factors, hue_shift, perm, use_hue)
    apply = jax.random.uniform(kp, ()) < cfg.jitter_prob
    return jnp.where(apply, out, img)


def _grayscale(img):
    # ITU-R 601-2 luma, the PIL 'L' conversion torchvision uses
    return img[..., 0] * 0.299 + img[..., 1] * 0.587 + img[..., 2] * 0.114


def _random_grayscale(img, key, cfg: AugConfig):
    apply = jax.random.uniform(key, ()) < cfg.grayscale_prob
    gray = jnp.broadcast_to(_grayscale(img)[..., None], img.shape)
    return jnp.where(apply, gray, img)


def _gaussian_blur(img, key, cfg: AugConfig):
    from moco_tpu.ops.pallas_blur import blur_radius, blur_weights

    radius = blur_radius(cfg.out_size)
    # sigma + apply-probability sampling shared with the Pallas path (one
    # source of truth; skip == identity kernel, so it is applied unconditionally)
    kernel = blur_weights(key, radius, cfg.blur_sigma, cfg.blur_prob).astype(img.dtype)
    # Separable blur as weighted shifted-adds over STATIC slices. Two designs
    # were measured and rejected on the v5e: slice-stack + einsum fuses the
    # whole upstream jitter chain into every tap (~20x recompute), and a
    # grouped `conv_general_dilated` autotunes nondeterministically (12 ms or
    # 180 ms depending on compilation). Shifted-adds behind an
    # optimization_barrier are deterministic ALU/bandwidth work.
    img_b = optimization_barrier(img)

    def conv1d(x, axis):
        pad = [(0, 0)] * 3
        pad[axis] = (radius, radius)
        padded = jnp.pad(x, pad, mode="edge")
        acc = jnp.zeros_like(x)
        n = x.shape[axis]
        for i in range(2 * radius + 1):
            sl = [slice(None)] * 3
            sl[axis] = slice(i, i + n)
            acc = acc + kernel[i] * padded[tuple(sl)]
        return acc

    return conv1d(conv1d(img_b, 0), 1)


def _rrc_params(key, ext_h, ext_w, cfg: AugConfig):
    """Crop box `(y0, x0, ch, cw)` with torchvision `get_params` semantics
    over a (possibly per-sample) valid region `[0, ext_h) × [0, ext_w)`:

    - deterministic: centered square of side `crop_frac * min(h, w)` — the
      exact region resize(256)→center-crop(224) reads from the original.
    - else: `rrc_trials` (area, log-ratio) rejection draws, first in-bounds
      one wins; if none fits, torchvision's fallback — aspect clamped to
      [3/4, 4/3], centered. Statically shaped: all trials are drawn, the
      winner is selected by `argmax` over the validity mask.
    """
    ext_h = jnp.asarray(ext_h, jnp.float32)
    ext_w = jnp.asarray(ext_w, jnp.float32)
    if cfg.deterministic:
        side = cfg.crop_frac * jnp.minimum(ext_h, ext_w)
        return (ext_h - side) / 2.0, (ext_w - side) / 2.0, side, side
    karea, kratio, ky, kx = jax.random.split(key, 4)
    n = cfg.rrc_trials
    area = ext_h * ext_w * jax.random.uniform(
        karea, (n,), minval=cfg.min_scale, maxval=cfg.max_scale
    )
    log_ratio = jax.random.uniform(
        kratio, (n,), minval=np.log(3.0 / 4.0), maxval=np.log(4.0 / 3.0)
    )
    ratio = jnp.exp(log_ratio)
    ws = jnp.sqrt(area * ratio)
    hs = jnp.sqrt(area / ratio)
    valid = (ws <= ext_w) & (hs <= ext_h) & (ws >= 1.0) & (hs >= 1.0)
    idx = jnp.argmax(valid)  # first accepted draw (argmax → first True)
    ok = jnp.any(valid)
    # fallback (torchvision): clamp the IMAGE aspect into [3/4, 4/3], centered
    in_ratio = ext_w / ext_h
    fb_w = jnp.where(
        in_ratio < 0.75, ext_w, jnp.where(in_ratio > 4.0 / 3.0, ext_h * (4.0 / 3.0), ext_w)
    )
    fb_h = jnp.where(
        in_ratio < 0.75, ext_w / 0.75, jnp.where(in_ratio > 4.0 / 3.0, ext_h, ext_h)
    )
    cw = jnp.where(ok, ws[idx], fb_w)
    ch = jnp.where(ok, hs[idx], fb_h)
    y0 = jnp.where(ok, jax.random.uniform(ky) * (ext_h - ch), (ext_h - ch) / 2.0)
    x0 = jnp.where(ok, jax.random.uniform(kx) * (ext_w - cw), (ext_w - cw) / 2.0)
    return y0, x0, ch, cw


def _random_resized_crop(img, key, cfg: AugConfig, extent, flip_key=None):
    """torchvision RandomResizedCrop as fixed-shape dense-matmul resampling
    (crop + antialiased bilinear).

    `extent = (valid_h, valid_w, rot)`: the image content occupies the
    top-left `[valid_h, valid_w]` of the staged canvas (edge-replicated
    outside), and `rot=1` marks portrait images staged TRANSPOSED so one
    landscape canvas shape serves both orientations. The crop is sampled in
    staged coordinates and the output transposed back — exactly equivalent
    to sampling the original orientation, since the ratio distribution is
    symmetric (log-uniform) and the resample filter separable.

    `flip_key` folds the horizontal flip INTO the resample matrix (reversing
    the output-axis sampling rows) — bit-equivalent to flipping the crop
    afterwards, minus one full-image reverse+select pass per view. Every
    later op commutes with the flip: jitter/grayscale/solarize are
    pixelwise and the Gaussian blur kernel is symmetric."""
    y0, x0, ch, cw = _rrc_params(key, extent[0], extent[1], cfg)
    rot = extent[2] > 0
    if flip_key is not None and cfg.flip_prob > 0:
        flip = jax.random.uniform(flip_key, ()) < cfg.flip_prob
    else:
        flip = jnp.asarray(False)
    # a horizontal flip of the FINAL image flips the staged W axis for
    # normal samples, but the staged H axis for rot-staged (transposed) ones
    flip_v = jnp.logical_and(flip, rot)
    flip_h = jnp.logical_and(flip, jnp.logical_not(rot))
    # crop+resize as two dense matmuls (MXU) instead of gather-based
    # `scale_and_translate` — measured ~5x faster on the v5e for the same
    # separable triangle-filter math (see ops/matmul_resize.py)
    from moco_tpu.ops.matmul_resize import crop_resize

    out = crop_resize(
        img, y0, x0, ch, cw, cfg.out_size, antialias=True,
        valid_h=jnp.asarray(extent[0], jnp.float32),
        valid_w=jnp.asarray(extent[1], jnp.float32),
        flip_v=flip_v, flip_h=flip_h,
    )
    return jnp.where(rot, jnp.swapaxes(out, 0, 1), out)


def _random_solarize(img, key, cfg: AugConfig):
    """Invert pixels above 0.5 (torchvision RandomSolarize(threshold=128))."""
    apply = jax.random.uniform(key, ()) < cfg.solarize_prob
    sol = jnp.where(img >= 0.5, 1.0 - img, img)
    return jnp.where(apply, sol, img)


def _augment_one(img_u8, key, extent, cfg: AugConfig, skip_blur: bool = False):
    dt = jnp.dtype(cfg.dtype)
    img = img_u8.astype(dt) / dt.type(255.0)
    kcrop, kjit, kgray, kblur, kflip, ksol = jax.random.split(key, 6)
    # flip is folded into the crop's resample matrix (see _random_resized_crop)
    img = _random_resized_crop(img, kcrop, cfg, extent, flip_key=kflip)
    if cfg.grayscale_first:
        # v1 order (`main_moco.py:≈L232-244`): grayscale precedes jitter —
        # saturation/hue jitter on an already-gray image is a no-op, so the
        # two orders produce genuinely different distributions
        if cfg.grayscale_prob > 0:
            img = _random_grayscale(img, kgray, cfg)
        if cfg.jitter_prob > 0:
            img = _color_jitter(img, kjit, cfg)
    else:
        if cfg.jitter_prob > 0:
            img = _color_jitter(img, kjit, cfg)
        if cfg.grayscale_prob > 0:
            img = _random_grayscale(img, kgray, cfg)
    if cfg.blur_prob > 0 and not skip_blur:
        img = _gaussian_blur(img, kblur, cfg)
    if cfg.solarize_prob > 0:
        img = _random_solarize(img, ksol, cfg)
    return (img - IMAGENET_MEAN.astype(dt)) * IMAGENET_INV_STD.astype(dt)


def _use_pallas_blur(cfg: AugConfig) -> bool:
    if cfg.blur_prob <= 0 or cfg.pallas_blur == "off":
        return False
    if cfg.solarize_prob > 0:
        # the lifted kernel applies blur AFTER the pipeline, which only
        # commutes with linear ops — solarize is nonlinear, so v3's
        # solarizing view keeps the in-pipeline (portable) blur
        return False
    if cfg.pallas_blur == "on":
        # explicit force-on wins over backend/env (the AugConfig contract:
        # auto|on|off) — this is how the CPU interpret-mode equivalence
        # tests exercise the kernel off-TPU; the r5 env_flag refactor
        # briefly dropped this branch and the tests passed vacuously
        # (review, r5)
        return True
    from moco_tpu.utils.envflags import env_flag

    # MOCO_TPU_DISABLE_PALLAS_BLUR: blur-only switch so tools/_perf_ab.py
    # can attribute step time between the Pallas families (r5); uniform
    # "0"-means-off parsing via env_flag (review, r5)
    return (jax.default_backend() == "tpu"
            and not env_flag("MOCO_TPU_DISABLE_PALLAS")
            and not env_flag("MOCO_TPU_DISABLE_PALLAS_BLUR"))


def _sample_keys(key: jax.Array, start, n: int) -> jax.Array:
    """Per-sample keys by GLOBAL sample index (`fold_in(key, start+i)`), so a
    device holding shard [start, start+n) of the batch derives exactly the
    keys the unsharded pipeline would use for those samples."""
    return jax.vmap(lambda i: jax.random.fold_in(key, i))(start + jnp.arange(n))


def _full_extent(images_u8: jax.Array) -> jax.Array:
    """Whole-canvas extent (square staging / in-memory datasets): every
    sample's valid region is the full image, unrotated."""
    b, h, w = images_u8.shape[:3]
    return jnp.broadcast_to(jnp.asarray([h, w, 0], jnp.int32), (b, 3))


def _augment_with_keys(
    images_u8: jax.Array, keys: jax.Array, cfg: AugConfig, extents: jax.Array
) -> jax.Array:
    """Core batched pipeline given explicit per-sample keys.

    When the Pallas path is active, the blur is lifted out of the per-sample
    pipeline and applied as a VMEM stencil kernel over the finished batch —
    equivalent within float32 tolerance (the symmetric sum-1 kernel commutes
    with the flip and with the affine normalize; see
    tests/test_pallas_blur.py) but one HBM round-trip instead of ~46
    shifted-add passes. Same per-sample PRNG stream either way."""
    use_pallas = _use_pallas_blur(cfg)
    out = jax.vmap(_augment_one, in_axes=(0, 0, 0, None, None))(
        images_u8, keys, extents, cfg, use_pallas
    )
    if use_pallas:
        from moco_tpu.ops.pallas_blur import (
            blur_radius,
            blur_weights,
            gaussian_blur_batch,
        )

        radius = blur_radius(cfg.out_size)
        kblurs = jax.vmap(lambda k: jax.random.split(k, 6)[3])(keys)
        weights = jax.vmap(
            lambda k: blur_weights(k, radius, cfg.blur_sigma, cfg.blur_prob)
        )(kblurs)
        out = gaussian_blur_batch(
            out, weights, radius, interpret=jax.default_backend() != "tpu"
        )
    return out


@functools.partial(jax.jit, static_argnames=("cfg",))
def augment_batch(
    images_u8: jax.Array, key: jax.Array, cfg: AugConfig, extents=None
) -> jax.Array:
    """`[B, H, W, 3] uint8 → [B, S, S, 3] float32` — one independent random
    draw per sample. `extents` is an optional `[B, 3] (h, w, rot)` array for
    rectangle-staged batches (ImageFolder); None means the full canvas."""
    if extents is None:
        extents = _full_extent(images_u8)
    return _augment_with_keys(
        images_u8, _sample_keys(key, 0, images_u8.shape[0]), cfg, extents
    )


@functools.partial(jax.jit, static_argnames=("cfg",))
def two_crops(images_u8: jax.Array, key: jax.Array, cfg: AugConfig, extents=None):
    """The `TwoCropsTransform`: two INDEPENDENT draws of the same pipeline
    (`moco/loader.py:≈L8-18`) → `(im_q, im_k)`, one jitted program.

    Deliberately two [B] vmapped draws, NOT a concatenated [2B] pass: with
    the batch sharded P('data'), `concatenate([x, x], 0)` makes GSPMD
    reshard the whole batch across chips every step (measured: 12
    collective-permutes + 20 all-to-alls in the compiled HLO vs ZERO for
    this form). For MULTI-chip meshes with the Pallas blur, use
    `build_two_crops_sharded` — a pallas_call has no GSPMD partitioning rule
    and would otherwise be computed on a replicated (all-gathered) batch."""
    kq, kk = jax.random.split(key)
    return (
        augment_batch(images_u8, kq, cfg, extents),
        augment_batch(images_u8, kk, cfg, extents),
    )


def build_two_crops_sharded(cfg, mesh):
    """`two_crops` as an explicit per-device shard_map program.

    Each device augments only ITS shard of the global batch, deriving
    per-sample keys from GLOBAL sample indices (`axis_index * local_b + i`),
    so the output equals the unsharded `two_crops` exactly — while every op,
    including the Pallas blur kernel, runs purely device-local (no
    collectives, no replicated batch).

    `cfg` is one AugConfig (both views identical, v1/v2) or a
    `(cfg_view1, cfg_view2)` pair (v3's asymmetric blur/solarize recipes)."""
    from jax.sharding import PartitionSpec as P

    from moco_tpu.parallel.collectives import batch_axis_index
    from moco_tpu.parallel.mesh import batch_axes

    # the batch axis set: "data" on the 1-D mesh, ("data","fsdp") on the
    # 2-D one (ISSUE 15) — global sample indices stay identical because
    # the combined index ravels in the gather's own device order
    axes = batch_axes(mesh)
    axis = axes[0] if len(axes) == 1 else axes
    if isinstance(cfg, AugConfig):  # NB: AugConfig IS a tuple — check first
        cfg_q = cfg_k = cfg
    else:
        cfg_q, cfg_k = cfg
    if jax.default_backend() != "tpu":
        # interpret-mode pallas cannot run inside a shard_map region in this
        # jax version (vma mismatch in the discharged jaxpr); the portable
        # blur is equivalent (tests/test_pallas_blur.py) so use it off-TPU
        cfg_q = cfg_q._replace(pallas_blur="off")
        cfg_k = cfg_k._replace(pallas_blur="off")

    def body(imgs, extents, key):
        local_b = imgs.shape[0]
        start = batch_axis_index(axis) * local_b
        kq, kk = jax.random.split(key)

        def crop(k, c):
            return _augment_with_keys(imgs, _sample_keys(k, start, local_b), c, extents)

        return crop(kq, cfg_q), crop(kk, cfg_k)

    sharded = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P()),
            out_specs=(P(axis), P(axis)),
        )
    )

    def fn(imgs, key, extents=None):
        if extents is None:
            from moco_tpu.data.datasets import full_extents

            b, h, w = imgs.shape[:3]
            extents = full_extents(b, h, w)
        return sharded(imgs, extents, key)

    return fn
