"""Host→device input pipeline (rebuild of `DataLoader` + `DistributedSampler`
usage in `main_moco.py:≈L228-278`; parallel/overlapped staging is ISSUE 3).

- `epoch_permutation` replaces `DistributedSampler.set_epoch`: a
  deterministic per-epoch shuffle of the whole dataset, seeded identically on
  every host; each host then takes its contiguous shard (`process_index`), so
  shards are disjoint and exhaustive — the same guarantee the reference gets
  from `DistributedSampler`.
- `Prefetcher` is a staged pipeline replacing the reference's 32 worker
  processes + `pin_memory` H2D overlap:

    coordinator thread: per batch, fan out N contiguous sub-slices to the
    staging workers → workers decode INTO disjoint rows of a pooled canvas
    (`get_batch_into` when the dataset supports it — the native path's C++
    threads then write the final bytes in place) → the coordinator issues
    the device transfer itself (per-device-shard puts as aligned sub-slices
    complete, else one sharded put) → the ready queue holds DEVICE arrays.

  So JPEG decode, canvas assembly AND the H2D transfer all hide under the
  consumer's running train step; `__iter__` only pops finished device
  batches. Batches are BIT-IDENTICAL to single-worker staging (contiguous
  sub-slices of the same index order, written to disjoint rows —
  test-enforced), and per-sub-slice retry/backoff preserves the chaos/fault
  semantics of ISSUE 1: a transient read fault in one worker retries just
  that sub-slice, without reordering or duplicating batches.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from moco_tpu.parallel.mesh import DATA_AXIS
from moco_tpu.resilience.chaos import active_chaos
from moco_tpu.telemetry.trace import null_tracer
from moco_tpu.utils.logging import log_event


def epoch_permutation(n: int, epoch: int, seed: int, global_batch: int) -> np.ndarray:
    """Deterministic epoch shuffle, truncated to whole batches (the
    reference's `drop_last=True`)."""
    rng = np.random.RandomState((seed * 100003 + epoch) % (2**31))
    perm = rng.permutation(n)
    usable = (n // global_batch) * global_batch
    return perm[:usable]


def host_shard(indices: np.ndarray, global_batch: int) -> np.ndarray:
    """This host's slice of every global batch (multi-host data sharding)."""
    nproc = jax.process_count()
    if nproc == 1:
        return indices
    if global_batch % nproc != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by process count {nproc}"
        )
    pid = jax.process_index()
    per_host = global_batch // nproc
    batches = indices.reshape(-1, global_batch)
    return batches[:, pid * per_host : (pid + 1) * per_host].reshape(-1)


class _CloseRequested(Exception):
    """Internal: the consumer called close() while a staging worker was in
    retry backoff — the worker exits quietly instead of surfacing the
    transient error as if the run had failed."""


# jax on CPU may return a zero-copy ALIAS of a numpy array from device_put
# (device memory is host memory); recycling a pooled canvas that a live
# jax.Array aliases would corrupt staged batches. Whether a given put
# aliases depends on the allocation's alignment (measured on jax 0.4.37: a
# [16,3] int32 aliased while a [16,] int32 did not), so it cannot be probed
# reliably — on CPU backends every pooled buffer is COPIED before the put.
# Real accelerators always DMA a copy, so the hot path never pays this.
_HOST_IS_DEVICE: bool | None = None


def _host_memory_is_device_memory() -> bool:
    global _HOST_IS_DEVICE
    if _HOST_IS_DEVICE is None:
        _HOST_IS_DEVICE = jax.devices()[0].platform == "cpu"
    return _HOST_IS_DEVICE


class _Canvas:
    """One preallocated staging buffer: batch images + extents + labels."""

    def __init__(self, batch: int, img_shape: tuple, img_dtype, label_dtype):
        self.imgs = np.empty((batch,) + tuple(img_shape), img_dtype)
        self.extents = np.empty((batch, 3), np.int32)
        self.labels = np.empty((batch,), label_dtype)


class _BatchCollector:
    """Per-batch completion channel: workers report each finished (or
    failed) sub-slice; the coordinator drains one event per chunk so it
    can start per-shard H2D for finished rows while other workers still
    decode."""

    def __init__(self):
        self.events: queue.Queue = queue.Queue()

    def done_ok(self, chunk_id: int) -> None:
        self.events.put((chunk_id, None))

    def done_err(self, err: BaseException) -> None:
        self.events.put((-1, err))


class Prefetcher:
    """Iterate `(images_u8, labels)` device-sharded batches with parallel
    background staging and overlapped H2D.

    `workers` > 1 requires the standard 3-tuple batch protocol
    (`images, labels, extents`); `workers=1` keeps the generic single-call
    staging path (any tuple shape). `depth` is the ready-queue capacity in
    DEVICE batches (staged ahead of the consumer). `trim_h2d` slices the
    canvas to the batch's max extent (rounded up to 64) before transfer —
    single-host only, since hosts would otherwise disagree on the global
    shape — cutting transfer bytes and downstream augment FLOPs for
    content that does not fill the canvas. `stats` is an optional
    `InputPipelineStats` receiving staging telemetry."""

    def __init__(self, dataset, indices: np.ndarray, batch_per_host: int, mesh: Mesh,
                 depth: int = 2, retries: int = 3, backoff_secs: float = 0.5,
                 join_timeout: float = 5.0, workers: int = 1, stats=None,
                 trim_h2d: bool = False, tracer=None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        # span layer (ISSUE 8): the coordinator stamps one `stage_batch`
        # span per batch; its staging workers and the per-shard H2D puts
        # continue it as detail spans. The null tracer keeps the hot path
        # branch-free when tracing is off. The coordinator THREAD has no
        # span stack of its own, so its batch spans parent under whatever
        # span the CONSTRUCTING thread held (the driver's context).
        self._tracer = tracer if tracer is not None else null_tracer()
        self._trace_parent = self._tracer.current_context()
        self.dataset = dataset
        self.indices = indices
        self.batch = batch_per_host
        self.mesh = mesh
        # leading dim split over every mesh axis (ISSUE 15: the 2-D
        # data×fsdp mesh still spans the global batch across all devices)
        from moco_tpu.parallel.mesh import batch_axes

        self.sharding = NamedSharding(mesh, P(batch_axes(mesh)))
        self.num_batches = len(indices) // batch_per_host
        self.retries = retries
        self.backoff_secs = backoff_secs
        self._join_timeout = join_timeout
        self.workers = max(1, min(int(workers), batch_per_host or 1))
        self.trim_h2d = bool(trim_h2d) and jax.process_count() == 1
        self._stats = stats
        if stats is not None:
            stats.note_workers(self.workers)
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._err: BaseException | None = None
        self._err_delivered = False
        self._free: queue.Queue = queue.Queue()  # recycled _Canvas pool
        self._tasks: queue.Queue = queue.Queue()
        self._wthreads: list[threading.Thread] = []
        if self.workers > 1:
            self._wthreads = [
                threading.Thread(target=self._worker_loop, daemon=True,
                                 name=f"staging-w{w}")
                for w in range(self.workers)
            ]
            for t in self._wthreads:
                t.start()
        self._thread = threading.Thread(target=self._coordinator, daemon=True,
                                        name="staging-coord")
        self._thread.start()

    # -- staging workers -----------------------------------------------------
    def _worker_loop(self):
        while not self._stop.is_set():
            try:
                task = self._tasks.get(timeout=0.1)
            except queue.Empty:
                continue
            b, lo, hi, idx, canvas, collector, trace_ctx = task
            try:
                # detail span continuing the coordinator's stage_batch span
                # (explicit parent: thread-locals don't cross threads)
                with self._tracer.span("decode_slice", cat="input",
                                       detail=True, parent=trace_ctx,
                                       batch=b, lo=lo, hi=hi):
                    self._read_slice_into(b, idx, canvas, lo, hi,
                                          trace_ctx=trace_ctx)
                collector.done_ok(lo)
            except BaseException as e:  # routed, not swallowed: the
                # coordinator re-raises (or exits quietly on close)
                collector.done_err(e)

    def _read_slice_into(self, b: int, idx: np.ndarray, canvas: _Canvas,
                         lo: int, hi: int, trace_ctx=None):
        """Decode `idx` into canvas rows [lo, hi) with the same
        retry-with-backoff policy as `_read_batch` — per SUB-SLICE, so a
        transient fault in one worker retries only its rows while the rest
        of the batch proceeds; batch order and content are unaffected.
        Worker-busy telemetry books only the decode attempts themselves,
        NOT the backoff sleeps — `worker_busy_frac` must read LOW during a
        flaky-storage episode (workers idle-waiting), or it would steer an
        operator away from the storage problem."""
        attempt = 0
        while True:
            t0 = time.perf_counter()
            try:
                plan = active_chaos()
                if plan is not None:
                    plan.maybe_loader_error(b)
                if hasattr(self.dataset, "get_batch_into"):
                    canvas.labels[lo:hi] = self.dataset.get_batch_into(
                        idx, canvas.imgs[lo:hi], canvas.extents[lo:hi]
                    )
                else:
                    imgs, labels, extents = self.dataset.get_batch(idx)
                    canvas.imgs[lo:hi] = imgs
                    canvas.labels[lo:hi] = labels
                    canvas.extents[lo:hi] = extents
            except OSError as e:
                if self._stats is not None:
                    self._stats.note_worker_busy(time.perf_counter() - t0)
                attempt += 1
                if attempt > self.retries:
                    raise
                delay = self.backoff_secs * (2 ** (attempt - 1))
                log_event(
                    "loader",
                    f"batch {b} rows [{lo}:{hi}) read failed "
                    f"({type(e).__name__}: {e}); retry {attempt}/"
                    f"{self.retries} in {delay:.2f}s",
                )
                if self._stop.wait(delay):
                    raise _CloseRequested() from e
                continue
            if self._stats is not None:
                self._stats.note_worker_busy(time.perf_counter() - t0)
            return

    # -- coordinator ---------------------------------------------------------
    def _coordinator(self):
        # any dataset error (corrupt file, missing path) must reach the
        # consumer — a silently-dead thread would hang training on q.get()
        try:
            for b in range(self.num_batches):
                t0 = time.perf_counter()
                with self._tracer.span("stage_batch", cat="input",
                                       parent=self._trace_parent,
                                       batch=b) as sp:
                    if self.workers > 1:
                        item = self._stage_batch_parallel(b, sp)
                    else:
                        item = self._stage_to_device(self._read_batch(b))
                if item is None:  # close() during staging
                    return
                if not self._put(item):
                    return
                if self._stats is not None:
                    nbytes = sum(
                        getattr(a, "nbytes", 0) for a in item
                    )
                    self._stats.note_staged(
                        time.perf_counter() - t0, self._q.qsize(), nbytes
                    )
        except _CloseRequested:
            # consumer closed while a read was in retry backoff: the read
            # was still within its retry budget, so recording it as a
            # worker error would make close() crash a run that finished
            # all its steps
            return
        except Exception as e:
            self._err = e
        self._put(None)

    def _read_batch(self, b: int):
        """One staged batch via a single dataset call (workers=1 path, any
        tuple shape), with retry-with-backoff on transient read errors
        (flaky NFS/GCS, chaos-injected faults). OSError covers both real
        storage faults and `TransientDataError`; anything else is a
        programming/data-layout error and fails fast as before."""
        attempt = 0
        while True:
            try:
                plan = active_chaos()
                if plan is not None:
                    plan.maybe_loader_error(b)
                return self.dataset.get_batch(
                    self.indices[b * self.batch : (b + 1) * self.batch]
                )
            except OSError as e:
                attempt += 1
                if attempt > self.retries:
                    raise
                delay = self.backoff_secs * (2 ** (attempt - 1))
                log_event(
                    "loader",
                    f"batch {b} read failed ({type(e).__name__}: {e}); "
                    f"retry {attempt}/{self.retries} in {delay:.2f}s",
                )
                if self._stop.wait(delay):
                    raise _CloseRequested() from e

    def _get_canvas(self) -> _Canvas | None:
        """Pop a pooled canvas; None on close()."""
        while not self._stop.is_set():
            try:
                return self._free.get(timeout=0.1)
            except queue.Empty:
                continue
        return None

    def _chunks(self) -> tuple[list[tuple[int, int]], bool]:
        """(balanced contiguous row ranges, aligned) — one range per worker.
        `aligned` means every range covers whole per-device shards, which
        lets H2D start per shard as its rows complete."""
        n_dev = len(self.sharding.addressable_devices)
        w = self.workers
        if n_dev > 1 and self.batch % n_dev == 0 and w <= n_dev and n_dev % w == 0:
            per = n_dev // w
            shard_rows = self.batch // n_dev
            return [(c * per * shard_rows, (c + 1) * per * shard_rows)
                    for c in range(w)], True
        return [
            (self.batch * c // w, self.batch * (c + 1) // w) for c in range(w)
        ], False

    def _stage_batch_parallel(self, b: int, span=None):
        """Fan one batch out to the staging workers; start per-shard H2D as
        aligned sub-slices complete; return the assembled device tuple (or
        None when close() interrupted the batch). `span` is the batch's
        `stage_batch` trace span — its context rides each worker task so
        the decode-slice detail spans parent under it."""
        if not hasattr(self, "_pool_built"):
            # the first batch doubles as shape discovery for the canvas
            # pool: stage it through the single-call path (bit-identical by
            # protocol — the sub-slice fan-out concatenates to exactly this)
            item = self._read_batch(b)
            if len(item) != 3:
                raise TypeError(
                    "multi-worker staging requires the (images, labels, "
                    f"extents) batch protocol; got a {len(item)}-tuple"
                )
            imgs, labels, _extents = item
            for _ in range(2):  # double-buffered canvas pool
                self._free.put(
                    _Canvas(self.batch, imgs.shape[1:], imgs.dtype,
                            labels.dtype)
                )
            self._pool_built = True
            return self._stage_to_device(item)
        canvas = self._get_canvas()
        if canvas is None:
            return None
        batch_idx = self.indices[b * self.batch : (b + 1) * self.batch]
        collector = _BatchCollector()
        chunks, aligned = self._chunks()
        trace_ctx = span.context() if span is not None else None
        for lo, hi in chunks:
            self._tasks.put((b, lo, hi, batch_idx[lo:hi], canvas, collector,
                             trace_ctx))
        early = (self._early_put_plan()
                 if aligned and not self.trim_h2d else None)
        chunk_hi_of = dict(chunks)
        shard_arrays: dict = {}
        pending = len(chunks)
        err: BaseException | None = None
        while pending:
            try:
                chunk_lo, cerr = collector.events.get(timeout=0.1)
            except queue.Empty:
                if self._stop.is_set():
                    return None
                continue
            pending -= 1
            if cerr is not None:
                err = cerr
                continue
            if early is not None and err is None:
                # overlapped H2D: this sub-slice's rows cover whole device
                # shards — put them now, under the remaining workers'
                # decode time
                chunk_hi = chunk_hi_of[chunk_lo]
                for dev, (r0, r1) in early:
                    if r0 >= chunk_lo and r1 <= chunk_hi:
                        # detail span: the coordinator thread holds the
                        # stage_batch span, so parenting is automatic
                        with self._tracer.span("h2d_shard", cat="input",
                                               detail=True, batch=b,
                                               rows=f"{r0}:{r1}"):
                            shard_arrays[dev] = jax.device_put(
                                self._host_view(canvas.imgs[r0:r1]), dev
                            )
        if err is not None:
            self._free.put(canvas)
            raise err
        item = self._assemble_device(canvas, shard_arrays, early)
        self._free.put(canvas)
        return item

    def _early_put_plan(self):
        """[(device, (row0, row1)), ...] when per-shard H2D is possible:
        single host, every shard an even contiguous row range."""
        if jax.process_count() > 1:
            return None
        n_dev = len(self.sharding.addressable_devices)
        if n_dev <= 1 or self.batch % n_dev != 0:
            return None
        shard_rows = self.batch // n_dev
        try:
            idx_map = self.sharding.addressable_devices_indices_map(
                (self.batch,)
            )
        except Exception:  # conservative: any API surprise → whole-batch put
            return None
        plan = []
        for dev, index in idx_map.items():
            sl = index[0] if isinstance(index, tuple) else index
            r0 = 0 if sl.start is None else sl.start
            r1 = self.batch if sl.stop is None else sl.stop
            if r1 - r0 != shard_rows:
                return None
            plan.append((dev, (r0, r1)))
        # row order == device-assignment order for a 1-axis batch sharding,
        # which is the order make_array_from_single_device_arrays expects
        plan.sort(key=lambda p: p[1][0])
        return plan

    def _host_view(self, arr: np.ndarray) -> np.ndarray:
        """The array to hand to device_put: copied first when the backend
        aliases host memory (CPU zero-copy) — a recycled canvas must never
        be visible through a live jax.Array."""
        if _host_memory_is_device_memory():
            return np.array(arr)
        return arr

    def _trim(self, imgs: np.ndarray, extents: np.ndarray) -> np.ndarray:
        """Slice the canvas to the batch's max extent, rounded up to 64
        rows/cols (MXU-friendly, and it bounds the number of distinct
        compiled shapes): content never fills less than the trimmed area,
        padding beyond it is edge-replication the on-device crop never
        samples. extents are unchanged — they describe content, not canvas."""
        H, W = imgs.shape[1], imgs.shape[2]
        th = min(H, int(-(-int(extents[:, 0].max()) // 64) * 64))
        tw = min(W, int(-(-int(extents[:, 1].max()) // 64) * 64))
        if th == H and tw == W:
            return imgs
        return imgs[:, :th, :tw]

    def _assemble_device(self, canvas: _Canvas, shard_arrays: dict, early):
        imgs = canvas.imgs
        if self.trim_h2d:
            imgs = self._trim(imgs, canvas.extents)
        if early and len(shard_arrays) == len(early):
            img_arr = jax.make_array_from_single_device_arrays(
                (self.batch,) + imgs.shape[1:],
                self.sharding,
                [shard_arrays[dev] for dev, _ in early],
            )
        else:
            img_arr = self._to_device(self._host_view(imgs), self.sharding)
        labels = self._to_device(self._host_view(canvas.labels), self.sharding)
        extents = self._to_device(
            self._host_view(canvas.extents), self.sharding
        )
        item = (img_arr, labels, extents)
        # the transfer must COMPLETE before the canvas is recycled
        # (kImmutableUntilTransferCompletes semantics on real devices)
        jax.block_until_ready(item)
        return item

    def _stage_to_device(self, item):
        """Full-tuple transfer on the staging side (workers=1 path and the
        shape-discovery first batch): the H2D still hides under the
        consumer's running step, it just isn't per-shard-overlapped."""
        if len(item) == 3 and self.trim_h2d:
            imgs, labels, extents = item
            item = (self._trim(np.asarray(imgs), np.asarray(extents)),
                    labels, extents)
        staged = tuple(self._to_device(a, self.sharding) for a in item)
        jax.block_until_ready(staged)
        return staged

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def qsize(self) -> int:
        """Ready-queue depth (device batches staged ahead of the consumer)."""
        return self._q.qsize()

    def close(self):
        """Unblock and join the staging threads (consumers that break out of
        the iterator early MUST call this or the threads + `depth` staged
        batches leak for the life of the process). A worker error the
        iterator never reached (early break) is re-raised here — data
        corruption must not vanish just because the consumer left first."""
        self._stop.set()
        while not self._q.empty():
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=self._join_timeout)
        for t in self._wthreads:
            t.join(timeout=self._join_timeout)
        if self._thread.is_alive() or any(t.is_alive() for t in self._wthreads):
            log_event(
                "loader",
                f"staging thread still alive {self._join_timeout:.1f}s after "
                "close() — a dataset read is wedged; leaking the (daemon) "
                "thread(s) rather than blocking shutdown",
            )
        if self._err is not None and not self._err_delivered:
            self._err_delivered = True
            raise self._err

    def close_quietly(self) -> None:
        """close(), demoting a pending worker error to a loud log. For driver
        loops: the error necessarily belongs to a staged-ahead batch the
        consumer never used (errors on consumed batches surface through the
        iterator), so on an early stop (total_steps, preemption) it must not
        void a run whose every consumed step succeeded — and on an unwind it
        must not REPLACE the exception already in flight."""
        try:
            self.close()
        except Exception as e:
            log_event(
                "loader",
                f"staged-read error for a batch the consumer never used "
                f"(stopped early) — logged, not raised: {e!r}",
            )

    def _to_device(self, arr, sharding):
        if jax.process_count() > 1:
            # multi-host: each host holds only its slice of the global batch;
            # assemble a global array from per-process shards
            return jax.make_array_from_process_local_data(sharding, arr)
        return jax.device_put(arr, sharding)

    def __iter__(self) -> Iterator:
        """Pop finished device batches, booking credit stalls: time the
        consumer spends blocked on an EMPTY ready queue is the pipeline
        (in-process or service) failing to keep the device fed — the
        obsd `input_credit_stall_rate` input (ISSUE 14)."""
        while True:
            if self._stats is not None and self._q.empty():
                t0 = time.perf_counter()
                item = self._q.get()
                self._stats.note_credit_stall(time.perf_counter() - t0)
            else:
                item = self._q.get()
            if item is None:
                if self._err is not None:
                    self._err_delivered = True
                    raise self._err
                return
            # already device-resident (staging-side H2D): just relay
            yield item

    def __len__(self):
        return self.num_batches


def stage_eval_batch(item, batch: int, sharding=None, pad_label=None):
    """Pad a (possibly short) `(imgs, labels, extents)` batch to `batch` rows
    and place the arrays (device_put with `sharding`, or plain jnp).
    `pad_label` fills the label tail (e.g. -1 = never-matching); labels stay
    host-side numpy when `pad_label` is None (caller slices `[:valid]`).
    Shared by the kNN encoder and the lincls validator so their batch
    staging cannot drift apart. Padding rows are BROADCAST views of the
    last row until the single concatenate copy — `np.repeat` materialized a
    full duplicate-image block first, doubling the tail-batch allocation."""
    import jax.numpy as jnp

    imgs, labels, extents = item
    valid = imgs.shape[0]
    if valid < batch:
        pad = batch - valid
        imgs = np.concatenate(
            [imgs, np.broadcast_to(imgs[-1:], (pad,) + imgs.shape[1:])]
        )
        extents = np.concatenate(
            [extents, np.broadcast_to(extents[-1:], (pad,) + extents.shape[1:])]
        )
        if pad_label is not None:
            labels = np.concatenate(
                [labels, np.full(pad, pad_label, labels.dtype)]
            )
    if sharding is not None:
        imgs = jax.device_put(imgs, sharding)
        extents = jax.device_put(np.ascontiguousarray(extents), sharding)
    else:
        imgs = jnp.asarray(imgs)
        extents = jnp.asarray(extents)
    return imgs, labels, extents


def epoch_loader(
    dataset, epoch: int, seed: int, global_batch: int, mesh: Mesh,
    skip_batches: int = 0, retries: int = 3, backoff_secs: float = 0.5,
    depth: int = 2, workers: int = 1, stats=None, trim_h2d: bool = False,
    tracer=None,
) -> Prefetcher:
    """One epoch of sharded batches (sampler.set_epoch + DataLoader in one).

    `skip_batches` drops the first N global batches at the index level (no
    decode, no H2D) — used by mid-epoch resume to fast-forward to the first
    unconsumed batch of the interrupted epoch. `retries`/`backoff_secs`
    configure the transient-read retry policy; `depth`/`workers`/`stats`/
    `trim_h2d` configure the staging pipeline (config: `prefetch_depth`,
    `staging_workers`, `h2d_trim`)."""
    perm = epoch_permutation(len(dataset), epoch, seed, global_batch)
    local = host_shard(perm, global_batch)
    per_host = global_batch // jax.process_count()
    if skip_batches:
        local = local[skip_batches * per_host:]
    return Prefetcher(dataset, local, per_host, mesh,
                      depth=depth, retries=retries, backoff_secs=backoff_secs,
                      workers=workers, stats=stats, trim_h2d=trim_h2d,
                      tracer=tracer)
