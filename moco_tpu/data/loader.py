"""Host→device input pipeline (rebuild of `DataLoader` + `DistributedSampler`
usage in `main_moco.py:≈L228-278`).

- `epoch_permutation` replaces `DistributedSampler.set_epoch`: a
  deterministic per-epoch shuffle of the whole dataset, seeded identically on
  every host; each host then takes its contiguous shard (`process_index`), so
  shards are disjoint and exhaustive — the same guarantee the reference gets
  from `DistributedSampler`.
- `Prefetcher` double-buffers: a background thread stages the NEXT batch
  (host decode) while the device runs the current step, then `device_put`s
  with the batch sharding so each chip receives only its slice. This replaces
  the reference's worker processes + `pin_memory` H2D overlap.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from moco_tpu.parallel.mesh import DATA_AXIS
from moco_tpu.resilience.chaos import active_chaos
from moco_tpu.utils.logging import log_event


def epoch_permutation(n: int, epoch: int, seed: int, global_batch: int) -> np.ndarray:
    """Deterministic epoch shuffle, truncated to whole batches (the
    reference's `drop_last=True`)."""
    rng = np.random.RandomState((seed * 100003 + epoch) % (2**31))
    perm = rng.permutation(n)
    usable = (n // global_batch) * global_batch
    return perm[:usable]


def host_shard(indices: np.ndarray, global_batch: int) -> np.ndarray:
    """This host's slice of every global batch (multi-host data sharding)."""
    nproc = jax.process_count()
    if nproc == 1:
        return indices
    if global_batch % nproc != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by process count {nproc}"
        )
    pid = jax.process_index()
    per_host = global_batch // nproc
    batches = indices.reshape(-1, global_batch)
    return batches[:, pid * per_host : (pid + 1) * per_host].reshape(-1)


class _CloseRequested(Exception):
    """Internal: the consumer called close() while the staging worker was in
    retry backoff — the worker exits quietly instead of surfacing the
    transient error as if the run had failed."""


class Prefetcher:
    """Iterate `(images_u8, labels)` device-sharded batches with background
    host staging."""

    def __init__(self, dataset, indices: np.ndarray, batch_per_host: int, mesh: Mesh,
                 depth: int = 2, retries: int = 3, backoff_secs: float = 0.5,
                 join_timeout: float = 5.0):
        self.dataset = dataset
        self.indices = indices
        self.batch = batch_per_host
        self.sharding = NamedSharding(mesh, P(DATA_AXIS))
        self.num_batches = len(indices) // batch_per_host
        self.retries = retries
        self.backoff_secs = backoff_secs
        self._join_timeout = join_timeout
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._err: BaseException | None = None
        self._err_delivered = False
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        # any dataset error (corrupt file, missing path) must reach the
        # consumer — a silently-dead thread would hang training on q.get()
        try:
            for b in range(self.num_batches):
                item = self._read_batch(b)
                if not self._put(item):
                    return
        except _CloseRequested:
            # consumer closed while we were in retry backoff: the read was
            # still within its retry budget, so recording it as a worker
            # error would make close() crash a run that finished all its
            # steps
            return
        except Exception as e:
            self._err = e
        self._put(None)

    def _read_batch(self, b: int):
        """One staged batch, with retry-with-backoff on transient read
        errors (flaky NFS/GCS, chaos-injected faults). OSError covers both
        real storage faults and `TransientDataError`; anything else is a
        programming/data-layout error and fails fast as before."""
        attempt = 0
        while True:
            try:
                plan = active_chaos()
                if plan is not None:
                    plan.maybe_loader_error(b)
                return self.dataset.get_batch(
                    self.indices[b * self.batch : (b + 1) * self.batch]
                )
            except OSError as e:
                attempt += 1
                if attempt > self.retries:
                    raise
                delay = self.backoff_secs * (2 ** (attempt - 1))
                log_event(
                    "loader",
                    f"batch {b} read failed ({type(e).__name__}: {e}); "
                    f"retry {attempt}/{self.retries} in {delay:.2f}s",
                )
                if self._stop.wait(delay):
                    # consumer closed mid-backoff: stop retrying, and exit
                    # the worker WITHOUT recording the transient error
                    raise _CloseRequested() from e

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def close(self):
        """Unblock and join the staging thread (consumers that break out of
        the iterator early MUST call this or the thread + `depth` staged
        batches leak for the life of the process). A worker error the
        iterator never reached (early break) is re-raised here — data
        corruption must not vanish just because the consumer left first."""
        self._stop.set()
        while not self._q.empty():
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=self._join_timeout)
        if self._thread.is_alive():
            log_event(
                "loader",
                f"staging thread still alive {self._join_timeout:.1f}s after "
                "close() — a dataset read is wedged; leaking the (daemon) "
                "thread rather than blocking shutdown",
            )
        if self._err is not None and not self._err_delivered:
            self._err_delivered = True
            raise self._err

    def close_quietly(self) -> None:
        """close(), demoting a pending worker error to a loud log. For driver
        loops: the error necessarily belongs to a staged-ahead batch the
        consumer never used (errors on consumed batches surface through the
        iterator), so on an early stop (total_steps, preemption) it must not
        void a run whose every consumed step succeeded — and on an unwind it
        must not REPLACE the exception already in flight."""
        try:
            self.close()
        except Exception as e:
            log_event(
                "loader",
                f"staged-read error for a batch the consumer never used "
                f"(stopped early) — logged, not raised: {e!r}",
            )

    def _to_device(self, arr, sharding):
        if jax.process_count() > 1:
            # multi-host: each host holds only its slice of the global batch;
            # assemble a global array from per-process shards
            return jax.make_array_from_process_local_data(sharding, arr)
        return jax.device_put(arr, sharding)

    def __iter__(self) -> Iterator:
        while True:
            item = self._q.get()
            if item is None:
                if self._err is not None:
                    self._err_delivered = True
                    raise self._err
                return
            # (images, labels, extents) — every element is batch-leading,
            # so they all shard on the data axis
            yield tuple(self._to_device(a, self.sharding) for a in item)

    def __len__(self):
        return self.num_batches


def stage_eval_batch(item, batch: int, sharding=None, pad_label=None):
    """Pad a (possibly short) `(imgs, labels, extents)` batch to `batch` rows
    and place the arrays (device_put with `sharding`, or plain jnp).
    `pad_label` fills the label tail (e.g. -1 = never-matching); labels stay
    host-side numpy when `pad_label` is None (caller slices `[:valid]`).
    Shared by the kNN encoder and the lincls validator so their batch
    staging cannot drift apart."""
    import jax.numpy as jnp

    imgs, labels, extents = item
    valid = imgs.shape[0]
    if valid < batch:
        imgs = np.concatenate([imgs, np.repeat(imgs[-1:], batch - valid, 0)])
        extents = np.concatenate([extents, np.repeat(extents[-1:], batch - valid, 0)])
        if pad_label is not None:
            labels = np.concatenate(
                [labels, np.full(batch - valid, pad_label, labels.dtype)]
            )
    if sharding is not None:
        imgs = jax.device_put(imgs, sharding)
        extents = jax.device_put(np.ascontiguousarray(extents), sharding)
    else:
        imgs = jnp.asarray(imgs)
        extents = jnp.asarray(extents)
    return imgs, labels, extents


def epoch_loader(
    dataset, epoch: int, seed: int, global_batch: int, mesh: Mesh,
    skip_batches: int = 0, retries: int = 3, backoff_secs: float = 0.5,
) -> Prefetcher:
    """One epoch of sharded batches (sampler.set_epoch + DataLoader in one).

    `skip_batches` drops the first N global batches at the index level (no
    decode, no H2D) — used by mid-epoch resume to fast-forward to the first
    unconsumed batch of the interrupted epoch. `retries`/`backoff_secs`
    configure the Prefetcher's transient-read retry policy."""
    perm = epoch_permutation(len(dataset), epoch, seed, global_batch)
    local = host_shard(perm, global_batch)
    per_host = global_batch // jax.process_count()
    if skip_batches:
        local = local[skip_batches * per_host:]
    return Prefetcher(dataset, local, per_host, mesh,
                      retries=retries, backoff_secs=backoff_secs)
