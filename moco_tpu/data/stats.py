"""Input-pipeline instrumentation shared by the staging stack (ISSUE 3).

One `InputPipelineStats` lives for a whole driver pass (owned by
`RunTelemetry` when telemetry is on, or constructed standalone by benches)
and is threaded into every `Prefetcher` and `CachedDataset` of that pass —
epochs come and go, the counters accumulate. Everything here is pure
stdlib and updated from staging/worker threads, so every mutation holds
the lock; `snapshot()` is what lands in the telemetry `step` records at
the device-sampling stride and in the `run_end` summary.

Tracked:
  - staged-batch latency (decode→device-queue wall per batch) p50/p95
    over a rolling window of recent batches, plus cumulative staged bytes
  - ready-queue depth at enqueue time (last + mean): a queue that is
    always 0 means the consumer is starved (host-bound); always full
    means the device is the bottleneck — the one-number diagnosis of
    which side of the H2D edge to tune
  - worker-busy fraction: total worker decode seconds over
    workers × wall seconds — low busy + starved queue means the workers
    are blocked on something other than decode (lock, storage)
  - decode-once canvas-cache hits/misses (CachedDataset)
"""

from __future__ import annotations

import threading
import time


def _percentile(ordered: list[float], q: float) -> float:
    """Nearest-rank percentile of an ALREADY-SORTED list."""
    if not ordered:
        return 0.0
    rank = max(0, min(len(ordered) - 1, round(q / 100.0 * (len(ordered) - 1))))
    return ordered[int(rank)]


# staged-latency reservoir bound: snapshot() sorts it under the lock the
# staging coordinator shares, so it must stay small — keep a rolling
# window (recent behavior is also what an operator tunes against), trimmed
# amortized-O(1) at twice the window
_LATENCY_WINDOW = 4096


class InputPipelineStats:
    """Cumulative, thread-safe counters for one run's input pipeline."""

    def __init__(self):
        self._lock = threading.Lock()
        self._created = time.perf_counter()
        self.staged_batches = 0
        self.staged_bytes = 0
        self._staged_s: list[float] = []
        self.queue_depth_last = 0
        self._queue_depth_sum = 0
        self.workers = 1
        self._worker_busy_s = 0.0
        self.cache_hits = 0
        self.cache_misses = 0
        self._credit_stall_s = 0.0

    # -- producers ----------------------------------------------------------
    def note_workers(self, n: int) -> None:
        """Record the staging-worker count (max across loaders of the run:
        eval loaders may run narrower than the train loader)."""
        with self._lock:
            self.workers = max(self.workers, int(n))

    def note_staged(self, seconds: float, queue_depth: int, nbytes: int) -> None:
        """One batch fully staged (decoded + transferred + enqueued)."""
        with self._lock:
            self.staged_batches += 1
            self.staged_bytes += int(nbytes)
            self._staged_s.append(float(seconds))
            if len(self._staged_s) > 2 * _LATENCY_WINDOW:
                del self._staged_s[:-_LATENCY_WINDOW]
            self.queue_depth_last = int(queue_depth)
            self._queue_depth_sum += int(queue_depth)

    def note_worker_busy(self, seconds: float) -> None:
        with self._lock:
            self._worker_busy_s += float(seconds)

    def note_cache(self, hits: int, misses: int) -> None:
        with self._lock:
            self.cache_hits += int(hits)
            self.cache_misses += int(misses)

    def note_credit_stall(self, seconds: float) -> None:
        """Consumer-side starvation (ISSUE 14): time the training loop
        spent blocked on an EMPTY ready queue with its whole credit
        window outstanding — the input pipeline (in-process or service)
        could not keep the device fed. The obsd
        `input_credit_stall_rate` objective is the windowed rate of this
        counter: a sustained high rate IS a starving train host."""
        with self._lock:
            self._credit_stall_s += float(seconds)

    # -- consumer -----------------------------------------------------------
    def snapshot(self) -> dict:
        """One JSON-ready dict of everything above (cumulative)."""
        with self._lock:
            wall = max(time.perf_counter() - self._created, 1e-9)
            total_lookups = self.cache_hits + self.cache_misses
            ordered = sorted(self._staged_s)
            snap = {
                "staged_batches": self.staged_batches,
                "staged_mb": round(self.staged_bytes / 2**20, 1),
                "staged_batch_s_p50": round(_percentile(ordered, 50), 6),
                "staged_batch_s_p95": round(_percentile(ordered, 95), 6),
                "queue_depth": self.queue_depth_last,
                "queue_depth_mean": round(
                    self._queue_depth_sum / max(self.staged_batches, 1), 3
                ),
                "workers": self.workers,
                # busy fraction over run wall-clock: idle stretches (evals,
                # checkpoint stalls) dilute it — read it as "of the run so
                # far, how much worker capacity decode actually used"
                "worker_busy_frac": round(
                    self._worker_busy_s / (self.workers * wall), 4
                ),
                # cumulative pair: obsd's input_credit_stall_rate takes
                # the windowed DELTA ratio of these two
                "credit_stall_s": round(self._credit_stall_s, 3),
                "wall_s": round(wall, 3),
            }
            if total_lookups:
                snap["cache_hits"] = self.cache_hits
                snap["cache_misses"] = self.cache_misses
                snap["cache_hit_rate"] = round(self.cache_hits / total_lookups, 4)
            return snap
