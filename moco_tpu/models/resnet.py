"""Flax ResNet backbone zoo (layer L3b of SURVEY.md §1).

The reference takes its encoders from `torchvision.models`
(`models.__dict__[arch](num_classes=dim)`, `main_moco.py:≈L40-46,165`). This
is a from-scratch flax implementation with matching structure so that (a) the
linear-probe checkpoint surgery has the same named-part semantics (backbone
vs final `fc`) and (b) the exporter (checkpoint.py) can emit
torchvision-style names for downstream consumers (SURVEY §2.6).

TPU-first choices:
- NHWC layout throughout (XLA:TPU's native convolution layout; torchvision's
  NCHW is a CUDA convention, not semantics).
- Weights/activations can run in bfloat16 via `dtype=`, with BN statistics
  and the parameter master copies kept in float32 (`param_dtype`).
- BatchNorm is PER-DEVICE by default (no cross-replica axis): MoCo's
  ShuffleBN depends on per-device statistics (SURVEY §7 hard part 1).
  `bn_cross_replica_axis` enables SyncBN only for transfer configs that
  want it (e.g. detection's `Base-RCNN-C4-BN`).

Structure parity notes (vs torchvision `resnet.py`):
- Bottleneck is v1.5: the stride sits on the 3x3 conv, not the 1x1.
- 3x3 convs use EXPLICIT symmetric padding 1 (torch semantics): flax's
  default SAME pads (0,1) at stride 2, a one-pixel tap shift that would
  make exported checkpoints run a slightly different network in torch
  consumers (pinned by tests/test_torch_consumer.py against real torch).
- Stem: 7x7/2 conv, BN, ReLU, 3x3/2 max-pool. `cifar_stem=True` swaps in the
  community CIFAR variant (3x3/1 conv, no max-pool) used by every CIFAR MoCo
  demo (BASELINE config 1).
- `torch` BN defaults: momentum 0.1, eps 1e-5 → flax momentum 0.9, eps 1e-5.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


def _space_to_depth_stem(x, kernel, dtype):
    """The 7x7/2 ImageNet stem conv computed as a space-to-depth 4x4/1 conv.

    The MXU contracts over input channels in 128-lanes; a 3-channel conv
    leaves it ~2% utilized. Re-tiling the image into 2x2 blocks
    ([B,224,224,3] -> [B,112,112,12]) and zero-padding the kernel 7->8
    ([7,7,3,64] -> [4,4,12,64]) computes the IDENTICAL convolution (same
    products, regrouped) with 4x the contraction depth and no strided
    window. The parameter stays the torchvision-shaped [7,7,3,64] — only
    the trace-time compute is re-tiled, so checkpoints/exports are
    unchanged. (MLPerf-era TPU ResNet trick; derivation in the test.)

    Output position i reads x[2i+k-3], k=0..6. With the kernel left-padded
    to 8 taps (k'=k+1) this is x[2i+k'-4]; writing k'=2q+p with p the
    within-block offset gives blocks j=i+q-2, q=0..3 — a stride-1 4-tap
    block conv with padding (2,1).
    """
    b, h, w, c = x.shape
    kh, kw, cin, cout = kernel.shape  # [7,7,3,64]
    kpad = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))  # [8,8,3,64]
    k_s2d = (
        kpad.reshape(4, 2, 4, 2, cin, cout)
        .transpose(0, 2, 1, 3, 4, 5)
        .reshape(4, 4, 4 * cin, cout)
    )
    x_s2d = (
        x.reshape(b, h // 2, 2, w // 2, 2, c)
        .transpose(0, 1, 3, 2, 4, 5)
        .reshape(b, h // 2, w // 2, 4 * c)
    )
    return jax.lax.conv_general_dilated(
        x_s2d.astype(dtype),
        k_s2d.astype(dtype),
        window_strides=(1, 1),
        padding=((2, 1), (2, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


class BasicBlock(nn.Module):
    """2x3x3 residual block (ResNet-18/34).

    `fused_tail=True` runs the interior bn1→relu→conv2 pass (conv2 is
    ALWAYS stride 1 here) through the Pallas 3x3 fused kernel
    (models/fused_block.py) — same params/names/math as the unfused
    modules."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    fused_tail: bool = False
    bn_momentum: float = 0.9
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        # explicit pad 1 on 3x3 convs: flax's default SAME pads (0,1) at
        # stride 2 — a one-pixel tap shift vs torchvision's symmetric
        # padding=1 at every stage transition, which would make exported
        # checkpoints run a (slightly) different network in torch consumers
        y = self.conv(
            self.filters, (3, 3), (self.strides, self.strides),
            padding=[(1, 1), (1, 1)], name="conv1",
        )(x)
        if self.fused_tail:
            from moco_tpu.models.fused_block import (
                fused_bn_relu_conv2,
                norm_train_flag,
            )

            y = fused_bn_relu_conv2(
                self, y, self.filters, norm_train_flag(self.norm),
                self.bn_momentum, 1e-5, self.dtype,
            )
        else:
            y = self.norm(name="bn1")(y)
            y = nn.relu(y)
            y = self.conv(
                self.filters, (3, 3), padding=[(1, 1), (1, 1)], name="conv2"
            )(y)
        y = self.norm(name="bn2")(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), (self.strides, self.strides), name="downsample_conv"
            )(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class Bottleneck(nn.Module):
    """1x1 → 3x3(stride) → 1x1(x4) residual block (ResNet-50/101/152, v1.5).

    `fused_tail=True` computes BOTH interior normalize passes through Pallas
    fused kernels (models/fused_block.py): bn1→relu→conv2 (3x3; stride-1
    mids AND the stride-2 stage-first blocks) and bn2→relu→conv3 (1x1, all
    blocks) — identical params/names/math, the normalized activations never
    materialize in HBM.
    Engages the kernels on TPU only; incompatible with SyncBN (callers gate
    on that)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    expansion: int = 4
    fused_tail: bool = False
    bn_momentum: float = 0.9
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), name="conv1")(x)
        if self.fused_tail:
            from moco_tpu.models.fused_block import (
                fused_bn_relu_conv2,
                fused_bn_relu_conv2_s2,
                fused_bn_relu_conv3,
                norm_train_flag,
            )

            train = norm_train_flag(self.norm)
            # interior fusion #2: bn1→relu→conv2 through the Pallas 3x3
            # kernels — stride-1 mids and (since r4) the stride-2
            # stage-first blocks
            fuse2 = (fused_bn_relu_conv2 if self.strides == 1
                     else fused_bn_relu_conv2_s2)
            y = fuse2(
                self, y, self.filters, train, self.bn_momentum, 1e-5,
                self.dtype,
            )
        else:
            y = self.norm(name="bn1")(y)
            y = nn.relu(y)
            # explicit pad 1: torchvision-symmetric (see BasicBlock note)
            y = self.conv(
                self.filters, (3, 3), (self.strides, self.strides),
                padding=[(1, 1), (1, 1)], name="conv2",
            )(y)
        if self.fused_tail:
            y = fused_bn_relu_conv3(
                self, y, self.filters * self.expansion, train,
                self.bn_momentum, 1e-5, self.dtype,
            )
        else:
            y = self.norm(name="bn2")(y)
            y = nn.relu(y)
            y = self.conv(self.filters * self.expansion, (1, 1), name="conv3")(y)
        y = self.norm(name="bn3")(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * self.expansion,
                (1, 1),
                (self.strides, self.strides),
                name="downsample_conv",
            )(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """ResNet encoder ending in a `num_classes`-dim `fc` head.

    For MoCo pretraining `num_classes` is the embedding dim (128) and
    `mlp_head=True` swaps `fc` for the v2 2-layer MLP head
    (`moco/builder.py:≈L25-35`: Linear(d,d) → ReLU → Linear(d,dim)).
    `num_classes=None` returns pooled backbone features (used by the linear
    probe and the kNN feature bank).
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int | None = 128
    mlp_head: bool = False
    cifar_stem: bool = False
    width: int = 64
    dtype: Any = jnp.float32
    bn_momentum: float = 0.9
    bn_cross_replica_axis: str | None = None
    s2d_stem: bool = True  # compute the 7x7/2 stem as a space-to-depth conv
                           # (identical math, ~4x MXU contraction depth);
                           # params/exports unchanged. Auto-skipped for odd
                           # input sizes.
    fast_bn: bool = True   # FastBatchNorm: Pallas streaming BN reductions on
                           # TPU (identical flax math/params off-TPU)
    remat: bool = False    # per-residual-block rematerialization: save only
                           # block boundaries, recompute internals in the
                           # backward — trades (underutilized) MXU FLOPs for
                           # HBM traffic on the memory-bound step. Identical
                           # numerics (same ops, re-executed).
    fused_bn_conv: bool = False  # interior bn→relu→conv passes through the
                                 # Pallas fused kernels: Bottleneck conv3
                                 # tail + stride-1 conv2 mids, BasicBlock
                                 # conv2 (same params; TPU-only engagement;
                                 # ignored for SyncBN)

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32
        )
        if self.fast_bn:
            from moco_tpu.models.fast_bn import FastBatchNorm

            norm_cls = FastBatchNorm
        else:
            norm_cls = nn.BatchNorm
        norm = partial(
            norm_cls,
            use_running_average=not train,
            momentum=self.bn_momentum,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_cross_replica_axis,
        )

        x = x.astype(self.dtype)
        if self.cifar_stem:
            x = conv(self.width, (3, 3), name="conv1")(x)
            x = norm(name="bn1")(x)
            x = nn.relu(x)
        elif self.s2d_stem and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
            kernel = self.param(
                "conv1",
                # match nn.Conv's param tree: conv1/kernel with the default
                # initializer, so checkpoints are interchangeable with the
                # plain-conv stem
                lambda rng: {
                    "kernel": nn.initializers.lecun_normal()(
                        rng, (7, 7, x.shape[-1], self.width), jnp.float32
                    )
                },
            )["kernel"]
            x = _space_to_depth_stem(x, kernel, self.dtype)
            x = norm(name="bn1")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        else:
            x = conv(self.width, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv1")(x)
            x = norm(name="bn1")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])

        block_kwargs = {}
        if (
            self.fused_bn_conv
            and self.block_cls in (Bottleneck, BasicBlock)
            and self.bn_cross_replica_axis is None
            # engage on TPU only: the CPU fallback inside the fused tail is
            # mathematically equal but uses the closed-form BN backward,
            # while off-TPU goldens pin flax-autodiff numerics bit-exactly
            and jax.default_backend() == "tpu"
        ):
            block_kwargs = dict(
                fused_tail=True, bn_momentum=self.bn_momentum, dtype=self.dtype
            )
        block_cls = nn.remat(self.block_cls) if self.remat else self.block_cls
        for i, num_blocks in enumerate(self.stage_sizes):
            for j in range(num_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = block_cls(
                    filters=self.width * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    name=f"layer{i + 1}_{j}",
                    **block_kwargs,
                )(x)

        x = jnp.mean(x, axis=(1, 2))  # global average pool → [B, feat_dim]
        x = x.astype(jnp.float32)
        if self.num_classes is None:
            return x
        dense = partial(nn.Dense, dtype=jnp.float32, param_dtype=jnp.float32)
        if self.mlp_head:
            d = x.shape[-1]
            x = dense(d, name="fc_hidden")(x)
            x = nn.relu(x)
            x = dense(self.num_classes, name="fc")(x)
        else:
            x = dense(self.num_classes, name="fc")(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=Bottleneck)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=Bottleneck)
ResNet152 = partial(ResNet, stage_sizes=(3, 8, 36, 3), block_cls=Bottleneck)

# 2-stage, width-16 micro-ResNet: smoke tests / CI on the single-core CPU
# sandbox, where a full ResNet-18 compile is minutes. Not a reference arch.
ResNetTiny = partial(ResNet, stage_sizes=(1, 1), block_cls=BasicBlock, width=16)

# `--arch` registry (the reference's `model_names`/`models.__dict__[arch]`).
ARCHS: dict[str, Callable[..., ResNet]] = {
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "resnet152": ResNet152,
    "resnet_tiny": ResNetTiny,
}

FEATURE_DIMS = {
    "resnet18": 512,
    "resnet34": 512,
    "resnet50": 2048,
    "resnet101": 2048,
    "resnet152": 2048,
    "resnet_tiny": 32,
}


def build_resnet(arch: str, **kwargs) -> ResNet:
    if arch not in ARCHS:
        raise ValueError(f"unknown arch {arch!r}; choose from {sorted(ARCHS)}")
    return ARCHS[arch](**kwargs)
