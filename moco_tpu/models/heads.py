"""MoCo projection / prediction heads.

- The v2 head (Linear→ReLU→Linear, `moco/builder.py:≈L25-35`) is built into
  `ResNet(mlp_head=True)` since the reference splices it in place of `fc`.
- v3 heads (sibling repo `moco-v3/moco/builder.py`, SURVEY §2.9): projector =
  3-layer MLP, hidden 4096, out 256, BN after every linear, no affine+no ReLU
  after the last BN; predictor (query side only) = 2-layer MLP, hidden 4096,
  BN+ReLU between. Both operate on [B, D] vectors, dtype float32 (head math
  is tiny; keeping it f32 sidesteps bf16 BN-stat noise).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class _MLP(nn.Module):
    num_layers: int
    hidden_dim: int
    out_dim: int
    last_bn: bool

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(jnp.float32)
        for i in range(self.num_layers):
            last = i == self.num_layers - 1
            dim = self.out_dim if last else self.hidden_dim
            # every linear bias-free: hidden biases are absorbed by the BN
            # that follows, and the reference builds all of them bias-less
            x = nn.Dense(dim, use_bias=False, name=f"fc{i}")(x)
            if not last:
                x = nn.BatchNorm(
                    use_running_average=not train, momentum=0.9, epsilon=1e-5,
                    name=f"bn{i}",
                )(x)
                x = nn.relu(x)
            elif self.last_bn:
                # v3: final BN without affine params ("SimCLR-style" head)
                x = nn.BatchNorm(
                    use_running_average=not train, momentum=0.9, epsilon=1e-5,
                    use_bias=False, use_scale=False, name=f"bn{i}",
                )(x)
        return x


class V3Projector(nn.Module):
    """3-layer projector, hidden 4096 → out 256, BN throughout."""

    hidden_dim: int = 4096
    out_dim: int = 256

    @nn.compact
    def __call__(self, x, train: bool = True):
        return _MLP(3, self.hidden_dim, self.out_dim, last_bn=True, name="mlp")(
            x, train=train
        )


class V3Predictor(nn.Module):
    """2-layer predictor on the query side only."""

    hidden_dim: int = 4096
    out_dim: int = 256

    @nn.compact
    def __call__(self, x, train: bool = True):
        return _MLP(2, self.hidden_dim, self.out_dim, last_bn=False, name="mlp")(
            x, train=train
        )
