"""Train-mode BatchNorm with Pallas streaming reductions (custom VJP).

`nn.BatchNorm`'s train path lowers to XLA reduce fusions for the batch
statistics (forward) and the dgamma/dbeta reductions (backward); round-2
profiling measured those passes at ~half the MoCo-v2 step on the v5e,
running well under the HBM roof. `FastBatchNorm` is a drop-in replacement
(same param/`batch_stats` collections: `scale`, `bias` / `mean`, `var`;
flax running-stat semantics — biased variance, same `momentum`/`epsilon`)
whose train-mode statistics run through `ops/pallas_stats.py` streaming
kernels under a custom VJP:

    fwd:  (Σx, Σx²)  — one Pallas read of x; the normalize stays an XLA
          elementwise op (fuses with the following ReLU/residual-add).
    bwd:  (Σdy, Σdy·x̂) — one Pallas read of dy and x (x̂ recomputed
          in-register); dx is the standard closed form
          dx = γ·r·(dy − (x̂·Σ(dy·x̂) + Σdy)/N), an XLA elementwise pass.

This is the TPU-native equivalent of the reference's cuDNN fused-BN
reductions (`torch.nn.BatchNorm2d` internals; SURVEY §2.10 cuDNN →
MXU/Pallas).

Off-TPU (and for SyncBN via `axis_name`, and eval mode) the math runs as
plain jnp in EXACTLY flax's op order — f32 stats, promote-to-dtype
normalize — so CPU results (golden tests) are bit-identical to
`nn.BatchNorm`. Interpret-mode Pallas can't run inside shard_map regions
off-TPU in this jax version (same constraint as the Pallas blur).

Status (r5 first contact): the Pallas REDUCTION kernels now default OFF
even on TPU — the on-chip A/B measured them ~52 ms/step SLOWER than
today's XLA reduce fusions at R50/B=128 (per-launch overhead across ~106
pallas_calls; see `_use_pallas` and runs/perf_ab_*.log). They were a
measured r2 win and remain available via MOCO_TPU_PALLAS_BN=1. The
custom-VJP closed-form dx is gated SEPARATELY (`_use_custom_vjp`): on TPU
it stays on (measured win over plain autodiff with jnp reductions
inside); off-TPU it stays off so CPU goldens remain bit-identical to
`nn.BatchNorm`.
"""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from moco_tpu.ops.pallas_stats import channel_grad_sums, channel_sums


def _use_pallas() -> bool:
    # Default OFF since r5 first contact — set by DATA, not caution: the
    # tools/_perf_ab.py on-chip A/B (runs/perf_ab_*.log, 2026-07-31)
    # measured the R50 step at 70.1 ms (BN kernels off, blur on) vs
    # 122.3 ms with them on at B=128 — ~52 ms/step across the ~106
    # pallas_call launches of a 53-BN network, i.e. per-launch overhead on
    # the current Mosaic/relay toolchain, which no tile size fixes (the
    # MOCO_TPU_STATS_TILE_KIB sweep left the microbench at ~20 GB/s
    # against a ~494 GB/s roof). The kernels were a measured r2 win;
    # today's XLA reduce fusions beat them. Numerics are identical either
    # way (same math, f32 accumulation) — this is purely a perf default.
    # MOCO_TPU_PALLAS_BN=1 opts back in; MOCO_TPU_DISABLE_PALLAS (the
    # global kill-switch the bench retry uses) still wins over the opt-in.
    from moco_tpu.utils.envflags import env_flag

    return (jax.default_backend() == "tpu"
            and env_flag("MOCO_TPU_PALLAS_BN")
            and not env_flag("MOCO_TPU_DISABLE_PALLAS"))


def _use_custom_vjp() -> bool:
    """Route train-mode BN (axis_name=None) through `_bn_train`'s
    custom-VJP closed-form dx, with `_use_pallas()` separately choosing
    pallas-vs-jnp REDUCTIONS inside. Keeping this independent of the
    kernel opt-in lets the closed-form dx ship (or not) on its own merit:
    the r5 on-chip A/B measured jnp-reductions+custom-VJP at 71.4 ms/step
    vs 71.8-72.0 for plain autodiff at R50/B=128 (149.5 vs 151.9 at
    B=256; runs/perf_ab_bn_vjp.log vs perf_ab_bn_autodiff.log) — a small,
    repeatable win, so it stays ON for TPU. Off-TPU the plain jnp
    autodiff path is kept for bit-identical CPU goldens (the closed form
    differs from flax autodiff by ~1 ulp). MOCO_TPU_BN_VJP=1/0 forces —
    EXCEPT that MOCO_TPU_PALLAS_BN=1 implies the custom-VJP path
    regardless (the Pallas reduction kernels live inside `_bn_train`;
    "pallas reductions + plain autodiff" is not a constructible program,
    so BN_VJP=0 cannot carve it out — review, r5)."""
    import os

    v = os.environ.get("MOCO_TPU_BN_VJP", "")
    if v:
        return v != "0"
    return jax.default_backend() == "tpu"


def _batch_stats(x, use_pallas):
    """f32 (mean, var) over all but the channel axis — flax's
    `_compute_stats` math (biased variance, mean-of-squares form)."""
    n = x.size // x.shape[-1]
    if use_pallas:
        s, sq = channel_sums(x)
        return s / n, sq / n - (s / n) * (s / n)
    xf = x.astype(jnp.float32)
    axes = tuple(range(x.ndim - 1))
    mean = jnp.mean(xf, axis=axes)
    mean2 = jnp.mean(xf * xf, axis=axes)
    return mean, mean2 - mean * mean


def _normalize(x, mean, var, scale, bias, eps, dtype):
    """flax `_normalize` semantics (force_float32_reductions=True): the whole
    computation runs in f32 via promotion — `(x - mean) * (rsqrt(var + eps)
    * scale) + bias` with f32 mean/var/scale/bias — and only the RESULT is
    cast to `dtype`."""
    y = (x - mean) * (jax.lax.rsqrt(var + eps) * scale) + bias
    return y.astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train(x, scale, bias, eps, dtype):
    mean, var = _batch_stats(x, _use_pallas())
    return _normalize(x, mean, var, scale, bias, eps, dtype), mean, var


def _bn_train_fwd(x, scale, bias, eps, dtype):
    mean, var = _batch_stats(x, _use_pallas())
    y = _normalize(x, mean, var, scale, bias, eps, dtype)
    return (y, mean, var), (x, mean, var, scale)


def _bn_train_bwd(eps, dtype, res, cts):
    x, mean, var, scale = res
    dy, _dmean, _dvar = cts  # the stats outputs feed the (non-differentiated)
    #                          running-stat update: their cotangents are zero
    n = x.size // x.shape[-1]
    rstd = jax.lax.rsqrt(var + eps)  # f32
    if _use_pallas():
        dsum, dxh = channel_grad_sums(dy, x, mean, rstd)
    else:
        dyf = dy.astype(jnp.float32)
        xh = (x.astype(jnp.float32) - mean) * rstd
        axes = tuple(range(x.ndim - 1))
        dsum = jnp.sum(dyf, axis=axes)
        dxh = jnp.sum(dyf * xh, axis=axes)
    # dx = γ·r·(dy − (x̂·Σ(dy·x̂) + Σdy)/N): one f32 elementwise pass over
    # (dy, x), cast to x's dtype at the end (mirrors the fwd's f32 math)
    dyf = dy.astype(jnp.float32)
    xh = (x.astype(jnp.float32) - mean) * rstd
    dx = (scale.astype(jnp.float32) * rstd) * (dyf - (xh * (dxh / n) + dsum / n))
    return dx.astype(x.dtype), dxh.astype(scale.dtype), dsum.astype(scale.dtype)


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


class FastBatchNorm(nn.Module):
    """Drop-in `nn.BatchNorm` (same fields, params, and `batch_stats`
    collection) with Pallas train-mode statistics on TPU. `axis_name`
    (SyncBN) takes the inline jnp path with a `pmean` over the per-device
    mean/mean² (mathematically the cross-device batch stats; flax's exact op
    order, autodiff backward) — the Pallas custom-VJP path is per-device
    only, so sync mode never uses it."""

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, use_running_average: bool | None = None):
        use_ra = (
            self.use_running_average
            if use_running_average is None
            else use_running_average
        )
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (c,), self.param_dtype)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((c,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((c,), jnp.float32)
        )
        if use_ra:
            return _normalize(
                x, ra_mean.value, ra_var.value, scale, bias, self.epsilon, self.dtype
            )
        if self.axis_name is None and (_use_pallas() or _use_custom_vjp()):
            # TPU: closed-form custom VJP; reductions are pallas or jnp
            # per _use_pallas() inside _bn_train
            y, mean, var = _bn_train(x, scale, bias, self.epsilon, self.dtype)
        else:
            # off-TPU / SyncBN: plain jnp in flax's exact op order, autodiff
            # backward — bit-identical to nn.BatchNorm (pins CPU goldens)
            xf = x.astype(jnp.float32)
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(xf, axis=axes)
            mean2 = jnp.mean(jax.lax.square(xf), axis=axes)  # lax.square: flax's exact graph
            if self.axis_name is not None and not self.is_initializing():
                mean = jax.lax.pmean(mean, self.axis_name)
                mean2 = jax.lax.pmean(mean2, self.axis_name)
            var = mean2 - mean * mean
            y = _normalize(x, mean, var, scale, bias, self.epsilon, self.dtype)
        if not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1 - m) * mean
            ra_var.value = m * ra_var.value + (1 - m) * var
        return y
