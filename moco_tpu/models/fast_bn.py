"""Train-mode BatchNorm with Pallas streaming reductions (custom VJP).

`nn.BatchNorm`'s train path lowers to XLA reduce fusions for the batch
statistics (forward) and the dgamma/dbeta reductions (backward); round-2
profiling measured those passes at ~half the MoCo-v2 step on the v5e,
running well under the HBM roof. `FastBatchNorm` is a drop-in replacement
(same param/`batch_stats` collections: `scale`, `bias` / `mean`, `var`;
flax running-stat semantics — biased variance, same `momentum`/`epsilon`)
whose train-mode statistics run through `ops/pallas_stats.py` streaming
kernels under a custom VJP:

    fwd:  (Σx, Σx²)  — one Pallas read of x; the normalize stays an XLA
          elementwise op (fuses with the following ReLU/residual-add).
    bwd:  (Σdy, Σdy·x̂) — one Pallas read of dy and x (x̂ recomputed
          in-register); dx is the standard closed form
          dx = γ·r·(dy − (x̂·Σ(dy·x̂) + Σdy)/N), an XLA elementwise pass.

This is the TPU-native equivalent of the reference's cuDNN fused-BN
reductions (`torch.nn.BatchNorm2d` internals; SURVEY §2.10 cuDNN →
MXU/Pallas).

Off-TPU (and for SyncBN via `axis_name`, and eval mode) the math runs as
plain jnp in EXACTLY flax's op order — f32 stats, promote-to-dtype
normalize — so CPU results (golden tests) are bit-identical to
`nn.BatchNorm`. Interpret-mode Pallas can't run inside shard_map regions
off-TPU in this jax version (same constraint as the Pallas blur).
"""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from moco_tpu.ops.pallas_stats import channel_grad_sums, channel_sums


def _use_pallas() -> bool:
    # MOCO_TPU_DISABLE_PALLAS: global kill-switch so the bench orchestrator's
    # retry can rule out EVERY custom Pallas kernel (not just the fused-conv
    # family) as the cause of an on-chip failure
    import os

    return (jax.default_backend() == "tpu"
            and not os.environ.get("MOCO_TPU_DISABLE_PALLAS"))


def _batch_stats(x, use_pallas):
    """f32 (mean, var) over all but the channel axis — flax's
    `_compute_stats` math (biased variance, mean-of-squares form)."""
    n = x.size // x.shape[-1]
    if use_pallas:
        s, sq = channel_sums(x)
        return s / n, sq / n - (s / n) * (s / n)
    xf = x.astype(jnp.float32)
    axes = tuple(range(x.ndim - 1))
    mean = jnp.mean(xf, axis=axes)
    mean2 = jnp.mean(xf * xf, axis=axes)
    return mean, mean2 - mean * mean


def _normalize(x, mean, var, scale, bias, eps, dtype):
    """flax `_normalize` semantics (force_float32_reductions=True): the whole
    computation runs in f32 via promotion — `(x - mean) * (rsqrt(var + eps)
    * scale) + bias` with f32 mean/var/scale/bias — and only the RESULT is
    cast to `dtype`."""
    y = (x - mean) * (jax.lax.rsqrt(var + eps) * scale) + bias
    return y.astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train(x, scale, bias, eps, dtype):
    mean, var = _batch_stats(x, _use_pallas())
    return _normalize(x, mean, var, scale, bias, eps, dtype), mean, var


def _bn_train_fwd(x, scale, bias, eps, dtype):
    mean, var = _batch_stats(x, _use_pallas())
    y = _normalize(x, mean, var, scale, bias, eps, dtype)
    return (y, mean, var), (x, mean, var, scale)


def _bn_train_bwd(eps, dtype, res, cts):
    x, mean, var, scale = res
    dy, _dmean, _dvar = cts  # the stats outputs feed the (non-differentiated)
    #                          running-stat update: their cotangents are zero
    n = x.size // x.shape[-1]
    rstd = jax.lax.rsqrt(var + eps)  # f32
    if _use_pallas():
        dsum, dxh = channel_grad_sums(dy, x, mean, rstd)
    else:
        dyf = dy.astype(jnp.float32)
        xh = (x.astype(jnp.float32) - mean) * rstd
        axes = tuple(range(x.ndim - 1))
        dsum = jnp.sum(dyf, axis=axes)
        dxh = jnp.sum(dyf * xh, axis=axes)
    # dx = γ·r·(dy − (x̂·Σ(dy·x̂) + Σdy)/N): one f32 elementwise pass over
    # (dy, x), cast to x's dtype at the end (mirrors the fwd's f32 math)
    dyf = dy.astype(jnp.float32)
    xh = (x.astype(jnp.float32) - mean) * rstd
    dx = (scale.astype(jnp.float32) * rstd) * (dyf - (xh * (dxh / n) + dsum / n))
    return dx.astype(x.dtype), dxh.astype(scale.dtype), dsum.astype(scale.dtype)


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


class FastBatchNorm(nn.Module):
    """Drop-in `nn.BatchNorm` (same fields, params, and `batch_stats`
    collection) with Pallas train-mode statistics on TPU. `axis_name`
    (SyncBN) takes the inline jnp path with a `pmean` over the per-device
    mean/mean² (mathematically the cross-device batch stats; flax's exact op
    order, autodiff backward) — the Pallas custom-VJP path is per-device
    only, so sync mode never uses it."""

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, use_running_average: bool | None = None):
        use_ra = (
            self.use_running_average
            if use_running_average is None
            else use_running_average
        )
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (c,), self.param_dtype)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((c,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((c,), jnp.float32)
        )
        if use_ra:
            return _normalize(
                x, ra_mean.value, ra_var.value, scale, bias, self.epsilon, self.dtype
            )
        if self.axis_name is None and _use_pallas():
            # TPU: Pallas streaming reductions under the custom VJP
            y, mean, var = _bn_train(x, scale, bias, self.epsilon, self.dtype)
        else:
            # off-TPU / SyncBN: plain jnp in flax's exact op order, autodiff
            # backward — bit-identical to nn.BatchNorm (pins CPU goldens)
            xf = x.astype(jnp.float32)
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(xf, axis=axes)
            mean2 = jnp.mean(jax.lax.square(xf), axis=axes)  # lax.square: flax's exact graph
            if self.axis_name is not None and not self.is_initializing():
                mean = jax.lax.pmean(mean, self.axis_name)
                mean2 = jax.lax.pmean(mean2, self.axis_name)
            var = mean2 - mean * mean
            y = _normalize(x, mean, var, scale, bias, self.epsilon, self.dtype)
        if not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1 - m) * mean
            ra_var.value = m * ra_var.value + (1 - m) * var
        return y
