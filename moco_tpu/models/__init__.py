from moco_tpu.models.resnet import (
    ARCHS,
    FEATURE_DIMS,
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    build_resnet,
)
from moco_tpu.models.heads import V3Predictor, V3Projector

__all__ = [
    "ARCHS",
    "FEATURE_DIMS",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "build_resnet",
    "V3Predictor",
    "V3Projector",
]
