from moco_tpu.models.resnet import (
    ARCHS,
    FEATURE_DIMS,
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    build_resnet,
)
from moco_tpu.models.heads import V3Predictor, V3Projector


def build_backbone(arch: str, *, cifar_stem: bool = False, num_classes=None):
    """Feature-mode encoder for NON-TRAINING consumers (the lincls probe,
    the serve/ embedding service): one arch router for both families, so
    'which constructor does this arch use' is decided in exactly one place.
    `num_classes=None` yields pooled backbone features, the transfer
    product both consumers read."""
    if arch.startswith("vit"):
        from moco_tpu.models.vit import build_vit

        return build_vit(arch, num_classes=num_classes)
    return build_resnet(arch, num_classes=num_classes, cifar_stem=cifar_stem)


__all__ = [
    "build_backbone",
    "ARCHS",
    "FEATURE_DIMS",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "build_resnet",
    "V3Predictor",
    "V3Projector",
]
