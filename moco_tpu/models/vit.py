"""Vision Transformer backbone for MoCo v3 (BASELINE config 5; SURVEY §2.9).

Rebuild of the sibling repo's `vits.py` (`moco-v3`): ViT-S/16 = 12 blocks,
width 384, **12 heads** (head dim 32 — moco-v3's `vit_small` deliberately
doubles timm's 6 heads); 224² → 14×14 = 196 patch tokens + a class token.
MoCo-v3 specifics reproduced here:

- FIXED 2-D sin-cos positional embedding (not learned) — the paper's choice
  for stability.
- `frozen_patch_embed=True` applies `stop_gradient` to the patch-projection
  output, so no gradient reaches the patch-embed kernel (the paper's
  "random patch projection" stability trick). The optimizer additionally
  masks those params out (see v3_step.patch_embed_trainable_mask) so weight
  decay cannot move them either — together these equal the reference's
  `requires_grad=False`.

At 197 tokens the attention is tiny by TPU standards — XLA compiles it
straight to MXU matmuls; no custom flash-attention kernel is warranted at
this scale (SURVEY §5.7).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def sincos_2d_position_embedding(h: int, w: int, dim: int) -> jnp.ndarray:
    """Fixed 2-D sin-cos embedding `[1, h*w, dim]` (moco-v3's
    `build_2d_sincos_position_embedding`; temperature 10000)."""
    assert dim % 4 == 0, "sin-cos embedding needs dim divisible by 4"
    grid_h = np.arange(h, dtype=np.float32)
    grid_w = np.arange(w, dtype=np.float32)
    gw, gh = np.meshgrid(grid_w, grid_h)  # [h, w] each
    pos_dim = dim // 4
    omega = 1.0 / (10000 ** (np.arange(pos_dim, dtype=np.float32) / pos_dim))
    out_w = np.einsum("hw,d->hwd", gw, omega).reshape(h * w, pos_dim)
    out_h = np.einsum("hw,d->hwd", gh, omega).reshape(h * w, pos_dim)
    emb = np.concatenate(
        [np.sin(out_w), np.cos(out_w), np.sin(out_h), np.cos(out_h)], axis=1
    )
    return jnp.asarray(emb[None], jnp.float32)


class TransformerBlock(nn.Module):
    dim: int
    num_heads: int
    mlp_ratio: float = 4.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(dtype=self.dtype, name="norm1")(x)
        y = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="attn",
        )(y, y)
        x = x + y
        y = nn.LayerNorm(dtype=self.dtype, name="norm2")(x)
        y = nn.Dense(int(self.dim * self.mlp_ratio), dtype=self.dtype,
                     param_dtype=jnp.float32, name="mlp_fc1")(y)
        # exact erf GELU — timm/moco-v3's nn.GELU (flax's default is the
        # tanh approximation, a real if small distributional deviation)
        y = nn.gelu(y, approximate=False)
        y = nn.Dense(self.dim, dtype=self.dtype, param_dtype=jnp.float32,
                     name="mlp_fc2")(y)
        return x + y


class ViT(nn.Module):
    """ViT encoder; returns the class-token feature (`num_classes=None`) or a
    linear head over it."""

    patch_size: int = 16
    width: int = 384
    depth: int = 12
    num_heads: int = 12
    mlp_ratio: float = 4.0
    num_classes: int | None = None
    frozen_patch_embed: bool = True
    remat: bool = False   # rematerialize each block (trade FLOPs for HBM —
                          # lets the v3 large-batch recipe fit; SURVEY §7 /
                          # scaling-book recipe)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        b, h, w, _ = x.shape
        gh, gw = h // self.patch_size, w // self.patch_size
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.width,
            (self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size),
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="patch_embed",
        )(x)
        x = x.reshape(b, gh * gw, self.width)
        if self.frozen_patch_embed:
            # moco-v3 stability trick: random, never-trained patch projection
            x = jax.lax.stop_gradient(x)
        x = x + sincos_2d_position_embedding(gh, gw, self.width).astype(self.dtype)
        cls = self.param(
            "cls_token", nn.initializers.normal(1e-6), (1, 1, self.width), jnp.float32
        )
        x = jnp.concatenate([jnp.broadcast_to(cls, (b, 1, self.width)).astype(self.dtype), x], axis=1)
        block_cls = nn.remat(TransformerBlock) if self.remat else TransformerBlock
        for i in range(self.depth):
            x = block_cls(
                self.width, self.num_heads, self.mlp_ratio, self.dtype, name=f"block{i}"
            )(x)
        x = nn.LayerNorm(dtype=self.dtype, name="norm")(x)
        feat = x[:, 0].astype(jnp.float32)  # class token
        if self.num_classes is None:
            return feat
        return nn.Dense(self.num_classes, param_dtype=jnp.float32, name="head")(feat)


# moco-v3's vits.py defines vit_small with 12 heads (head dim 32), NOT
# timm's 6 — matching it exactly so the preset reproduces the reference
# attention architecture (ADVICE r1).
ViT_Small = partial(ViT, width=384, depth=12, num_heads=12)
ViT_Base = partial(ViT, width=768, depth=12, num_heads=12)
# the paper's scaling study archs (moco-v3 §4/Table 3: ViT-L/H train with
# the same recipe at batch 4096; standard timm geometry, 64-dim heads)
ViT_Large = partial(ViT, width=1024, depth=24, num_heads=16)
ViT_Huge = partial(ViT, width=1280, depth=32, num_heads=16, patch_size=14)
# test/debug arch (keeps moco-v3's 32-per-head convention at width 64)
ViT_Tiny = partial(ViT, width=64, depth=2, num_heads=2)

VIT_ARCHS = {"vit_tiny": ViT_Tiny, "vit_small": ViT_Small,
             "vit_base": ViT_Base, "vit_large": ViT_Large,
             "vit_huge": ViT_Huge}
VIT_FEATURE_DIMS = {"vit_tiny": 64, "vit_small": 384, "vit_base": 768,
                    "vit_large": 1024, "vit_huge": 1280}


def build_vit(arch: str, num_classes: int | None = None, **kwargs) -> ViT:
    if arch not in VIT_ARCHS:
        raise ValueError(f"unknown vit arch {arch!r}; choose from {sorted(VIT_ARCHS)}")
    return VIT_ARCHS[arch](num_classes=num_classes, **kwargs)
