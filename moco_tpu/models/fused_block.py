"""Fused bn→relu→1x1-conv tail for the Bottleneck block (custom VJP).

The Bottleneck's `bn2 → relu → conv3` sequence materializes the normalized
activation in HBM twice (write after normalize, read by the conv). On the
HBM-bound MoCo step that's pure waste: a 1x1 conv is a matmul, and the
normalize+ReLU is an affine-plus-clamp that can run in-register while tiles
stream into the MXU (`ops/pallas_fused_conv.py`). This module packages that
kernel with

- parameter/variable declaration that EXACTLY mirrors the unfused modules
  (`bn2/{scale,bias}`, `batch_stats bn2/{mean,var}`, `conv3/kernel` of shape
  [1,1,K,N]) so checkpoints/exports are byte-compatible either way, and
- a custom VJP whose backward recomputes z = relu(x̂) inside the dW matmul
  operand (one extra streaming read of x instead of a stored z) and reuses
  FastBatchNorm's closed-form BN chain (`pallas_stats` reductions on TPU).

Off-TPU the SAME params drive a plain `lax.conv`-based path (flax op order),
so golden tests and CPU training are unchanged; the Pallas path engages on
TPU only. SyncBN (`axis_name`) is not supported here — the caller falls back
to the unfused modules (MoCo's BN is per-device by design, SURVEY §7).

Reference equivalent: cuDNN fused conv+BN epilogues (SURVEY §2.10).
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp

from moco_tpu.models.fast_bn import _batch_stats, _normalize
from moco_tpu.ops.pallas_fused_conv import bn_relu_matmul, bn_relu_matmul_dw
from moco_tpu.ops.pallas_fused_conv3x3 import (
    bn_relu_conv3x3,
    bn_relu_conv3x3_s2,
    conv3x3_dw,
)
from moco_tpu.ops.pallas_stats import channel_grad_sums


def _use_pallas() -> bool:
    """Gate for the fused-conv kernel family — a block only reaches this
    module when `config.fused_bn_conv=True` routed it here, so this is
    deliberately INDEPENDENT of fast_bn's BN-stats opt-in
    (MOCO_TPU_PALLAS_BN): the r5 A/B that turned the stats kernels off by
    default must not silently disable the separately-validated fused
    family's documented config switch (review, r5). The global
    MOCO_TPU_DISABLE_PALLAS kill-switch (bench retry) still applies; off
    TPU the blocks fall back to `_plain_apply`."""
    from moco_tpu.utils.envflags import env_flag

    return (jax.default_backend() == "tpu"
            and not env_flag("MOCO_TPU_DISABLE_PALLAS"))


def norm_train_flag(norm) -> bool:
    """Train-mode sniff shared by the fused blocks: the ResNet passes its
    norm as a `functools.partial` carrying `use_running_average=not train`.
    A bare module class (no `keywords`) yields train=True, matching
    `nn.BatchNorm`'s own `use_running_average=False` default."""
    return not getattr(norm, "keywords", {}).get("use_running_average", False)


def _plain_apply(x, mean, var, scale, bias, w4d, eps, dtype):
    """The unfused math in flax's exact op order: f32 normalize cast to
    `dtype`, ReLU, then the 1x1 conv as `lax.conv` in `dtype` (what
    `nn.Conv(use_bias=False, dtype=...)` lowers to)."""
    z = nn.relu(_normalize(x, mean, var, scale, bias, eps, dtype))
    return jax.lax.conv_general_dilated(
        z,
        w4d.astype(dtype),
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _train_impl(x, scale, bias, w4d, eps, dtype):
    mean, var = _batch_stats(x, _use_pallas())
    if _use_pallas():
        k, n = w4d.shape[-2], w4d.shape[-1]
        rstd = jax.lax.rsqrt(var + eps)
        a = scale * rstd
        y = bn_relu_matmul(
            x.reshape(-1, k),
            a,
            bias - mean * a,
            w4d.reshape(k, n).astype(dtype),
            out_dtype=dtype,
        ).reshape(*x.shape[:-1], n)
    else:
        y = _plain_apply(x, mean, var, scale, bias, w4d, eps, dtype)
    return y, mean, var


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _bn_relu_conv_train(x, scale, bias, w4d, eps, dtype):
    return _train_impl(x, scale, bias, w4d, eps, dtype)


def _fwd(x, scale, bias, w4d, eps, dtype):
    y, mean, var = _train_impl(x, scale, bias, w4d, eps, dtype)
    return (y, mean, var), (x, mean, var, scale, bias, w4d)


def _bwd(eps, dtype, res, cts):
    x, mean, var, scale, bias, w4d = res
    dy, _dmean, _dvar = cts  # stats feed the (non-differentiated) running
    #                          stats; their cotangents are zero
    k, n = w4d.shape[-2], w4d.shape[-1]
    m_rows = x.size // k
    xr = x.reshape(m_rows, k)
    dyr = dy.reshape(m_rows, n)
    rstd = jax.lax.rsqrt(var + eps)  # f32
    a = (scale * rstd).astype(jnp.float32)
    shift = (bias - mean * a).astype(jnp.float32)
    if _use_pallas():
        # ẑ recomputed inside the Pallas dW kernel's VMEM tiles — x streams
        # once, the normalized activation never exists in HBM in the
        # backward either (no bet on XLA operand fusion)
        dw = bn_relu_matmul_dw(xr, a, shift, dyr).reshape(
            w4d.shape).astype(w4d.dtype)
        zpre = xr.astype(jnp.float32) * a + shift  # XLA fuses into the mask
    else:
        zpre = xr.astype(jnp.float32) * a + shift
        z = jnp.maximum(zpre, 0.0).astype(dtype)
        dw = jnp.einsum(
            "mk,mn->kn", z, dyr, preferred_element_type=jnp.float32
        ).reshape(w4d.shape).astype(w4d.dtype)
    # gradient at the normalize output, ReLU-masked
    g = jnp.einsum(
        "mn,kn->mk", dyr, w4d.reshape(k, n).astype(dyr.dtype),
        preferred_element_type=jnp.float32,
    ) * (zpre > 0)
    g = g.reshape(x.shape)
    # BN chain (FastBatchNorm's closed form): dγ = Σg·x̂, dβ = Σg,
    # dx = γ·r·(g − (x̂·Σ(g·x̂) + Σg)/N)
    if _use_pallas():
        dsum, dxh = channel_grad_sums(g, x, mean, rstd)
    else:
        gf = g.reshape(m_rows, k)
        xh = (xr.astype(jnp.float32) - mean) * rstd
        dsum = jnp.sum(gf, axis=0)
        dxh = jnp.sum(gf * xh, axis=0)
    nelem = m_rows
    xh_full = (x.astype(jnp.float32) - mean) * rstd
    dx = (scale * rstd) * (
        g.astype(jnp.float32) - (xh_full * (dxh / nelem) + dsum / nelem)
    )
    return (
        dx.astype(x.dtype),
        dxh.astype(scale.dtype),
        dsum.astype(bias.dtype),
        dw,
    )


_bn_relu_conv_train.defvjp(_fwd, _bwd)


def _conv3x3(z, w4d, dtype):
    return jax.lax.conv_general_dilated(
        z, w4d.astype(dtype), (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _plain_apply3x3(x, mean, var, scale, bias, w4d, eps, dtype):
    z = nn.relu(_normalize(x, mean, var, scale, bias, eps, dtype))
    return _conv3x3(z, w4d, dtype)


def _train3x3_impl(x, scale, bias, w4d, eps, dtype):
    mean, var = _batch_stats(x, _use_pallas())
    if _use_pallas():
        rstd = jax.lax.rsqrt(var + eps)
        a = scale * rstd
        y = bn_relu_conv3x3(x, a, bias - mean * a, w4d, out_dtype=dtype)
    else:
        y = _plain_apply3x3(x, mean, var, scale, bias, w4d, eps, dtype)
    return y, mean, var


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _bn_relu_conv3x3_train(x, scale, bias, w4d, eps, dtype):
    return _train3x3_impl(x, scale, bias, w4d, eps, dtype)


def _fwd3x3(x, scale, bias, w4d, eps, dtype):
    y, mean, var = _train3x3_impl(x, scale, bias, w4d, eps, dtype)
    return (y, mean, var), (x, mean, var, scale, bias, w4d)


def _bn_chain(g, x, mean, rstd, scale):
    """The closed-form BN backward shared by every fused conv: given the
    ReLU-masked gradient g at the normalize output, return (dx, dγ, dβ)."""
    k = x.shape[-1]
    if _use_pallas():
        dsum, dxh = channel_grad_sums(g, x, mean, rstd)
    else:
        gf = g.reshape(-1, k)
        xh = (x.reshape(-1, k).astype(jnp.float32) - mean) * rstd
        dsum = jnp.sum(gf, axis=0)
        dxh = jnp.sum(gf * xh, axis=0)
    nelem = x.size // k
    xh_full = (x.astype(jnp.float32) - mean) * rstd
    dx = (scale * rstd) * (g - (xh_full * (dxh / nelem) + dsum / nelem))
    return dx, dxh, dsum


def _bwd3x3(eps, dtype, res, cts):
    x, mean, var, scale, bias, w4d = res
    dy, _dmean, _dvar = cts
    rstd = jax.lax.rsqrt(var + eps)
    a = (scale * rstd).astype(jnp.float32)
    shift = (bias - mean * a).astype(jnp.float32)
    zpre = x.astype(jnp.float32) * a + shift
    # the input-gradient never reads z's VALUE — it is the transposed conv
    # of dy with the spatially-flipped, channel-transposed taps, already an
    # optimal MXU conv as plain XLA on every backend
    dz = _conv3x3(dy, w4d[::-1, ::-1].transpose(0, 1, 3, 2), dtype)
    if _use_pallas():
        # filter gradient with ẑ recomputed in VMEM (conv3x3_dw): z now
        # never exists in HBM in the backward either; the ReLU mask below
        # fuses into g's multiply
        dw = conv3x3_dw(x, a, shift, dy).astype(w4d.dtype)
    else:
        z = jnp.maximum(zpre, 0.0).astype(dtype)
        _, conv_vjp = jax.vjp(lambda w_: _conv3x3(z, w_, dtype), w4d)
        (dw,) = conv_vjp(dy)
    g = dz.astype(jnp.float32) * (zpre > 0)
    dx, dxh, dsum = _bn_chain(g, x, mean, rstd, scale)
    return (
        dx.astype(x.dtype),
        dxh.astype(scale.dtype),
        dsum.astype(bias.dtype),
        dw.astype(w4d.dtype),
    )


_bn_relu_conv3x3_train.defvjp(_fwd3x3, _bwd3x3)


def _conv3x3s2(z, w4d, dtype):
    return jax.lax.conv_general_dilated(
        z, w4d.astype(dtype), (2, 2), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _plain_apply3x3s2(x, mean, var, scale, bias, w4d, eps, dtype):
    z = nn.relu(_normalize(x, mean, var, scale, bias, eps, dtype))
    return _conv3x3s2(z, w4d, dtype)


def _train3x3s2_impl(x, scale, bias, w4d, eps, dtype):
    mean, var = _batch_stats(x, _use_pallas())
    if _use_pallas():
        rstd = jax.lax.rsqrt(var + eps)
        a = scale * rstd
        y = bn_relu_conv3x3_s2(x, a, bias - mean * a, w4d, out_dtype=dtype)
    else:
        y = _plain_apply3x3s2(x, mean, var, scale, bias, w4d, eps, dtype)
    return y, mean, var


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _bn_relu_conv3x3s2_train(x, scale, bias, w4d, eps, dtype):
    return _train3x3s2_impl(x, scale, bias, w4d, eps, dtype)


def _fwd3x3s2(x, scale, bias, w4d, eps, dtype):
    y, mean, var = _train3x3s2_impl(x, scale, bias, w4d, eps, dtype)
    return (y, mean, var), (x, mean, var, scale, bias, w4d)


def _bwd3x3s2(eps, dtype, res, cts):
    """Stride-2 backward: z is recomputed (not stored — the forward kernel
    never wrote it) and materialized ONCE here for the two conv VJPs; the
    fusion still nets one HBM round-trip vs the unfused block, whose
    forward writes z AND whose backward reads it back."""
    x, mean, var, scale, bias, w4d = res
    dy, _dmean, _dvar = cts
    rstd = jax.lax.rsqrt(var + eps)
    a = (scale * rstd).astype(jnp.float32)
    shift = (bias - mean * a).astype(jnp.float32)
    zpre = x.astype(jnp.float32) * a + shift
    z = jnp.maximum(zpre, 0.0).astype(dtype)
    _, conv_vjp = jax.vjp(lambda z_, w_: _conv3x3s2(z_, w_, dtype), z, w4d)
    dz, dw = conv_vjp(dy)
    g = dz.astype(jnp.float32) * (zpre > 0)
    dx, dxh, dsum = _bn_chain(g, x, mean, rstd, scale)
    return (
        dx.astype(x.dtype),
        dxh.astype(scale.dtype),
        dsum.astype(bias.dtype),
        dw.astype(w4d.dtype),
    )


_bn_relu_conv3x3s2_train.defvjp(_fwd3x3s2, _bwd3x3s2)


def _fused_bn_relu_conv(
    mdl: nn.Module,
    x: jax.Array,
    bn_name: str,
    conv_name: str,
    kshape: tuple,
    train: bool,
    momentum: float,
    eps: float,
    dtype,
    plain_fn,
    train_fn,
) -> jax.Array:
    """Shared scaffolding for both fusions: declare bn+conv params/stats
    under `mdl`'s scope with the UNFUSED module names (checkpoint/export
    byte-compatible), gate eval/init onto `plain_fn` (running stats), and
    run `train_fn` (the custom-VJP fused path) with the flax running-stat
    update."""
    k = x.shape[-1]
    bn = mdl.param(
        bn_name,
        lambda rng: {
            "scale": jnp.ones((k,), jnp.float32),
            "bias": jnp.zeros((k,), jnp.float32),
        },
    )
    w4d = mdl.param(
        conv_name,
        lambda rng: {
            "kernel": nn.initializers.lecun_normal()(rng, kshape, jnp.float32)
        },
    )["kernel"]
    ra = mdl.variable(
        "batch_stats",
        bn_name,
        lambda: {
            "mean": jnp.zeros((k,), jnp.float32),
            "var": jnp.ones((k,), jnp.float32),
        },
    )
    if not train or mdl.is_initializing():
        return plain_fn(
            x, ra.value["mean"], ra.value["var"], bn["scale"], bn["bias"],
            w4d, eps, dtype,
        )
    y, mean, var = train_fn(x, bn["scale"], bn["bias"], w4d, eps, dtype)
    ra.value = {
        "mean": momentum * ra.value["mean"] + (1 - momentum) * mean,
        "var": momentum * ra.value["var"] + (1 - momentum) * var,
    }
    return y


def fused_bn_relu_conv2(
    mdl: nn.Module, x, features: int, train: bool, momentum: float,
    eps: float, dtype,
) -> jax.Array:
    """The bn1→relu→conv2 (3x3, stride-1) interior fusion — Bottleneck mids
    and BasicBlock tails."""
    return _fused_bn_relu_conv(
        mdl, x, "bn1", "conv2", (3, 3, x.shape[-1], features), train,
        momentum, eps, dtype, _plain_apply3x3, _bn_relu_conv3x3_train,
    )


def fused_bn_relu_conv2_s2(
    mdl: nn.Module, x, features: int, train: bool, momentum: float,
    eps: float, dtype,
) -> jax.Array:
    """The stride-2 bn1→relu→conv2 fusion — the stage-first Bottleneck
    blocks (VERDICT r3 #5); forward through the Pallas stride-2 kernel,
    backward recomputes z once for the plain-XLA conv VJPs."""
    return _fused_bn_relu_conv(
        mdl, x, "bn1", "conv2", (3, 3, x.shape[-1], features), train,
        momentum, eps, dtype, _plain_apply3x3s2, _bn_relu_conv3x3s2_train,
    )


def fused_bn_relu_conv3(
    mdl: nn.Module, x, features: int, train: bool, momentum: float,
    eps: float, dtype,
) -> jax.Array:
    """The Bottleneck's bn2→relu→conv3 (1x1) tail fusion."""
    return _fused_bn_relu_conv(
        mdl, x, "bn2", "conv3", (1, 1, x.shape[-1], features), train,
        momentum, eps, dtype, _plain_apply, _bn_relu_conv_train,
    )
