"""The negative-key queue (TPU-native rebuild of `moco/builder.py:≈L40-70`).

The reference holds the queue as a `[dim, K]` module buffer and enqueues with
a sliced assignment under `no_grad`. Here the queue is an ordinary array in
the train-state pytree:

- Stored `[K, dim]` (row-major keys) so the enqueue is a single
  `lax.dynamic_update_slice_in_dim` over rows and the negatives logits are a
  `[B, dim] x [K, dim]^T` matmul — both MXU/HBM friendly. The reference's
  `[dim, K]` layout exists only to make `queue[:, ptr:ptr+bs] = keys.T` read
  nicely in torch; the transposition is a layout choice, not semantics.
- In-place semantics come from BUFFER DONATION: the train step is jitted with
  the state donated, so XLA aliases the 65536x128 queue update into the input
  buffer (the north-star's "donated buffer with in-place _dequeue_and_enqueue").
- Replicated consistency: every device computes the identical enqueue from
  the all-gathered global key batch, so no DDP-style buffer re-broadcast
  (`broadcast_buffers`) is needed (SURVEY §2.2 note).

Ordering invariant kept by the caller (train_step): enqueue happens AFTER the
logits are computed — the current batch's keys are never their own negatives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def init_queue(key: jax.Array, num_negatives: int, dim: int, dtype=jnp.float32) -> tuple[jax.Array, jax.Array]:
    """Random L2-normalized queue + zero pointer.

    Mirrors `register_buffer("queue", F.normalize(randn(dim, K), dim=0))`
    (`moco/builder.py:≈L38-42`), transposed to `[K, dim]` (each ROW unit-norm).
    """
    from moco_tpu.ops.losses import l2_normalize

    q = l2_normalize(jax.random.normal(key, (num_negatives, dim), dtype=jnp.float32))
    return q.astype(dtype), jnp.zeros((), dtype=jnp.int32)


def dequeue_and_enqueue(
    queue: jax.Array, ptr: jax.Array, keys: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """FIFO ring-buffer enqueue of the GLOBAL key batch.

    Rebuild of `_dequeue_and_enqueue` (`moco/builder.py:≈L56-70`):
    `queue[ptr:ptr+B] = keys; ptr = (ptr+B) % K`, with the reference's
    `assert K % batch_size == 0` enforced statically at trace time so the
    dynamic-slice never wraps (same precondition, checked earlier).

    `keys` must already be the all-gathered global batch and stop-gradiented
    by the caller (the reference runs this under `@torch.no_grad()`).
    """
    k_slots, b = queue.shape[0], keys.shape[0]
    if k_slots % b != 0:
        raise ValueError(
            f"queue size {k_slots} must be divisible by global batch {b} "
            "(reference asserts K % batch_size == 0)"
        )
    queue = lax.dynamic_update_slice_in_dim(queue, keys.astype(queue.dtype), ptr, axis=0)
    new_ptr = (ptr + b) % k_slots
    return queue, new_ptr
