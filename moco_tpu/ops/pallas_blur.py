"""Pallas TPU kernel: per-sample separable Gaussian blur.

The v2 augmentation stack blurs each key/query crop with a per-sample random
sigma (SimCLR-style `GaussianBlur`, `moco/loader.py:≈L20-32`). The portable
implementation (data/augment.py) is 2x(2R+1) weighted shifted-adds over the
full image — ~46 full-image HBM round-trips per sample. This kernel does the
whole separable stencil in VMEM: ONE read of the padded image, one write of
the result, with both convolution passes and the intermediate transpose
on-chip. A measured ~10% of the MoCo-v2 step time on v5e rides on this op.

Layout notes (TPU tiling wants the last dim to be lanes=128-ish):
- Images are processed as `[3, H, W]` (channels first), so H/W land on the
  sublane/lane dims instead of the 3-wide channel axis.
- The H pass shifts along sublanes; the array is then transposed in VMEM so
  the W pass also shifts along sublanes (lane shifts are the slow path).
- Per-sample kernel WEIGHTS carry both the sigma and the apply/skip draw
  (skip == identity kernel: one-hot at the center tap) so there is no
  divergent control flow.

The public entry `gaussian_blur_batch` is vmapped over the batch (pallas
lifts the vmap axis into the grid); `interpret=True` is used automatically
off-TPU so the same code path is unit-testable on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from moco_tpu.utils.compat import shape_dtype_struct
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _blur_kernel(img_ref, kern_ref, out_ref):
    """One sample. img_ref: [3, H+2R, W+2R] edge-padded; kern_ref: [1, 2R+1]
    (SMEM); out_ref: [3, H, W]. Accumulates in f32 whatever the I/O dtype."""
    taps = kern_ref.shape[-1]
    h, w = out_ref.shape[1], out_ref.shape[2]
    x = img_ref[...].astype(jnp.float32)  # [3, H+2R, W+2R] in VMEM
    # H pass: shift along sublanes
    acc = jnp.zeros((3, h, x.shape[2]), jnp.float32)
    for j in range(taps):
        acc = acc + kern_ref[0, j] * x[:, j : j + h, :]
    # transpose so the W pass also shifts along sublanes
    t = jnp.transpose(acc, (0, 2, 1))  # [3, W+2R, H]
    acc2 = jnp.zeros((3, w, h), jnp.float32)
    for j in range(taps):
        acc2 = acc2 + kern_ref[0, j] * t[:, j : j + w, :]
    out_ref[...] = jnp.transpose(acc2, (0, 2, 1)).astype(out_ref.dtype)  # [3, H, W]


@functools.partial(jax.jit, static_argnames=("radius", "interpret"))
def gaussian_blur_batch(
    images: jax.Array,   # [B, H, W, 3] float (NHWC, the pipeline dtype)
    kernels: jax.Array,  # [B, 2R+1] per-sample normalized tap weights
    radius: int,
    interpret: bool = False,
) -> jax.Array:
    """Apply each sample's separable kernel to its image; returns NHWC in
    the input dtype (f32 accumulation inside the kernel)."""
    b, h, w, _ = images.shape
    taps = 2 * radius + 1
    assert kernels.shape == (b, taps), (kernels.shape, (b, taps))
    chw = jnp.transpose(images, (0, 3, 1, 2))  # [B, 3, H, W]
    padded = jnp.pad(
        chw, ((0, 0), (0, 0), (radius, radius), (radius, radius)), mode="edge"
    )

    def one(img_padded, kern):
        # inside a shard_map region the replication checker needs to know the
        # output varies the same way the input does (vma must be explicit on
        # pallas outputs); outside, vma is just empty
        vma = getattr(getattr(img_padded, "aval", None), "vma", frozenset())
        return pl.pallas_call(
            _blur_kernel,
            out_shape=shape_dtype_struct((3, h, w), images.dtype, vma=vma),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            interpret=interpret,
        )(img_padded, kern.reshape(1, taps))

    out = jax.vmap(one)(padded, kernels.astype(jnp.float32))
    return jnp.transpose(out, (0, 2, 3, 1))


def blur_radius(out_size: int) -> int:
    """Fixed tap radius for a given crop size (single source of truth for
    both the portable and Pallas blur paths)."""
    return max(1, int(0.05 * out_size))


def blur_weights(key: jax.Array, radius: int, sigma_range, prob: float) -> jax.Array:
    """Per-sample tap weights folding in BOTH the sigma draw and the
    apply-probability draw (skip == identity one-hot kernel). The single
    source of the sigma/apply sampling math — the portable shifted-add blur
    in data/augment.py consumes these same weights."""
    ksig, kp = jax.random.split(key)
    sigma = jax.random.uniform(ksig, (), minval=sigma_range[0], maxval=sigma_range[1])
    offs = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    kernel = jnp.exp(-0.5 * (offs / sigma) ** 2)
    kernel = kernel / jnp.sum(kernel)
    identity = jnp.zeros((2 * radius + 1,), jnp.float32).at[radius].set(1.0)
    apply = jax.random.uniform(kp, ()) < prob
    return jnp.where(apply, kernel, identity)
