"""Weighted-kNN classification on frozen features (BASELINE config 4; SURVEY
§2.5, §3.3 — the InstDisc protocol used by every MoCo kNN monitor).

Protocol: cosine similarity of each query feature against a normalized
feature bank, top-`k` neighbors (200), votes weighted `exp(sim / T)` with
T=0.07, argmax class. Zero trainable parameters.

TPU mapping: the similarity is ONE `[B, dim] x [N_bank, dim]^T` matmul
(MXU-friendly, SURVEY §3.3); `lax.top_k` runs on-device; the class vote is a
one-hot einsum rather than a scatter so the whole classifier is a fused,
static-shaped XLA program.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from moco_tpu.ops.losses import l2_normalize


@functools.partial(jax.jit, static_argnames=("num_classes", "k", "bank_chunk"))
def _knn_predict_prenormalized(
    feats: jax.Array,         # [B, dim] L2-normalized queries
    bank: jax.Array,          # [N, dim] L2-normalized bank
    bank_labels: jax.Array,   # [N] int labels
    num_classes: int,
    k: int = 200,
    temperature: float = 0.07,
    bank_chunk: int | None = None,
) -> jax.Array:
    """`bank_chunk` streams the bank through a `lax.scan`, carrying a running
    top-k merge, so peak live memory is `[B, bank_chunk]` sims + `[B, 2k]`
    merge instead of the full `[B, N]` similarity matrix — the ImageNet-scale
    path (N=1.28M: a [512, 1.28M] f32 matrix is 2.6 GB and `top_k` over 1.28M
    columns is the slow/hungry op; chunked at 64k it is 134 MB/step and 20
    cheap top-ks). Exact: per-chunk top-k ∪ running top-k ⊇ global top-k."""
    n = bank.shape[0]
    if bank_chunk is None or bank_chunk >= n:
        sims = jnp.einsum("bc,nc->bn", feats, bank, preferred_element_type=jnp.float32)
        k = min(k, n)
        top_sims, top_idx = lax.top_k(sims, k)                  # [B, k]
        neigh_labels = bank_labels[top_idx]                     # [B, k]
    else:
        # exact for ANY k ≤ N (ADVICE r2: k used to be silently clamped to
        # bank_chunk): each chunk can contribute at most min(k, bank_chunk)
        # rows to the global top-k, so a carry of k rows merged with
        # per-chunk top-min(k, chunk) loses nothing
        k = min(k, n)
        chunk_k = min(k, bank_chunk)
        b = feats.shape[0]
        n_chunks = -(-n // bank_chunk)
        pad = n_chunks * bank_chunk - n
        bank = jnp.pad(bank, ((0, pad), (0, 0)))
        # padded rows have sim 0 to everything; push them below any real
        # neighbor with an ADDITIVE -inf mask (0 on valid rows) so real
        # similarities pass through bit-exact — a min/clamp sentinel would
        # flatten sims that exceed it (normalized features can give
        # 1+ulp sims) into artificial ties with path-dependent winners
        valid = jnp.pad(jnp.zeros((n,), jnp.float32), (0, pad),
                        constant_values=-jnp.inf)
        bank_labels = jnp.pad(bank_labels, (0, pad))
        chunks = bank.reshape(n_chunks, bank_chunk, -1)
        label_chunks = bank_labels.reshape(n_chunks, bank_chunk)
        valid_chunks = valid.reshape(n_chunks, bank_chunk)

        def merge(carry, chunk):
            best_s, best_l = carry
            cb, cl, cv = chunk
            sims = jnp.einsum("bc,nc->bn", feats, cb,
                              preferred_element_type=jnp.float32)
            sims = sims + cv[None, :]               # -inf on padded rows
            top_s, top_i = lax.top_k(sims, chunk_k)
            cand_s = jnp.concatenate([best_s, top_s], axis=1)       # [B, k+chunk_k]
            cand_l = jnp.concatenate([best_l, cl[top_i]], axis=1)
            best_s, sel = lax.top_k(cand_s, k)
            best_l = jnp.take_along_axis(cand_l, sel, axis=1)
            return (best_s, best_l), None

        init = (
            jnp.full((b, k), -jnp.inf, jnp.float32),
            jnp.zeros((b, k), bank_labels.dtype),
        )
        (top_sims, neigh_labels), _ = lax.scan(
            merge, init, (chunks, label_chunks, valid_chunks)
        )
    weights = jnp.exp(top_sims / temperature)
    onehot = jax.nn.one_hot(neigh_labels, num_classes, dtype=jnp.float32)
    votes = jnp.einsum("bk,bkc->bc", weights, onehot)
    return jnp.argmax(votes, axis=-1)


def knn_predict(
    features: jax.Array,
    bank: jax.Array,
    bank_labels: jax.Array,
    num_classes: int,
    k: int = 200,
    temperature: float = 0.07,
    bank_chunk: int | None = None,
) -> jax.Array:
    """Return predicted class ids `[B]` (normalizes both sides; for repeated
    calls against the same bank use `knn_accuracy`, which normalizes once)."""
    return _knn_predict_prenormalized(
        l2_normalize(features.astype(jnp.float32)),
        l2_normalize(bank.astype(jnp.float32)),
        bank_labels,
        num_classes,
        k=k,
        temperature=temperature,
        bank_chunk=bank_chunk,
    )


def knn_accuracy(
    features: jax.Array,
    labels: jax.Array,
    bank: jax.Array,
    bank_labels: jax.Array,
    num_classes: int,
    k: int = 200,
    temperature: float = 0.07,
    batch: int = 512,
    bank_chunk: int | None = 65536,
) -> float:
    """Top-1 kNN accuracy, evaluated in fixed-size query batches with the
    bank streamed in `bank_chunk` slices, so peak HBM is
    `[batch, bank_chunk]` sims + the `[N_bank, dim]` bank itself — at
    ImageNet scale (1.28M × 128 f32 bank = 655 MB, chunk sims = 134 MB)
    comfortably inside one chip's 16 GB. The bank is normalized ONCE, and
    the ragged final batch is padded to `batch` rows so the jitted kernel
    compiles exactly once."""
    n = features.shape[0]
    feats = l2_normalize(jnp.asarray(features, jnp.float32))
    bank = l2_normalize(jnp.asarray(bank, jnp.float32))
    correct = 0
    for start in range(0, n, batch):
        f = feats[start : start + batch]
        y = labels[start : start + batch]
        valid = f.shape[0]
        if valid < batch:
            f = jnp.pad(f, ((0, batch - valid), (0, 0)))
        pred = _knn_predict_prenormalized(
            f, bank, bank_labels, num_classes, k=k, temperature=temperature,
            bank_chunk=bank_chunk,
        )
        correct += int(jnp.sum(pred[:valid] == y))
    return correct / n
