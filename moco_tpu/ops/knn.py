"""Weighted-kNN classification on frozen features (BASELINE config 4; SURVEY
§2.5, §3.3 — the InstDisc protocol used by every MoCo kNN monitor).

Protocol: cosine similarity of each query feature against a normalized
feature bank, top-`k` neighbors (200), votes weighted `exp(sim / T)` with
T=0.07, argmax class. Zero trainable parameters.

TPU mapping: the similarity is ONE `[B, dim] x [N_bank, dim]^T` matmul
(MXU-friendly, SURVEY §3.3); `lax.top_k` runs on-device; the class vote is a
one-hot einsum rather than a scatter so the whole classifier is a fused,
static-shaped XLA program.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from moco_tpu.ops.losses import l2_normalize


@functools.partial(jax.jit, static_argnames=("num_classes", "k"))
def _knn_predict_prenormalized(
    feats: jax.Array,         # [B, dim] L2-normalized queries
    bank: jax.Array,          # [N, dim] L2-normalized bank
    bank_labels: jax.Array,   # [N] int labels
    num_classes: int,
    k: int = 200,
    temperature: float = 0.07,
) -> jax.Array:
    sims = jnp.einsum("bc,nc->bn", feats, bank, preferred_element_type=jnp.float32)
    k = min(k, bank.shape[0])
    top_sims, top_idx = lax.top_k(sims, k)                      # [B, k]
    weights = jnp.exp(top_sims / temperature)
    neigh_labels = bank_labels[top_idx]                          # [B, k]
    onehot = jax.nn.one_hot(neigh_labels, num_classes, dtype=jnp.float32)
    votes = jnp.einsum("bk,bkc->bc", weights, onehot)
    return jnp.argmax(votes, axis=-1)


def knn_predict(
    features: jax.Array,
    bank: jax.Array,
    bank_labels: jax.Array,
    num_classes: int,
    k: int = 200,
    temperature: float = 0.07,
) -> jax.Array:
    """Return predicted class ids `[B]` (normalizes both sides; for repeated
    calls against the same bank use `knn_accuracy`, which normalizes once)."""
    return _knn_predict_prenormalized(
        l2_normalize(features.astype(jnp.float32)),
        l2_normalize(bank.astype(jnp.float32)),
        bank_labels,
        num_classes,
        k=k,
        temperature=temperature,
    )


def knn_accuracy(
    features: jax.Array,
    labels: jax.Array,
    bank: jax.Array,
    bank_labels: jax.Array,
    num_classes: int,
    k: int = 200,
    temperature: float = 0.07,
    batch: int = 512,
) -> float:
    """Top-1 kNN accuracy, evaluated in fixed-size batches so the similarity
    matrix never exceeds `[batch, N_bank]` in HBM. The bank is normalized
    ONCE, and the ragged final batch is padded to `batch` rows so the jitted
    kernel compiles exactly once."""
    n = features.shape[0]
    feats = l2_normalize(jnp.asarray(features, jnp.float32))
    bank = l2_normalize(jnp.asarray(bank, jnp.float32))
    correct = 0
    for start in range(0, n, batch):
        f = feats[start : start + batch]
        y = labels[start : start + batch]
        valid = f.shape[0]
        if valid < batch:
            f = jnp.pad(f, ((0, batch - valid), (0, 0)))
        pred = _knn_predict_prenormalized(
            f, bank, bank_labels, num_classes, k=k, temperature=temperature
        )
        correct += int(jnp.sum(pred[:valid] == y))
    return correct / n
