# NO eager schedules re-export here: importing ANY ops submodule executes
# this __init__, so `from moco_tpu.ops.knn import knn_predict` on the serve
# path would drag the optimizer-side schedule module into every serving
# process (import-boundary lint R11, generalizing R6). Schedule users
# (train_step, the drivers) import moco_tpu.ops.schedules directly.
from moco_tpu.ops.queue import init_queue, dequeue_and_enqueue
from moco_tpu.ops.ema import ema_update, momentum_schedule
from moco_tpu.ops.losses import (
    l2_normalize,
    infonce_logits,
    softmax_cross_entropy,
    contrastive_accuracy,
    v3_contrastive_loss,
)

__all__ = [
    "init_queue",
    "dequeue_and_enqueue",
    "ema_update",
    "momentum_schedule",
    "l2_normalize",
    "infonce_logits",
    "softmax_cross_entropy",
    "contrastive_accuracy",
    "v3_contrastive_loss",
]
