from moco_tpu.ops.queue import init_queue, dequeue_and_enqueue
from moco_tpu.ops.ema import ema_update, momentum_schedule
from moco_tpu.ops.losses import (
    l2_normalize,
    infonce_logits,
    softmax_cross_entropy,
    contrastive_accuracy,
    v3_contrastive_loss,
)
from moco_tpu.ops.schedules import cosine_lr, step_lr, warmup_cosine_lr

__all__ = [
    "init_queue",
    "dequeue_and_enqueue",
    "ema_update",
    "momentum_schedule",
    "l2_normalize",
    "infonce_logits",
    "softmax_cross_entropy",
    "contrastive_accuracy",
    "v3_contrastive_loss",
    "cosine_lr",
    "step_lr",
    "warmup_cosine_lr",
]
