"""Crop + bilinear resize as two DENSE matmuls (MXU work, not gathers).

`jax.image.scale_and_translate` applies separable interpolation with
gather-based sampling — measured ~10 ms per 128-image batch on the v5e
(gather-bound; ~20 ms of a ~79 ms MoCo-v2 step across the two crops). The
same math is exactly expressible as

    out[c] = Rv @ img[:, :, c] @ Rh^T

with per-sample interpolation matrices `Rv: [S_out, H_src]`,
`Rh: [S_out, W_src]` whose rows hold the (antialiased) triangle-filter
weights for one output coordinate. Dense matmuls cost ~170 MFLOP per image —
noise for the MXU — and vmap batches them straight into bmms.

Weight construction mirrors scale_and_translate's `linear` method: triangle
kernel, support scaled by the minification factor when `antialias` (PIL
semantics), weights renormalized over in-bounds taps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def interp_matrix(
    src_size: int, out_size: int, crop_start, crop_size, antialias: bool = True,
    valid_size=None,
) -> jax.Array:
    """[out_size, src_size] row-stochastic interpolation weights mapping the
    window [crop_start, crop_start + crop_size) onto out_size samples.
    `crop_start`/`crop_size` may be traced scalars (static shapes).

    `valid_size` (optional, traced): image content occupies rows
    `[0, valid_size)` of the source (rectangle staging, datasets.py) — taps
    beyond it are masked out and the row renormalized, which reproduces
    exactly the boundary handling a tightly-sized image would get."""
    scale = crop_size / out_size
    o = jnp.arange(out_size, dtype=jnp.float32)
    pos = crop_start + (o + 0.5) * scale - 0.5          # source-space centers
    idx = jnp.arange(src_size, dtype=jnp.float32)
    support = jnp.maximum(scale, 1.0) if antialias else jnp.float32(1.0)
    dist = jnp.abs(pos[:, None] - idx[None, :]) / support
    w = jnp.clip(1.0 - dist, 0.0, None)
    if valid_size is not None:
        w = w * (idx[None, :] < valid_size)
    return w / jnp.maximum(jnp.sum(w, axis=1, keepdims=True), 1e-8)


def crop_resize(
    img: jax.Array,  # [H, W, C] float (pipeline dtype)
    y0,
    x0,
    crop_h,
    crop_w,
    out_size: int,
    antialias: bool = True,
    valid_h=None,
    valid_w=None,
    flip_v=None,
    flip_h=None,
) -> jax.Array:
    """Resample the box [y0:y0+crop_h, x0:x0+crop_w] to [out, out, C].

    `flip_v`/`flip_h` (traced bools) reverse the output rows/columns by
    reversing the interpolation-matrix rows — a free flip (the reversal
    touches a [out, src] matrix, not the image)."""
    rv = interp_matrix(img.shape[0], out_size, y0, crop_h, antialias, valid_h)
    rh = interp_matrix(img.shape[1], out_size, x0, crop_w, antialias, valid_w)
    if flip_v is not None:
        rv = jnp.where(flip_v, rv[::-1], rv)
    if flip_h is not None:
        rh = jnp.where(flip_h, rh[::-1], rh)
    # matrices in the image dtype: a bf16 pipeline then runs both
    # contractions natively on the MXU (weight quantization ~2^-8 ≈ the u8
    # source precision); accumulation stays f32
    rv = rv.astype(img.dtype)
    rh = rh.astype(img.dtype)
    # [O,H]x[H,W,C] then [O,W,C]x[W,O'] — two dense contractions on the MXU
    tmp = jnp.einsum("oh,hwc->owc", rv, img, preferred_element_type=jnp.float32)
    return jnp.einsum(
        "pw,owc->opc", rh, tmp.astype(img.dtype), preferred_element_type=jnp.float32
    ).astype(img.dtype)
