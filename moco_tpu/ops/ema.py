"""Momentum (EMA) key-encoder update (rebuild of `_momentum_update_key_encoder`,
`moco/builder.py:≈L47-54`) and the MoCo-v3 momentum ramp (SURVEY §2.9).

The reference mutates the key encoder's parameters in a `no_grad` loop:
`p_k = p_k*m + p_q*(1-m)`. Functionally in JAX this is one fused tree-map —
a device-side weighted add over the whole parameter pytree (the north-star's
wording), executed identically on every replica so the key params stay
bit-identical with zero communication.

Parameters only: the key encoder's BatchNorm *running stats* are NOT EMA'd —
they evolve through the key encoder's own forward passes, exactly as in the
reference (SURVEY §2.2 row 2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ema_update(params_k, params_q, momentum) -> dict:
    """`p_k ← m·p_k + (1−m)·p_q` over the whole pytree. `momentum` may be a
    python float or a traced scalar (the v3 cosine ramp passes a traced one)."""
    return jax.tree.map(
        lambda k, q: (k * momentum + q.astype(k.dtype) * (1.0 - momentum)).astype(
            k.dtype  # keep the key dtype even when a traced f32 momentum promotes
        ),
        params_k,
        params_q,
    )


def momentum_schedule(base_m: float, step, total_steps: int):
    """MoCo-v3 momentum ramp: m cosine-increases from `base_m` to 1.0 over
    training (arXiv:2104.02057 §4; sibling-repo `main_moco.py` adjusts per
    iteration). v1/v2 use a constant m=0.999 and never call this."""
    frac = jnp.asarray(step, jnp.float32) / max(total_steps, 1)
    return 1.0 - (1.0 - base_m) * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
