"""Pallas TPU kernel: BatchNorm-normalize → ReLU fused into a 1x1 conv.

The r2 profile left the MoCo-v2 R50 step HBM-bound (~29 GB/step vs a
~494 GB/s roof) with the named next lever "fuse the BN normalize+ReLU
consumer into the conv epilogue" (README perf notes; VERDICT r2 #2). A 1x1
convolution IS a matmul over [B·H·W, C_in], so for the Bottleneck's
bn2→relu→conv3 tail the normalized activation never needs to exist in HBM:

    y[M, N] = relu(x[M, K]·a[K] + b[K]) @ W[K, N]
    with a = γ·rstd, b = β − μ·a  (the affine form of the BN normalize)

This kernel streams x through VMEM tiles, applies the normalize+ReLU
in-register, and feeds the MXU directly — saving the write+read of the
normalized tensor (2 passes over [M, K] per bottleneck, both encoders).

The backward runs as plain XLA ops under a custom VJP in models/fused_block:
dW recomputes z = relu(x·a+b) inside its matmul operand (fusable), and the
BN chain reuses the closed-form/`pallas_stats` machinery of FastBatchNorm.

Reference equivalent: cuDNN's fused conv+BN epilogues (SURVEY §2.10
cuDNN → MXU/Pallas). `interpret=True` makes the kernel testable on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from moco_tpu.utils.compat import shape_dtype_struct
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, a_ref, b_ref, w_ref, o_ref, acc_ref):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...].astype(jnp.float32)
    # normalize+ReLU in-register; cast to the weight dtype so the MXU runs
    # the same bf16 contraction the unfused graph would
    z = jnp.maximum(x * a_ref[...] + b_ref[...], 0.0).astype(w_ref.dtype)
    acc_ref[...] += jnp.dot(
        z, w_ref[...], preferred_element_type=jnp.float32
    )

    @pl.when(k == pl.num_programs(2) - 1)
    def _():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _pick_tile(n: int, candidates) -> int:
    for c in candidates:
        if n % c == 0:
            return c
    return n


def _dw_kernel(x_ref, a_ref, b_ref, dy_ref, o_ref, acc_ref):
    m = pl.program_id(2)

    @pl.when(m == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...].astype(jnp.float32)
    z = jnp.maximum(x * a_ref[...] + b_ref[...], 0.0).astype(dy_ref.dtype)
    # contract over the row (m) axis: zᵀ·dy without materializing z
    acc_ref[...] += jax.lax.dot_general(
        z, dy_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(m == pl.num_programs(2) - 1)
    def _():
        o_ref[...] = acc_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def bn_relu_matmul_dw(
    x: jax.Array,      # [M, K] activations (pre-normalize)
    a: jax.Array,      # [K] f32
    b: jax.Array,      # [K] f32
    dy: jax.Array,     # [M, N] upstream cotangent
    interpret: bool = False,
) -> jax.Array:
    """dW[K, N] = relu(x·a + b)ᵀ @ dy with ẑ recomputed in VMEM — the
    backward twin of `bn_relu_matmul` (one streaming read of x and dy; the
    normalized activation never exists in HBM in either pass)."""
    m, k = x.shape
    m2, n = dy.shape
    assert m == m2, (x.shape, dy.shape)
    bm = _pick_tile(m, (512, 256, 128, 64, 32, 16, 8))
    bn = _pick_tile(n, (256, 128, 64, 32, 16, 8))
    bk = _pick_tile(k, (512, 256, 128, 64, 32, 16, 8))
    vma = getattr(getattr(x, "aval", None), "vma", frozenset())
    return pl.pallas_call(
        _dw_kernel,
        grid=(k // bk, n // bn, m // bm),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda kk, j, i: (i, kk)),
            pl.BlockSpec((1, bk), lambda kk, j, i: (0, kk)),
            pl.BlockSpec((1, bk), lambda kk, j, i: (0, kk)),
            pl.BlockSpec((bm, bn), lambda kk, j, i: (i, j)),
        ],
        out_specs=pl.BlockSpec((bk, bn), lambda kk, j, i: (kk, j)),
        out_shape=shape_dtype_struct((k, n), jnp.float32, vma=vma),
        scratch_shapes=[pltpu.VMEM((bk, bn), jnp.float32)],
        interpret=interpret,
    )(x, a.reshape(1, k).astype(jnp.float32),
      b.reshape(1, k).astype(jnp.float32), dy)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "interpret")
)
def bn_relu_matmul(
    x: jax.Array,      # [M, K] activations (pre-normalize), bf16/f32
    a: jax.Array,      # [K] f32  (γ·rstd)
    b: jax.Array,      # [K] f32  (β − μ·γ·rstd)
    w: jax.Array,      # [K, N] weights (conv3 kernel reshaped)
    out_dtype=jnp.bfloat16,
    interpret: bool = False,
) -> jax.Array:
    """relu(x·a + b) @ w with the normalized tensor kept in VMEM only."""
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    bm = _pick_tile(m, (512, 256, 128, 64, 32, 16, 8))
    bn = _pick_tile(n, (256, 128, 64, 32, 16, 8))
    bk = _pick_tile(k, (512, 256, 128, 64, 32, 16, 8))
    vma = getattr(getattr(x, "aval", None), "vma", frozenset())
    return pl.pallas_call(
        _kernel,
        grid=(m // bm, n // bn, k // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((1, bk), lambda i, j, kk: (0, kk)),
            pl.BlockSpec((1, bk), lambda i, j, kk: (0, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=shape_dtype_struct((m, n), out_dtype, vma=vma),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x, a.reshape(1, k).astype(jnp.float32),
      b.reshape(1, k).astype(jnp.float32), w)
