"""Pallas TPU kernels: streaming per-channel reduction passes for BatchNorm.

Round-2 profiling of the MoCo-v2 R50 step (xplane, v5e) put ~35 ms of the
~70 ms step in XLA's per-channel reduce fusions — the train-mode BN batch
statistics (forward) and the dgamma/dbeta-style reductions (backward). Those
passes are pure streaming reads of the fattest activations in the network,
but XLA's reduce fusions run well below the HBM roof (~55-60% measured in
isolation). These kernels do the same reductions as explicit Pallas
streaming loops tiled for VMEM, with f32 accumulation:

- `channel_sums(x)`        → (Σx, Σx²) over N,H,W          (BN fwd stats)
- `channel_grad_sums(dy, xhat)` → (Σdy, Σdy·x̂) over N,H,W  (BN bwd terms)

Both read each element exactly once. Used by `models/fast_bn.py`'s
custom-VJP BatchNorm; `interpret=True` makes the same code path testable on
CPU (see tests/test_pallas_stats.py).

The reference's cuDNN BN kernels do these same fused reductions on GPU
(`torch.nn.BatchNorm2d` internals) — this is the TPU-native equivalent
(SURVEY §2.10: cuDNN → MXU/Pallas).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from moco_tpu.utils.compat import shape_dtype_struct
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Read ONCE at import: these kernels are traced inside jitted programs
# (fast_bn inside the train step), so a mid-process env change could never
# reach an already-compiled program — the jit cache does not key on it.
# Import-time semantics make that staleness impossible instead of silent
# (tools/_perf_ab.py sweeps the knob one subprocess per setting).
_TILE_KIB = int(os.environ.get("MOCO_TPU_STATS_TILE_KIB", "0") or 0)


def _sums_kernel(x_ref, sum_ref, sq_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)

    x = x_ref[...].astype(jnp.float32)  # [T, C]
    sum_ref[...] += jnp.sum(x, axis=0, keepdims=True)
    sq_ref[...] += jnp.sum(x * x, axis=0, keepdims=True)


def _grad_sums_kernel(dy_ref, x_ref, mu_ref, r_ref, dsum_ref, dxh_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dsum_ref[...] = jnp.zeros_like(dsum_ref)
        dxh_ref[...] = jnp.zeros_like(dxh_ref)

    dy = dy_ref[...].astype(jnp.float32)  # [T, C]
    # recompute x̂ = (x-μ)·r in-register: saves materializing x̂ in HBM
    xh = (x_ref[...].astype(jnp.float32) - mu_ref[...]) * r_ref[...]
    dsum_ref[...] += jnp.sum(dy, axis=0, keepdims=True)
    dxh_ref[...] += jnp.sum(dy * xh, axis=0, keepdims=True)


def _tile_rows(n: int, c: int, kib: int | None = None) -> int:
    """Rows per VMEM tile: target ~1 MB per streamed operand tile, keep the
    row count a divisor-friendly power of two, and never exceed n.

    Why 1 MB (first-chip finding, r5): the grad-sums kernel keeps ~4 f32
    tile-sized intermediates live on the Mosaic stack (dy, x̂, their
    product, plus the cast of x); at the old 2 MB bf16 tile (t=16384,
    c=64) that stack plus the double-buffered input windows totalled
    19.87 MB against the 16 MB scoped-VMEM limit and the R50 step failed
    to compile on the v5e (runs/tpu_validate_tpu.log, 2026-07-31). The
    forward microbench only ever passed because its row count happened to
    be indivisible by 16384. 1 MB tiles put the worst case ~10 MB. The
    floor is 8 (the f32 sublane count), NOT a round 512: a 512-row floor
    would recreate the same 1M-element tile at c=2048 (R50 layer4) that
    blew the limit at c=64.

    MOCO_TPU_STATS_TILE_KIB (read at import, see _TILE_KIB above)
    overrides the per-operand byte target (tools/_perf_ab.py sweeps it to
    bound the tile size's share of the r5-vs-r2 step-time gap)."""
    if kib is None:
        kib = _TILE_KIB
    budget = kib * 1024 if kib else (1 << 20)
    # the row cap scales with the budget (fractionally — an integer >>20
    # would floor a 1.5 MiB budget back to the default cap): a fixed 1<<13
    # cap would make a 2 MiB override compile the SAME program as the
    # default at c<=64 (R50 layer1 — exactly the pre-fix operating point
    # the sweep exists to reach), silently voiding the A/B (review, r5)
    row_cap = max(8, (1 << 13) * budget // (1 << 20))
    target = max(8, min(row_cap, budget // (2 * c)))
    # floor to a power of two BEFORE the divisibility loop: a factor-3
    # target (e.g. a 768 KiB budget) would otherwise never divide a
    # pow2-shaped n and halve all the way to degenerate 1-row tiles
    # (review, r5)
    target = 1 << (target.bit_length() - 1)
    while n % target:
        target //= 2
        if target == 0:
            return n  # pathological n: single tile
    return target


@functools.partial(jax.jit, static_argnames=("interpret",))
def channel_sums(x: jax.Array, interpret: bool = False):
    """(Σx, Σx²) over all but the last axis. x: [..., C] (any rank), returns
    two f32 [C] vectors. One streaming read of x."""
    c = x.shape[-1]
    xr = x.reshape(-1, c)
    n = xr.shape[0]
    t = _tile_rows(n, c)
    vma = getattr(getattr(x, "aval", None), "vma", frozenset())
    s, sq = pl.pallas_call(
        _sums_kernel,
        grid=(n // t,),
        in_specs=[pl.BlockSpec((t, c), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_shape=[
            shape_dtype_struct((1, c), jnp.float32, vma=vma),
            shape_dtype_struct((1, c), jnp.float32, vma=vma),
        ],
        interpret=interpret,
    )(xr)
    return s[0], sq[0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def channel_grad_sums(
    dy: jax.Array,
    x: jax.Array,
    mean: jax.Array,
    rstd: jax.Array,
    interpret: bool = False,
):
    """(Σdy, Σdy·x̂) over all but the last axis, with x̂ = (x-mean)·rstd
    recomputed in-register — the two reductions of the BN backward. One
    streaming read of dy and x each; x̂ never touches HBM."""
    c = dy.shape[-1]
    dyr = dy.reshape(-1, c)
    xr = x.reshape(-1, c)
    n = dyr.shape[0]
    t = _tile_rows(n, c)
    vma = getattr(getattr(dy, "aval", None), "vma", frozenset())
    s, sx = pl.pallas_call(
        _grad_sums_kernel,
        grid=(n // t,),
        in_specs=[
            pl.BlockSpec((t, c), lambda i: (i, 0)),
            pl.BlockSpec((t, c), lambda i: (i, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_shape=[
            shape_dtype_struct((1, c), jnp.float32, vma=vma),
            shape_dtype_struct((1, c), jnp.float32, vma=vma),
        ],
        interpret=interpret,
    )(dyr, xr, mean.reshape(1, c).astype(jnp.float32),
      rstd.reshape(1, c).astype(jnp.float32))
    return s[0], sx[0]
