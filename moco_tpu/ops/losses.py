"""Contrastive losses: queue-based InfoNCE (MoCo v1/v2) and the queue-free
symmetric in-batch loss (MoCo v3).

Rebuilds the logits construction of `MoCo.forward` (`moco/builder.py:≈L117-165`)
and the v3 `ctr` loss (sibling repo `moco-v3/moco/builder.py`; SURVEY §2.9,
§3.5). Shapes are row-major and the negative block is one `[B, dim] x
[K, dim]^T` matmul so XLA tiles it straight onto the MXU; accumulation happens
in float32 regardless of input dtype (`preferred_element_type`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from moco_tpu.parallel.collectives import all_gather_batch, batch_axis_index


def l2_normalize(x: jax.Array, eps: float = 1e-12) -> jax.Array:
    """Row-wise L2 normalization (the reference's `nn.functional.normalize`)."""
    return x / jnp.sqrt(
        jnp.maximum(jnp.sum(jnp.square(x), axis=-1, keepdims=True), eps)
    )


def infonce_logits(
    q: jax.Array, k: jax.Array, queue: jax.Array, temperature: float
) -> tuple[jax.Array, jax.Array]:
    """(K+1)-way contrastive logits with the positive at column 0.

    Rebuild of `moco/builder.py:≈L140-160`:
      l_pos = einsum('nc,nc->n', q, k);  l_neg = q @ queue^T  (queue detached)
      logits = concat([l_pos, l_neg]) / T;  labels = zeros (positive first).

    `q`/`k` must be L2-normalized; `k` and `queue` must be stop-gradiented by
    the caller (no gradient ever reaches the key encoder or the queue —
    pinned by tests/test_train_step.py).
    """
    l_pos = jnp.einsum(
        "nc,nc->n", q, k, preferred_element_type=jnp.float32
    )[:, None]
    l_neg = jnp.einsum(
        "nc,kc->nk", q, lax.stop_gradient(queue), preferred_element_type=jnp.float32
    )
    logits = jnp.concatenate([l_pos, l_neg], axis=1) / temperature
    labels = jnp.zeros(q.shape[0], dtype=jnp.int32)
    return logits, labels


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean CE over the batch (the reference's `nn.CrossEntropyLoss`)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=-1))


def contrastive_accuracy(
    logits: jax.Array, labels: jax.Array, topk: tuple[int, ...] = (1, 5)
) -> tuple[jax.Array, ...]:
    """Top-k accuracy over the (K+1)-way logits (rebuild of `accuracy`,
    `main_moco.py:≈L390-405`): the fraction of samples whose positive
    outranks all queue negatives (within top-k).

    Rank-count formulation instead of `lax.top_k`: the label column is in
    the top-k iff fewer than k columns score strictly higher. One compare +
    row-sum over [B, K+1] — O(BK) elementwise, no sort. This matters twice:
    `lax.top_k` over K+1 columns ran EVERY train step (it dominated the CPU
    horizon step at K=4096, ~22 of 25 s), and on TPU at K=65536 the per-step
    sort network is pure overhead for a 2-number metric. Tie semantics:
    strictly-greater counting credits the positive on exact float ties,
    matching torch `topk`'s first-occurrence behavior for equal values up
    to column order. A NaN label logit compares False against everything
    (n_better = 0), which would silently score as a top-k hit — the
    finiteness AND below keeps a diverged row a miss, like the old top_k
    formulation."""
    valid = labels >= 0  # eval paths pad ragged tails with label -1
    label_logit = jnp.take_along_axis(
        logits, jnp.maximum(labels, 0)[:, None], axis=-1
    )
    valid &= jnp.isfinite(label_logit[:, 0])
    n_better = jnp.sum((logits > label_logit), axis=-1)  # [B]
    return tuple(100.0 * jnp.mean((n_better < k) & valid) for k in topk)


def v3_contrastive_loss(
    q: jax.Array, k: jax.Array, temperature: float, axis_name,
    chunks: int = 1
) -> jax.Array:
    """One direction of the MoCo-v3 queue-free loss (SURVEY §3.5).

    `k` is all-gathered over the data axis so negatives are the OTHER
    in-batch samples across the whole global batch; the positive for local
    row i is global row `rank*B_local + i` (the reference's
    `labels = arange(N) + N*rank`). Loss is scaled by 2*T as in the paper's
    implementation. `q`/`k` must be L2-normalized, `k` stop-gradiented.

    `axis_name` may be a tuple (the 2-D data×fsdp mesh, ISSUE 15); `chunks`
    applies the FAST-style chunked gather schedule — the reassembled
    negatives are bit-identical either way (collectives.all_gather_batch).
    """
    k = lax.stop_gradient(k)
    if axis_name is not None:
        k_all = all_gather_batch(k, axis_name, chunks)
        offset = batch_axis_index(axis_name) * q.shape[0]
    else:
        k_all, offset = k, 0
    logits = (
        jnp.einsum("nc,mc->nm", q, k_all, preferred_element_type=jnp.float32)
        / temperature
    )
    labels = jnp.arange(q.shape[0], dtype=jnp.int32) + offset
    return softmax_cross_entropy(logits, labels) * (2.0 * temperature)
