"""Learning-rate schedules (rebuild of `adjust_learning_rate`,
`main_moco.py:≈L377-388`, plus MoCo-v3's warmup+cosine, SURVEY §2.9).

The reference adjusts the LR once per EPOCH (the cosine is evaluated at
integer epochs). These helpers take a (possibly fractional) epoch so callers
can choose per-epoch fidelity (pass `floor(epoch)`, the default in the train
driver, matching the reference exactly) or smooth per-step decay.
All are pure jnp so they can live inside the jitted step.
"""

from __future__ import annotations

import jax.numpy as jnp


def cosine_lr(base_lr: float, epoch, total_epochs: int):
    """`lr = base * 0.5 * (1 + cos(pi * epoch / total))` — the `--cos` branch."""
    frac = jnp.asarray(epoch, jnp.float32) / total_epochs
    return base_lr * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))


def step_lr(base_lr: float, epoch, milestones: tuple[int, ...]):
    """x0.1 at each milestone in `--schedule` (default 120,160) — the v1 branch."""
    e = jnp.asarray(epoch, jnp.float32)
    drops = sum((e >= m).astype(jnp.float32) for m in milestones)
    return base_lr * jnp.power(0.1, drops)


def warmup_cosine_lr(base_lr: float, epoch, total_epochs: int, warmup_epochs: int):
    """MoCo-v3 recipe: linear warmup then cosine (arXiv:2104.02057 recipe;
    40-epoch warmup at batch 4096)."""
    e = jnp.asarray(epoch, jnp.float32)
    warm = base_lr * e / jnp.maximum(warmup_epochs, 1e-8)
    frac = (e - warmup_epochs) / jnp.maximum(total_epochs - warmup_epochs, 1e-8)
    cos = base_lr * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
    return jnp.where(e < warmup_epochs, warm, cos)
