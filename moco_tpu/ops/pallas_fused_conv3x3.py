"""Pallas TPU kernel: BN-normalize → ReLU fused into a stride-1 3x3 conv.

Companion to `pallas_fused_conv.py` (the 1x1 tail): the Bottleneck's OTHER
interior normalize pass is bn1→relu feeding the 3x3 conv2. A 3x3 stride-1
convolution is nine channel-contractions over row/column-shifted views, so
the same in-register trick applies — normalize+ReLU each x tile in VMEM and
accumulate the nine `[rows·W, K] @ [K, N]` tap matmuls without the
normalized tensor ever reaching HBM.

Halo handling: the kernel receives the SAME array through three input refs
whose index maps point at the previous / current / next row-block (clamped
at the boundary); row masks zero the out-of-range contributions, and column
shifts are masked at the W edges, reproducing the conv's zero padding
exactly.

Stride-2 conv2 (the first block of each stage) is fused too:
`bn_relu_conv3x3_s2` below tiles the OUTPUT rows and reads the strided
input halo through one widened ref (even/odd row decomposition, two edge
masks), so all 16 R50 interior 3x3s go through the fused family.
`interpret=True` runs on CPU for the equivalence tests;
`tests/test_fused_conv3x3.py` also pins the TPU (Mosaic) lowering
hardware-free via cross-platform export.

The backward twin `conv3x3_dw` (VERDICT r3 #5) closes the remaining HBM
leak: the custom VJP used to materialize z = relu(x̂) in HBM solely to feed
the filter-gradient correlation (the input-gradient dz never reads z — it
is a transposed conv of dy, already optimal as plain XLA). Here the nine
tap gradients dW[di,dj] = Σ z[i+di, j+dj]ᵀ·dy[i,j] accumulate in one VMEM
scratch while z is recomputed tile-by-tile from x with the same halo refs
and edge masks as the forward — so the normalized activation now never
exists in HBM in EITHER direction for the 3x3, matching the 1x1 tail's
`bn_relu_matmul_dw` story.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from moco_tpu.utils.compat import shape_dtype_struct
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _conv3x3_kernel(xm_ref, x0_ref, xp_ref, a_ref, b_ref, w_ref, o_ref, *,
                    bh, h, blocks_per_img):
    """One row-block [bh, W, K] → [bh, W, N].

    x0 is the current row-block; xm/xp are SINGLE halo rows (the row just
    above / below the block, index maps clamped WITHIN the image; masks
    below zero the clamped rows) — x streams at ~(bh+2)/bh reads, not 3x.
    The batch is folded into the row grid, so all row coordinates here are
    per-IMAGE (a block never straddles an image). w_ref holds the taps as
    [9, K, N].
    """
    i = pl.program_id(0)  # row-block index over B*H/bh
    w_all = w_ref[...]
    bw = x0_ref.shape[1]  # W (full width in this block)
    k = x0_ref.shape[2]
    n = w_all.shape[-1]

    def normalize(ref):
        x = ref[...].astype(jnp.float32)
        return jnp.maximum(x * a_ref[0, 0] + b_ref[0, 0], 0.0).astype(w_all.dtype)

    zm = normalize(xm_ref)  # [1, W, K] halo row above (clamped at image top)
    z0 = normalize(x0_ref)  # [bh, W, K] current row-block
    zp = normalize(xp_ref)  # [1, W, K] halo row below (clamped at bottom)

    acc = jnp.zeros((bh * bw, n), jnp.float32)
    col = jax.lax.broadcasted_iota(jnp.int32, (bh, bw, 1), 1)
    row_in_block = jax.lax.broadcasted_iota(jnp.int32, (bh, bw, 1), 0)
    # row within THIS IMAGE (zero pad happens at image edges, not batch ones)
    img_row = (i % blocks_per_img) * bh + row_in_block

    for di in (-1, 0, 1):
        # source rows (img_row + di): build the di-shifted row view of the
        # current block from the halo rows + z0
        if di == 0:
            z_rows = z0
            row_ok = jnp.ones((bh, bw, 1), jnp.bool_)
        elif di == -1:
            # shift down: row r reads source row r-1 → top row is the halo
            # (bh == 1: the shifted block IS the halo row; avoids a
            # zero-size slice, which Mosaic rejects)
            z_rows = zm if bh == 1 else jnp.concatenate(
                [zm, z0[:-1]], axis=0
            )
            row_ok = img_row - 1 >= 0
        else:
            z_rows = zp if bh == 1 else jnp.concatenate(
                [z0[1:], zp], axis=0
            )
            row_ok = img_row + 1 <= h - 1
        for dj in (-1, 0, 1):
            if dj == 0:
                z_tap = z_rows
                col_ok = jnp.ones((bh, bw, 1), jnp.bool_)
            elif dj == -1:
                z_tap = jnp.concatenate(
                    [jnp.zeros_like(z_rows[:, :1]), z_rows[:, :-1]], axis=1
                )
                col_ok = col - 1 >= 0
            else:
                z_tap = jnp.concatenate(
                    [z_rows[:, 1:], jnp.zeros_like(z_rows[:, :1])], axis=1
                )
                col_ok = col + 1 <= bw - 1
            mask = (row_ok & col_ok).astype(w_all.dtype)
            z_masked = (z_tap * mask).reshape(bh * bw, k)
            tap = w_all[(di + 1) * 3 + (dj + 1)]
            acc += jnp.dot(z_masked, tap, preferred_element_type=jnp.float32)
    o_ref[...] = acc.reshape(bh, bw, n).astype(o_ref.dtype)


def _dw3x3_kernel(xm_ref, x0_ref, xp_ref, a_ref, b_ref, dy_ref, o_ref,
                  acc_ref, *, bh, h, blocks_per_img):
    """Accumulate the nine tap gradients over row-blocks.

    Grid is (n_blocks, row_blocks) with the ROW dim last (the sequential
    accumulation axis, `_dw_kernel` convention): for each row-block the
    di/dj-shifted masked ẑ views — identical construction to the forward —
    contract against the local dy tile, `acc[tap] += ẑ_tapᵀ @ dy`.
    """
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    dy = dy_ref[...]
    bw = x0_ref.shape[1]
    k = x0_ref.shape[2]

    def normalize(ref):
        x = ref[...].astype(jnp.float32)
        return jnp.maximum(x * a_ref[0, 0] + b_ref[0, 0], 0.0).astype(dy.dtype)

    zm = normalize(xm_ref)
    z0 = normalize(x0_ref)
    zp = normalize(xp_ref)

    col = jax.lax.broadcasted_iota(jnp.int32, (bh, bw, 1), 1)
    row_in_block = jax.lax.broadcasted_iota(jnp.int32, (bh, bw, 1), 0)
    img_row = (i % blocks_per_img) * bh + row_in_block
    dyr = dy.reshape(bh * bw, dy.shape[-1])

    for di in (-1, 0, 1):
        if di == 0:
            z_rows = z0
            row_ok = jnp.ones((bh, bw, 1), jnp.bool_)
        elif di == -1:
            z_rows = zm if bh == 1 else jnp.concatenate([zm, z0[:-1]], axis=0)
            row_ok = img_row - 1 >= 0
        else:
            z_rows = zp if bh == 1 else jnp.concatenate([z0[1:], zp], axis=0)
            row_ok = img_row + 1 <= h - 1
        for dj in (-1, 0, 1):
            if dj == 0:
                z_tap = z_rows
                col_ok = jnp.ones((bh, bw, 1), jnp.bool_)
            elif dj == -1:
                z_tap = jnp.concatenate(
                    [jnp.zeros_like(z_rows[:, :1]), z_rows[:, :-1]], axis=1
                )
                col_ok = col - 1 >= 0
            else:
                z_tap = jnp.concatenate(
                    [z_rows[:, 1:], jnp.zeros_like(z_rows[:, :1])], axis=1
                )
                col_ok = col + 1 <= bw - 1
            mask = (row_ok & col_ok).astype(z_tap.dtype)
            z_masked = (z_tap * mask).reshape(bh * bw, k)
            tap = (di + 1) * 3 + (dj + 1)
            acc_ref[tap] += jax.lax.dot_general(
                z_masked, dyr, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    @pl.when(i == pl.num_programs(1) - 1)
    def _():
        o_ref[...] = acc_ref[...]


def _pick_rows(h: int, w: int, k: int) -> int:
    """Row-block: target a few hundred KB of z tile, divide H."""
    target = max(1, (256 << 10) // max(1, 2 * w * k))
    bh = 1
    for c in (32, 16, 8, 4, 2, 1):
        if c <= target and h % c == 0:
            bh = c
            break
    return bh


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret"))
def bn_relu_conv3x3(
    x: jax.Array,      # [B, H, W, K] pre-normalize activations
    a: jax.Array,      # [K] f32 (γ·rstd)
    b: jax.Array,      # [K] f32 (β − μ·γ·rstd)
    w: jax.Array,      # [3, 3, K, N] conv kernel
    out_dtype=jnp.bfloat16,
    interpret: bool = False,
) -> jax.Array:
    """relu(x·a + b) ⊛ w (stride 1, zero pad 1), normalized tensor VMEM-only.

    The batch folds into the row grid: blocks never straddle a batch
    boundary (bh divides H), and the row masks use per-image coordinates.
    """
    bsz, h, wd, k = x.shape
    n = w.shape[-1]
    bh = _pick_rows(h, wd, k)
    xr = x.reshape(bsz * h, wd, k)
    w9 = w.reshape(9, k, n).astype(x.dtype)
    nblocks = (bsz * h) // bh
    blocks_per_img = h // bh

    # current row-block, plus SINGLE-ROW halo blocks above/below (block
    # shape (1, W, K) → the row index IS the block index), clamped to the
    # same image; the kernel's row masks zero the clamped contributions
    def idx_cur(i):
        return (i, 0, 0)

    def idx_prev_row(i):
        img = i // blocks_per_img
        return (jnp.maximum(i * bh - 1, img * h), 0, 0)

    def idx_next_row(i):
        img = i // blocks_per_img
        return (jnp.minimum((i + 1) * bh, (img + 1) * h - 1), 0, 0)

    vma = getattr(getattr(x, "aval", None), "vma", frozenset())
    kernel = functools.partial(_conv3x3_kernel, bh=bh, h=h,
                               blocks_per_img=blocks_per_img)
    out = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((1, wd, k), idx_prev_row),
            pl.BlockSpec((bh, wd, k), idx_cur),
            pl.BlockSpec((1, wd, k), idx_next_row),
            pl.BlockSpec((1, 1, k), lambda i: (0, 0, 0)),
            pl.BlockSpec((1, 1, k), lambda i: (0, 0, 0)),
            pl.BlockSpec((9, k, n), lambda i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bh, wd, n), idx_cur),
        out_shape=shape_dtype_struct((bsz * h, wd, n), out_dtype, vma=vma),
        interpret=interpret,
    )(xr, xr, xr, a.reshape(1, 1, k).astype(jnp.float32),
      b.reshape(1, 1, k).astype(jnp.float32), w9)
    return out.reshape(bsz, h, wd, n)


def _conv3x3s2_kernel(xm_ref, x0_ref, a_ref, b_ref, w_ref, o_ref, *,
                      bho, blocks_per_img):
    """One OUTPUT row-block [bho, W/2, N] of the stride-2 fused conv.

    x0 holds the 2·bho input rows [2r₀, 2r₀+2bho) — output row r reads
    input rows 2r−1/2r/2r+1 (symmetric pad 1, torch semantics), so the even
    rows of x0 are the di=0 taps, the odd rows the di=+1 taps, and di=−1 is
    the odd rows shifted down with xm (the single row above, clamped within
    the image) sliding in at the top. With H and W even, only the image-top
    row (di=−1) and the first output column (dj=−1) ever touch padding —
    the only two masks in the kernel.
    """
    i = pl.program_id(0)
    w_all = w_ref[...]
    w_in = x0_ref.shape[1]
    k = x0_ref.shape[2]
    n = w_all.shape[-1]
    wo = w_in // 2

    def normalize(ref):
        x = ref[...].astype(jnp.float32)
        return jnp.maximum(x * a_ref[0, 0] + b_ref[0, 0], 0.0).astype(w_all.dtype)

    zm = normalize(xm_ref)                       # [1, W, K] row 2r₀−1
    zpair = normalize(x0_ref).reshape(bho, 2, w_in, k)
    even = zpair[:, 0]                           # input rows 2r   [bho, W, K]
    odd = zpair[:, 1]                            # input rows 2r+1
    above = zm if bho == 1 else jnp.concatenate([zm, odd[:-1]], axis=0)

    acc = jnp.zeros((bho * wo, n), jnp.float32)
    out_row = jax.lax.broadcasted_iota(jnp.int32, (bho, wo, 1), 0)
    img_out_row = (i % blocks_per_img) * bho + out_row
    out_col = jax.lax.broadcasted_iota(jnp.int32, (bho, wo, 1), 1)

    for di, z_rows in ((-1, above), (0, even), (1, odd)):
        row_ok = (2 * img_out_row - 1 >= 0) if di == -1 else None
        pairs = z_rows.reshape(bho, wo, 2, k)
        for dj in (-1, 0, 1):
            if dj == 0:
                z_tap = pairs[:, :, 0]           # input col 2c
                col_ok = None
            elif dj == 1:
                z_tap = pairs[:, :, 1]           # input col 2c+1
                col_ok = None
            else:                                # input col 2c−1
                odd_cols = pairs[:, :, 1]
                z_tap = jnp.concatenate(
                    [jnp.zeros_like(odd_cols[:, :1]), odd_cols[:, :-1]],
                    axis=1,
                )
                col_ok = out_col - 1 >= 0
            ok = row_ok if col_ok is None else (
                col_ok if row_ok is None else row_ok & col_ok)
            if ok is not None:
                z_tap = z_tap * ok.astype(z_tap.dtype)
            tap = w_all[(di + 1) * 3 + (dj + 1)]
            acc += jnp.dot(z_tap.reshape(bho * wo, k), tap,
                           preferred_element_type=jnp.float32)
    o_ref[...] = acc.reshape(bho, wo, n).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret"))
def bn_relu_conv3x3_s2(
    x: jax.Array,      # [B, H, W, K] pre-normalize activations (H, W even)
    a: jax.Array,      # [K] f32 (γ·rstd)
    b: jax.Array,      # [K] f32 (β − μ·γ·rstd)
    w: jax.Array,      # [3, 3, K, N] conv kernel
    out_dtype=jnp.bfloat16,
    interpret: bool = False,
) -> jax.Array:
    """relu(x·a + b) ⊛ w at stride 2, symmetric pad 1 — the stage-first
    Bottleneck conv2 sites (VERDICT r3 #5), normalized tensor VMEM-only."""
    bsz, h, wd, k = x.shape
    assert h % 2 == 0 and wd % 2 == 0, (h, wd)
    n = w.shape[-1]
    ho = h // 2
    # one output row costs two input rows of VMEM: halve the row target
    bho = _pick_rows(ho, wd, 2 * k)
    xr = x.reshape(bsz * h, wd, k)
    w9 = w.reshape(9, k, n).astype(x.dtype)
    nblocks = (bsz * ho) // bho
    blocks_per_img = ho // bho

    def idx_cur(i):
        # output block i consumes the contiguous input rows
        # [2·bho·i, 2·bho·(i+1)) — block-aligned by construction
        return (i, 0, 0)

    def idx_above(i):
        img = i // blocks_per_img
        return (jnp.maximum(2 * bho * i - 1, img * h), 0, 0)

    vma = getattr(getattr(x, "aval", None), "vma", frozenset())
    kernel = functools.partial(_conv3x3s2_kernel, bho=bho,
                               blocks_per_img=blocks_per_img)
    out = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((1, wd, k), idx_above),
            pl.BlockSpec((2 * bho, wd, k), idx_cur),
            pl.BlockSpec((1, 1, k), lambda i: (0, 0, 0)),
            pl.BlockSpec((1, 1, k), lambda i: (0, 0, 0)),
            pl.BlockSpec((9, k, n), lambda i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bho, wd // 2, n), lambda i: (i, 0, 0)),
        out_shape=shape_dtype_struct((bsz * ho, wd // 2, n), out_dtype,
                                       vma=vma),
        interpret=interpret,
    )(xr, xr, a.reshape(1, 1, k).astype(jnp.float32),
      b.reshape(1, 1, k).astype(jnp.float32), w9)
    return out.reshape(bsz, ho, wd // 2, n)


@functools.partial(jax.jit, static_argnames=("interpret",))
def conv3x3_dw(
    x: jax.Array,      # [B, H, W, K] pre-normalize activations
    a: jax.Array,      # [K] f32 (γ·rstd)
    b: jax.Array,      # [K] f32 (β − μ·γ·rstd)
    dy: jax.Array,     # [B, H, W, N] upstream cotangent
    interpret: bool = False,
) -> jax.Array:
    """dW[3, 3, K, N] of relu(x·a+b) ⊛ w with ẑ recomputed in VMEM.

    The [9,K,bn] f32 accumulator lives in VMEM across the row grid,
    N-blocked so the 512-channel stages stay within the ~16 MB/core
    budget. x and dy stream once PER N-BLOCK (n//bn passes — 2 at the
    K=N=512 stage, 1 elsewhere); the normalized activation still never
    exists in HBM, which is the HBM saving the fusion is after.
    """
    bsz, h, wd, k = x.shape
    n = dy.shape[-1]
    bh = _pick_rows(h, wd, k)
    xr = x.reshape(bsz * h, wd, k)
    dyr = dy.reshape(bsz * h, wd, n)
    nblocks = (bsz * h) // bh
    blocks_per_img = h // bh
    # N-block the accumulator: 9·K·bn·4 B ≤ ~4.7 MB at K=512, bn=256
    bn = n
    while 9 * k * bn * 4 > (5 << 20) and bn % 2 == 0:
        bn //= 2

    def idx_cur(j, i):
        return (i, 0, 0)

    def idx_prev_row(j, i):
        img = i // blocks_per_img
        return (jnp.maximum(i * bh - 1, img * h), 0, 0)

    def idx_next_row(j, i):
        img = i // blocks_per_img
        return (jnp.minimum((i + 1) * bh, (img + 1) * h - 1), 0, 0)

    vma = getattr(getattr(x, "aval", None), "vma", frozenset())
    kernel = functools.partial(_dw3x3_kernel, bh=bh, h=h,
                               blocks_per_img=blocks_per_img)
    out = pl.pallas_call(
        kernel,
        grid=(n // bn, nblocks),
        in_specs=[
            pl.BlockSpec((1, wd, k), idx_prev_row),
            pl.BlockSpec((bh, wd, k), idx_cur),
            pl.BlockSpec((1, wd, k), idx_next_row),
            pl.BlockSpec((1, 1, k), lambda j, i: (0, 0, 0)),
            pl.BlockSpec((1, 1, k), lambda j, i: (0, 0, 0)),
            pl.BlockSpec((bh, wd, bn), lambda j, i: (i, 0, j)),
        ],
        out_specs=pl.BlockSpec((9, k, bn), lambda j, i: (0, 0, j)),
        out_shape=shape_dtype_struct((9, k, n), jnp.float32, vma=vma),
        scratch_shapes=[pltpu.VMEM((9, k, bn), jnp.float32)],
        interpret=interpret,
    )(xr, xr, xr, a.reshape(1, 1, k).astype(jnp.float32),
      b.reshape(1, 1, k).astype(jnp.float32), dyr)
    return out.reshape(3, 3, k, n)
