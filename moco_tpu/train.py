"""Pretrain driver (layer L4; rebuild of `main_moco.py`).

Control flow parity with `main_moco.py:≈L114-320` — argparse → build model/
optimizer/data → epoch loop → per-step train → meters → rank-0 checkpoint —
minus the process fan-out: there is no `mp.spawn`, no per-GPU worker; ONE
controller process per host drives all local chips through the jitted SPMD
step (SURVEY §2.10 process-topology row).

Usage:
    python -m moco_tpu.train --preset cifar10-moco-v1 --data-dir /data/cifar
    python -m moco_tpu.train --preset imagenet-moco-v2 --data-dir /data/imagenet
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from moco_tpu.checkpoint import (
    checkpoint_manager,
    finalize_checkpoints,
    maybe_resume,
    read_position,
    save_checkpoint,
)
from moco_tpu.config import PRESETS, PretrainConfig, get_preset
from moco_tpu.data import (
    aug_config_for,
    build_dataset,
    build_two_crops_sharded,
    epoch_loader,
)
from moco_tpu.ops.knn import knn_accuracy
from moco_tpu.parallel.mesh import create_mesh, local_batch_size
from moco_tpu.resilience import (
    CollapseError,
    CollapseSentinel,
    DataQualityError,
    NaNSentinel,
    NonFiniteLossError,
    PreemptionHandler,
    ResizeListener,
    RollbackExhaustedError,
    StepWatchdog,
    active_chaos,
    clear_chaos,
    install_chaos,
    parse_chaos_spec,
    write_resize_request,
)
from moco_tpu.train_state import create_train_state
from moco_tpu.train_step import build_encoder, build_optimizer, build_train_step
from moco_tpu.utils.logging import ProfilerWindow, ScalarWriter, info, log_event
from moco_tpu.utils.meters import AverageMeter, ProgressMeter, RateMeter, Throughput


def make_feature_fn(model, variant: str):
    """Jitted frozen-encoder embedding fn for the kNN monitor (eval-mode BN).

    v3 embeds with the BACKBONE only — the probe/kNN protocol (and the
    sibling repo's eval) scores backbone features, not the 256-d projector
    space; the projector would make the monitor track a different geometry
    than the metric it is a proxy for (VERDICT r2 weak #5)."""

    if variant == "v3":
        backbone = model.backbone

        @jax.jit
        def feature_fn(params, batch_stats, images_f32):
            out = backbone.apply(
                {
                    "params": params["backbone"],
                    "batch_stats": batch_stats.get("backbone", {}),
                },
                images_f32,
                train=False,
            )
            return out / jnp.linalg.norm(out, axis=-1, keepdims=True)

        return feature_fn

    @jax.jit
    def feature_fn(params, batch_stats, images_f32):
        out = model.apply(
            {"params": params, "batch_stats": batch_stats},
            images_f32,
            train=False,
        )
        return out / jnp.linalg.norm(out, axis=-1, keepdims=True)

    return feature_fn


def knn_monitor(
    config, feature_fn, state, dataset, mesh=None, val_dataset=None
) -> tuple[float, bool]:
    """Periodic kNN top-1 (SURVEY §2.5 protocol at monitoring scale). The
    bank is a train subset; queries come from `val_dataset` when one exists
    (imagefolder `val/`, CIFAR test split) — a REAL val metric — else from a
    held-out train slice (logged as `knn_train_top1`). Returns
    (accuracy, is_real_val). `feature_fn` comes from `make_feature_fn` ONCE
    per run (recompiling the eval forward every epoch costs minutes on the
    sandbox)."""
    from moco_tpu.evals.knn import encode_dataset

    n = min(len(dataset), config.knn_bank_size)
    rng = np.random.RandomState(config.seed)
    idx = rng.permutation(len(dataset))[:n]
    if val_dataset is not None:
        bank_idx = idx
        q_set = val_dataset
        q_idx = rng.permutation(len(val_dataset))[: max(n // 4, 1)]
    else:
        split = int(n * 0.8)
        bank_idx, q_idx = idx[:split], idx[split:]
        q_set = dataset
    bank, bank_labels = encode_dataset(
        None, state.params_q, state.batch_stats_q, dataset, config,
        indices=bank_idx, feature_fn=feature_fn, mesh=mesh,
    )
    val, val_labels = encode_dataset(
        None, state.params_q, state.batch_stats_q, q_set, config,
        indices=q_idx, feature_fn=feature_fn, mesh=mesh,
    )
    acc = knn_accuracy(
        jnp.asarray(val), jnp.asarray(val_labels), jnp.asarray(bank),
        jnp.asarray(bank_labels), num_classes=dataset.num_classes,
        k=min(200, len(bank_idx)), temperature=0.07,
    )
    return acc, val_dataset is not None


def _monitor_val_split(config, train_dataset):
    """A real validation split for the kNN monitor, when the dataset has
    one: imagefolder `val/` dir or the CIFAR-10 test batch. None otherwise
    (synthetic / no val dir) — the monitor then holds out train data.

    The val split must share the train split's label space: ImageFolder
    derives class ids from its own directory listing, so a partial or
    differently-listed `val/` would silently shift every label. Mismatched
    class maps fall back to the train hold-out with a visible notice."""
    if config.dataset == "imagefolder":
        val_dir = os.path.join(config.data_dir, "val")
        if os.path.isdir(val_dir):
            try:
                val = build_dataset(
                    "imagefolder", val_dir, image_size=config.image_size,
                    stage_size=config.stage_size, num_workers=config.num_workers,
                )
            except FileNotFoundError:
                return None  # empty val/ placeholder: no class subdirs
            if val.class_to_idx != getattr(train_dataset, "class_to_idx", None):
                info(
                    "kNN monitor: val/ class directories differ from train/ "
                    "— labels would misalign; falling back to a train "
                    "hold-out split"
                )
                return None
            return val
    if config.dataset == "cifar10":
        try:
            return build_dataset("cifar10", config.data_dir, train=False)
        except FileNotFoundError:
            return None
    if config.dataset == "synthetic_texture":
        # a held-out draw from the same distribution (class tiles come from
        # a FIXED seed, so labels align across seeds by construction): the
        # monitor reports real generalization, not train-set recall
        from moco_tpu.data.datasets import SyntheticTextureDataset

        return SyntheticTextureDataset(
            num_samples=2048, image_size=config.image_size,
            num_classes=config.num_classes,
            seed=getattr(train_dataset, "seed", 0) + 10007
            if hasattr(train_dataset, "seed") else 10007,
            # mirror the train distribution's knobs: a val split drawn with
            # different texture_amp/cast_strength would skew the monitor
            texture_amp=getattr(train_dataset, "texture_amp", 0.4),
            cast_strength=getattr(train_dataset, "cast_strength", 0.5),
        )
    return None


def train(config: PretrainConfig, mesh=None, max_steps: int | None = None,
          dataset=None):
    """Run pretraining; returns (final_state, last_metrics_dict).

    `dataset` overrides the config-built one (callers that need a custom
    size/source, e.g. the horizon runs, without widening the flag surface).

    Fault tolerance (resilience/): SIGTERM/SIGINT finishes the in-flight
    step, writes an emergency checkpoint, and returns cleanly; a non-finite
    loss triggers a bounded rollback — restore the last good checkpoint,
    advance the data stream past the poisoned window, and retry, aborting
    with `RollbackExhaustedError` only after `config.max_rollbacks`
    consecutive rollbacks that make no net progress. Note a rollback
    intentionally alters the data stream, so the post-rollback trajectory is
    no longer bit-identical to an uninterrupted run (preemption resume IS).
    """
    if mesh is None:
        mesh = create_mesh()
    # ISSUE 15: fsdp runs need the 2-D (data, fsdp) mesh; callers (tests,
    # main) hand in the plain 1-D mesh and this folds it — same devices,
    # same order — into the layout config.sharding asks for. dp passes
    # through untouched.
    from moco_tpu.parallel.mesh import mesh_for_config

    mesh = mesh_for_config(config, mesh)
    installed_chaos = False
    if config.chaos:
        if active_chaos() is None:
            plan = parse_chaos_spec(config.chaos)
            if plan is not None:
                # same cross-restart fire-once persistence as env-installed
                # plans: a supervised drill restarts the process, and a
                # --chaos kill/freeze re-firing on every re-traversal would
                # crash-loop the drill
                plan.state_dir = os.environ.get("MOCO_TPU_CHAOS_STATE") or None
            install_chaos(plan)
            installed_chaos = True
        else:
            # an already-active plan (chaos_context in tests, or a
            # MOCO_TPU_CHAOS env plan) wins — its fire-once state must not
            # be clobbered mid-scenario — but say so LOUDLY: an operator's
            # --chaos drill silently exercising someone else's faults would
            # be vacuous
            log_event(
                "chaos",
                f"--chaos {config.chaos!r} IGNORED: a plan is already "
                f"active for this process ({active_chaos()!r}) — unset "
                "MOCO_TPU_CHAOS to use the CLI spec",
            )
    rollbacks = 0
    last_nan_step = -1
    data_advance = 0
    poison_pos = None
    run_config = config
    try:
        while True:
            try:
                return _train_once(run_config, mesh, max_steps, dataset,
                                   data_advance=data_advance,
                                   poison_pos=poison_pos)
            except NonFiniteLossError as e:
                if not config.ckpt_dir or config.max_rollbacks <= 0:
                    raise
                # "consecutive" = no net progress: a NaN at or before the
                # last poisoned step means the run never got past it
                rollbacks = rollbacks + 1 if e.step <= last_nan_step else 1
                last_nan_step = max(last_nan_step, e.step)
                if rollbacks > config.max_rollbacks:
                    raise RollbackExhaustedError(
                        f"{rollbacks} consecutive rollbacks without progress "
                        f"past step {last_nan_step} (max_rollbacks="
                        f"{config.max_rollbacks}): the divergence is "
                        "structural, not a poisoned data window — aborting "
                        "for a human"
                    ) from e
                reason = ("representation collapse"
                          if isinstance(e, CollapseError)
                          else "non-finite loss")
                log_event(
                    "rollback",
                    f"{reason} at step {e.step}: restoring the last "
                    f"good checkpoint and advancing the data stream past the "
                    f"poisoned window (rollback {rollbacks}/"
                    f"{config.max_rollbacks})",
                )
                run_config = config.replace(resume="auto")
                data_advance = e.step
                poison_pos = e.pos
    finally:
        if installed_chaos:
            # a plan left installed would hijack the NEXT train() call in
            # this process: its own --chaos spec would be silently ignored
            # (a vacuous drill), or this run's unspent faults would fire
            # into it
            clear_chaos()


def _train_once(config: PretrainConfig, mesh, max_steps: int | None = None,
                dataset=None, data_advance: int = 0,
                poison_pos: tuple[int, int] | None = None):
    """Safety shell around `_train_once_impl`: telemetry is created early in
    the pass (so rollback/resume incidents are captured) but the step loop's
    own finally is far below — an exception in between (corrupt restore,
    baseline-eval failure) must still unregister the log_event sink and
    close the events file. `close()` is idempotent, so the impl's rich
    summary close wins when both run."""
    open_telemetry: list = []
    try:
        return _train_once_impl(config, mesh, max_steps, dataset,
                                data_advance, poison_pos, open_telemetry)
    finally:
        for tel in open_telemetry:
            tel.close()


def _service_dataset_len(endpoints_spec) -> int:
    """Dataset length from the first staging server that answers a meta
    probe. Every endpoint is tried once; total unreachability is a
    configuration error (the servers are expected up before the train
    host starts — same contract as ServiceClient's handshake). A
    same-length-different-data server is still caught per-connection by
    the client's meta check."""
    from moco_tpu.data.service import protocol
    from moco_tpu.data.service.client import ServiceConfigError

    endpoints = (protocol.parse_endpoints(endpoints_spec)
                 if isinstance(endpoints_spec, str) else endpoints_spec)
    tried = []
    for host, port in endpoints:
        meta = protocol.fetch_meta(host, port)
        if meta is not None and int(meta.get("n", 0)) > 0:
            return int(meta["n"])
        tried.append(f"{host}:{port}")
    raise ServiceConfigError(
        "no staging server answered a meta probe (tried "
        + ", ".join(tried)
        + ") — start the servers first, or unset input_service"
    )


def _train_once_impl(config: PretrainConfig, mesh, max_steps: int | None = None,
                     dataset=None, data_advance: int = 0,
                     poison_pos: tuple[int, int] | None = None,
                     _telemetry_out: list | None = None):
    """One driver pass (the body `train` retries around on rollback).
    `data_advance`: skip the data stream forward past the poisoned window —
    weights restart from the restored checkpoint but the window is never
    re-consumed. `poison_pos` is the `(epoch, batch_index)` the poisoned
    batch was consumed at; when absent it is derived from `data_advance`
    (only correct while steps and batches are still aligned)."""
    if config.knn_monitor and config.knn_every_epochs < 1:
        raise ValueError(
            f"knn_every_epochs must be >= 1 (got {config.knn_every_epochs}); "
            "disable the monitor with knn_monitor=False instead")
    if config.debug_nans:
        # numeric sanitizer (SURVEY §5.2): raise at the op that produced the
        # first NaN instead of training through garbage
        jax.config.update("jax_debug_nans", True)
    n_chips = mesh.size
    local_b = local_batch_size(config.batch_size, mesh)  # validates divisibility

    dataset_len = None
    if dataset is None:
        if config.input_prestage:
            # pre-staged epoch cache (ISSUE 14): the dataset IS the mmap —
            # epochs are row gathers, decode happened once offline
            from moco_tpu.data.service.prestage import PrestagedDataset

            dataset = PrestagedDataset(config.input_prestage)
        elif config.input_service and not config.knn_monitor:
            # input_service is the remote-decode topology: the train host
            # may not even mount the data tree, and the only local use of
            # the dataset would be len(). The handshake meta already
            # carries the length every ServiceClient connection validates
            # against — probe it instead of paying an ImageFolder scan.
            # (The kNN monitor genuinely decodes locally, so it keeps the
            # local build.)
            dataset_len = _service_dataset_len(config.input_service)
        else:
            dataset = build_dataset(
                config.dataset, config.data_dir, image_size=config.image_size,
                stage_size=config.stage_size, num_workers=config.num_workers,
            )
    if dataset_len is None:
        dataset_len = len(dataset)
    # clamp to the batches the loader can actually yield: a steps_per_epoch
    # above that silently truncated epochs (and stretched the lr schedule) —
    # the r2 "3200-step" horizon run actually ran 768 steps this way
    available = max(dataset_len // config.batch_size, 1)
    steps_per_epoch = min(config.steps_per_epoch or available, available)
    if config.steps_per_epoch and steps_per_epoch < config.steps_per_epoch:
        info(
            f"steps_per_epoch clamped {config.steps_per_epoch} -> "
            f"{steps_per_epoch}: the {dataset_len}-sample dataset yields only "
            f"{available} batches of {config.batch_size}"
        )

    # observability on process 0 only: every host writing the same tags into
    # one tb_dir duplicates curves, and concurrent profiler traces race
    is_main = jax.process_index() == 0
    n_procs = jax.process_count()
    # structured telemetry (ISSUE 2): EVERY process builds one (the pod
    # allgather needs all hosts' vectors) but only process 0 writes
    # events.jsonl + heartbeat. None when off — the step loop then runs
    # zero telemetry code (no fences, no sampling: the overhead contract).
    # Created BEFORE the rollback/data-advance events below so every
    # incident of this driver pass lands in the stream.
    telemetry = None
    if config.telemetry_dir:
        from moco_tpu.telemetry import RunTelemetry

        telemetry = RunTelemetry(
            config, n_chips=n_chips, n_procs=n_procs,
            process_index=jax.process_index(), steps_per_epoch=steps_per_epoch,
        )
        if _telemetry_out is not None:
            _telemetry_out.append(telemetry)
    input_stats = telemetry.input_stats if telemetry is not None else None

    if (config.input_cache_mb and not config.input_prestage
            and dataset is not None):
        # decode-once canvas cache (ISSUE 3): wrapped per driver pass, so a
        # NaN rollback restarts it cold (safe — it is index-keyed, carries
        # no positional state, and the skipped window is simply never asked
        # for). Lives OUTSIDE the epoch loop: epochs >= 2 are the payoff.
        # (A prestage is already the cache-everything case — wrapping it
        # would spend RAM duplicating an mmap the page cache shares. The
        # guard is "a local decoding dataset exists": a service-fed run
        # without the kNN monitor built none, while service + kNN keeps
        # one whose repeated bank encodes are exactly this cache's
        # workload.)
        from moco_tpu.data.canvas_cache import CachedDataset

        dataset = CachedDataset(dataset, config.input_cache_mb,
                                stats=input_stats)

    model = build_encoder(config)
    tx, sched = build_optimizer(config, steps_per_epoch)
    init_key = jax.random.key(config.seed)
    if config.variant == "v3":
        from moco_tpu.v3_step import create_v3_train_state

        state = create_v3_train_state(
            init_key, model, tx, (local_b, config.image_size, config.image_size, 3)
        )
    else:
        state = create_train_state(
            init_key,
            model,
            tx,
            (local_b, config.image_size, config.image_size, 3),
            config.num_negatives,
            config.embed_dim,
        )
    # gradient-sync accumulators (ISSUE 6): attached BEFORE any resume so
    # the restore target carries the dialect-2 leaves (quantized/demo);
    # fused/bucketed attach an empty tree
    from moco_tpu.parallel.gradsync import GradSync

    # bound to the mesh's own axes (for_mesh): on the 2-D fsdp_tp mesh the
    # quantized reduce is the multihop one, and the telemetry describe()
    # below must account the same per-hop bytes the program moves
    gradsync = GradSync.for_mesh(config, mesh)
    state = gradsync.attach(state, mesh)
    if config.sharding != "dp":
        # FSDP placement (ISSUE 15): params/opt leaves land sharded over
        # the fsdp axis BEFORE the step builds, so jit compiles against
        # the committed input shardings (the zero_sharding pattern)
        from moco_tpu.parallel import fsdp

        state = fsdp.place_state(state, mesh, config)
    step_fn = build_train_step(config, model, tx, mesh, steps_per_epoch,
                               sched, state=state)
    if telemetry is not None:
        # static comm facts for the record stream: mode, knobs, analytic
        # per-device sync payload (bytes/step) — rendered by
        # telemetry_report. `sharding` stamps the mode the numbers were
        # measured under (ISSUE 15 satellite).
        telemetry.set_grad_sync(
            dict(gradsync.describe(state.params_q),
                 sharding=config.sharding))
        # per-device state inventory: under fsdp the params/opt bytes
        # measure ~1/N of dp — the acceptance gate and bench read this
        from moco_tpu.parallel.fsdp import state_bytes_per_device

        telemetry.set_sharding(dict(
            mode=config.sharding,
            mesh_shape={str(a): int(s) for a, s in mesh.shape.items()},
            **state_bytes_per_device(state),
        ))

    mgr = checkpoint_manager(config.ckpt_dir) if config.ckpt_dir else None
    if mgr is not None and config.resume:
        # restore straight into the run's own placement: Orbax places
        # every host's shards locally (a restore-then-`device_put` would
        # need cross-host transfers, unsupported on multi-process CPU and a
        # DCN round-trip on real pods). dp restores replicated; fsdp passes
        # the per-leaf NamedSharding TREE (dialect 3) so dp→fsdp and N→M
        # checkpoints land sharded without a resharding pass.
        from moco_tpu.parallel.mesh import replicated

        if config.sharding != "dp":
            from moco_tpu.parallel.fsdp import state_shardings

            restore_sharding = state_shardings(state, mesh, config)
        else:
            restore_sharding = replicated(mesh)
        state = maybe_resume(mgr, state, config.resume,
                             sharding=restore_sharding)
        if gradsync.needs_state:
            # re-place the per-device accumulators (the replicated-restore
            # path lands them replicated) — mirrors the ZeRO re-shard below
            state = state.replace(
                gradsync=gradsync.place_state(state.gradsync, mesh))
            # sharding-MODE change (ISSUE 15): at equal mesh size the
            # accumulator shapes match, so the dialect shim cannot see it —
            # but the EF residuals were accumulated under a different
            # reduce topology. The sidecar stamp is the tiebreaker.
            resumed_step = int(state.step)
            if resumed_step:
                from moco_tpu.checkpoint import read_recorded_sharding

                recorded = read_recorded_sharding(
                    config.ckpt_dir, resumed_step) or "dp"
                if recorded != config.sharding:
                    log_event(
                        "ckpt-dialect",
                        f"step {resumed_step} was saved under sharding="
                        f"{recorded!r}, this run uses {config.sharding!r} — "
                        "discarding its gradsync accumulators: error-"
                        "feedback/momentum state restarts from zeros",
                    )
                    state = state.replace(gradsync=jax.tree.map(
                        jnp.zeros_like, state.gradsync))
    if config.zero_sharding:
        # ZeRO-1 (after any resume, so the placement survives it): optimizer
        # state sharded over the data axis; jit propagates the committed
        # input shardings through every subsequent step
        from moco_tpu.parallel.zero import shard_opt_state

        state = state.replace(opt_state=shard_opt_state(state.opt_state, mesh))

    aug_cfg = aug_config_for(config)
    # image pipeline in the model's compute dtype: bf16 halves the aug's HBM
    # traffic on TPU (the encoder casts to bf16 immediately anyway)
    from moco_tpu.data.augment import with_dtype
    from moco_tpu.train_step import build_fused_step

    aug_cfg = with_dtype(aug_cfg, config.compute_dtype)
    data_key = jax.random.key(config.seed + 1)
    two_crops_fn = build_two_crops_sharded(aug_cfg, mesh)
    fused_step = build_fused_step(step_fn, two_crops_fn, data_key)

    # host-side step counter mirroring state.step: int(state.step) would be a
    # device→host sync (~70 ms on the relay) serializing every iteration
    global_step = int(state.step)
    # data-stream position: prefer the checkpoint's position sidecar — step
    # arithmetic replays consumed batches once a NaN rollback's data-window
    # skip has drifted the step↔batch mapping. Arithmetic remains the
    # fallback for sidecar-less checkpoints (pre-feature, or lost to a
    # mid-save kill): skip the resumed epoch's already-consumed batches so
    # no data is replayed (the epoch_loader permutation is deterministic per
    # epoch, so batch i here is bit-identical to batch i of the interrupted
    # run)
    pos = (read_position(config.ckpt_dir, global_step)
           if config.ckpt_dir and global_step else None)
    if pos is not None:
        start_epoch, resume_skip = pos
    else:
        start_epoch = global_step // steps_per_epoch
        resume_skip = global_step % steps_per_epoch
    poison_epoch = poison_batch = None
    if data_advance > global_step:
        # NaN rollback: weights restart from the restored step, but the data
        # stream must not replay the poisoned window — every batch from the
        # restore point THROUGH the poisoned batch is skipped, across epoch
        # boundaries when the restored checkpoint is older than the poison's
        # epoch (ckpt_every_epochs > 1, or an integrity walk-back past a
        # corrupt save). Skipped epochs yield fewer steps than
        # steps_per_epoch, so the run's step count drifts from epoch
        # alignment — accepted: the trajectory already diverged the moment
        # data was skipped.
        if poison_pos is not None:
            poison_epoch, poison_batch = poison_pos
        else:
            poison_epoch = (data_advance - 1) // steps_per_epoch
            poison_batch = (data_advance - 1) % steps_per_epoch
        log_event(
            "rollback",
            f"advancing the data stream past the poisoned window: restored "
            f"step {global_step}, skipping through batch {poison_batch} of "
            f"epoch {poison_epoch}",
        )
    total_steps = max_steps or config.epochs * steps_per_epoch
    last_metrics: dict = {}
    baseline_metrics: dict = {}
    feature_fn = make_feature_fn(model, config.variant) if config.knn_monitor else None
    monitor_val = _monitor_val_split(config, dataset) if config.knn_monitor else None
    writer = ScalarWriter(config.tb_dir if is_main else "")
    profiler = ProfilerWindow(
        config.profile_dir if is_main else "", config.profile_start, config.profile_stop
    )
    done = False

    # untrained-baseline row (VERDICT r3 weak #3): a kNN curve is only
    # evidence of learning relative to what RANDOM features score on the
    # same data — print it before any step so every horizon log carries it.
    # The monitor itself is a mesh-sharded (collective) computation, so
    # EVERY process must enter it; only the print/writer are main-gated
    baseline_sidecar = (
        os.path.join(config.ckpt_dir, "untrained_baseline.json")
        if config.ckpt_dir else None
    )
    if config.knn_monitor and start_epoch == 0 and global_step == 0:
        acc0, is_val0 = knn_monitor(
            config, feature_fn, state, dataset, mesh, val_dataset=monitor_val
        )
        tag0 = "knn_val_top1_untrained" if is_val0 else "knn_train_top1_untrained"
        # separate dict: the step loop REBINDS last_metrics each logging
        # interval, which would silently drop the baseline row
        baseline_metrics[tag0] = acc0
        if is_main:
            info(
                f"Epoch [-1] kNN({'val' if is_val0 else 'train'}) top-1 "
                f"{100 * acc0:.2f}% (UNTRAINED baseline; chance "
                f"{100.0 / dataset.num_classes:.2f}%)"
            )
            writer.write(0, {tag0: acc0})
        if telemetry is not None:
            telemetry.event("knn_eval", step=0, tag=tag0, acc=float(acc0))
        if is_main and baseline_sidecar:
            # persist next to the checkpoints: a resumed run can no
            # longer MEASURE the untrained baseline (the restored
            # encoder is trained), so it must inherit the recorded
            # one — otherwise resume silently weakens any gate that
            # compares against it
            # atomic: a preemption mid-write must not leave truncated
            # JSON that bricks every later resume (the whole point of
            # the sidecar is surviving preemption)
            tmp = baseline_sidecar + ".tmp"
            with open(tmp, "w") as f:
                json.dump({tag0: float(acc0)}, f)
            os.replace(tmp, baseline_sidecar)
    elif config.knn_monitor and global_step > 0 and baseline_sidecar and \
            os.path.exists(baseline_sidecar):
        try:
            with open(baseline_sidecar) as f:
                restored = json.load(f)
        except (json.JSONDecodeError, OSError):
            restored = {}
        if not isinstance(restored, dict):  # e.g. a file containing `null`
            restored = {}
        # empty/corrupt sidecar: leave baseline_metrics alone — the caller
        # (tools/_horizon_run.py) refuses to gate without a baseline,
        # which is the honest outcome
        baseline_metrics.update(restored)
        if is_main and restored:
            tag0, acc0 = next(iter(restored.items()))
            info(
                f"Epoch [-1] kNN top-1 {100 * acc0:.2f}% (UNTRAINED "
                f"baseline, restored from {baseline_sidecar})"
            )

    # resilience hooks (ISSUE 1): signal-flag preemption, every-step NaN
    # sentinel (one-step lag), hang watchdog, decode-failure meter, chaos
    plan = active_chaos()
    sentinel = NaNSentinel() if config.loss_sentinel else None
    # learning-health sentinel (ISSUE 13): armed when any predicate has a
    # nonzero threshold; consumes the popped health scalars below with
    # the same one-step-lag device-read discipline as the NaN sentinel
    collapse = None
    if config.collapse_acc1 or config.collapse_emb_std or config.collapse_margin:
        collapse = CollapseSentinel(
            config.collapse_window,
            acc1_floor=config.collapse_acc1,
            emb_std_eps=config.collapse_emb_std,
            margin_eps=config.collapse_margin,
            min_step=config.collapse_min_step,
            rollback=config.collapse_rollback,
        )
    preempted = False
    resized = False
    _resilience = contextlib.ExitStack()
    preempt = _resilience.enter_context(PreemptionHandler())
    # elastic resize (ISSUE 11): SIGUSR2 or a <telemetry_dir>/resize.request
    # trigger file asks for a clean checkpoint + EXIT_RESIZE so the
    # supervisor can relaunch onto a different mesh
    resize = _resilience.enter_context(ResizeListener(config.telemetry_dir))
    watchdog = _resilience.enter_context(StepWatchdog(config.watchdog_secs))
    try:
        for epoch in range(start_epoch, config.epochs):
            if done:
                break
            batch_time = AverageMeter("Time", ":6.3f")
            data_time = AverageMeter("Data", ":6.3f")
            losses = AverageMeter("Loss", ":.4e")
            top1 = AverageMeter("Acc@1", ":6.2f")
            top5 = AverageMeter("Acc@5", ":6.2f")
            decode_fail = RateMeter("DecFail")
            progress = ProgressMeter(
                steps_per_epoch,
                [batch_time, data_time, losses, top1, top5, decode_fail],
                prefix=f"Epoch: [{epoch}]",
            )
            # rolling window for the per-step line: the cumulative view is
            # polluted by the first-step compile stall for the whole epoch
            # (ISSUE 2 satellite); epoch summary still reports cumulative
            throughput = Throughput(n_chips, window=32)
            skip = resume_skip if epoch == start_epoch else 0
            if poison_epoch is not None and epoch <= poison_epoch:
                # inside the poisoned window: epochs before the poison's are
                # skipped wholesale, the poison's own epoch through the
                # poisoned batch itself
                skip = steps_per_epoch if epoch < poison_epoch else max(
                    skip, poison_batch + 1)
            epoch_start_step = global_step
            if config.input_service:
                # disaggregated input service (ISSUE 14): the SAME epoch
                # permutation/shard/fast-forward, but canvas rows stream
                # from standalone staging servers — bit-identical to the
                # in-process branch below on the same seed/epoch
                from moco_tpu.data.service.client import service_epoch_loader

                loader = service_epoch_loader(
                    config.input_service, dataset_len, epoch, config.seed,
                    config.batch_size, mesh, skip_batches=skip,
                    retries=config.loader_retries,
                    backoff_secs=config.loader_backoff_secs,
                    depth=config.prefetch_depth,
                    streams=config.staging_workers, stats=input_stats,
                    tracer=telemetry.tracer if telemetry is not None
                    else None,
                    request_timeout_s=config.input_request_timeout_s,
                )
            else:
                loader = epoch_loader(
                    dataset, epoch, config.seed, config.batch_size, mesh,
                    skip_batches=skip, retries=config.loader_retries,
                    backoff_secs=config.loader_backoff_secs,
                    depth=config.prefetch_depth,
                    workers=config.staging_workers,
                    stats=input_stats, trim_h2d=config.h2d_trim,
                    tracer=telemetry.tracer if telemetry is not None
                    else None,
                )
            end = time.perf_counter()
            if telemetry is not None:
                telemetry.timer.epoch_start()
            try:
                for i, (imgs, _labels, extents) in enumerate(loader, start=skip):
                    if i >= steps_per_epoch:  # steps_per_epoch may cap the epoch
                        break
                    data_time.update(time.perf_counter() - end)
                    if telemetry is not None:
                        telemetry.timer.mark_data()
                    profiler.maybe_toggle(global_step)
                    state, metrics = fused_step(state, imgs, extents, global_step)
                    global_step += 1
                    # comm-phase probes (ISSUE 6): device scalars marking
                    # grads-ready / grads-reduced, popped so meters and the
                    # scalar writer never see them
                    gs_pre = metrics.pop("gs_comm_pre", None)
                    gs_post = metrics.pop("gs_comm_post", None)
                    # learning-health scalars (ISSUE 13): popped like the
                    # gs probes so meters/scalar-writer never see them.
                    # The h_* block carries cond-selected ZEROS on
                    # off-stride steps — only on-stride values are real.
                    neg_sim = metrics.pop("neg_sim", None)
                    logit_margin = metrics.pop("logit_margin", None)
                    health_dev = {
                        k: metrics.pop(k)
                        for k in [k for k in metrics if k.startswith("h_")]
                    }
                    on_health_stride = bool(
                        config.health_stride
                        and (global_step - 1) % config.health_stride == 0
                    )
                    if telemetry is not None:
                        telemetry.timer.mark_dispatch()
                        # stride-gated device fence: off-stride steps stay
                        # fully async (the overhead contract)
                        telemetry.timer.maybe_fence(
                            global_step, metrics["loss"],
                            comm_pre=gs_pre, comm_post=gs_post,
                        )
                    if plan is not None and plan.maybe_nan(global_step):
                        # emulate a real divergence end-to-end: the NaN flows
                        # through the same metrics dict the sentinel/meters see
                        metrics = dict(metrics, loss=float("nan"))
                    if sentinel is not None:
                        sentinel.observe(global_step, metrics["loss"],
                                         pos=(epoch, i))
                    if collapse is not None:
                        obs = {"logit_margin": logit_margin,
                               "acc1": metrics.get("acc1")}
                        if on_health_stride:
                            # stride-gated diagnostics are real only on
                            # stride steps; feeding the off-stride zeros
                            # would read as instant collapse
                            obs.update(health_dev)
                        collapse.observe(global_step, obs, pos=(epoch, i))
                    if plan is not None:
                        # slow-step drill (ISSUE 8): the sleep lands inside
                        # THIS step's timer window, so the anomaly detector
                        # sees a real step_s blowout end-to-end
                        plan.maybe_slow(global_step)
                    watchdog.beat(global_step)
                    d_fail = getattr(dataset, "decode_failures", 0)
                    d_total = getattr(dataset, "decode_total", 0)
                    # per-host fault signals (SIGTERM flag, decode counters)
                    # must be ACTED on identically everywhere: one host
                    # raising or breaking alone leaves the rest hung in the
                    # next collective. Multi-host runs agree on them at a
                    # fixed step cadence; single-host acts immediately.
                    # refresh the resize flag from the trigger file (time-
                    # gated; SIGUSR2 needs no poll) before the pod sync so
                    # every host folds the same observation
                    resize.poll()
                    preempt_agreed = False
                    resize_agreed = False
                    abort_fail, abort_total = d_fail, d_total
                    if n_procs > 1:
                        abort_fail = abort_total = 0
                        if (config.resilience_sync_steps > 0 and
                                global_step % config.resilience_sync_steps == 0):
                            from jax.experimental import multihost_utils

                            agg = multihost_utils.process_allgather(
                                np.asarray(
                                    [int(preempt.triggered), d_fail, d_total,
                                     int(resize.triggered)],
                                    np.int64,
                                )
                            )
                            preempt_agreed = bool(agg[:, 0].max())
                            resize_agreed = bool(agg[:, 3].max())
                            abort_fail = int(agg[:, 1].sum())
                            abort_total = int(agg[:, 2].sum())
                            if telemetry is not None:
                                # pod telemetry piggybacks on this already-
                                # synchronizing cadence: one extra small
                                # allgather, no new sync points; process 0
                                # folds the matrix into a `pod` record
                                telemetry.pod_record(
                                    global_step,
                                    multihost_utils.process_allgather(
                                        telemetry.pod_vector()
                                    ),
                                )
                    if (
                        config.decode_abort_rate
                        and abort_total >= config.batch_size
                        and abort_fail / abort_total > config.decode_abort_rate
                    ):
                        raise DataQualityError(
                            f"decode-failure rate {abort_fail}/{abort_total} = "
                            f"{abort_fail / abort_total:.1%} exceeds "
                            f"decode_abort_rate={config.decode_abort_rate:.1%}: "
                            "training on zero canvases would silently waste "
                            "the run"
                        )
                    step_loss = None  # host-synced loss, when printing pulls it
                    if i % config.print_freq == 0:
                        # pull metrics (host sync) only when printing
                        last_metrics = {k: float(v) for k, v in metrics.items()}
                        step_loss = last_metrics["loss"]
                        if config.debug_nans and not np.isfinite(last_metrics["loss"]):
                            raise FloatingPointError(
                                f"non-finite loss {last_metrics['loss']} at step {global_step}"
                            )
                        losses.update(last_metrics["loss"], config.batch_size)
                        top1.update(last_metrics.get("acc1", 0.0), config.batch_size)
                        top5.update(last_metrics.get("acc5", 0.0), config.batch_size)
                        decode_fail.update(d_fail, d_total)
                        progress.display(i)
                        writer.write(
                            global_step,
                            dict(
                                last_metrics,
                                # per-step line reports the ROLLING rate (the
                                # cumulative one drags the compile stall
                                # through the whole epoch); the epoch summary
                                # below stays cumulative
                                imgs_per_sec=throughput.rolling_imgs_per_sec,
                                imgs_per_sec_per_chip=(
                                    throughput.rolling_imgs_per_sec
                                    / max(n_chips, 1)
                                ),
                                decode_failures=d_fail,
                                decode_failure_rate=decode_fail.rate,
                            ),
                        )
                    throughput.update(config.batch_size)
                    batch_time.update(time.perf_counter() - end)
                    end = time.perf_counter()
                    health_rec = None
                    if telemetry is not None and on_health_stride:
                        # health block for the step record (ISSUE 13):
                        # pulled to host only on health-stride steps, as
                        # ONE batched transfer — per-scalar float() would
                        # pay a device→host round trip each (~70 ms on
                        # the tunneled relay) × a dozen scalars. Keys
                        # drop the h_ prefix — obsd rules address them
                        # as health:<key>.
                        pull = dict(health_dev)
                        if logit_margin is not None:
                            pull["_logit_margin"] = logit_margin
                            pull["_neg_sim"] = neg_sim
                            pull["_pos_sim"] = metrics["pos_sim"]
                            pull["_acc1"] = metrics["acc1"]
                        host = jax.device_get(pull)
                        health_rec = {
                            k[2:]: round(float(v), 6)
                            for k, v in host.items()
                            if k.startswith("h_")
                        }
                        if logit_margin is not None:
                            health_rec["logit_margin"] = round(
                                float(host["_logit_margin"]), 6)
                            health_rec["neg_sim"] = round(
                                float(host["_neg_sim"]), 6)
                            health_rec["pos_sim"] = round(
                                float(host["_pos_sim"]), 6)
                            health_rec["acc1"] = round(
                                float(host["_acc1"]), 4)
                    if telemetry is not None:
                        phases = telemetry.timer.finish_step()
                        if telemetry.on_step(global_step, phases, throughput,
                                             loss=step_loss,
                                             health=health_rec):
                            # flushed: land the TensorBoard curves at the
                            # same cadence (ISSUE 2 satellite)
                            writer.flush()
                    if plan is not None:
                        plan.maybe_sigterm(global_step)
                        # elastic-resize drill (ISSUE 11): record the target
                        # device count where the supervisor will look for
                        # it, then exit through the same path an operator
                        # request takes
                        chaos_devices = plan.maybe_resize(global_step)
                        if chaos_devices is not None:
                            if config.telemetry_dir:
                                write_resize_request(
                                    config.telemetry_dir,
                                    devices=chaos_devices or None,
                                )
                            resize.trigger()
                        if plan.maybe_collapse(global_step):
                            # collapse drill (ISSUE 13): crush the key
                            # encoder to a constant-feature tree, EVERY
                            # step from here on — the in-step EMA would
                            # heal a one-shot crush within one step
                            from moco_tpu.telemetry.health import (
                                crush_key_params,
                            )

                            state = state.replace(
                                params_k=crush_key_params(state.params_k))
                        # process-level faults (ISSUE 4): SIGKILL-grade death
                        # and wedged-collective freeze — both invisible to
                        # the in-process handlers, recoverable only by the
                        # out-of-process supervisor. After on_step, so the
                        # heartbeat's last beat records this step.
                        plan.maybe_kill(global_step)
                        plan.maybe_freeze(global_step)
                    if preempt_agreed or (n_procs == 1 and preempt.triggered):
                        # finish-the-step-then-exit: the emergency checkpoint
                        # (a COLLECTIVE save) lands after the loop, at a step
                        # every host agrees on — a signaled host breaking by
                        # itself would leave the others in a hung collective
                        preempted = True
                        done = True
                        break
                    if resize_agreed or (n_procs == 1 and resize.triggered):
                        # same finish-the-step-then-exit shape as preemption,
                        # but the exit code says "relaunch me onto a NEW
                        # mesh" (EXIT_RESIZE) instead of "same argv"
                        resized = True
                        done = True
                        break
                    if global_step >= total_steps:
                        done = True
                        break
            finally:
                # unblock the prefetch thread on early break; quietly — a
                # pending staged-read error raised here would replace an
                # in-flight exception (disarming the NaN rollback) or void a
                # completed/preempted run whose every consumed step succeeded
                loader.close_quietly()
            if sentinel is not None:
                # check the epoch's LAST loss now (its one-step-lag check
                # would otherwise land after the epoch-end save below, and a
                # NaN state would be checkpointed — then restored by the very
                # rollback trying to escape it)
                sentinel.flush()
            if collapse is not None:
                # same reasoning for the collapse predicates: a collapsed
                # state must not be checkpointed past its own detection
                collapse.flush()
            if preempted or resized:
                break  # no epoch eval/save: the emergency checkpoint follows
            # epoch summary stays CUMULATIVE (honest average incl. the
            # compile stall); the per-step line above reports rolling
            info(
                f"Epoch [{epoch}] imgs/sec {throughput.imgs_per_sec:.1f} "
                f"({throughput.imgs_per_sec_per_chip:.1f}/chip)"
            )
            if telemetry is not None:
                telemetry.event(
                    "epoch_summary", epoch=epoch, step=global_step,
                    imgs_per_sec=round(throughput.imgs_per_sec, 2),
                    imgs_per_sec_rolling=round(
                        throughput.rolling_imgs_per_sec, 2),
                )
            # cadence: every knn_every_epochs, plus the run's final epoch
            # (early `done` break included) so end-of-run gates always see a
            # current number. Zero-step epochs (a rollback skipped them
            # wholesale) have nothing new to report: the weights are
            # unchanged, so the eval would burn minutes re-measuring the
            # previous point and write a duplicate at the same global_step
            if config.knn_monitor and global_step > epoch_start_step and (
                (epoch + 1) % config.knn_every_epochs == 0
                or epoch == config.epochs - 1
                or done
            ):
                if telemetry is not None:
                    # the supervisor's analogue of watchdog.suspended():
                    # an "eval" beat widens its staleness window so the
                    # beat-less minutes below aren't killed as a hang
                    telemetry.phase_beat("eval", global_step)
                with watchdog.suspended():
                    # a multi-minute eval with no step beats is a guaranteed
                    # false 'possible hang' flag otherwise
                    acc, is_val = knn_monitor(
                        config, feature_fn, state, dataset, mesh,
                        val_dataset=monitor_val,
                    )
                # with a real val split the tag is a true val metric;
                # otherwise the held-out slice comes from the TRAIN set and
                # the tag says so, to avoid misreading it
                tag = "knn_val_top1" if is_val else "knn_train_top1"
                label = "val" if is_val else "train"
                last_metrics[tag] = acc
                info(f"Epoch [{epoch}] kNN({label}) top-1 {100 * acc:.2f}%")
                writer.write(global_step, {tag: acc})
                if telemetry is not None:
                    telemetry.event("knn_eval", step=global_step, epoch=epoch,
                                    tag=tag, acc=float(acc))
            if (
                mgr is not None
                and global_step > epoch_start_step  # an epoch the rollback
                # skipped wholesale made no progress — re-saving the restored
                # step would collide with the existing checkpoint
                and (epoch + 1) % config.ckpt_every_epochs == 0
            ):
                # unlike the reference's rank-0-only torch.save, Orbax saving
                # of multi-process arrays is COLLECTIVE — every process must
                # call it. Async (wait=False): serialization overlaps the
                # next epoch's compute; the integrity manifest is deferred to
                # the next save / finalize_checkpoints
                save_checkpoint(mgr, state, global_step, wait=False,
                                position=(epoch + 1, 0), devices=n_chips,
                                sharding=config.sharding)
        if sentinel is not None:
            # the final step's loss is still pending (one-step lag)
            sentinel.flush()
        if collapse is not None:
            collapse.flush()
    finally:
        # always land the profiler trace and flush buffered scalars,
        # even when the loop raises (debug_nans, data errors, ^C);
        # restore signal dispositions and stop the watchdog thread
        _resilience.close()
        profiler.close()
        if telemetry is not None:
            # run_end summary + final flush; also surfaces the writer's
            # dropped-scalar count (ISSUE 2 satellite) so silent drops are
            # visible in the machine record. `preempted` routes the final
            # heartbeat's phase (preempt_exit vs run_end) so the supervisor
            # knows a relaunch is expected without scraping logs.
            telemetry.close(scalar_drops=writer.dropped, last_step=global_step,
                            preempted=preempted, resized=resized)
        writer.close()
        if mgr is not None:
            # commit any in-flight async epoch save (and its deferred
            # manifest) BEFORE a rollback's restore walks the directory —
            # otherwise "latest" may be a step Orbax is still writing
            finalize_checkpoints(mgr)
    if (preempted or resized) and mgr is not None:
        # step-tagged emergency checkpoint: the position sidecar (plus the
        # mid-epoch `resume_skip` path) makes the resumed run bit-identical
        # to the uninterrupted one. `epoch`/`i` survive the loop: the
        # preempted/resized break only fires inside an iteration
        emergency_pos = ((epoch + 1, 0) if i + 1 >= steps_per_epoch
                         else (epoch, i + 1))
        log_event(
            "resize" if resized else "preempt",
            f"writing {'elastic' if resized else 'emergency'} checkpoint at "
            f"step {global_step}, then exiting cleanly",
            step=global_step, pid=os.getpid(),
        )
        save_checkpoint(mgr, state, global_step, position=emergency_pos,
                        devices=n_chips, sharding=config.sharding)
    if preempted:
        # surfaced to callers (absent otherwise): main() turns it into
        # EXIT_PREEMPTED so the supervisor can tell a preemption's clean
        # exit (“relaunch me”) from a natural end without log forensics
        last_metrics = dict(last_metrics, preempted=True)
    if resized:
        # main() turns it into EXIT_RESIZE: "relaunch me onto the new mesh"
        last_metrics = dict(last_metrics, resized=True)
    if mgr is not None:
        finalize_checkpoints(mgr)
    if config.export_path and is_main and not preempted and not resized:
        # close the pretrain→probe loop: v1/v2 write the query encoder in the
        # reference checkpoint dialect (torchvision names) for evals.lincls /
        # evals.knn / export_detectron2; v3 writes its backbone tree dialect
        if config.variant == "v3":
            from moco_tpu.checkpoint import export_v3_backbone

            export_v3_backbone(state, config.export_path, config.image_size)
        elif config.arch.startswith("vit"):
            from moco_tpu.checkpoint import export_vit_encoder

            export_vit_encoder(state, config.export_path, config.image_size)
        else:
            from moco_tpu.checkpoint import export_encoder_q

            export_encoder_q(state, config.export_path)
        info(f"exported encoder -> {config.export_path}")
    return state, {**baseline_metrics, **last_metrics}


def main(argv=None):
    """CLI entry. Exits through the named codes in resilience/exitcodes.py
    (the supervisor's classification protocol — lint rule R5 forbids bare
    `sys.exit(<int>)` here): 0 clean, EXIT_PREEMPTED after an honored
    SIGTERM + emergency checkpoint, EXIT_RESIZE after an honored elastic
    resize (clean checkpoint, relaunch onto a new mesh expected),
    EXIT_ROLLBACK_EXHAUSTED / EXIT_DATA_QUALITY for the deliberate
    run-enders a restart cannot fix, EXIT_CONFIG_ERROR for a bad
    preset/flag. Anything else propagates as a traceback (python's exit 1
    → classified as a generic crash)."""
    from moco_tpu.config import add_config_flags, collect_overrides
    from moco_tpu.resilience.exitcodes import (
        EXIT_CONFIG_ERROR,
        EXIT_DATA_QUALITY,
        EXIT_PREEMPTED,
        EXIT_RESIZE,
        EXIT_ROLLBACK_EXHAUSTED,
    )

    parser = argparse.ArgumentParser(description="moco_tpu pretraining")
    pretrain_presets = sorted(
        name for name, cfg in PRESETS.items() if isinstance(cfg, PretrainConfig)
    )
    parser.add_argument("--preset", default="cifar10-moco-v1", choices=pretrain_presets)
    parser.add_argument("--max-steps", type=int, default=None)
    parser.add_argument("--num-devices", type=int, default=None)
    parser.add_argument("--fake-devices", type=int, default=0,
                        help="force N fake CPU devices (testing)")
    parser.add_argument("--multihost", action="store_true",
                        help="call jax.distributed.initialize() (multi-host pods; "
                             "args auto-detected on Cloud TPU)")
    parser.add_argument("--coordinator-address", default=None)
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    add_config_flags(parser, PretrainConfig)
    args = parser.parse_args(argv)
    if args.fake_devices:
        from moco_tpu.parallel.mesh import force_cpu_devices

        force_cpu_devices(args.fake_devices)
    if args.multihost:
        from moco_tpu.parallel.mesh import distributed_init

        distributed_init(args.coordinator_address, args.num_processes, args.process_id)
    try:
        config = get_preset(args.preset).replace(
            **collect_overrides(args, PretrainConfig)
        )
    except (TypeError, ValueError) as e:
        # bad flag value / preset / __post_init__ validation: the same argv
        # can never succeed, so the exit code must say "don't restart me"
        log_event("exit", f"config error: {e}", code=EXIT_CONFIG_ERROR)
        sys.exit(EXIT_CONFIG_ERROR)
    # persistent XLA compile cache: a restarted/resumed run (or the bench
    # re-running this config) skips the multi-minute cold compile
    from moco_tpu.utils.cache import enable_persistent_cache

    enable_persistent_cache()
    try:
        # fold in the config's sharding layout HERE so an unsatisfiable
        # combination (--sharding-axis-size not dividing the device count,
        # a resize-appended --sharding onto the wrong mesh) exits
        # config_error like any other bad argv — train()'s own re-fold is
        # then a no-op for the CLI path
        from moco_tpu.parallel.mesh import mesh_for_config

        mesh = mesh_for_config(config, create_mesh(args.num_devices))
    except ValueError as e:
        # more devices requested than exist (e.g. a typo'd resize request's
        # --num-devices append), or a sharding layout the device count
        # cannot satisfy: the same argv can never succeed — the supervisor
        # must classify this config_error and revert/stop, not relaunch a
        # generic "crash" into a loop
        log_event("exit", f"mesh config error: {e}", code=EXIT_CONFIG_ERROR)
        sys.exit(EXIT_CONFIG_ERROR)
    info(f"config: {config}")
    info(f"mesh: {mesh}")
    try:
        _state, metrics = train(config, mesh, max_steps=args.max_steps)
    except RollbackExhaustedError as e:
        log_event("exit", f"rollback budget exhausted: {e}",
                  code=EXIT_ROLLBACK_EXHAUSTED)
        sys.exit(EXIT_ROLLBACK_EXHAUSTED)
    except DataQualityError as e:
        log_event("exit", f"data quality abort: {e}", code=EXIT_DATA_QUALITY)
        sys.exit(EXIT_DATA_QUALITY)
    if metrics.get("preempted"):
        log_event("exit", "preemption honored: emergency checkpoint written, "
                          "exiting for relaunch", code=EXIT_PREEMPTED)
        sys.exit(EXIT_PREEMPTED)
    if metrics.get("resized"):
        log_event("exit", "resize honored: elastic checkpoint written, "
                          "exiting for relaunch onto the new mesh",
                  code=EXIT_RESIZE)
        sys.exit(EXIT_RESIZE)


if __name__ == "__main__":
    main()
