"""Out-of-process run supervisor (ISSUE 4 tentpole).

PR 1 made the driver survive every fault it can OBSERVE; this module closes
the loop for the ones it structurally cannot: SIGKILL-grade preemption, a
segfault in the native staging loader, an OOM-killed process, and the
silence of a wedged pod collective. The `Supervisor` runs the training
driver as a child process and:

1. detects HANGS by polling `heartbeat.json` staleness (the every-step,
   time-gated beat from telemetry) and kills wedged children with a
   SIGTERM → grace → SIGKILL escalation — SIGTERM first, because a merely
   slow child still gets its emergency-checkpoint exit;
2. CLASSIFIES each death from the structured exit-code protocol
   (resilience/exitcodes.py), the death signal, and an `events.jsonl` tail
   forensic pass (OOM suspicion from the last RSS samples, native-loader
   frames);
3. applies a PER-CLASS restart policy: fatal classes (clean finish,
   rollback exhausted, config error, data quality) never restart;
   restartable classes draw on a budget with exponential backoff + jitter,
   and the budget is REFUNDED whenever the child made step progress since
   its last launch (read from the heartbeat / checkpoint sidecars) — so a
   run that keeps advancing restarts indefinitely while a crash loop
   exhausts the budget in `max_restarts` tries;
4. runs a resume-integrity PREFLIGHT before each relaunch: every
   checkpoint step that fails its PR 1 manifest is quarantined out of the
   directory, so a corrupt emergency checkpoint cannot crash-loop the
   child through `--resume auto`;
5. records every lifecycle event (launch, kill, exit classification,
   backoff, budget state, give-up) as structured `kind: "supervisor"`
   records appended to the child's own events.jsonl — one stream, rendered
   by tools/telemetry_report.py.

The CLI wrapper is tools/supervise.py. Everything here is pure stdlib —
the supervisor must not import jax (it has to stay alive and tiny while
the child OOMs the machine).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import signal
import subprocess
import time

from moco_tpu.resilience.exitcodes import (
    EXIT_CODE_NAMES,
    EXIT_CONFIG_ERROR,
    EXIT_DATA_QUALITY,
    EXIT_FLEET_BIND,
    EXIT_OK,
    EXIT_PREEMPTED,
    EXIT_RESIZE,
    EXIT_ROLLBACK_EXHAUSTED,
    EXIT_SERVE_BIND,
    EXIT_STAGING_BIND,
    USAGE_ERROR,
)
from moco_tpu.resilience.resize import (
    ResizeController,
    argv_device_count,
    read_recorded_devices,
)
# pure-stdlib by contract (mocolint R12; the lazy telemetry __init__ keeps
# this import numpy/jax-free): the supervisor is the trace ROOT — it mints
# the run id, stamps the child's env, and its launch/kill spans join the
# same timeline the child writes
from moco_tpu.telemetry.trace import Tracer
from moco_tpu.utils.logging import log_event

EVENTS_FILENAME = "events.jsonl"
HEARTBEAT_FILENAME = "heartbeat.json"
QUARANTINE_DIRNAME = ".quarantine"

# -- failure classes ---------------------------------------------------------
# the supervisor's whole vocabulary: every child death maps to exactly one
CLASS_CLEAN = "clean"                          # ran to the configured end
CLASS_PREEMPTED = "preempted"                  # honored SIGTERM, ckpt written
CLASS_ROLLBACK_EXHAUSTED = "rollback_exhausted"  # structural divergence
CLASS_CONFIG_ERROR = "config_error"            # same argv can never succeed
CLASS_DATA_QUALITY = "data_quality"            # dataset itself is bad
CLASS_HANG = "hang"                            # supervisor killed a stale child
CLASS_NATIVE_CRASH = "native_crash"            # SIGSEGV/SIGABRT/SIGBUS/...
CLASS_OOM = "oom"                              # SIGKILL + high tail RSS
CLASS_KILLED = "killed"                        # external SIGKILL/SIGTERM death
CLASS_CRASH = "crash"                          # any other nonzero exit
CLASS_SERVE_BIND = "serve_bind"                # serve.py couldn't bind its port
CLASS_FLEET_BIND = "fleet_bind"                # serve_fleet.py couldn't bind
                                               # its front-end router port
CLASS_RESIZE = "resize"                        # elastic checkpoint written;
                                               # relaunch onto the new mesh
                                               # (ISSUE 11)
CLASS_STAGING_BIND = "staging_bind"            # staging_server.py (or its
                                               # decode worker) couldn't bind
                                               # its health/data port (ISSUE
                                               # 14): reschedule, don't race
                                               # the socket

# classes where restarting can never help — the run is OVER
FATAL_CLASSES = frozenset({
    CLASS_CLEAN, CLASS_ROLLBACK_EXHAUSTED, CLASS_CONFIG_ERROR,
    CLASS_DATA_QUALITY, CLASS_SERVE_BIND, CLASS_FLEET_BIND,
    CLASS_STAGING_BIND,
})
RESTARTABLE_CLASSES = frozenset({
    CLASS_PREEMPTED, CLASS_HANG, CLASS_NATIVE_CRASH, CLASS_OOM,
    CLASS_KILLED, CLASS_CRASH, CLASS_RESIZE,
})

_CRASH_SIGNALS = {
    int(getattr(signal, name))
    for name in ("SIGSEGV", "SIGABRT", "SIGBUS", "SIGILL", "SIGFPE")
    if hasattr(signal, name)
}


# -- forensics ---------------------------------------------------------------


def read_events_tail(path: str, max_bytes: int = 1 << 16) -> list[dict]:
    """Parse the last `max_bytes` of an events.jsonl (torn first/last lines
    skipped — the file may have died mid-flush with its writer)."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            if size > max_bytes:
                f.seek(size - max_bytes)
                f.readline()  # drop the (likely) partial first line
            raw = f.read()
    except OSError:
        return []
    records = []
    for line in raw.decode("utf-8", errors="replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            records.append(rec)
    return records


def tail_rss_bytes(records: list[dict]) -> float:
    """Last host-RSS sample in a record tail (0.0 when none): the OOM
    forensic — a SIGKILL that follows samples near the host's memory is the
    kernel's OOM killer, not a preemption."""
    for rec in reversed(records):
        if rec.get("kind") in ("step", "pod"):
            rss = rec.get("host_rss_bytes", rec.get("host_rss_bytes_max"))
            if rss is not None:
                try:
                    return float(rss)
                except (TypeError, ValueError):
                    return 0.0
    return 0.0


def read_heartbeat(path: str) -> dict | None:
    """Parse heartbeat.json; None when absent/torn (the write is atomic, so
    torn means no heartbeat was ever completed)."""
    try:
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return payload if isinstance(payload, dict) else None


def beat_marker(hb: dict):
    """Change-detection key for one heartbeat payload (ISSUE 12
    satellite): the writer-side monotonic `seq` when present — a wall
    step can make two distinct beats stamp the same `t` (backwards jump)
    and silently mask progress — else the wall stamp for old payloads.
    Tagged so a `seq` value can never compare equal to a `t` value."""
    seq = hb.get("seq")
    if isinstance(seq, int) and not isinstance(seq, bool):
        return ("seq", seq)
    return ("t", hb.get("t"))


# how far the writer's (wall − mono) clock offset may differ from the
# reader's before the two monotonic clocks are judged incomparable
# (different host, or a wall step since the beat was written)
_SAME_BOOT_SKEW_S = 5.0


def beat_is_fresh(hb: dict, launched_wall: float,
                  launched_mono: float) -> bool:
    """Was this beat written after OUR launch? Prefers the monotonic
    `mono_s` (CLOCK_MONOTONIC — shared by every process on a host, so it
    orders a same-host child's write against the supervisor's launch
    without consulting the steppable wall clock): a backward wall jump
    can no longer unfresh a live child's beats. The mono comparison is
    used only when the beat's own (t − mono_s) offset agrees with this
    process's current offset — same boot, no wall step since the write —
    because CLOCK_MONOTONIC is meaningless across hosts: a wrapper
    child (srun) beating from ANOTHER node over a shared filesystem
    keeps the wall-clock semantics that worked for it before the pair
    existed. Old payloads without the pair fall back to wall `t`."""
    mono = hb.get("mono_s")
    t = hb.get("t")
    wall_ok = isinstance(t, (int, float)) and not isinstance(t, bool)
    if (isinstance(mono, (int, float)) and not isinstance(mono, bool)
            and wall_ok):
        offset_writer = t - mono
        offset_reader = time.time() - time.monotonic()
        if abs(offset_writer - offset_reader) <= _SAME_BOOT_SKEW_S:
            return mono > launched_mono
    return wall_ok and t > launched_wall


def classify_exit(
    returncode: int,
    *,
    hang_killed: bool = False,
    events_tail: list[dict] | None = None,
    oom_rss_bytes: float = 0.0,
) -> tuple[str, str]:
    """(failure class, human-readable detail) for one child death.

    `hang_killed`: the supervisor itself ended this child for heartbeat
    staleness — that classification wins over the exit code, because a
    SIGTERM-responsive child exits EXIT_PREEMPTED on the way down and would
    otherwise masquerade as an ordinary preemption."""
    if hang_killed:
        return CLASS_HANG, (
            f"killed by supervisor for heartbeat staleness (exited "
            f"{returncode})"
        )
    named = {
        EXIT_OK: CLASS_CLEAN,
        EXIT_PREEMPTED: CLASS_PREEMPTED,
        EXIT_ROLLBACK_EXHAUSTED: CLASS_ROLLBACK_EXHAUSTED,
        EXIT_CONFIG_ERROR: CLASS_CONFIG_ERROR,
        EXIT_DATA_QUALITY: CLASS_DATA_QUALITY,
        # relaunching the same argv races the same occupied socket: the
        # orchestrator one level up must reschedule, not retry-loop
        EXIT_SERVE_BIND: CLASS_SERVE_BIND,
        EXIT_FLEET_BIND: CLASS_FLEET_BIND,
        EXIT_STAGING_BIND: CLASS_STAGING_BIND,
        EXIT_RESIZE: CLASS_RESIZE,
        USAGE_ERROR: CLASS_CONFIG_ERROR,
    }
    if returncode in named:
        return named[returncode], (
            f"exit {returncode} ({EXIT_CODE_NAMES.get(returncode, '?')})"
        )
    if returncode < 0:
        sig = -returncode
        try:
            signame = signal.Signals(sig).name
        except ValueError:
            signame = f"signal {sig}"
        if sig in _CRASH_SIGNALS:
            return CLASS_NATIVE_CRASH, (
                f"died on {signame}: native crash (staging loader / XLA "
                "runtime)"
            )
        if sig == int(signal.SIGKILL):
            rss = tail_rss_bytes(events_tail or [])
            if oom_rss_bytes > 0 and rss >= oom_rss_bytes:
                return CLASS_OOM, (
                    f"SIGKILL with tail RSS {rss / 2**30:.2f} GiB >= the "
                    f"{oom_rss_bytes / 2**30:.2f} GiB OOM threshold"
                )
            return CLASS_KILLED, (
                "SIGKILL from outside (hard preemption or OOM killer; tail "
                f"RSS {rss / 2**30:.2f} GiB)"
            )
        return CLASS_KILLED, f"died on external {signame}"
    return CLASS_CRASH, f"unrecognized exit {returncode} (python traceback?)"


# -- resume-integrity preflight ---------------------------------------------


def preflight_resume(ckpt_dir: str, emit=None) -> list[int]:
    """Quarantine every checkpoint step that fails its integrity manifest
    BEFORE relaunching the child, so `--resume auto` never even sees a
    corrupt emergency checkpoint. (The child's own restore walks back past
    corrupt steps too — but a restore crash inside a freshly-launched
    child costs a whole restart out of the budget; here it costs a rename.)

    Newest-first, stopping at the first step that verifies: `--resume
    auto` only ever restores the newest surviving candidate, so hashing
    the older steps too would add minutes of sha256 I/O (multi-GB states ×
    max_to_keep) to every relaunch — including the no-backoff preemption
    relaunches that are supposed to be immediate. A corrupt step BEHIND a
    verifying one is unreachable except through the child's own
    restore-time walk-back, which re-verifies per candidate anyway.

    Steps are moved to `<ckpt_dir>/.quarantine/<step>` (dot-prefixed:
    invisible to Orbax's step listing) with their sidecars; manifest-less
    steps are left alone — pre-manifest checkpoints stay restorable, the
    restore itself is then the gate. Returns the quarantined step numbers."""
    from moco_tpu.resilience.integrity import (
        manifest_path,
        position_path,
        verify_step,
    )

    quarantined: list[int] = []
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return quarantined
    for name in sorted((n for n in names if n.isdigit()), key=int,
                       reverse=True):
        step = int(name)
        reason = verify_step(ckpt_dir, step)
        if reason is None:
            break  # newest surviving candidate: the only one resume reads
        qdir = os.path.join(ckpt_dir, QUARANTINE_DIRNAME)
        os.makedirs(qdir, exist_ok=True)
        target = os.path.join(qdir, name)
        if os.path.exists(target):  # quarantined twice across restarts
            target = os.path.join(qdir, f"{name}.{int(time.time())}")
        os.rename(os.path.join(ckpt_dir, name), target)
        for sidecar in (
            manifest_path(ckpt_dir, step),
            position_path(ckpt_dir, step),
        ):
            try:
                os.remove(sidecar)
            except OSError:
                pass  # sidecar absent (pre-position checkpoint) — fine
        quarantined.append(step)
        if emit is not None:
            emit("preflight_quarantine", step=step, reason=reason,
                 moved_to=target)
        log_event(
            "supervisor",
            f"preflight: quarantined corrupt checkpoint step {step} "
            f"({reason}) -> {target}",
        )
    return quarantined


# -- policy ------------------------------------------------------------------


@dataclasses.dataclass
class RestartPolicy:
    """Per-class restart policy knobs (tools/supervise.py exposes each)."""

    max_restarts: int = 5             # consecutive no-progress restarts
                                      # before giving up; any step progress
                                      # refunds the full budget
    backoff_base_secs: float = 1.0    # exponential backoff base ...
    backoff_max_secs: float = 60.0    # ... capped here ...
    backoff_jitter: float = 0.2       # ... times (1 + U[0, jitter]) so a
                                      # pod of supervisors doesn't relaunch
                                      # in lockstep
    heartbeat_stale_secs: float = 120.0  # kill the child when its newest
                                      # step-phase beat is older than this.
                                      # <= 0 disables hang detection
                                      # entirely (exit classification and
                                      # restarts still run) — REQUIRED for
                                      # supervisors of non-main pod hosts,
                                      # which never write a heartbeat
                                      # (telemetry is process-0-only) and
                                      # would otherwise be killed as
                                      # "hung" on a cycle
    startup_grace_secs: float = 900.0  # staleness allowance before the
                                      # first step-phase beat of each
                                      # launch (cold XLA compile + restore
                                      # legitimately produce no steps)
    term_grace_secs: float = 30.0     # SIGTERM -> this grace -> SIGKILL
    poll_secs: float = 2.0            # supervisor wake-up cadence
    oom_rss_bytes: float = 0.0        # classify SIGKILL as OOM when the
                                      # events tail shows RSS >= this (0 =
                                      # never; there is no portable way to
                                      # read the cgroup limit from here)
    restart_on: frozenset = RESTARTABLE_CLASSES
    no_backoff: frozenset = frozenset({CLASS_PREEMPTED, CLASS_RESIZE})
                                      # a preempted VM that came back is
                                      # healthy — relaunch immediately; a
                                      # resize exit is VOLUNTARY (the child
                                      # checkpointed on request) — backoff
                                      # would just stretch the capacity gap

    def backoff_secs(self, consecutive_failures: int, rng: random.Random) -> float:
        """Exponential in the number of consecutive no-progress failures,
        capped, with multiplicative jitter."""
        base = min(
            self.backoff_base_secs * (2.0 ** max(consecutive_failures - 1, 0)),
            self.backoff_max_secs,
        )
        return base * (1.0 + self.backoff_jitter * rng.random())


@dataclasses.dataclass
class SupervisorResult:
    final_class: str
    exit_code: int | None
    launches: int               # total child launches (restarts + 1)
    restarts: int
    gave_up: bool               # budget exhausted with the run unfinished
    classifications: list[str]  # one per child death, in order


class Supervisor:
    """Run `child_argv` under supervision until it finishes or the policy
    gives up. `telemetry_dir` must match the child's `--telemetry-dir`
    (heartbeat + events live there); `ckpt_dir` (the child's `--ckpt-dir`)
    enables the resume preflight and the checkpoint-step progress fallback.

    On every launch (the first included — a restarted supervisor over an
    existing ckpt_dir must continue the run, not retrain from step 0
    underneath it) `--resume auto` is appended to the child argv unless
    the caller already passed a `--resume` (`force_resume=False` disables
    this) — a supervisor that restarts from scratch would be a very slow
    crash loop."""

    def __init__(
        self,
        child_argv: list[str],
        *,
        telemetry_dir: str,
        ckpt_dir: str = "",
        policy: RestartPolicy | None = None,
        env: dict | None = None,
        force_resume: bool = True,
        child_log_path: str = "",
        seed: int | None = None,
        time_fn=time.monotonic,
        resize_device_flag: str = "",
        resize_slow_cadence: int = 0,
        resize_rotate_cache: bool = True,
    ):
        self.child_argv = list(child_argv)
        self.telemetry_dir = telemetry_dir
        self.ckpt_dir = ckpt_dir
        self.policy = policy or RestartPolicy()
        self.env = env
        self.force_resume = force_resume
        self.child_log_path = child_log_path or os.path.join(
            telemetry_dir, "child.log"
        )
        self.events_path = os.path.join(telemetry_dir, EVENTS_FILENAME)
        self.heartbeat_path = os.path.join(telemetry_dir, HEARTBEAT_FILENAME)
        self.incidents: list[dict] = []  # in-memory mirror of emitted records
        # seed=None (the CLI default) draws system entropy: a fleet of
        # supervisors hit by one pod-wide fault must NOT share a jitter
        # stream, or they relaunch in lockstep — the stampede the jitter
        # exists to prevent. Tests pass an explicit seed for determinism.
        self._rng = random.Random(seed)
        self._now = time_fn
        # trace root (ISSUE 8): one run_id for the whole supervised run
        # (inherited from MOCO_TPU_RUN_ID when an orchestrator set one);
        # every child launch gets the ids via env, every supervisor
        # incident record carries them, and the supervisor's own spans
        # (one per child lifetime) land in the shared spans.jsonl.
        # Supervisor spans always record: a handful per launch is free,
        # and a timeline with the children but not their supervisor would
        # bury exactly the restart/kill context it exists to show.
        self.tracer = Tracer(telemetry_dir, "steps", proc="supervisor")
        self.run_id = self.tracer.run_id
        self._child_capturing = False
        self._budget = self.policy.max_restarts
        self._consecutive_failures = 0
        self._ever_beat = False  # any beat in any launch: distinguishes a
                                 # wedged child from a missing heartbeat
                                 # channel (telemetry off / wrong dir)
        # elastic resize (ISSUE 11): trigger-file / SIGUSR2 requests and
        # the relaunch-argv rewrite. tools/supervise.py routes the
        # supervisor's own SIGUSR2 to resize.signal_resize.
        self.resize = ResizeController(
            telemetry_dir, device_flag=resize_device_flag,
            slow_cadence=resize_slow_cadence,
            rotate_cache=resize_rotate_cache,
        )
        # per-launch extra env (the resize rewrite's fresh compile-cache
        # dir lands here; _launch overlays it on the base env)
        self._launch_env: dict = {}
        self._last_mesh_change: tuple | None = None
        self._resize_signaled = False
        self._resize_request_emitted = False
        # argv length snapshot taken just before a resize rewrite: if the
        # VERY NEXT launch dies config_error (an unsatisfiable device
        # count), the appended flags are reverted instead of ending the
        # run — a typo'd resize request must not take a healthy run down
        self._resize_fallback: int | None = None

    # -- structured incidents (same stream the child writes) ----------------
    def _emit(self, event: str, **fields) -> None:
        record = {"v": 1, "t": round(time.time(), 3), "kind": "supervisor",
                  "event": event, "run_id": self.run_id,
                  "trace_id": self.tracer.trace_id}
        record.update(fields)
        self.incidents.append(record)
        os.makedirs(self.telemetry_dir, exist_ok=True)
        # O_APPEND one-line writes: safe to interleave with the child's own
        # appends (the child is usually dead when the supervisor writes; a
        # concurrent kill record lands on its own line either way)
        with open(self.events_path, "a", encoding="utf-8") as f:
            f.write(json.dumps(record) + "\n")
            f.flush()
            os.fsync(f.fileno())
        detail = " ".join(f"{k}={v}" for k, v in fields.items())
        log_event("supervisor", f"{event} {detail}".strip())

    # -- progress (heartbeat + checkpoint sidecar fallback) -----------------
    def _progress_marker(self) -> int:
        """Newest known completed step: the heartbeat's (whoever wrote it —
        across a death it is the dead child's last word), else the newest
        on-disk checkpoint step. -1 when nothing has ever progressed."""
        marker = -1
        hb = read_heartbeat(self.heartbeat_path)
        if hb is not None:
            try:
                marker = max(marker, int(hb.get("step", -1)))
            except (TypeError, ValueError):
                pass  # foreign heartbeat shape: fall through to checkpoints
        if self.ckpt_dir:
            try:
                for name in os.listdir(self.ckpt_dir):
                    if name.isdigit():
                        marker = max(marker, int(name))
            except OSError:
                pass  # no checkpoint dir yet
        return marker

    # -- budget (crash-loop detection) --------------------------------------
    def _note_exit(self, progressed: bool) -> bool:
        """Update the restart budget after a restartable death; True when a
        restart is still allowed. Progress refunds the FULL budget and its
        restart is free: only consecutive no-progress deaths count toward
        the crash-loop limit, so a multi-day run that keeps advancing
        restarts indefinitely."""
        if progressed:
            self._budget = self.policy.max_restarts
            self._consecutive_failures = 0
            return self._budget > 0
        self._consecutive_failures += 1
        if self._budget <= 0:
            return False
        self._budget -= 1
        return True

    # -- child lifecycle -----------------------------------------------------
    def _launch(self, attempt: int) -> subprocess.Popen:
        argv = list(self.child_argv)
        has_resume = any(
            a == "--resume" or a.startswith("--resume=")
            for a in self.child_argv
        )
        if self.force_resume and not has_resume:
            # EVERY launch, attempt 0 included: a restarted SUPERVISOR
            # (host reboot, cron) over an existing ckpt_dir must continue
            # the run, not retrain from step 0 underneath it — and on an
            # empty directory `--resume auto` restores nothing, so this is
            # strictly safe
            argv += ["--resume", "auto"]
        # the supervisor usually starts BEFORE the child ever creates the
        # telemetry dir — the log (and the first incident record) must not
        # depend on the child having run
        os.makedirs(os.path.dirname(self.child_log_path) or ".", exist_ok=True)
        # trace propagation (ISSUE 8): the child's tracer adopts this
        # run_id and parents its root spans under the CURRENT supervisor
        # span (the per-launch `child` span run() holds open) — one
        # trace_id from supervisor through driver to staging worker
        env = dict(os.environ if self.env is None else self.env)
        env.update(self._launch_env)  # resize: fresh per-resize cache dir
        env.update(self.tracer.child_env())
        log_file = open(self.child_log_path, "ab")
        try:
            child = subprocess.Popen(
                argv, stdout=log_file, stderr=subprocess.STDOUT, env=env
            )
        finally:
            # the child holds its own descriptor; keeping ours open would
            # leak one fd per restart for the supervisor's lifetime
            log_file.close()
        self._emit("launch", attempt=attempt, pid=child.pid,
                   budget_left=self._budget, argv=argv)
        return child

    def _kill_for_hang(self, child: subprocess.Popen, stale_for: float) -> None:
        self.tracer.instant("hang_kill", cat="supervisor", pid=child.pid,
                            stale_secs=round(stale_for, 3))
        self._emit("kill", pid=child.pid, reason="heartbeat_stale",
                   stale_secs=round(stale_for, 3), phase="sigterm")
        child.send_signal(signal.SIGTERM)
        deadline = self._now() + self.policy.term_grace_secs
        while child.poll() is None and self._now() < deadline:
            time.sleep(min(self.policy.poll_secs, 0.2))
        if child.poll() is None:
            self._emit("kill", pid=child.pid, reason="heartbeat_stale",
                       phase="sigkill")
            child.kill()
            child.wait()

    def _monitor(self, child: subprocess.Popen) -> bool:
        """Block until the child exits; True when the supervisor killed it
        for heartbeat staleness. The tight staleness window only applies
        while the newest beat from THIS child has phase "step" — during
        startup (jax import, XLA compile, restore) and every other
        declared phase (an "eval" beat before a multi-minute kNN eval, the
        "run_end"/"preempt_exit" beat before finalize/export) silence is
        normal and only the generous startup grace applies. A supervisor
        that NEVER sees a beat in any launch (telemetry off, mismatched
        --telemetry-dir) disables hang detection with a loud incident
        instead of kill-looping a healthy child forever."""
        launched = self._now()
        launched_wall = time.time()
        launched_mono = time.monotonic()  # freshness basis for mono_s
                                          # beats (wall-jump-immune)
        beat_phase = None     # phase of the newest beat from this child
        last_beat = launched  # supervisor-clock time of the newest beat
        last_marker = None    # the beat's own change marker (seq, else t)
        warned_pid = False
        hang_detection = self.policy.heartbeat_stale_secs > 0
        self._resize_signaled = False  # a still-armed request re-signals
                                       # THIS launch once it starts stepping
        while child.poll() is None:
            time.sleep(self.policy.poll_secs)
            self._poll_resize(child, beat_phase, hang_detection,
                              self._now() - launched)
            if not hang_detection:
                continue  # non-main pod hosts: no heartbeat ever exists
            hb = read_heartbeat(self.heartbeat_path)
            if hb is not None:
                # a beat counts when its pid is our direct child, OR when
                # it is fresher than this launch — the trainer may be a
                # grandchild behind a wrapper (srun, bash -c, docker run),
                # whose pid never equals Popen's. The freshness bound
                # keeps a STALE file from the previous incarnation from
                # arming the tight window during this child's compile —
                # judged on the heartbeat's monotonic mono_s when present
                # (seq/mono_s pair: a wall-clock step must read as
                # neither hang nor freshness), wall t for old payloads.
                mine = hb.get("pid") == child.pid
                fresh = beat_is_fresh(hb, launched_wall, launched_mono)
                if (mine or fresh) and beat_marker(hb) != last_marker:
                    last_marker = beat_marker(hb)
                    last_beat = self._now()
                    beat_phase = hb.get("phase")
                    self._ever_beat = True
                    if fresh and not mine and not warned_pid:
                        warned_pid = True
                        self._emit(
                            "heartbeat_pid_mismatch", child_pid=child.pid,
                            beat_pid=hb.get("pid"),
                            note="wrapper command? beats accepted by "
                                 "freshness; progress checks unaffected",
                        )
                if mine or fresh:
                    # same staleness guard as the beat bookkeeping: a
                    # stale file from the PREVIOUS incarnation (which may
                    # have died mid-capture) must not fabricate
                    # "currently profiling" transitions for this child
                    self._note_trace_state(hb)
            window = (self.policy.heartbeat_stale_secs
                      if beat_phase == "step"
                      else self.policy.startup_grace_secs)
            stale_for = self._now() - last_beat
            if stale_for > window:
                if last_marker is None and not self._ever_beat:
                    # no beat EVER, in this or any previous launch: the
                    # heartbeat channel itself is missing (telemetry off,
                    # mismatched --telemetry-dir) — killing a child that
                    # never promised beats would loop forever, each kill
                    # refunded by checkpoint progress
                    self._emit(
                        "no_heartbeat", child_pid=child.pid,
                        heartbeat_path=self.heartbeat_path,
                        note="no heartbeat observed in any launch — hang "
                             "detection DISABLED; is --telemetry-dir the "
                             "child's telemetry dir, and telemetry on?",
                    )
                    hang_detection = False
                    continue
                self._kill_for_hang(child, stale_for)
                return True
        if hang_detection:
            # one post-exit read: a short capture window (or a child that
            # DIED while capturing — the interesting case) must not slip
            # between two polls unseen. Same mine-or-fresh guard: a child
            # that never beat leaves the previous incarnation's file.
            hb = read_heartbeat(self.heartbeat_path)
            if hb is not None and (
                    hb.get("pid") == child.pid
                    or beat_is_fresh(hb, launched_wall, launched_mono)):
                self._note_trace_state(hb)
        return False

    def _poll_resize(self, child: subprocess.Popen,
                     beat_phase: str | None,
                     hang_detection: bool,
                     child_age: float) -> None:
        """Arm a pending resize request (trigger file / SIGUSR2-to-the-
        supervisor) and signal the child to take its elastic checkpoint.

        The signal is HELD until the newest beat says the child is in its
        step loop: before that (jax import, compile, restore) the driver
        has not installed its SIGUSR2 listener yet, and the default
        disposition would TERMINATE the child mid-boot. With hang
        detection off there is no phase to wait for — signal immediately,
        best effort. `hang_detection` is the monitor's LIVE state, not
        the policy knob: a run whose heartbeat channel turned out missing
        (the `no_heartbeat` incident) will never produce a "step" beat,
        and holding the signal there would strand an armed request — its
        trigger file already consumed — forever. SIGUSR2 goes to the Popen pid; a
        wrapper command (srun, docker) that doesn't forward it still
        converges — the child's own listener polls the same trigger file,
        and the file claim is atomic (exactly one side wins; both roads
        end at an EXIT_RESIZE)."""
        req = self.resize.poll()
        if req is not None:
            self._resize_signaled = False
            self._resize_request_emitted = True
            self.tracer.instant("resize_request", cat="supervisor",
                                source=req.source, devices=req.devices)
            self._emit(
                "resize_request", pid=child.pid, source=req.source,
                devices=req.devices, grad_sync_cadence=req.grad_sync_cadence,
                slow=req.slow,
            )
        if self.resize.armed is None or self._resize_signaled:
            return
        # no-heartbeat children still get the startup grace before the
        # signal: an immediate SIGUSR2 would land during jax import on
        # EVERY relaunch (no handler yet → terminated mid-boot) and one
        # armed request would kill-loop the run to budget exhaustion
        ready_blind = (not hang_detection
                       and child_age > self.policy.startup_grace_secs)
        if beat_phase == "step" or ready_blind:
            self._resize_signaled = True
            try:
                child.send_signal(signal.SIGUSR2)
            except OSError:
                pass  # child died between poll() and the signal: the exit
                      # classification (and resize.take) handle the rest

    def _note_trace_state(self, hb: dict) -> None:
        """"Currently profiling" surfacing (ISSUE 8 satellite): the beat
        carries the child's capture state, so the operator watching
        supervisor output learns a capture started/ended without reading
        events.jsonl. Emits one `child_trace` record per transition."""
        trace_state = hb.get("trace")
        if not isinstance(trace_state, dict):
            return
        capturing = bool(trace_state.get("capturing"))
        if capturing == self._child_capturing:
            return
        self._child_capturing = capturing
        self._emit(
            "child_trace",
            capturing=capturing,
            step=hb.get("step"),
            captures_used=trace_state.get("captures_used"),
            capture_budget=trace_state.get("capture_budget"),
        )

    # -- elastic resize (ISSUE 11) ------------------------------------------
    def _apply_resize(self, child_span, step: int) -> None:
        """Consume the honored resize request and rewrite the relaunch:
        device-count append (argparse last-wins), optional grad-sync
        cadence override for slow-linked meshes, fresh per-resize compile
        cache dir. Emits the `resize_relaunch` incident and records the
        whole request→relaunch interval as a `resize` span parented under
        the exiting launch's `child` span (retroactive: the interval is
        only known now — the Tracer's record_span API exists for exactly
        this shape)."""
        t_armed = self.resize.armed_at_wall or time.time()
        req = self.resize.take()
        if not self._resize_request_emitted:
            # the child honored the request before the supervisor's poll
            # ever armed it (the chaos drill, or the child's own file
            # claim): the request must still appear in the stream — a
            # report showing relaunches "from 0 requests" reads as
            # resizes nobody asked for
            self._emit(
                "resize_request", source=req.source, devices=req.devices,
                grad_sync_cadence=req.grad_sync_cadence, slow=req.slow,
            )
        self._resize_request_emitted = False
        # the rewrite sees the EFFECTIVE child env (base + overlay): a
        # MOCO_TPU_NO_CACHE in the base env must suppress the cache
        # rotation, not be shadowed by the empty overlay
        env = dict(os.environ if self.env is None else self.env)
        env.update(self._launch_env)
        self._resize_fallback = len(self.child_argv)
        summary = self.resize.apply(req, self.child_argv, env)
        if "cache_dir" in summary:
            self._launch_env["MOCO_TPU_CACHE_DIR"] = summary["cache_dir"]
        summary["step"] = step
        self.tracer.record_span(
            "resize", t_armed, max(time.time() - t_armed, 0.0),
            cat="supervisor", parent=child_span.context(), **summary,
        )
        self._emit("resize_relaunch", **summary)

    def _check_mesh_change(self) -> None:
        """`mesh_change` incident when the device count this launch's argv
        pins differs from the mesh the newest checkpoint records (the
        position sidecar's `devices` stamp) — the relaunch-preflight
        membership check. Silent when either side is unknown (no sidecar
        yet / argv leaves the mesh to the hardware): never guessed."""
        if not self.ckpt_dir:
            return
        recorded = read_recorded_devices(self.ckpt_dir)
        declared = argv_device_count(self.child_argv)
        if recorded is None or declared is None:
            return
        step, old = recorded
        if old == declared:
            self._last_mesh_change = None
            return
        key = (step, old, declared)
        if key == self._last_mesh_change:
            return  # this exact mismatch was already reported
        self._last_mesh_change = key
        self.tracer.instant("mesh_change", cat="supervisor",
                            devices_from=old, devices_to=declared)
        self._emit(
            "mesh_change", ckpt_step=step, devices_from=old,
            devices_to=declared,
            note="relaunch mesh differs from the newest checkpoint's "
                 "recorded mesh; the dialect shim restores with fresh-zero "
                 "gradsync accumulators",
        )

    # -- main loop -----------------------------------------------------------
    def run(self) -> SupervisorResult:
        try:
            return self._run()
        finally:
            self.tracer.close()  # land any buffered supervisor spans

    def _run(self) -> SupervisorResult:
        attempt = 0
        classifications: list[str] = []
        marker_before = self._progress_marker()
        while True:
            if self.ckpt_dir and attempt > 0:
                preflight_resume(self.ckpt_dir, emit=self._emit)
            # membership check (ISSUE 11 satellite): the mesh this launch
            # will build differs from the one the newest checkpoint was
            # saved under — say so HERE, not first inside the restore shim
            self._check_mesh_change()
            # one span per child LIFETIME (launch → death): the child's own
            # root spans parent under it via the env stamped in _launch,
            # so the merged timeline nests each incarnation's work beneath
            # the supervisor's view of it
            with self.tracer.span("child", cat="supervisor",
                                  attempt=attempt) as child_span:
                child = self._launch(attempt)
                self._child_capturing = False
                hang_killed = self._monitor(child)
                rc = child.returncode
                cls, detail = classify_exit(
                    rc,
                    hang_killed=hang_killed,
                    events_tail=read_events_tail(self.events_path),
                    oom_rss_bytes=self.policy.oom_rss_bytes,
                )
                child_span.set(pid=child.pid, returncode=rc,
                               classification=cls)
            marker_now = self._progress_marker()
            progressed = marker_now > marker_before
            marker_before = max(marker_before, marker_now)
            classifications.append(cls)
            self._emit("exit", pid=child.pid, returncode=rc,
                       classification=cls, detail=detail,
                       progressed=progressed, last_step=marker_now)
            # one-shot resize fallback: this exit is the FIRST after a
            # resize rewrite (the snapshot is cleared here regardless of
            # class). A config_error death on that launch means the
            # rewritten argv can never boot (typo'd device count > the
            # hardware): revert the appended flags and keep the run alive
            # on the old mesh instead of ending it for a bad request.
            reverted = False
            if self._resize_fallback is not None:
                snapshot, self._resize_fallback = self._resize_fallback, None
                if cls == CLASS_CONFIG_ERROR:
                    dropped = self.child_argv[snapshot:]
                    del self.child_argv[snapshot:]
                    reverted = True
                    self._emit(
                        "resize_revert", dropped=dropped, returncode=rc,
                        note="resized argv failed config validation — "
                             "relaunching on the previous mesh",
                    )
            if cls == CLASS_CLEAN:
                self._emit("done", launches=attempt + 1, restarts=attempt)
                return SupervisorResult(cls, rc, attempt + 1, attempt,
                                        False, classifications)
            if not reverted and cls not in self.policy.restart_on:
                self._emit("give_up", reason=f"fatal class {cls}",
                           returncode=rc, restarts=attempt)
                return SupervisorResult(cls, rc, attempt + 1, attempt,
                                        False, classifications)
            if not self._note_exit(progressed):
                self._emit(
                    "give_up",
                    reason=(
                        f"restart budget exhausted: "
                        f"{self._consecutive_failures} consecutive "
                        f"no-progress deaths (max_restarts="
                        f"{self.policy.max_restarts})"
                    ),
                    returncode=rc, restarts=attempt,
                )
                return SupervisorResult(cls, rc, attempt + 1, attempt,
                                        True, classifications)
            if cls == CLASS_RESIZE:
                # the child honored the resize: rewrite the relaunch argv
                # (device count, cadence override, fresh compile cache)
                # before the next launch — the whole incident lands as one
                # `resize` span under this launch's child span
                self._apply_resize(child_span, step=marker_now)
            if cls not in self.policy.no_backoff:
                delay = self.policy.backoff_secs(
                    self._consecutive_failures, self._rng
                )
                self._emit("backoff", secs=round(delay, 3),
                           consecutive_failures=self._consecutive_failures,
                           budget_left=self._budget)
                time.sleep(delay)
            attempt += 1
            self._emit("restart", attempt=attempt, after=cls,
                       budget_left=self._budget)
