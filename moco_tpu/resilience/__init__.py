"""Fault tolerance for long-horizon pretraining (ISSUE 1 tentpole).

The north-star run is a multi-day MoCo pretrain on PREEMPTIBLE TPU VMs;
this package makes that survivable without a babysitter:

- `preemption.PreemptionHandler` — SIGTERM/SIGINT caught, the in-flight
  step finishes, an emergency step-tagged checkpoint lands, the process
  exits cleanly (the driver's mid-epoch `resume_skip` path makes the
  resumed run bit-identical to the uninterrupted one).
- `integrity.write_manifest`/`verify_step` — per-save digest sidecars so
  `--resume auto` walks BACK to the newest verifiable step instead of
  crashing on a truncated/partial latest checkpoint.
- `sentinel.NaNSentinel` — every-step non-finite-loss detection (one-step
  lag, so the device pipeline never bubbles); the driver answers with a
  bounded rollback: restore the last good checkpoint, advance the data
  permutation past the poisoned window, abort only after
  `max_rollbacks` consecutive rollbacks.
- `watchdog.StepWatchdog` — flags step-time hangs from a background
  thread (a stuck collective on a pod otherwise looks like silence).
- `supervisor.Supervisor` (ISSUE 4) — the OUT-OF-PROCESS layer for the
  faults none of the above can observe: SIGKILL-grade preemption, native
  crashes, OOM kills, and wedged collectives. Runs the driver as a child,
  kills it on heartbeat staleness, classifies every death via the
  `exitcodes` protocol + forensics, and restarts within a
  progress-refunded budget. CLI: tools/supervise.py.
- `resize.ResizeListener`/`ResizeController` (ISSUE 11) — elastic
  training: a resize.request trigger file or SIGUSR2 makes the driver
  take a clean elastic checkpoint and exit `EXIT_RESIZE`; the supervisor
  rewrites the relaunch argv (device count, grad-sync cadence, fresh
  compile cache) and `--resume auto` + the checkpoint dialect shim land
  the state on the new mesh.
- `chaos.ChaosPlan` — the deterministic fault-injection harness that
  makes all of the above TESTABLE on CPU: SIGTERM-at-step-k,
  kill/freeze-at-step-k (process death / wedged-collective simulation),
  resize-at-step-k, NaN-at-step-k, loader faults, checkpoint truncation.

Errors are typed (`errors.py`) so callers can route retryable faults
(`TransientDataError`) differently from run-enders
(`RollbackExhaustedError`, `DataQualityError`).
"""

from moco_tpu.resilience.chaos import (
    ChaosPlan,
    active_chaos,
    chaos_context,
    clear_chaos,
    install_chaos,
    parse_chaos_spec,
    truncate_checkpoint,
)
from moco_tpu.resilience.errors import (
    CollapseError,
    DataQualityError,
    NonFiniteLossError,
    RollbackExhaustedError,
    TransientDataError,
)
from moco_tpu.resilience.exitcodes import (
    EXIT_CODE_NAMES,
    EXIT_CONFIG_ERROR,
    EXIT_DATA_QUALITY,
    EXIT_OK,
    EXIT_PREEMPTED,
    EXIT_RESIZE,
    EXIT_ROLLBACK_EXHAUSTED,
)
from moco_tpu.resilience.integrity import (
    manifest_path,
    verify_step,
    write_manifest,
)
from moco_tpu.resilience.preemption import PreemptionHandler
from moco_tpu.resilience.resize import (
    ResizeController,
    ResizeListener,
    ResizeRequest,
    consume_resize_request,
    parse_resize_request,
    read_recorded_devices,
    write_resize_request,
)
from moco_tpu.resilience.sentinel import CollapseSentinel, NaNSentinel
from moco_tpu.resilience.supervisor import (
    RestartPolicy,
    Supervisor,
    SupervisorResult,
    classify_exit,
    preflight_resume,
)
from moco_tpu.resilience.watchdog import StepWatchdog

__all__ = [
    "ChaosPlan",
    "CollapseError",
    "CollapseSentinel",
    "DataQualityError",
    "EXIT_CODE_NAMES",
    "EXIT_CONFIG_ERROR",
    "EXIT_DATA_QUALITY",
    "EXIT_OK",
    "EXIT_PREEMPTED",
    "EXIT_RESIZE",
    "EXIT_ROLLBACK_EXHAUSTED",
    "NaNSentinel",
    "NonFiniteLossError",
    "PreemptionHandler",
    "ResizeController",
    "ResizeListener",
    "ResizeRequest",
    "RestartPolicy",
    "RollbackExhaustedError",
    "StepWatchdog",
    "Supervisor",
    "SupervisorResult",
    "TransientDataError",
    "active_chaos",
    "classify_exit",
    "preflight_resume",
    "chaos_context",
    "clear_chaos",
    "consume_resize_request",
    "install_chaos",
    "manifest_path",
    "parse_chaos_spec",
    "parse_resize_request",
    "read_recorded_devices",
    "truncate_checkpoint",
    "verify_step",
    "write_manifest",
    "write_resize_request",
]
