"""Checkpoint integrity sidecars (tentpole part 2).

A preempted or out-of-quota writer leaves PARTIAL checkpoint steps on
disk; Orbax's `latest_step()` happily points at them and the restore
crashes — which used to brick `--resume auto` entirely. After every
finalized save we record a manifest (relative path, size, sha256 per
file) in `<ckpt_dir>/.integrity/<step>.json`; `--resume auto` then walks
back from the newest step to the newest step that VERIFIES (see
`checkpoint.restore_with_fallback`).

The manifest directory name starts with a dot so Orbax never mistakes it
for a step; manifests are written atomically (tmp + rename) so the
sidecar itself cannot be left half-written by the same fault class it
guards against.
"""

from __future__ import annotations

import hashlib
import json
import os

from moco_tpu.utils.logging import log_event

INTEGRITY_DIRNAME = ".integrity"
POSITION_DIRNAME = ".position"
_CHUNK = 1 << 20


def position_path(ckpt_dir: str, step: int) -> str:
    """Path of a step's data-stream position sidecar. Lives here (stdlib-
    only) rather than checkpoint.py because the jax-free supervisor needs
    the same layout knowledge for its quarantine preflight — one source of
    truth for the sidecar scheme."""
    return os.path.join(ckpt_dir, POSITION_DIRNAME, f"{step}.json")


def _digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(_CHUNK)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def digest_file(path: str) -> str:
    """Public chunked sha256 of one file — the same digest the manifests
    record, exported so bank manifests (serve/bankbuild.py) can bind a
    bank to its checkpoint with the identical hash scheme."""
    return _digest(path)


def manifest_path(ckpt_dir: str, step: int) -> str:
    return os.path.join(
        os.path.abspath(ckpt_dir), INTEGRITY_DIRNAME, f"{step}.json"
    )


def _walk_step_files(step_dir: str) -> list[str]:
    out = []
    for dirpath, _dirnames, filenames in os.walk(step_dir):
        for fname in filenames:
            out.append(
                os.path.relpath(os.path.join(dirpath, fname), step_dir)
            )
    return sorted(out)


def write_manifest(ckpt_dir: str, step: int) -> dict:
    """Record the finalized step's file inventory + digests. Must run AFTER
    the save is finished (`mgr.wait_until_finished()`) — a manifest of an
    in-flight save would certify garbage."""
    step_dir = os.path.join(os.path.abspath(ckpt_dir), str(step))
    files = {}
    for rel in _walk_step_files(step_dir):
        full = os.path.join(step_dir, rel)
        files[rel] = {"size": os.path.getsize(full), "sha256": _digest(full)}
    manifest = {"step": int(step), "files": files}
    path = manifest_path(ckpt_dir, step)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, path)
    return manifest


def verify_step(ckpt_dir: str, step: int) -> str | None:
    """None when the step's files match its manifest (or when no manifest
    exists — pre-manifest checkpoints stay restorable, the restore itself is
    then the only gate). A human-readable mismatch reason otherwise."""
    path = manifest_path(ckpt_dir, step)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        return f"unreadable manifest {path}: {e}"
    step_dir = os.path.join(os.path.abspath(ckpt_dir), str(step))
    expected = manifest.get("files", {})
    for rel, meta in expected.items():
        full = os.path.join(step_dir, rel)
        if not os.path.exists(full):
            return f"missing file {rel}"
        size = os.path.getsize(full)
        if size != meta["size"]:
            return f"size mismatch on {rel}: {size} != {meta['size']}"
        if _digest(full) != meta["sha256"]:
            return f"digest mismatch on {rel}"
    actual = set(_walk_step_files(step_dir))
    extra = actual - set(expected)
    if extra:
        # extra files are tolerated (a newer orbax may add bookkeeping), but
        # note them — they can explain a later restore surprise
        log_event(
            "ckpt-verify",
            f"step {step}: {len(extra)} file(s) not in manifest: "
            f"{sorted(extra)[:4]}",
        )
    return None
