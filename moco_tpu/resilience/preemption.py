"""Preemption-safe shutdown (tentpole part 1).

Cloud TPU VMs get a SIGTERM with a short grace window before the plug is
pulled. The reference (`main_moco.py`) only checkpoints at epoch
boundaries, so a preemption loses up to a full epoch. Here the handler
turns the signal into a FLAG; the driver finishes the in-flight step,
writes a step-tagged emergency checkpoint, and returns cleanly — the
mid-epoch `resume_skip` path in train.py then makes the resumed run
bit-identical to the uninterrupted one (tests/test_resilience.py pins
this end to end).
"""

from __future__ import annotations

import signal
import threading

from moco_tpu.utils.logging import log_event


class PreemptionHandler:
    """Context manager that converts SIGTERM/SIGINT into a poll-able flag.

    First signal: set the flag and keep running (the driver checkpoints and
    exits at the next step boundary). Second signal: chain to the original
    disposition — the operator hammering Ctrl-C twice gets the immediate
    exit they are asking for instead of a silent wait.

    Signal handlers can only be installed from the main thread; entered from
    any other thread (pytest workers, nested drivers) the handler is inert
    and `triggered` just stays False — callers need no special-casing.
    """

    def __init__(self, signums: tuple[int, ...] = (signal.SIGTERM, signal.SIGINT)):
        self._signums = signums
        self._flag = threading.Event()
        self._prev: dict[int, object] = {}
        self._installed = False

    def _handle(self, signum, frame):
        if self._flag.is_set():
            log_event("preempt", f"second signal {signum}: chaining to the "
                                 "original handler (immediate exit)")
            prev = self._prev.get(signum, signal.SIG_DFL)
            if callable(prev):
                prev(signum, frame)
            else:
                signal.signal(signum, prev)
                signal.raise_signal(signum)
            return
        self._flag.set()
        log_event(
            "preempt",
            f"caught signal {signum}; finishing the in-flight step, then "
            "writing an emergency checkpoint and exiting cleanly",
        )

    def __enter__(self) -> "PreemptionHandler":
        if threading.current_thread() is threading.main_thread():
            for s in self._signums:
                self._prev[s] = signal.signal(s, self._handle)
            self._installed = True
        return self

    @property
    def triggered(self) -> bool:
        return self._flag.is_set()

    def __exit__(self, *exc) -> bool:
        if self._installed:
            for s, prev in self._prev.items():
                signal.signal(s, prev)
            self._installed = False
        return False
