"""Structured driver exit codes (ISSUE 4 tentpole part 2).

The supervisor restarts a dead child based on WHY it died, and the exit
code is the only channel that survives every death mode short of SIGKILL.
The drivers therefore exit through these named constants — never bare
`sys.exit(<int>)`, which tools/lint_robustness.py rule R5 forbids inside
the package — so `supervisor.classify_exit` can route each class to its
restart policy without scraping logs.

The codes start at 43 to stay clear of the shells' own vocabulary
(0 success, 1 generic python traceback, 2 argparse usage error,
126/127 exec failures, 128+N signal deaths); a supervisor seeing an
unknown positive code treats it as a generic crash.
"""

from __future__ import annotations

EXIT_OK = 0                    # train loop ran to its configured end
EXIT_PREEMPTED = 43            # SIGTERM/SIGINT honored: emergency checkpoint
                               # written, clean exit — relaunch resumes it
EXIT_ROLLBACK_EXHAUSTED = 44   # RollbackExhaustedError: structural divergence,
                               # restarting would loop — a human has to look
EXIT_CONFIG_ERROR = 45         # bad preset/flag/config validation: restarting
                               # the same argv can never succeed
EXIT_DATA_QUALITY = 46         # DataQualityError: the dataset itself is bad
                               # (decode-abort threshold); restart won't fix it
EXIT_SERVE_BIND = 47           # tools/serve.py could not bind its host:port
                               # (address in use / privileged port): restarting
                               # the same argv races the same socket — an
                               # orchestrator should reschedule, not retry-loop
EXIT_FLEET_BIND = 48           # tools/serve_fleet.py could not bind the
                               # FRONT-END router port (the replica ports are
                               # the replicas' own 47s): same fatal semantics
                               # — rescheduling beats racing the socket
EXIT_STAGING_BIND = 50         # tools/staging_server.py (or its decode
                               # worker) could not bind its health/data
                               # port: same fatal reschedule-don't-retry
                               # semantics as the serve binds 47/48 — the
                               # staging supervisor classifies a worker's 50
                               # as fatal instead of burning its restart
                               # budget racing the same socket
EXIT_RESIZE = 49               # elastic resize honored (ISSUE 11): a clean
                               # checkpoint was written and the driver exited
                               # so the supervisor can relaunch it onto a
                               # DIFFERENT mesh — like a preemption's 43
                               # (restart immediately, no backoff) but the
                               # relaunch argv changes (device count, cadence)

# argparse's own usage-error exit — not ours to raise, but the classifier
# treats it like EXIT_CONFIG_ERROR (same argv can never succeed)
USAGE_ERROR = 2

EXIT_CODE_NAMES: dict[int, str] = {
    EXIT_OK: "clean",
    EXIT_PREEMPTED: "preempted",
    EXIT_ROLLBACK_EXHAUSTED: "rollback_exhausted",
    EXIT_CONFIG_ERROR: "config_error",
    EXIT_DATA_QUALITY: "data_quality",
    EXIT_SERVE_BIND: "serve_bind",
    EXIT_FLEET_BIND: "fleet_bind",
    EXIT_RESIZE: "resize",
    EXIT_STAGING_BIND: "staging_bind",
    USAGE_ERROR: "usage_error",
}
