"""Typed fault-tolerance errors (resilience package contract).

The type encodes the RECOVERY POLICY, which is why these are not plain
RuntimeErrors: `TransientDataError` is retried with backoff by the
Prefetcher, `NonFiniteLossError` triggers a checkpoint rollback in the
driver, and the two *Exhausted/Quality errors are deliberate run-enders
that no layer should catch."""

from __future__ import annotations


class TransientDataError(OSError):
    """A dataset/storage read that is worth retrying (flaky NFS/GCS read,
    chaos-injected loader fault). Subclasses OSError so generic IO retry
    policies treat the two identically."""


class NonFiniteLossError(FloatingPointError):
    """The per-step sentinel saw a non-finite loss. `step` is the number of
    COMPLETED steps at the poisoned step. `pos` is the `(epoch, batch_index)`
    the poisoned batch was consumed at — the rollback skips THROUGH that
    position, which stays correct even when earlier skips have drifted the
    step↔batch mapping (step arithmetic alone cannot recover it then)."""

    def __init__(self, step: int, value: float,
                 pos: tuple[int, int] | None = None):
        super().__init__(f"non-finite loss {value!r} at step {step}")
        self.step = int(step)
        self.value = value
        self.pos = pos


class CollapseError(NonFiniteLossError):
    """A CollapseSentinel predicate fired with rollback opted in
    (`collapse_rollback=True`). Subclasses NonFiniteLossError so the
    driver's existing bounded-rollback machinery (restore the last good
    checkpoint, advance the data window, `max_rollbacks` cap) handles a
    detected representation collapse exactly like a non-finite loss —
    the recovery policy IS the type, and it is the same policy."""

    def __init__(self, step: int, predicate: str, value: float,
                 pos: tuple[int, int] | None = None):
        FloatingPointError.__init__(
            self,
            f"collapse predicate {predicate!r} fired at step {step} "
            f"(value {value!r}); requesting rollback",
        )
        self.step = int(step)
        self.predicate = predicate
        self.value = value
        self.pos = pos


class RollbackExhaustedError(RuntimeError):
    """More than `max_rollbacks` consecutive NaN rollbacks — the divergence
    is not a poisoned data window, something is structurally wrong (lr blowup,
    corrupt state); a human has to look."""


class DataQualityError(RuntimeError):
    """The decode-failure rate crossed the configured abort threshold —
    enough zero-canvas batches to poison training, so continuing would waste
    the run silently."""
