"""Step-time watchdog (tentpole part 5).

A stuck collective on a pod (one host preempted mid-all-reduce, a wedged
DCN link) looks like SILENCE from the driver: the step never completes, no
exception fires, and a multi-day run burns quota doing nothing. The
watchdog is a background thread that flags — loudly, and again every
further interval — when no `beat()` has arrived within the configured
window. It deliberately only FLAGS (via `log_event`): killing the process
from a watchdog thread would turn a transient stall into data loss. The
KILL decision belongs to the out-of-process supervisor
(resilience/supervisor.py), which watches the same silence through
heartbeat.json staleness and escalates SIGTERM → grace → SIGKILL →
classified restart; this in-process flag remains the operator's early
warning and the telemetry stream's record of the stall.
"""

from __future__ import annotations

import contextlib
import threading
import time

from moco_tpu.utils.logging import log_event


class StepWatchdog:
    """Context manager; `beat(step)` after every completed train step.

    `interval_secs <= 0` disables the thread entirely — `beat` stays a cheap
    attribute write so callers need no gating. `stalls` counts flags raised
    (testable without log scraping).
    """

    def __init__(self, interval_secs: float):
        self.interval = float(interval_secs)
        self.stalls = 0
        self._suspend = 0
        self._step = 0
        self._last = time.monotonic()
        # re-arm threshold: after flagging once, flag again only after a
        # FURTHER full interval of silence (one line per interval, not per poll)
        self._warn_after = self.interval
        # guards the re-arm state written from both sides (lint R10): an
        # unlocked `beat()` racing `_watch`'s `+=` could lose the re-arm
        # and either re-flag every poll or go silent for an extra interval
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def beat(self, step: int) -> None:
        with self._lock:
            self._step = int(step)
            self._last = time.monotonic()
            self._warn_after = self.interval

    @contextlib.contextmanager
    def suspended(self):
        """Scope for KNOWN-long non-step work (epoch-boundary kNN eval, a
        blocking save): a flag fired there is a false positive that trains
        operators to ignore the real ones. Re-arms fresh on exit. Safe when
        the watchdog is disabled; nests."""
        with self._lock:
            self._suspend += 1
        try:
            yield
        finally:
            with self._lock:
                self._suspend -= 1
                self._last = time.monotonic()
                self._warn_after = self.interval

    def _watch(self) -> None:
        poll = max(self.interval / 4.0, 0.01)
        while not self._stop.wait(poll):
            with self._lock:
                if self._suspend:
                    continue
                gap = time.monotonic() - self._last
                flag = gap > self._warn_after
                if flag:
                    self.stalls += 1
                    self._warn_after += self.interval
                    step = self._step
            if flag:
                log_event(
                    "watchdog",
                    f"no step completed in {gap:.1f}s (last completed step "
                    f"{step}, threshold {self.interval:.1f}s) — "
                    "possible hang (stuck collective / wedged input pipeline)",
                )

    def __enter__(self) -> "StepWatchdog":
        if self.interval > 0:
            self._last = time.monotonic()
            self._stop.clear()
            self._thread = threading.Thread(target=self._watch, daemon=True)
            self._thread.start()
        return self

    def __exit__(self, *exc) -> bool:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        return False
