"""Elastic training: checkpoint–resize–relaunch across changing hardware
(ISSUE 11 tentpole).

A pod resize used to mean a manual restart even though every piece needed
to survive it already existed separately: the checkpoint dialect shim
rebuilds gradsync accumulators across mesh-size changes
(`checkpoint.TRAIN_STATE_DIALECTS`), the run supervisor classifies deaths
and relaunches within a budget (`resilience/supervisor.py`), and the
position sidecars preserve the data window. This module is the wiring
that turns those pieces into ONE automatic loop:

  - `ResizeListener` (child side, wired by the train driver): a
    `<telemetry_dir>/resize.request` trigger file (polled time-gated at
    step boundaries, the `trace.trigger` pattern) or a SIGUSR2 flips a
    flag; the driver finishes the in-flight step, writes a clean elastic
    checkpoint, and exits `EXIT_RESIZE` (49) — the "relaunch me onto a
    different mesh" exit, distinct from a preemption's 43.
  - `ResizeController` (supervisor side): accepts resize requests (the
    same trigger file, or a SIGUSR2 delivered to the SUPERVISOR), signals
    the child, and on the child's 49 rewrites the relaunch argv — the new
    device count (argparse last-wins append), an optional
    `--grad-sync-cadence` override when the new mesh is flagged
    slow-linked, and a FRESH per-resize compile cache dir so the resized
    relaunch never touches a cache a killed predecessor may have poisoned
    (the PR 4 finding). `--resume auto` + the dialect shim then restore
    the state onto the new mesh with fresh-zero gradsync accumulators.
  - `read_recorded_devices` / `argv_device_count`: the relaunch-preflight
    membership check — every checkpoint's position sidecar records the
    mesh size it was saved under, so a supervisor about to relaunch onto
    a different device count can log the `mesh_change` incident BEFORE
    the restore shim discovers it.

Request file format: `key=value` pairs, whitespace- or comma-separated,
e.g. `devices=2 grad_sync_cadence=4` or just an empty file ("resize to
whatever is visible now"). `slow=1` flags the new mesh as slow-linked
without naming a cadence — the supervisor then applies its configured
`--resize-slow-cadence`. Consumption renames the file to
`resize.request.honored` (atomic), so a stale request can never re-fire a
resize into the next incarnation.

Everything here is PURE stdlib — the supervisor imports it, and the
supervisor's contract is surviving the failures that kill the jax
runtime (mocolint R11 pins the import discipline).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import threading
import time

from moco_tpu.utils.logging import log_event

RESIZE_REQUEST_FILENAME = "resize.request"
HONORED_SUFFIX = ".honored"

# argv spellings that pin a device count, in either `--flag N` or
# `--flag=N` form. `--fake-devices` is the CPU-proxy spelling (forces N
# fake XLA CPU devices — the 1→2→1 drill), `--num-devices` caps the real
# visible device set.
DEVICE_FLAGS = ("--num-devices", "--fake-devices")


@dataclasses.dataclass
class ResizeRequest:
    """One parsed resize request. `devices=None` means "resize to whatever
    the relaunch sees" (the membership-change case — the argv keeps its
    device flags and the new hardware defines the mesh)."""

    devices: int | None = None
    grad_sync_cadence: int | None = None
    sharding: str | None = None  # ISSUE 15: switch the sharding mode on
                                 # relaunch (dp/fsdp/fsdp_tp) — e.g. a
                                 # grow onto a pod flips dp→fsdp in the
                                 # same resize; the dialect-3 restore +
                                 # sidecar stamp make the mode hop safe
    slow: bool = False           # new mesh flagged slow-linked: the
                                 # supervisor applies its configured
                                 # cadence override
    source: str = "request"      # "request" | "sigusr2" | "chaos" |
                                 # "mesh_change"


def parse_resize_request(text: str, source: str = "request") -> ResizeRequest:
    """`"devices=2 grad_sync_cadence=4"` → ResizeRequest. Empty text is a
    valid request (resize to the visible device count). Unknown keys are
    rejected loudly — a typo'd `device=2` silently resizing to the old
    count would be worse than the crash."""
    req = ResizeRequest(source=source)
    for part in text.replace(",", " ").split():
        key, sep, value = part.partition("=")
        key = key.strip()
        if not sep:
            raise ValueError(f"malformed resize request entry {part!r} "
                             "(expected key=value)")
        if key == "devices":
            req.devices = int(value)
            if req.devices < 1:
                raise ValueError(f"resize devices must be >= 1, got {value}")
        elif key == "grad_sync_cadence":
            req.grad_sync_cadence = int(value)
            if req.grad_sync_cadence < 1:
                raise ValueError(
                    f"resize grad_sync_cadence must be >= 1, got {value}")
        elif key == "sharding":
            if value not in ("dp", "fsdp", "fsdp_tp"):
                raise ValueError(
                    f"resize sharding must be dp/fsdp/fsdp_tp, got {value!r}")
            req.sharding = value
        elif key == "slow":
            req.slow = bool(int(value))
        else:
            raise ValueError(
                f"unknown resize request key {key!r}; known: devices, "
                "grad_sync_cadence, sharding, slow"
            )
    return req


def request_path(telemetry_dir: str) -> str:
    return os.path.join(telemetry_dir, RESIZE_REQUEST_FILENAME)


def write_resize_request(
    telemetry_dir: str,
    devices: int | None = None,
    grad_sync_cadence: int | None = None,
    slow: bool = False,
    sharding: str | None = None,
) -> str:
    """Drop a resize request next to trace.trigger (atomic: a supervisor
    polling mid-write must never parse half a request). Returns the path."""
    parts = []
    if devices is not None:
        parts.append(f"devices={int(devices)}")
    if grad_sync_cadence is not None:
        parts.append(f"grad_sync_cadence={int(grad_sync_cadence)}")
    if sharding is not None:
        parts.append(f"sharding={sharding}")
    if slow:
        parts.append("slow=1")
    os.makedirs(telemetry_dir, exist_ok=True)
    path = request_path(telemetry_dir)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(" ".join(parts) + "\n")
    os.replace(tmp, path)
    return path


def consume_resize_request(telemetry_dir: str,
                           source: str = "request") -> ResizeRequest | None:
    """Atomically claim a pending request (rename to `.honored` — exactly
    one of N racing consumers wins, and a relaunched child can never
    re-trigger on a stale file). None when no request is pending or it is
    unparseable (logged, never fatal: a malformed operator request must
    not take the run down)."""
    path = request_path(telemetry_dir)
    honored = path + HONORED_SUFFIX
    try:
        os.replace(path, honored)  # atomic claim; overwrites the last one
    except OSError:
        return None  # no pending request
    return read_honored_request(telemetry_dir, source=source)


def read_honored_request(telemetry_dir: str,
                         source: str = "request") -> ResizeRequest | None:
    """The last CLAIMED request's payload. The supervisor falls back to
    this when the child's own file poll won the consume race (the claim
    is a rename, so the payload — the target device count — survives it);
    `ResizeController.apply` deletes the file once honored so a stale
    payload can never leak into a later, payload-less resize."""
    honored = request_path(telemetry_dir) + HONORED_SUFFIX
    try:
        with open(honored, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return None
    try:
        return parse_resize_request(text, source=source)
    except ValueError as e:
        log_event("resize", f"ignoring unparseable resize request: {e}")
        return None


# -- membership bookkeeping ---------------------------------------------------


def read_recorded_devices(ckpt_dir: str) -> tuple[int, int] | None:
    """`(step, devices)` of the NEWEST checkpoint step whose position
    sidecar records the mesh size it was saved under (checkpoint.
    write_position stamps `devices` on every save). None when no step
    records one — pre-elastic checkpoints stay silent, never guessed at.
    Stdlib-only: the jax-free supervisor runs this at relaunch preflight."""
    from moco_tpu.resilience.integrity import position_path

    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return None
    for name in sorted((n for n in names if n.isdigit()), key=int,
                       reverse=True):
        try:
            with open(position_path(ckpt_dir, int(name)),
                      encoding="utf-8") as f:
                payload = json.load(f)
            devices = int(payload["devices"])
        except (OSError, ValueError, KeyError, TypeError,
                json.JSONDecodeError):
            continue
        return int(name), devices
    return None


def argv_device_count(argv: list[str]) -> int | None:
    """The device count the argv pins (`--num-devices N` /
    `--fake-devices N`, either flag form; LAST occurrence wins — the same
    argparse semantics the resize append relies on). None when the argv
    leaves the mesh to the visible hardware."""
    found: int | None = None
    i = 0
    while i < len(argv):
        arg = argv[i]
        for flag in DEVICE_FLAGS:
            value = None
            if arg == flag and i + 1 < len(argv):
                value = argv[i + 1]
            elif arg.startswith(flag + "="):
                value = arg[len(flag) + 1:]
            if value is not None:
                try:
                    n = int(value)
                except ValueError:
                    continue
                if n > 0:  # --fake-devices 0 means "off", not a count
                    found = n
        i += 1
    return found


def pick_device_flag(argv: list[str], default: str = "--num-devices") -> str:
    """The flag the resize append should use: whichever device flag the
    argv already speaks (a `--fake-devices` CPU drill must be resized in
    its own dialect), else `default`."""
    for arg in argv:
        for flag in DEVICE_FLAGS:
            if arg == flag or arg.startswith(flag + "="):
                return flag
    return default


# -- child side ---------------------------------------------------------------


class ResizeListener:
    """Converts a resize request into a poll-able flag inside the train
    driver (the `PreemptionHandler` pattern): SIGUSR2 sets it immediately;
    `poll()` additionally checks the trigger file time-gated (`poll_secs`),
    consuming it on trigger so an unsupervised relaunch can never re-fire
    on the stale file. The driver finishes the in-flight step, writes the
    elastic checkpoint, and exits `EXIT_RESIZE`.

    Signal handlers install from the main thread only (pytest workers and
    nested drivers get a file-poll-only listener, no special-casing)."""

    def __init__(self, telemetry_dir: str = "", poll_secs: float = 0.5):
        self.telemetry_dir = telemetry_dir
        self.poll_secs = float(poll_secs)
        self._flag = threading.Event()
        self._last_poll = float("-inf")
        self._prev = None
        self._installed = False

    def _handle(self, signum, frame):
        if not self._flag.is_set():
            log_event(
                "resize",
                "caught SIGUSR2: finishing the in-flight step, then writing "
                "an elastic checkpoint and exiting for the resize relaunch",
            )
        self._flag.set()

    def __enter__(self) -> "ResizeListener":
        if threading.current_thread() is threading.main_thread():
            self._prev = signal.signal(signal.SIGUSR2, self._handle)
            self._installed = True
        return self

    def __exit__(self, *exc) -> bool:
        if self._installed:
            if self._flag.is_set():
                # the resize is being HONORED: the listener exits (the
                # driver's ExitStack closes) BEFORE the elastic checkpoint
                # is written, and the supervisor may still deliver its
                # SIGUSR2 in that window — restoring the default
                # disposition would let a late duplicate signal TERMINATE
                # the child mid-save. Leave SIGUSR2 ignored for the rest
                # of this (already-exiting) process.
                signal.signal(signal.SIGUSR2, signal.SIG_IGN)
            else:
                signal.signal(signal.SIGUSR2, self._prev)
            self._installed = False
        return False

    @property
    def triggered(self) -> bool:
        return self._flag.is_set()

    def trigger(self, source: str = "chaos") -> None:
        """Programmatic trigger (the chaos `resize_at_step` drill)."""
        if not self._flag.is_set():
            log_event("resize", f"resize triggered ({source}): exiting for "
                                "relaunch after the elastic checkpoint")
        self._flag.set()

    def poll(self, now: float | None = None) -> bool:
        """Current flag state, refreshed from the trigger file at most once
        per `poll_secs` (one `os.replace` attempt — the fast path is a
        monotonic-clock compare). Supervised runs normally never reach the
        file: the supervisor consumes it first and SIGUSR2s us."""
        if self._flag.is_set():
            return True
        if not self.telemetry_dir:
            return False
        now = time.monotonic() if now is None else now
        if now - self._last_poll < self.poll_secs:
            return False
        self._last_poll = now
        req = consume_resize_request(self.telemetry_dir)
        if req is not None:
            self.trigger(source="trigger file")
        return self._flag.is_set()


# -- supervisor side ----------------------------------------------------------


class ResizeController:
    """The supervisor's half of the elastic loop. Owns the armed request
    state and the relaunch-argv rewrite; the `Supervisor` calls:

      - `poll()` each monitor cycle — arms from the trigger file (or a
        SIGUSR2 the CLI routed to `signal_resize`), returns the request
        once so the supervisor can signal the child and emit the
        `resize_request` incident;
      - `take()` after a child exits `EXIT_RESIZE` — the armed request,
        else a last-chance file claim (the chaos drill's child writes the
        file and exits faster than the poll cadence), else an empty
        request (resize to whatever the hardware shows);
      - `apply(argv, env)` before the relaunch — mutates argv/env in
        place: device-count append (argparse last-wins), the cadence
        override, and a fresh per-resize compile cache dir.
    """

    def __init__(self, telemetry_dir: str, *,
                 device_flag: str = "",
                 slow_cadence: int = 0,
                 poll_gate_secs: float = 0.5,
                 rotate_cache: bool = True):
        self.telemetry_dir = telemetry_dir
        self.device_flag = device_flag  # "" = pick from the argv itself
        self.slow_cadence = int(slow_cadence)
        self.poll_gate_secs = float(poll_gate_secs)
        # False when the operator pinned the cache themselves
        # (--shared-compile-cache, or an explicit MOCO_TPU_CACHE_DIR in
        # the environment before the supervisor derived its own): a
        # resize must not silently override that choice
        self.rotate_cache = bool(rotate_cache)
        self.armed: ResizeRequest | None = None
        self.armed_at_wall: float = 0.0
        self.resizes_applied = 0
        self._signal_flag = threading.Event()
        self._last_poll = float("-inf")

    def signal_resize(self) -> None:
        """SIGUSR2-to-the-supervisor entry point (tools/supervise.py
        installs it): arm a resize using the trigger file's payload when
        one is pending, else an empty request. Signal-handler-safe: just
        an Event set; the monitor loop's next poll does the file I/O."""
        self._signal_flag.set()

    def poll(self, now: float | None = None) -> ResizeRequest | None:
        """Newly-armed request, exactly once per arming; None otherwise."""
        if self.armed is not None:
            return None  # already armed: waiting for the child to exit
        via_signal = self._signal_flag.is_set()
        now = time.monotonic() if now is None else now
        if not via_signal and now - self._last_poll < self.poll_gate_secs:
            return None
        self._last_poll = now
        req = consume_resize_request(self.telemetry_dir)
        if via_signal:
            self._signal_flag.clear()
            if req is None:
                # the CHILD's listener may have won the file-claim race
                # between the operator's write and this SIGUSR2: the
                # payload (the target device count) survives at the
                # honored path — dropping it would resize to "visible"
                # instead of what the operator asked for
                req = read_honored_request(self.telemetry_dir)
            if req is None:
                req = ResizeRequest(source="sigusr2")
            else:
                req.source = "sigusr2"
        if req is not None:
            self.armed = req
            self.armed_at_wall = time.time()
        return req

    def take(self) -> ResizeRequest:
        """Claim the request a just-exited `EXIT_RESIZE` child honored:
        the armed one, else an unconsumed file (the chaos drill's child
        writes it and exits faster than the poll cadence), else the
        honored file the CHILD's own poll claimed, else an empty request
        (resize to whatever the hardware shows)."""
        req = (self.armed
               or consume_resize_request(self.telemetry_dir)
               or read_honored_request(self.telemetry_dir, source="exit"))
        if req is None:
            req = ResizeRequest(source="exit")
        if not self.armed_at_wall:
            self.armed_at_wall = time.time()
        self.armed = None
        return req

    def cadence_override(self, req: ResizeRequest) -> int | None:
        """The `--grad-sync-cadence` the relaunch should carry: an explicit
        request value wins; a `slow=1` flag applies the supervisor's
        configured slow-link cadence; neither means no override."""
        if req.grad_sync_cadence is not None:
            return req.grad_sync_cadence
        if req.slow and self.slow_cadence > 0:
            return self.slow_cadence
        return None

    def apply(self, req: ResizeRequest, argv: list[str],
              env: dict) -> dict:
        """Rewrite the relaunch argv/env IN PLACE for the resize; returns
        a summary dict for the `resize_relaunch` incident record.

        Appends (argparse last-wins) rather than edits: the original
        operator argv stays visible in the launch record, and repeated
        resizes stack correctly. The compile cache rotates to a fresh
        per-resize dir unless the operator disabled caching outright —
        the resized shapes compile fresh either way, and a cache a
        SIGKILL-grade predecessor poisoned must never brick the relaunch."""
        old_devices = argv_device_count(argv)
        summary: dict = {"source": req.source, "devices_from": old_devices}
        if req.devices is not None:
            flag = self.device_flag or pick_device_flag(argv)
            argv += [flag, str(int(req.devices))]
            summary["devices_to"] = int(req.devices)
            summary["device_flag"] = flag
        else:
            summary["devices_to"] = None  # whatever the hardware shows
        cadence = self.cadence_override(req)
        if cadence is not None:
            argv += ["--grad-sync-cadence", str(int(cadence))]
            summary["grad_sync_cadence"] = int(cadence)
        if req.sharding is not None:
            # ISSUE 15: the sharding mode rides the same last-wins append —
            # an argv that already says --sharding fsdp keeps saying it on
            # a mode-less resize (nothing appended), and a mode-carrying
            # request flips it for the relaunch
            argv += ["--sharding", req.sharding]
            summary["sharding"] = req.sharding
        if self.rotate_cache and not env.get("MOCO_TPU_NO_CACHE"):
            from moco_tpu.utils.cache import per_run_cache_dir  # stdlib-only

            env["MOCO_TPU_CACHE_DIR"] = per_run_cache_dir(
                tag=f"resize{self.resizes_applied}")
            summary["cache_dir"] = env["MOCO_TPU_CACHE_DIR"]
        try:
            # honored payload applied: a stale copy must not leak into a
            # later payload-less resize's take() fallback
            os.remove(request_path(self.telemetry_dir) + HONORED_SUFFIX)
        except OSError:
            pass
        self.resizes_applied += 1
        self.armed_at_wall = 0.0
        return summary
