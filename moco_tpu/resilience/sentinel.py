"""Every-step non-finite-loss detection (tentpole part 3).

The old guard (`debug_nans` + a finiteness check at `print_freq`) noticed a
NaN up to `print_freq - 1` steps late and then simply killed the run. The
sentinel checks EVERY step with a one-step lag: step k's loss (still a
device array) is held, and pulled to host while step k+1 executes — the
host read overlaps device compute, so the pipeline never bubbles the way a
same-step `float(loss)` would. On detection it raises
`NonFiniteLossError(step)`; the driver answers with a bounded checkpoint
rollback (`train.train`), not a crash.
"""

from __future__ import annotations

import math

from moco_tpu.resilience.errors import NonFiniteLossError
from moco_tpu.utils.logging import log_event


class NaNSentinel:
    """Hold each step's loss for one step, then verify it is finite.

    `observe(step, loss)` swaps the pending (step, loss) pair and checks the
    previous one; `flush()` checks the final pending pair at epoch/run end so
    the last step is never left unverified. `loss` may be a device array
    (the normal case) or a plain float (chaos injection).
    """

    def __init__(self) -> None:
        self._pending: tuple[int, object, tuple[int, int] | None] | None = None

    def observe(self, step: int, loss,
                pos: tuple[int, int] | None = None) -> None:
        """`pos` is the `(epoch, batch_index)` the step consumed — carried
        onto the error so the rollback can target the poisoned batch without
        step arithmetic (which breaks once skips have drifted the mapping)."""
        prev, self._pending = self._pending, (int(step), loss, pos)
        if prev is not None:
            self._check(*prev)

    def flush(self) -> None:
        prev, self._pending = self._pending, None
        if prev is not None:
            self._check(*prev)

    def _check(self, step: int, loss, pos: tuple[int, int] | None) -> None:
        value = float(loss)
        if not math.isfinite(value):
            log_event(
                "sentinel",
                f"non-finite loss {value!r} at step {step}; requesting rollback",
            )
            raise NonFiniteLossError(step, value, pos)
