"""Every-step learning sentinels: non-finite loss + windowed collapse.

The old guard (`debug_nans` + a finiteness check at `print_freq`) noticed a
NaN up to `print_freq - 1` steps late and then simply killed the run. The
sentinel checks EVERY step with a one-step lag: step k's loss (still a
device array) is held, and pulled to host while step k+1 executes — the
host read overlaps device compute, so the pipeline never bubbles the way a
same-step `float(loss)` would. On detection it raises
`NonFiniteLossError(step)`; the driver answers with a bounded checkpoint
rollback (`train.train`), not a crash.

`CollapseSentinel` (ISSUE 13) generalizes that pattern from point-in-time
non-finite checks to WINDOWED health predicates over the learning-health
scalars the step already computes (telemetry/health.py): an acc1 floor
sustained over W observations, embedding std pinned at ~0, a vanishing
logit margin. The same one-step-lag device-read discipline applies — the
scalars are held as device arrays and pulled while the next step runs.
A fired predicate defaults to ONE structured `health` incident per
excursion (re-armed only after the predicate observes a clean window
again); with `collapse_rollback=True` it instead raises `CollapseError`
into the driver's bounded NaN-rollback path.
"""

from __future__ import annotations

import math
from collections import deque

from moco_tpu.resilience.errors import CollapseError, NonFiniteLossError
from moco_tpu.utils.logging import log_event


class NaNSentinel:
    """Hold each step's loss for one step, then verify it is finite.

    `observe(step, loss)` swaps the pending (step, loss) pair and checks the
    previous one; `flush()` checks the final pending pair at epoch/run end so
    the last step is never left unverified. `loss` may be a device array
    (the normal case) or a plain float (chaos injection).
    """

    def __init__(self) -> None:
        self._pending: tuple[int, object, tuple[int, int] | None] | None = None

    def observe(self, step: int, loss,
                pos: tuple[int, int] | None = None) -> None:
        """`pos` is the `(epoch, batch_index)` the step consumed — carried
        onto the error so the rollback can target the poisoned batch without
        step arithmetic (which breaks once skips have drifted the mapping)."""
        prev, self._pending = self._pending, (int(step), loss, pos)
        if prev is not None:
            self._check(*prev)

    def flush(self) -> None:
        prev, self._pending = self._pending, None
        if prev is not None:
            self._check(*prev)

    def _check(self, step: int, loss, pos: tuple[int, int] | None) -> None:
        value = float(loss)
        if not math.isfinite(value):
            log_event(
                "sentinel",
                f"non-finite loss {value!r} at step {step}; requesting rollback",
            )
            raise NonFiniteLossError(step, value, pos)


class CollapseSentinel:
    """Windowed learning-health predicates over the step's own collapse
    scalars (ISSUE 13).

    `observe(step, scalars, pos)` takes a dict of DEVICE (or host)
    scalars for the just-dispatched step — the always-on `logit_margin`
    and `acc1` every step, the stride-sampled `h_emb_std_*` only on
    health-stride steps — holds it for one step (the NaNSentinel lag:
    the host pull overlaps the next step's device compute), then folds
    the previous step's values into per-predicate rings and evaluates:

      margin    every margin in a FULL window  <= collapse_margin
      emb_std   every sampled embedding std in a FULL window
                <= collapse_emb_std (the smaller of the q/k stds per
                sample — either side collapsing is collapse)
      acc1      every acc1 in a FULL window  < collapse_acc1

    A threshold of 0 disables its predicate. Observations at or before
    `min_step` are DISCARDED, not just muted (init-time acc1 IS chance;
    the margin is still forming — warmup values must never satisfy a
    window that fires right after the grace period ends). Requiring the whole window to violate — not a mean — is
    the hysteresis: one healthy observation inside W re-arms the count,
    so a noisy metric cannot page on a blip. Each predicate fires ONE
    `health` incident per excursion and re-arms only after observing a
    fully clean window; with `rollback=True` the first firing raises
    `CollapseError` into the driver's bounded rollback instead.
    """

    #: predicate name -> (scalar keys consumed, comparison label)
    _EMB_KEYS = ("h_emb_std_q", "h_emb_std_k")

    def __init__(self, window: int, *, acc1_floor: float = 0.0,
                 emb_std_eps: float = 0.0, margin_eps: float = 0.0,
                 min_step: int = 0, rollback: bool = False) -> None:
        self.window = max(int(window), 1)
        self.min_step = int(min_step)
        self.rollback = bool(rollback)
        self._thresholds = {
            "margin": float(margin_eps),
            "emb_std": float(emb_std_eps),
            "acc1": float(acc1_floor),
        }
        self._rings: dict[str, deque] = {
            name: deque(maxlen=self.window)
            for name, eps in self._thresholds.items() if eps > 0
        }
        self._alerting: set[str] = set()
        self.fired: list[dict] = []
        self._pending: tuple | None = None

    @property
    def armed(self) -> bool:
        return bool(self._rings)

    def observe(self, step: int, scalars: dict,
                pos: tuple[int, int] | None = None) -> None:
        prev, self._pending = self._pending, (int(step), dict(scalars), pos)
        if prev is not None:
            self._check(*prev)

    def flush(self) -> None:
        prev, self._pending = self._pending, None
        if prev is not None:
            self._check(*prev)

    def _ingest(self, scalars: dict) -> None:
        values = {}
        if "logit_margin" in scalars and "margin" in self._rings:
            values["margin"] = float(scalars["logit_margin"])
        if "acc1" in scalars and "acc1" in self._rings:
            values["acc1"] = float(scalars["acc1"])
        if "emb_std" in self._rings:
            stds = [float(scalars[k]) for k in self._EMB_KEYS
                    if scalars.get(k) is not None]
            if stds:
                values["emb_std"] = min(stds)
        for name, value in values.items():
            self._rings[name].append(value)

    def _violated(self, name: str) -> float | None:
        """The window's worst (most-healthy) value when the predicate is
        violated by the WHOLE window; None otherwise."""
        ring = self._rings[name]
        if len(ring) < self.window:
            return None
        worst = max(ring)
        eps = self._thresholds[name]
        if (name == "acc1" and worst < eps) or (
                name != "acc1" and worst <= eps):
            return worst
        return None

    def _check(self, step: int, scalars: dict,
               pos: tuple[int, int] | None) -> None:
        if step <= self.min_step:
            # the grace period keeps values OUT of the rings too: a
            # window must never be satisfied by warmup-era observations
            # the very knob exists to suppress (they'd otherwise fire a
            # predicate at min_step + 1)
            return
        self._ingest(scalars)
        for name in self._rings:
            value = self._violated(name)
            if value is None:
                if name in self._alerting:
                    # a fully-clean window re-arms the predicate and
                    # says so: the operator sees the excursion END in
                    # the same stream its start landed in
                    if (len(self._rings[name]) == self.window
                            and self._is_clean(name)):
                        self._alerting.discard(name)
                        # its OWN event name: `health` counts incidents
                        # (obsd's collapse_events objective pages on it —
                        # a recovery under the same name would page the
                        # operator for the excursion ENDING)
                        log_event(
                            "health_recovered",
                            f"collapse predicate {name!r} recovered at "
                            f"step {step}",
                            step=step, predicate=name,
                        )
                continue
            if name in self._alerting:
                continue  # one incident per excursion
            self._alerting.add(name)
            incident = dict(step=step, predicate=name, value=value,
                            threshold=self._thresholds[name],
                            window=self.window)
            self.fired.append(incident)
            log_event(
                "health",
                f"collapse predicate {name!r} fired at step {step}: "
                f"window-worst {value:.6g} vs threshold "
                f"{self._thresholds[name]:.6g} over {self.window} "
                f"observation(s)"
                + ("; requesting rollback" if self.rollback else ""),
                **incident,
            )
            if self.rollback:
                raise CollapseError(step, name, value, pos)

    def _is_clean(self, name: str) -> bool:
        """Every value in the (full) window healthy — the re-arm bar."""
        ring = self._rings[name]
        eps = self._thresholds[name]
        if name == "acc1":
            return all(v >= eps for v in ring)
        return all(v > eps for v in ring)
