"""Deterministic fault-injection harness (the ISSUE 1 headline deliverable).

Every recovery path in this package is exercised by INJECTED faults in CPU
tier-1 tests instead of trusted on faith: a `ChaosPlan` names the fault and
the exact step/batch it fires at, the driver and loader poll the installed
plan at their hook points, and each fault fires AT MOST ONCE — so a run
that rolls back and re-traverses the same step numbers is not re-poisoned,
and the whole scenario is reproducible bit-for-bit.

Install programmatically (tests):

    with chaos_context(ChaosPlan(sigterm_at_step=11)):
        train(config, mesh)

or from the CLI / env for operational drills:

    python -m moco_tpu.train --preset ... --chaos "nan_at_step=300"
    MOCO_TPU_CHAOS="sigterm_at_step=5000" python -m moco_tpu.train ...

`truncate_checkpoint` is the storage-fault injector: it corrupts the
largest payload file of a saved step in place, the way a preempted or
out-of-quota writer leaves partial checkpoints.
"""

from __future__ import annotations

import contextlib
import os
import signal
import threading
from dataclasses import dataclass, field

from moco_tpu.resilience.errors import TransientDataError
from moco_tpu.utils.logging import log_event


@dataclass
class ChaosPlan:
    """One deterministic fault scenario. Steps count COMPLETED train steps
    (the driver's `global_step` after the increment); batches are the
    Prefetcher's 0-based batch index within its epoch."""

    sigterm_at_step: int | None = None      # deliver SIGTERM after step k
    nan_at_step: int | None = None          # poison the reported loss at step k
    nan_count: int = 1                      # re-poison step k on re-traversal
                                            # up to this many times (>1 models
                                            # a STRUCTURAL divergence that the
                                            # data-window advance cannot fix —
                                            # the rollback-exhaustion path)
    loader_error_at_batch: int | None = None  # Prefetcher read fault at batch b
    loader_error_count: int = 1             # consecutive faults before recovery
    _fired: set = field(default_factory=set, repr=False)
    _nans_raised: int = field(default=0, repr=False)
    _loader_errors_raised: int = field(default=0, repr=False)
    # loader faults are polled CONCURRENTLY by the staging workers
    # (ISSUE 3): an unsynchronized check-then-increment would let two
    # workers both observe the budget unspent and inject more faults than
    # the plan configured
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def _fire_once(self, key: str) -> bool:
        if key in self._fired:
            return False
        self._fired.add(key)
        return True

    def maybe_sigterm(self, step: int) -> None:
        """Deliver a real SIGTERM through the OS so the actual signal-handler
        path is exercised, not a simulation of it."""
        if self.sigterm_at_step == step and self._fire_once("sigterm"):
            log_event("chaos", f"injecting SIGTERM at step {step}")
            signal.raise_signal(signal.SIGTERM)

    def maybe_nan(self, step: int) -> bool:
        """True at the configured step (the first `nan_count` traversals of
        it): the caller replaces the step's reported loss with NaN — the
        sentinel's detection and the driver's rollback then run for real."""
        if self.nan_at_step == step and self._nans_raised < self.nan_count:
            self._nans_raised += 1
            log_event(
                "chaos",
                f"injecting non-finite loss at step {step} "
                f"({self._nans_raised}/{self.nan_count})",
            )
            return True
        return False

    def maybe_loader_error(self, batch_index: int) -> None:
        """Raise `TransientDataError` for the first `loader_error_count`
        attempts at the configured batch — the retry-with-backoff path must
        survive exactly that many consecutive failures. Thread-safe: with
        multi-worker staging the fault budget is spent exactly
        `loader_error_count` times across all workers (which worker draws
        a fault is scheduler-dependent; the batch-level scenario — N
        transient faults at batch b, then recovery — stays deterministic)."""
        if self.loader_error_at_batch != batch_index:
            return
        with self._lock:
            if self._loader_errors_raised >= self.loader_error_count:
                return
            self._loader_errors_raised += 1
            n = self._loader_errors_raised
        raise TransientDataError(
            f"chaos: injected read failure {n}/"
            f"{self.loader_error_count} at batch {batch_index}"
        )


_INT_FIELDS = (
    "sigterm_at_step",
    "nan_at_step",
    "nan_count",
    "loader_error_at_batch",
    "loader_error_count",
)


def parse_chaos_spec(spec: str) -> ChaosPlan | None:
    """`"sigterm_at_step=11,nan_at_step=3"` → ChaosPlan. Empty spec → None.
    Unknown keys are rejected loudly — a typo'd fault that silently never
    fires would make a chaos drill vacuous."""
    spec = spec.strip()
    if not spec:
        return None
    kw: dict[str, int] = {}
    for part in spec.split(","):
        key, _, value = part.partition("=")
        key = key.strip()
        if key not in _INT_FIELDS:
            raise ValueError(
                f"unknown chaos fault {key!r}; known: {', '.join(_INT_FIELDS)}"
            )
        kw[key] = int(value)
    return ChaosPlan(**kw)


# One plan per process: the hooks live in a worker thread (Prefetcher) and
# the main loop, so the registry is module-global rather than threaded
# through every call signature.
_ACTIVE: ChaosPlan | None = None


def install_chaos(plan: ChaosPlan | None) -> None:
    global _ACTIVE
    _ACTIVE = plan


def clear_chaos() -> None:
    install_chaos(None)


def active_chaos() -> ChaosPlan | None:
    if _ACTIVE is None:
        env = os.environ.get("MOCO_TPU_CHAOS", "")
        if env:
            # env-installed plans persist for the process (fire-once state
            # must survive multiple polls)
            install_chaos(parse_chaos_spec(env))
    return _ACTIVE


@contextlib.contextmanager
def chaos_context(plan: ChaosPlan):
    """Scoped install for tests — guarantees no plan leaks into the next
    test even when the body raises (most chaos scenarios end in a raise)."""
    install_chaos(plan)
    try:
        yield plan
    finally:
        clear_chaos()


def truncate_checkpoint(ckpt_dir: str, step: int) -> str:
    """Corrupt the saved `step` the way a preempted writer does: truncate its
    largest payload file to half. Returns the mangled file's path."""
    root = os.path.join(os.path.abspath(ckpt_dir), str(step))
    largest, size = None, -1
    for dirpath, _dirnames, filenames in os.walk(root):
        for fname in filenames:
            p = os.path.join(dirpath, fname)
            s = os.path.getsize(p)
            if s > size:
                largest, size = p, s
    if largest is None:
        raise FileNotFoundError(f"no files under checkpoint step dir {root}")
    with open(largest, "r+b") as f:
        f.truncate(size // 2)
    log_event("chaos", f"truncated {largest} from {size} to {size // 2} bytes")
    return largest
