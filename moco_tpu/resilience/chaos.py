"""Deterministic fault-injection harness (the ISSUE 1 headline deliverable).

Every recovery path in this package is exercised by INJECTED faults in CPU
tier-1 tests instead of trusted on faith: a `ChaosPlan` names the fault and
the exact step/batch it fires at, the driver and loader poll the installed
plan at their hook points, and each fault fires AT MOST ONCE — so a run
that rolls back and re-traverses the same step numbers is not re-poisoned,
and the whole scenario is reproducible bit-for-bit.

Install programmatically (tests):

    with chaos_context(ChaosPlan(sigterm_at_step=11)):
        train(config, mesh)

or from the CLI / env for operational drills:

    python -m moco_tpu.train --preset ... --chaos "nan_at_step=300"
    MOCO_TPU_CHAOS="sigterm_at_step=5000" python -m moco_tpu.train ...

`truncate_checkpoint` is the storage-fault injector: it corrupts the
largest payload file of a saved step in place, the way a preempted or
out-of-quota writer leaves partial checkpoints.
"""

from __future__ import annotations

import contextlib
import os
import signal
import threading
import time
from dataclasses import dataclass, field

from moco_tpu.resilience.errors import TransientDataError
from moco_tpu.utils.logging import log_event


@dataclass
class ChaosPlan:
    """One deterministic fault scenario. Steps count COMPLETED train steps
    (the driver's `global_step` after the increment); batches are the
    Prefetcher's 0-based batch index within its epoch.

    `state_dir` (set from the MOCO_TPU_CHAOS_STATE env var for env-installed
    plans) makes fire-once state SURVIVE process death: a `kill_at_step`
    SIGKILL or a supervisor-killed `freeze_at_step` hang ends the process,
    and the restarted child — resuming from a checkpoint BEFORE the fault's
    step — would otherwise re-fire the same fault on every traversal and
    turn the drill into a crash loop. With a state dir, each FIRE-ONCE
    fault (sigterm/kill/freeze) drops a marker file before executing and
    never fires again across restarts. The counted faults (nan_count,
    loader_error_count) stay per-process by design: their counts exist to
    model repeated in-process re-traversal (the rollback-exhaustion path),
    which marker booleans cannot express."""

    sigterm_at_step: int | None = None      # deliver SIGTERM after step k
    kill_at_step: int | None = None         # self-SIGKILL after step k: the
                                            # un-catchable death (hard
                                            # preemption, OOM-killer) — no
                                            # emergency checkpoint, no clean
                                            # exit; only an out-of-process
                                            # supervisor can recover it
    freeze_at_step: int | None = None       # stop dead after step k (no more
                                            # beats): simulates a wedged pod
                                            # collective / DCN hang — the
                                            # silence mode the supervisor's
                                            # heartbeat-staleness kill exists
                                            # for. The process sleeps until
                                            # killed from outside.
    slow_at_step: int | None = None         # sleep slow_ms inside step k's
                                            # timer window: a deterministic
                                            # step-time blowout for the
                                            # slow-step capture detector
                                            # (ISSUE 8) — and for proving
                                            # the watchdog flags without a
                                            # kill
    slow_ms: int = 1000                     # how long the slow step stalls
    nan_at_step: int | None = None          # poison the reported loss at step k
    nan_count: int = 1                      # re-poison step k on re-traversal
                                            # up to this many times (>1 models
                                            # a STRUCTURAL divergence that the
                                            # data-window advance cannot fix —
                                            # the rollback-exhaustion path)
    loader_error_at_batch: int | None = None  # Prefetcher read fault at batch b
    loader_error_count: int = 1             # consecutive faults before recovery
    kill_at_request: int | None = None      # serve-side (ISSUE 10 fleet
                                            # drills): self-SIGKILL after the
                                            # k-th admitted request — a replica
                                            # dying mid-load; only the fleet
                                            # supervisor + router retry recover
                                            # it
    resize_at_step: int | None = None       # elastic-resize drill (ISSUE
                                            # 11): after step k, write a
                                            # resize.request (devices=
                                            # resize_devices) and exit
                                            # EXIT_RESIZE through the same
                                            # clean-checkpoint path an
                                            # operator request takes — the
                                            # supervisor relaunches onto the
                                            # new mesh. Fire-once with
                                            # MOCO_TPU_CHAOS_STATE, so the
                                            # resized relaunch (which
                                            # re-traverses nothing — the
                                            # elastic ckpt is AT step k —
                                            # but re-polls every later step)
                                            # is never re-poisoned
    resize_devices: int = 0                 # target device count for the
                                            # drill (spec alias: `devices=M`;
                                            # 0 = "whatever is visible")
    collapse_at_step: int | None = None     # learning-health drill (ISSUE
                                            # 13): from step k onward the
                                            # driver rewrites the key-
                                            # encoder params with
                                            # health.crush_key_params so
                                            # its features degenerate to
                                            # one constant vector — the
                                            # injected representation
                                            # collapse the in-graph
                                            # diagnostics, the
                                            # CollapseSentinel, the obsd
                                            # learning-health SLOs and the
                                            # serve reload drift guard are
                                            # all drilled against. A
                                            # PERSISTENT fault (re-applied
                                            # every step: the EMA would
                                            # otherwise heal it within one
                                            # step), logged once.
    kill_at_shard: int | None = None        # staging-server-side (ISSUE 14
                                            # input-service drills): self-
                                            # SIGKILL after the k-th served
                                            # shard request — a decode
                                            # worker dying mid-epoch; the
                                            # client's retry-on-another-
                                            # server and the staging
                                            # supervisor's relaunch recover
                                            # it. Fire-once via
                                            # MOCO_TPU_CHAOS_STATE like
                                            # kill_at_request, so the
                                            # relaunched worker (which
                                            # re-counts shards from 0) is
                                            # never re-poisoned into a
                                            # crash loop
    stall_at_shard: int | None = None       # staging-server-side: the k-th
                                            # served shard stalls stall_ms
                                            # before answering (fire-once,
                                            # marker-persisted) — the slow-
                                            # server mode the client's
                                            # request timeout + retry-on-
                                            # another-server exists for
    stall_ms: int = 1000                    # how long the stalled shard
                                            # holds its answer
    wedge_at_request: int | None = None     # serve-side: after the k-th
                                            # admitted request, STOP answering
                                            # (every later HTTP request —
                                            # /healthz included — hangs on an
                                            # accepted socket): the
                                            # accepting-but-not-answering wedge
                                            # the fleet's probe-staleness kill
                                            # exists for
    state_dir: str | None = None            # fire-once markers persisted here
                                            # (supervised drills: faults fire
                                            # once ACROSS restarts, not once
                                            # per process)
    _fired: set = field(default_factory=set, repr=False)
    _nans_raised: int = field(default=0, repr=False)
    _loader_errors_raised: int = field(default=0, repr=False)
    # loader faults are polled CONCURRENTLY by the staging workers
    # (ISSUE 3): an unsynchronized check-then-increment would let two
    # workers both observe the budget unspent and inject more faults than
    # the plan configured
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def _fire_once(self, key: str) -> bool:
        if key in self._fired:
            return False
        if self.state_dir:
            # persistent marker, written BEFORE the fault executes: a
            # kill_at_step SIGKILL gives no later chance to record it, and
            # an unrecorded fire would re-fire in the restarted child
            marker = os.path.join(self.state_dir, f"fired_{key}")
            if os.path.exists(marker):
                self._fired.add(key)
                return False
            os.makedirs(self.state_dir, exist_ok=True)
            with open(marker, "w") as f:
                f.write(str(os.getpid()))
        self._fired.add(key)
        return True

    def maybe_sigterm(self, step: int) -> None:
        """Deliver a real SIGTERM through the OS so the actual signal-handler
        path is exercised, not a simulation of it."""
        if self.sigterm_at_step == step and self._fire_once("sigterm"):
            log_event("chaos", f"injecting SIGTERM at step {step}")
            signal.raise_signal(signal.SIGTERM)

    def maybe_kill(self, step: int) -> None:
        """Self-SIGKILL: the death mode no in-process handler can observe —
        the kernel never lets the process run again. The in-flight epoch's
        progress since the last checkpoint is genuinely lost; recovery is
        the supervisor's restart + `--resume auto`, nothing else."""
        if self.kill_at_step == step and self._fire_once("kill"):
            log_event("chaos", f"injecting SIGKILL at step {step}")
            os.kill(os.getpid(), signal.SIGKILL)

    def maybe_freeze(self, step: int) -> None:
        """Wedge the process: stop completing steps (and with them the
        heartbeat) without exiting — exactly what a stuck pod collective
        looks like from outside. Sleeps until killed; a SIGTERM still runs
        the preemption handler's flag-setter, but the flag is never polled
        again, so only the supervisor's SIGTERM→grace→SIGKILL escalation
        (or an operator) ends it."""
        if self.freeze_at_step == step and self._fire_once("freeze"):
            log_event("chaos", f"injecting freeze (wedged-collective "
                               f"simulation) at step {step}")
            while True:
                time.sleep(3600.0)

    def maybe_slow(self, step: int) -> None:
        """Stall the configured step by `slow_ms` (fire-once): an injected
        slow step that every layer sees for real — the phase timer books a
        step_s blowout, the anomaly detector arms a capture window, the
        heartbeat's `last_step_ms` spikes. A stall, not a hang: the step
        completes and the run proceeds, so no watchdog/supervisor kill."""
        if self.slow_at_step == step and self._fire_once("slow"):
            log_event(
                "chaos",
                f"injecting {self.slow_ms} ms slow step at step {step}",
            )
            time.sleep(self.slow_ms / 1e3)

    def maybe_kill_request(self, n_requests: int) -> None:
        """Serve-side SIGKILL after the n-th admitted request (fire-once,
        marker-persisted: the fleet-restarted replica re-counts requests
        from 0 and must not re-fire the drill into a crash loop)."""
        if (self.kill_at_request == n_requests
                and self._fire_once("kill_request")):
            log_event("chaos", f"injecting SIGKILL at request {n_requests}")
            os.kill(os.getpid(), signal.SIGKILL)

    def maybe_kill_shard(self, n_shards: int) -> None:
        """Staging-server SIGKILL after the n-th served shard (fire-once,
        marker-persisted: the supervisor-relaunched worker re-counts
        served shards from 0 and must not re-fire the drill into a crash
        loop). Fired BEFORE the shard's answer is sent, so the client
        observes a dead connection mid-request — the exact failure the
        retry-on-another-server path exists for."""
        if (self.kill_at_shard == n_shards
                and self._fire_once("kill_shard")):
            log_event("chaos", f"injecting SIGKILL at shard {n_shards}")
            os.kill(os.getpid(), signal.SIGKILL)

    def maybe_stall_shard(self, n_shards: int) -> None:
        """Stall the n-th served shard by `stall_ms` before answering
        (fire-once, marker-persisted): a deterministic slow-server
        episode. With `stall_ms` ABOVE the client's request timeout
        (default 30 s — size the knob accordingly) the client's read
        times out and the shard is re-fetched from another server; below
        it, the shard is merely answered slowly (a latency blip, no
        retry exercised). The stalled server stays healthy and keeps
        serving later shards either way."""
        if (self.stall_at_shard == n_shards
                and self._fire_once("stall_shard")):
            log_event(
                "chaos",
                f"injecting {self.stall_ms} ms stall at shard {n_shards}",
            )
            time.sleep(self.stall_ms / 1e3)

    def maybe_wedge_request(self, n_requests: int) -> bool:
        """True once, at the n-th admitted request: the caller (the serve
        front end) flips into accepting-but-not-answering — sockets still
        accept, every handler thread then sleeps forever. Unlike the kill,
        the wedge leaves a live process: only an outside probe-staleness
        kill (the fleet supervisor's) ends it."""
        if (self.wedge_at_request == n_requests
                and self._fire_once("wedge_request")):
            log_event(
                "chaos",
                f"injecting serve wedge (accepting-but-not-answering) at "
                f"request {n_requests}",
            )
            return True
        return False

    def maybe_resize(self, step: int) -> int | None:
        """The target device count at the configured step (fire-once,
        marker-persisted like kill/freeze: the relaunched child must not
        re-fire the drill into a resize loop); None otherwise. 0 means
        "resize without pinning a count". The caller (the driver) writes
        the resize.request and exits through the operator path — the drill
        exercises the REAL loop, not a simulation of it."""
        if self.resize_at_step == step and self._fire_once("resize"):
            log_event(
                "chaos",
                f"injecting resize request at step {step} "
                f"(devices={self.resize_devices or 'visible'})",
            )
            return self.resize_devices
        return None

    def maybe_collapse(self, step: int) -> bool:
        """True for EVERY step at/after `collapse_at_step`: the caller
        (the driver) rewrites the key-encoder params with the degenerate
        `health.crush_key_params` tree after each such step. Persistent
        by design — the in-step EMA leaks (1−m)·θ_q back before every key
        forward, so a one-shot crush would heal itself within one step;
        the fault models a momentum update that is wedged, not glitched.
        The onset is logged once (plain fire-once: the drill is not a
        process-killing fault, so no cross-restart marker is needed)."""
        if self.collapse_at_step is None or step < self.collapse_at_step:
            return False
        if self._fire_once("collapse"):
            log_event(
                "chaos",
                f"injecting representation collapse from step {step}: "
                "key-encoder params crushed to a constant-feature tree",
            )
        return True

    def maybe_nan(self, step: int) -> bool:
        """True at the configured step (the first `nan_count` traversals of
        it): the caller replaces the step's reported loss with NaN — the
        sentinel's detection and the driver's rollback then run for real."""
        if self.nan_at_step == step and self._nans_raised < self.nan_count:
            self._nans_raised += 1
            log_event(
                "chaos",
                f"injecting non-finite loss at step {step} "
                f"({self._nans_raised}/{self.nan_count})",
            )
            return True
        return False

    def maybe_loader_error(self, batch_index: int) -> None:
        """Raise `TransientDataError` for the first `loader_error_count`
        attempts at the configured batch — the retry-with-backoff path must
        survive exactly that many consecutive failures. Thread-safe: with
        multi-worker staging the fault budget is spent exactly
        `loader_error_count` times across all workers (which worker draws
        a fault is scheduler-dependent; the batch-level scenario — N
        transient faults at batch b, then recovery — stays deterministic)."""
        if self.loader_error_at_batch != batch_index:
            return
        with self._lock:
            if self._loader_errors_raised >= self.loader_error_count:
                return
            self._loader_errors_raised += 1
            n = self._loader_errors_raised
        raise TransientDataError(
            f"chaos: injected read failure {n}/"
            f"{self.loader_error_count} at batch {batch_index}"
        )


_INT_FIELDS = (
    "sigterm_at_step",
    "kill_at_step",
    "freeze_at_step",
    "slow_at_step",
    "slow_ms",
    "nan_at_step",
    "nan_count",
    "loader_error_at_batch",
    "loader_error_count",
    "kill_at_request",
    "kill_at_shard",
    "stall_at_shard",
    "stall_ms",
    "wedge_at_request",
    "collapse_at_step",
    "resize_at_step",
    "resize_devices",
)

# spec-key sugar: the resize drill reads `resize_at_step=6,devices=2`
# (the ISSUE 11 spelling) as well as the explicit field name
_SPEC_ALIASES = {"devices": "resize_devices"}


def parse_chaos_spec(spec: str) -> ChaosPlan | None:
    """`"sigterm_at_step=11,nan_at_step=3"` → ChaosPlan. Empty spec → None.
    Unknown keys are rejected loudly — a typo'd fault that silently never
    fires would make a chaos drill vacuous."""
    spec = spec.strip()
    if not spec:
        return None
    kw: dict[str, int] = {}
    for part in spec.split(","):
        key, _, value = part.partition("=")
        key = _SPEC_ALIASES.get(key.strip(), key.strip())
        if key not in _INT_FIELDS:
            raise ValueError(
                f"unknown chaos fault {key!r}; known: {', '.join(_INT_FIELDS)}"
            )
        kw[key] = int(value)
    return ChaosPlan(**kw)


# One plan per process: the hooks live in a worker thread (Prefetcher) and
# the main loop, so the registry is module-global rather than threaded
# through every call signature.
_ACTIVE: ChaosPlan | None = None


def install_chaos(plan: ChaosPlan | None) -> None:
    global _ACTIVE
    _ACTIVE = plan


def clear_chaos() -> None:
    install_chaos(None)


def active_chaos() -> ChaosPlan | None:
    if _ACTIVE is None:
        env = os.environ.get("MOCO_TPU_CHAOS", "")
        if env:
            # env-installed plans persist for the process (fire-once state
            # must survive multiple polls); MOCO_TPU_CHAOS_STATE additionally
            # persists it across PROCESSES — required for supervised drills
            # whose kill/freeze faults end the process and restart it
            plan = parse_chaos_spec(env)
            if plan is not None:
                plan.state_dir = os.environ.get("MOCO_TPU_CHAOS_STATE") or None
            install_chaos(plan)
    return _ACTIVE


@contextlib.contextmanager
def chaos_context(plan: ChaosPlan):
    """Scoped install for tests — guarantees no plan leaks into the next
    test even when the body raises (most chaos scenarios end in a raise)."""
    install_chaos(plan)
    try:
        yield plan
    finally:
        clear_chaos()


def truncate_checkpoint(ckpt_dir: str, step: int) -> str:
    """Corrupt the saved `step` the way a preempted writer does: truncate its
    largest payload file to half. Returns the mangled file's path."""
    root = os.path.join(os.path.abspath(ckpt_dir), str(step))
    largest, size = None, -1
    for dirpath, _dirnames, filenames in os.walk(root):
        for fname in filenames:
            p = os.path.join(dirpath, fname)
            s = os.path.getsize(p)
            if s > size:
                largest, size = p, s
    if largest is None:
        raise FileNotFoundError(f"no files under checkpoint step dir {root}")
    with open(largest, "r+b") as f:
        f.truncate(size // 2)
    log_event("chaos", f"truncated {largest} from {size} to {size // 2} bytes")
    return largest
